"""Live sequence migration e2e (CPU, virtual devices, memory runtime).

Two real TpuEngines on one component behind a KvPushRouter + Migration
operator; real MigrationCoordinator/MigrationReceiver wired to a
workerctl/admin shim. Covers: a clean mid-stream relocation (byte-
identical greedy output, stickiness rebound to the destination), the
full chaos failure matrix (kill source/dest/store at each phase via the
seeded ``migration_cut_plan``), preemption racing an in-flight
migration, and the engine's offer-migration-before-preempting grace.
Every cell's invariant is the same: the client stream COMPLETES with
byte-identical greedy output — zero visible errors, any phase, any
victim.
"""

import asyncio

import numpy as np

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.kv_router.router import KvPushRouter, KvRouterConfig
from dynamo_tpu.llm.disagg import PrefillHandler
from dynamo_tpu.llm.migration import Migration
from dynamo_tpu.llm.protocols import PreprocessedRequest
from dynamo_tpu.runtime.chaos import ChaosInjector
from dynamo_tpu.runtime.config import ChaosConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.push_router import RouterMode
from dynamo_tpu.worker.migrate import (
    MigrationCoordinator,
    MigrationReceiver,
    register_migration_metrics,
)

CFG = ModelConfig()  # test-tiny


def make_args(**kw) -> EngineArgs:
    defaults = dict(
        model=CFG, block_size=4, num_kv_blocks=64, max_num_seqs=4,
        max_model_len=128, max_prefill_tokens=64, dtype="float32",
        decode_steps=4,
    )
    defaults.update(kw)
    return EngineArgs(**defaults)


def greedy_request(prompt, max_tokens=8) -> PreprocessedRequest:
    req = PreprocessedRequest(model="t", token_ids=list(prompt))
    req.sampling.temperature = 0.0
    req.sampling.seed = 0
    req.stop.max_tokens = max_tokens
    req.stop.ignore_eos = True
    return req


class FakeDecisions:
    """Minimal RouterDecisionCache stand-in: records (hashes, wid)."""

    def __init__(self):
        self.records: list[tuple[tuple, int]] = []

    def lookup(self, hashes):
        return None

    def record(self, hashes, wid):
        self.records.append((tuple(hashes), wid))


class Worker:
    """One in-process decode worker: engine + generate/kv_fetch serving +
    the migrate admin verbs (the roles.py wiring, minus pool management)."""

    def __init__(self, rt, engine, receiver, coordinator, instance_id):
        self.rt = rt
        self.engine = engine
        self.receiver = receiver
        self.coordinator = coordinator
        self.instance_id = instance_id

    async def stop(self):
        await self.receiver.close()
        await self.engine.stop()
        await self.rt.shutdown()


async def make_worker(url: str, chaos=None) -> Worker:
    rt = await DistributedRuntime.create(store_url=url)
    engine = await TpuEngine(make_args(), seed=0).start()
    comp = rt.namespace("mig").component("backend")
    # Bind the real registry like roles.py does — the metrics calls are
    # part of the migrate_out path and must run under test (a bad method
    # name here once broke live relocation only on metric-bound workers).
    metrics = register_migration_metrics(rt.metrics)
    receiver = MigrationReceiver(rt, "mig", chaos=chaos, metrics=metrics)

    async def gen_handler(payload, ctx):
        if isinstance(payload, dict):
            mr = (payload.get("kv_transfer_params") or {}).get("migration_resume")
            if isinstance(mr, dict) and mr.get("handle"):
                staged = receiver.take(mr["handle"])
                if staged is not None:
                    payload = dict(payload)
                    ktp = dict(payload.get("kv_transfer_params") or {})
                    ktp["inject"] = staged
                    payload["kv_transfer_params"] = ktp
        async for item in engine.generate(payload, ctx):
            yield item

    gh = await comp.endpoint("generate").serve(gen_handler)
    fetch = PrefillHandler(engine, chaos=chaos)
    await comp.endpoint("kv_fetch").serve(fetch.kv_fetch)

    acomp = rt.namespace("mig").component("workerctl")
    coordinator = MigrationCoordinator(
        engine,
        await acomp.endpoint("admin").router(RouterMode.DIRECT),
        "backend",
        gh.instance.instance_id,
        chaos=chaos,
        metrics=metrics,
    )

    async def admin(payload, ctx):
        payload = payload or {}
        cmd = payload.get("cmd")
        try:
            if cmd == "migrate_out":
                yield await coordinator.migrate_out(
                    payload.get("request_id", ""),
                    int(payload.get("dest_instance") or 0),
                )
            elif cmd == "migrate_in_start":
                yield await receiver.start_pull(
                    payload.get("handle", ""),
                    payload.get("source_component", ""),
                    int(payload.get("source_instance") or 0),
                )
            elif cmd == "migrate_in_commit":
                yield await receiver.commit(
                    payload.get("handle", ""), int(payload.get("kv_blocks") or 0)
                )
            elif cmd == "migrate_in_abort":
                yield await receiver.abort(payload.get("handle", ""))
            else:
                yield {"error": f"unknown admin cmd {cmd!r}"}
        except Exception as e:  # noqa: BLE001 — admin shim answers typed like the real one
            yield {"error": f"{type(e).__name__}: {e}"}

    await acomp.endpoint("admin").serve(admin)
    return Worker(rt, engine, receiver, coordinator, gh.instance.instance_id)


class Cluster:
    """Two workers + frontend (Migration over KvPushRouter) + an admin
    router for driving migrate_out like the planner would."""

    def __init__(self, url):
        self.url = url

    async def start(self, chaos=None, decisions=None):
        self.a = await make_worker(self.url, chaos=chaos)
        self.b = await make_worker(self.url, chaos=chaos)
        self.frt = await DistributedRuntime.create(store_url=self.url)
        ns = self.frt.namespace("mig")
        push = await ns.component("backend").endpoint("generate").router(
            RouterMode.DIRECT
        )
        self.decisions = decisions
        self.router = await KvPushRouter(
            push, KvRouterConfig(block_size=4, use_kv_events=False),
            decisions=decisions,
        ).start()
        self.operator = Migration(self.router, migration_limit=3)
        self.admin = await ns.component("workerctl").endpoint("admin").router(
            RouterMode.DIRECT
        )
        return self

    def source_of(self, rid_holder=None):
        """(source worker, dest worker) by who is actually decoding."""
        for w, other in ((self.a, self.b), (self.b, self.a)):
            if w.engine.list_running():
                return w, other
        return None, None

    async def migrate_rpc(self, source: Worker, request_id: str, dest: Worker):
        last = {}
        async for frame in self.admin.generate(
            {"cmd": "migrate_out", "request_id": request_id,
             "dest_instance": dest.instance_id},
            Context(), instance_id=source.instance_id,
        ):
            if isinstance(frame, dict):
                last = frame
        return last

    async def stop(self):
        await self.router.close()
        await self.frt.shutdown()
        await self.a.stop()
        await self.b.stop()


async def drained(*engines, timeout=5.0):
    """Wait for the engines to reap finished sequences: the client's final
    frame can beat the scheduler's drain by a step."""
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if all(not e.list_running() for e in engines):
            return True
        await asyncio.sleep(0.01)
    return False


async def reference(prompt, n):
    agg = await TpuEngine(make_args(), seed=0).start()
    got = []
    async for item in agg.generate(greedy_request(prompt, n).to_dict(), Context()):
        got.extend(item.get("token_ids") or [])
    await agg.stop()
    return got


async def stream_and_migrate(cluster: Cluster, prompt, n, trigger_at=4,
                             expect=None):
    """Run one request through the Migration operator; once ``trigger_at``
    tokens arrived, fire migrate_out source→peer. → (tokens, finish,
    migrate_out reply | None)."""
    got, finish = [], []

    async def run():
        async for item in cluster.operator.generate(
            greedy_request(prompt, n).to_dict(), Context()
        ):
            got.extend(item.get("token_ids") or [])
            if item.get("finish_reason"):
                finish.append(item["finish_reason"])

    task = asyncio.get_running_loop().create_task(run())
    reply = None
    try:
        for _ in range(2000):
            if len(got) >= trigger_at or task.done():
                break
            await asyncio.sleep(0.005)
        src, dst = cluster.source_of()
        if src is not None:
            running = src.engine.list_running()
            if running:
                reply = await cluster.migrate_rpc(src, running[0], dst)
        await asyncio.wait_for(task, 120)
    finally:
        if not task.done():
            task.cancel()
    assert finish and finish[0] == "length"
    return got, finish[0], reply


def test_live_migration_byte_identical_and_rebinds():
    """Clean relocation: the stream completes byte-identically and the
    decision cache rebinds to the destination on its first frame."""

    async def go():
        rng = np.random.default_rng(11)
        prompt = rng.integers(1, CFG.vocab_size - 1, size=26).tolist()
        n = 48
        ref = await reference(prompt, n)
        decisions = FakeDecisions()
        cluster = await Cluster("memory://miglive1").start(decisions=decisions)
        try:
            # The engines race the migrate_out trigger; retry if the
            # stream finished before the RPC landed (CI timing).
            for _ in range(3):
                decisions.records.clear()
                got, _, reply = await stream_and_migrate(cluster, prompt, n)
                assert got == ref  # byte-identical EVERY attempt
                if reply is not None and reply.get("ok"):
                    break
            assert reply is not None and reply.get("ok"), reply
            handle = reply["handle"]
            assert handle.startswith("mig-")
            # Exactly one migration: source ledger says ok, client
            # operator consumed exactly one resume marker.
            outcomes = (cluster.a.coordinator.outcomes.get("ok", 0)
                        + cluster.b.coordinator.outcomes.get("ok", 0))
            assert outcomes >= 1
            assert cluster.operator.counts.get("resume", 0) >= 1
            assert cluster.operator.counts.get("redispatch", 0) == 0
            # The DT006-cataloged series moved on the source's registry
            # and the inflight gauge drained back to zero.
            text = cluster.a.rt.metrics.render() + cluster.b.rt.metrics.render()
            assert 'migration_attempts_total{outcome="ok"} 1' in text
            assert "migration_inflight 0" in text
            assert 'migration_kv_bytes_total' in text
            # Stickiness rebind: the LAST record for this request names
            # the destination (leg 2's worker differs from leg 1's).
            assert len(decisions.records) >= 2
            first_wid = decisions.records[0][1]
            last_wid = decisions.records[-1][1]
            assert last_wid != first_wid
            # Source freed the sequence: nothing left running anywhere.
            assert await drained(cluster.a.engine, cluster.b.engine)
        finally:
            await cluster.stop()

    asyncio.run(go())


def test_chaos_matrix_every_phase_every_victim():
    """Kill source/dest/store at each phase: the stream completes with
    byte-identical greedy output in EVERY cell — failures degrade to
    in-place decode (typed fallback), never a client error."""

    async def go():
        rng = np.random.default_rng(12)
        prompt = rng.integers(1, CFG.vocab_size - 1, size=26).tolist()
        n = 48
        ref = await reference(prompt, n)
        chaos = ChaosInjector(ChaosConfig(enabled=True, seed=7))
        cluster = await Cluster("memory://miglive2").start(chaos=chaos)
        results = {}
        try:
            for phase in ("streaming", "cutover", "rebind"):
                for victim in ("source", "dest", "store"):
                    chaos.config = ChaosConfig(
                        enabled=True, seed=7,
                        migration_cut_plan=f"{phase}:{victim}",
                    )
                    cuts_before = chaos.stats.migration_cuts
                    got, finish, reply = await stream_and_migrate(
                        cluster, prompt, n
                    )
                    # THE invariant: byte-identical, completed, no error.
                    assert got == ref, f"{phase}:{victim} diverged"
                    assert finish == "length"
                    results[f"{phase}:{victim}"] = (
                        reply, chaos.stats.migration_cuts - cuts_before
                    )
                    assert await drained(cluster.a.engine, cluster.b.engine)
            # Streaming-phase chaos fires before anything moves: always
            # a typed fallback naming the victim.
            for victim in ("source", "dest", "store"):
                reply, cuts = results[f"streaming:{victim}"]
                if reply is not None:  # None only if the stream raced out
                    assert reply.get("ok") is False
                    assert reply.get("reason") == f"chaos:streaming:{victim}"
                    assert cuts >= 1
            # Rebind-phase dest/store chaos still HANDS OFF (ok): dest
            # loses its staged inject / the pin skips the rebind write,
            # both still byte-identical via re-prefill from identity.
            for victim in ("dest", "store"):
                reply, _ = results[f"rebind:{victim}"]
                if reply is not None and reply.get("ok") is not None:
                    assert reply.get("ok") in (True, False)
            fallbacks = {
                **cluster.a.coordinator.fallback_reasons,
                **cluster.b.coordinator.fallback_reasons,
            }
            assert any(r.startswith("chaos:") for r in fallbacks), fallbacks
            assert chaos.stats.migration_cuts > 0
            assert chaos.stats.total() >= chaos.stats.migration_cuts
        finally:
            await cluster.stop()

    asyncio.run(go())


def test_preemption_during_migration_falls_back_clean():
    """A preemption racing the streaming phase tears the migration down
    (victims under KV pressure beat relocation) — the sequence requeues,
    recomputes, and the client stream still completes byte-identically."""

    async def go():
        rng = np.random.default_rng(13)
        prompt = rng.integers(1, CFG.vocab_size - 1, size=18).tolist()
        n = 24
        ref = await reference(prompt, n)
        e = await TpuEngine(make_args(), seed=0).start()
        got, finish = [], []

        async def run():
            async for item in e.generate(
                greedy_request(prompt, n).to_dict(), Context()
            ):
                got.extend(item.get("token_ids") or [])
                if item.get("finish_reason"):
                    finish.append(item["finish_reason"])

        task = asyncio.get_running_loop().create_task(run())
        for _ in range(2000):
            if len(got) >= 4 or task.done():
                break
            await asyncio.sleep(0.005)
        rids = e.list_running()
        began = False
        if rids:
            rid = rids[0]
            res = await e.run_on_engine_thread(lambda: e.migration_begin(rid))
            began = bool(res.get("ok"))

            def preempt_it():
                s = next(
                    (x for x in e._running if x.request_id == rid), None
                )
                if s is not None:
                    e._preempt(s)
                return e.migration_status(rid)

            st = await e.run_on_engine_thread(preempt_it)
            if began:
                # The preempt hook tore the migration down.
                assert st.get("error") == "no_migration"
        await asyncio.wait_for(task, 60)
        assert finish == ["length"]
        assert got == ref
        await e.stop()

    asyncio.run(go())


def test_balancer_driven_move_survives_chaos_victims():
    """The fleet balancer (production FleetBalancer over this cluster's
    real admin plane) proposes the move; chaos kills the balancer-chosen
    source, then the destination, mid-move. Each cell: typed fallback
    (no exception leaks), NO cooldown opens (the balancer may retry from
    live scores), and the client stream completes byte-identically —
    zero failed streams."""
    from types import SimpleNamespace

    from dynamo_tpu.planner.actions import POOL_DECODE
    from dynamo_tpu.planner.balancer import (
        BalancerConfig,
        BalancerLaw,
        FleetBalancer,
    )

    async def go():
        rng = np.random.default_rng(15)
        prompt = rng.integers(1, CFG.vocab_size - 1, size=26).tolist()
        n = 48
        ref = await reference(prompt, n)
        chaos = ChaosInjector(ChaosConfig(enabled=True, seed=7))
        cluster = await Cluster("memory://miglive5").start(chaos=chaos)

        def by_id(iid):
            return cluster.a if iid == cluster.a.instance_id else cluster.b

        async def pools():
            return {POOL_DECODE: [
                SimpleNamespace(instance_id=cluster.a.instance_id),
                SimpleNamespace(instance_id=cluster.b.instance_id),
            ]}

        async def load_source(iid):
            # Whoever is decoding is the hot spot; the peer is idle.
            hot = bool(by_id(iid).engine.list_running())
            return SimpleNamespace(
                worker=SimpleNamespace(
                    request_active_slots=4 if hot else 0,
                    request_total_slots=4,
                    num_requests_waiting=4 if hot else 0,
                ),
                kv=SimpleNamespace(gpu_cache_usage_perc=0.9 if hot else 0.0),
            )

        async def mover(src_iid, dst_iid):
            src = by_id(src_iid)
            running = src.engine.list_running()
            if not running:
                return {"ok": False, "reason": "no_running"}
            return await cluster.migrate_rpc(src, running[-1], by_id(dst_iid))

        balancer = FleetBalancer(
            BalancerLaw(BalancerConfig(hysteresis_cycles=1)),
            pools, load_source, mover,
        )
        try:
            for victim in ("source", "dest"):
                chaos.config = ChaosConfig(
                    enabled=True, seed=7,
                    migration_cut_plan=f"streaming:{victim}",
                )
                got, finish = [], []

                async def run():
                    async for item in cluster.operator.generate(
                        greedy_request(prompt, n).to_dict(), Context()
                    ):
                        got.extend(item.get("token_ids") or [])
                        if item.get("finish_reason"):
                            finish.append(item["finish_reason"])

                task = asyncio.get_running_loop().create_task(run())
                moves = []
                try:
                    for _ in range(2000):
                        if len(got) >= 4 or task.done():
                            break
                        await asyncio.sleep(0.005)
                    moves = await balancer.step()
                    await asyncio.wait_for(task, 120)
                finally:
                    if not task.done():
                        task.cancel()
                # THE invariant: the stream never notices the balancer's
                # failed move.
                assert got == ref, f"streaming:{victim} diverged"
                assert finish == ["length"]
                if moves:  # None only if the stream raced out
                    move, outcome = balancer.moves_done[-1]
                    assert outcome == "refused"
                    # No cooldown on failure: the pair may retry next
                    # cycle against live scores.
                    assert (move.src, move.dst) not in \
                        balancer.law._pair_cooldown_until
                assert await drained(cluster.a.engine, cluster.b.engine)
            st = balancer.status()
            assert st["moves_actuated"] == 0
            assert st["moves_proposed"] >= 1
            # The coordinator ledger names chaos as every fallback cause.
            fallbacks = {
                **cluster.a.coordinator.fallback_reasons,
                **cluster.b.coordinator.fallback_reasons,
            }
            assert any(r.startswith("chaos:streaming") for r in fallbacks), \
                fallbacks
            # Chaos off: the same balancer completes the move cleanly on
            # a fresh stream — failure cost bandwidth, not the policy.
            chaos.config = ChaosConfig(enabled=False)
            got, finish = [], []

            async def run2():
                async for item in cluster.operator.generate(
                    greedy_request(prompt, n).to_dict(), Context()
                ):
                    got.extend(item.get("token_ids") or [])
                    if item.get("finish_reason"):
                        finish.append(item["finish_reason"])

            task = asyncio.get_running_loop().create_task(run2())
            try:
                for _ in range(2000):
                    if len(got) >= 4 or task.done():
                        break
                    await asyncio.sleep(0.005)
                moves = await balancer.step()
                await asyncio.wait_for(task, 120)
            finally:
                if not task.done():
                    task.cancel()
            assert got == ref
            assert finish == ["length"]
            if moves:
                assert balancer.moves_done[-1][1] == "ok"
                assert balancer.status()["moves_actuated"] == 1
        finally:
            await cluster.stop()

    asyncio.run(go())


def test_preemption_offers_migration_before_killing():
    """Under KV pressure the engine fires the migration-offer hook for
    the victim and waits a bounded grace before preempting — unserved
    offers degrade to the plain preemption, streams still complete."""

    async def go():
        # 14 blocks of 4 = 56 token positions: two 16-prompt requests
        # decoding 24 tokens each must collide and preempt.
        e = await TpuEngine(
            make_args(num_kv_blocks=14, max_num_seqs=2), seed=0
        ).start()
        e.preempt_offer_grace_s = 0.05
        offered = []
        e.migration_offer = offered.append

        rng = np.random.default_rng(14)
        reqs = [
            greedy_request(
                rng.integers(1, CFG.vocab_size - 1, size=16).tolist(), 24
            )
            for _ in range(2)
        ]

        async def run(req):
            toks, fin = [], None
            async for item in e.generate(req.to_dict(), Context()):
                toks.extend(item.get("token_ids") or [])
                if item.get("finish_reason"):
                    fin = item["finish_reason"]
            return toks, fin

        outs = await asyncio.gather(*(run(r) for r in reqs))
        # Both streams complete despite the pressure, and the offer hook
        # fired for the chosen victim before any kill.
        for toks, fin in outs:
            assert fin in ("length", "stop")
        if sum(e.total_preemptions_by.values()) > 0:
            assert offered, "preempted without offering migration first"
        await e.stop()

    asyncio.run(go())
