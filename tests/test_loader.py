"""Checkpoint loader tests: HF safetensors → engine pytree, with logit
parity against the trusted transformers CPU implementation.

This is the correctness anchor for real-model serving (VERDICT r2 next
#2): if prefill/decode logits match HF's forward on a random-init tiny
llama, the weight mapping, RoPE convention, GQA head ordering and norm
placement are all right.
"""

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp  # noqa: E402

from dynamo_tpu.engine import model as M  # noqa: E402
from dynamo_tpu.engine.loader import config_from_hf, load_model  # noqa: E402


def make_hf_llama(tmp_path, tie: bool, num_kv_heads: int = 2):
    cfg = transformers.LlamaConfig(
        vocab_size=97,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=num_kv_heads,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=tie,
        torch_dtype="float32",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg).eval()
    path = tmp_path / "tiny-llama"
    model.save_pretrained(path, safe_serialization=True)
    return model, str(path)


@pytest.mark.parametrize("tie", [False, True])
def test_logit_parity_prefill(tmp_path, tie):
    hf, path = make_hf_llama(tmp_path, tie)
    cfg, params = load_model(path, dtype=jnp.float32)
    assert cfg.tie_embeddings == tie
    assert cfg.num_kv_heads == 2 and cfg.num_heads == 4

    rng = np.random.default_rng(0)
    T = 12
    toks = rng.integers(1, cfg.vocab_size - 1, size=T).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(toks[None].astype(np.int64))).logits[0, -1].numpy()

    bs = 4
    cache = M.init_kv_cache(cfg, num_blocks=16, block_size=bs, dtype=jnp.float32)
    table = np.zeros((4,), np.int32)
    table[: (T + bs - 1) // bs] = np.arange(1, 1 + (T + bs - 1) // bs)
    pad = np.zeros((16,), np.int32)
    pad[:T] = toks
    logits, cache = M.prefill(
        cfg, params, cache, jnp.asarray(pad), jnp.asarray(table),
        jnp.int32(0), jnp.int32(T),
    )
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-4, atol=2e-4)


def test_logit_parity_decode_step(tmp_path):
    hf, path = make_hf_llama(tmp_path, tie=False)
    cfg, params = load_model(path, dtype=jnp.float32)

    rng = np.random.default_rng(1)
    T = 9
    toks = rng.integers(1, cfg.vocab_size - 1, size=T).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(toks[None].astype(np.int64))).logits[0, -1].numpy()

    # Prefill T-1 tokens, then decode the final token through decode_step.
    bs = 4
    cache = M.init_kv_cache(cfg, num_blocks=16, block_size=bs, dtype=jnp.float32)
    nblocks = (T + bs - 1) // bs
    table = np.zeros((4,), np.int32)
    table[:nblocks] = np.arange(1, 1 + nblocks)
    pad = np.zeros((8,), np.int32)
    pad[: T - 1] = toks[: T - 1]
    _, cache = M.prefill(
        cfg, params, cache, jnp.asarray(pad), jnp.asarray(table),
        jnp.int32(0), jnp.int32(T - 1),
    )
    logits, cache = M.decode_step(
        cfg, params, cache,
        jnp.asarray([toks[-1]]), jnp.asarray([T - 1], jnp.int32),
        jnp.asarray(table[None, :]), jnp.asarray([True]),
    )
    np.testing.assert_allclose(np.asarray(logits)[0], ref, rtol=2e-4, atol=2e-4)


def test_config_from_hf_fields(tmp_path):
    _, path = make_hf_llama(tmp_path, tie=True)
    cfg = config_from_hf(path)
    assert cfg.vocab_size == 97
    assert cfg.hidden_size == 64
    assert cfg.intermediate_size == 128
    assert cfg.num_layers == 2
    assert cfg.head_dim == 16
    assert cfg.rope_theta == 10000.0
    assert cfg.max_position == 256


def test_sharded_index_checkpoint(tmp_path):
    """Loader follows model.safetensors.index.json across shards."""
    import os

    from safetensors.numpy import load_file, save_file

    _, path = make_hf_llama(tmp_path, tie=False)
    tensors = load_file(os.path.join(path, "model.safetensors"))
    names = sorted(tensors)
    half = len(names) // 2
    shards = {
        "model-00001-of-00002.safetensors": {n: tensors[n] for n in names[:half]},
        "model-00002-of-00002.safetensors": {n: tensors[n] for n in names[half:]},
    }
    weight_map = {}
    for fname, part in shards.items():
        save_file(part, os.path.join(path, fname))
        weight_map.update({n: fname for n in part})
    os.remove(os.path.join(path, "model.safetensors"))
    with open(os.path.join(path, "model.safetensors.index.json"), "w") as f:
        json.dump({"weight_map": weight_map}, f)

    cfg, params = load_model(path, dtype=jnp.float32)
    assert params["layers"]["wq"].shape == (2, 64, 64)


def test_engine_greedy_generation_matches_hf(tmp_path):
    """Full engine path (chunked prefill → fused multi-step decode →
    sampling) on real loaded weights reproduces transformers' greedy
    continuation token-for-token."""
    import asyncio

    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.llm.protocols import PreprocessedRequest
    from dynamo_tpu.runtime.engine import Context

    hf, path = make_hf_llama(tmp_path, tie=False)
    cfg, params = load_model(path, dtype=jnp.float32)

    rng = np.random.default_rng(7)
    prompt = rng.integers(1, cfg.vocab_size - 1, size=11).astype(np.int64)
    N = 16
    with torch.no_grad():
        ref = hf.generate(
            torch.tensor(prompt[None]), max_new_tokens=N, do_sample=False,
            eos_token_id=None, pad_token_id=0,
        )[0, len(prompt):].tolist()

    async def go():
        eargs = EngineArgs(
            model=cfg, block_size=4, num_kv_blocks=64, max_num_seqs=2,
            max_model_len=64, dtype="float32", decode_steps=4,
        )
        engine = await TpuEngine(eargs, params=params).start()
        req = PreprocessedRequest(model=cfg.name, token_ids=prompt.tolist())
        req.sampling.temperature = 0.0
        req.sampling.seed = 0  # greedy, but unseeded requests draw global RNG (DT004)
        req.stop.max_tokens = N
        req.stop.ignore_eos = True
        out = []
        async for item in engine.generate(req, Context()):
            out.extend(item.get("token_ids") or [])
        await engine.stop()
        return out

    got = asyncio.run(go())
    assert got == ref


def test_missing_tensor_raises(tmp_path):
    import os

    from safetensors.numpy import load_file, save_file

    _, path = make_hf_llama(tmp_path, tie=False)
    tensors = load_file(os.path.join(path, "model.safetensors"))
    tensors.pop("model.layers.1.mlp.up_proj.weight")
    save_file(tensors, os.path.join(path, "model.safetensors"))
    with pytest.raises(KeyError, match="up_proj"):
        load_model(path, dtype=jnp.float32)


def test_golden_parity_vs_transformers(tmp_path):
    """Load a REAL HF-format Llama checkpoint (written by transformers
    itself) and match transformers' logits. This pins the RoPE layout
    claim (loader.py: HF q/k load with no permutation fix-up) against the
    reference implementation — a silent q/k permutation bug passes the
    synthetic-checkpoint tests but fails here (VERDICT r3 weak #8)."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=256, rope_theta=10000.0,
        rms_norm_eps=1e-5, tie_word_embeddings=False, torch_dtype="float32",
    )
    torch.manual_seed(0)
    hf_model = LlamaForCausalLM(hf_cfg).eval()
    path = tmp_path / "tiny-llama"
    hf_model.save_pretrained(path, safe_serialization=True)

    prompt = [3, 17, 99, 4, 56, 23, 81, 7]
    with torch.no_grad():
        ref = hf_model(torch.tensor([prompt])).logits[0].numpy()  # [T, V]

    cfg, params = load_model(str(path), dtype="float32")
    assert cfg.num_kv_heads == 2 and cfg.head_dim == 16

    bs = 4
    nblocks = (len(prompt) + bs - 1) // bs + 1
    cache = M.init_kv_cache(cfg, 16, bs, jnp.float32)
    table = jnp.asarray(list(range(1, nblocks + 1)), jnp.int32)
    logits, _ = M.prefill(
        cfg, params, cache, jnp.asarray(prompt, jnp.int32), table,
        jnp.int32(0), jnp.int32(len(prompt)),
    )
    # prefill returns last-token logits; compare against transformers'.
    np.testing.assert_allclose(
        np.asarray(logits), ref[-1], atol=2e-4, rtol=2e-3
    )


def test_qwen2_attn_bias_logit_parity(tmp_path):
    """Qwen2-family: QKV projection biases must load and apply — golden
    logits vs transformers' Qwen2ForCausalLM (biases ignored = this test
    fails loudly)."""
    qcfg = transformers.Qwen2Config(
        vocab_size=97,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        torch_dtype="float32",
    )
    torch.manual_seed(1)
    hf = transformers.Qwen2ForCausalLM(qcfg).eval()
    # Bias tensors must be non-trivial or the test proves nothing.
    with torch.no_grad():
        for layer in hf.model.layers:
            layer.self_attn.q_proj.bias.normal_(0.0, 0.5)
            layer.self_attn.k_proj.bias.normal_(0.0, 0.5)
            layer.self_attn.v_proj.bias.normal_(0.0, 0.5)
    path = tmp_path / "tiny-qwen2"
    hf.save_pretrained(path, safe_serialization=True)

    cfg, params = load_model(str(path), dtype=jnp.float32)
    assert cfg.attn_bias
    assert params["layers"]["bq"].shape == (2, 64)

    rng = np.random.default_rng(3)
    T = 10
    toks = rng.integers(1, cfg.vocab_size - 1, size=T).astype(np.int32)
    with torch.no_grad():
        ref = hf(torch.tensor(toks[None].astype(np.int64))).logits[0, -1].numpy()

    bs = 4
    cache = M.init_kv_cache(cfg, num_blocks=16, block_size=bs, dtype=jnp.float32)
    table = np.zeros((4,), np.int32)
    table[: (T + bs - 1) // bs] = np.arange(1, 1 + (T + bs - 1) // bs)
    pad = np.zeros((16,), np.int32)
    pad[:T] = toks
    logits, cache = M.prefill(
        cfg, params, cache, jnp.asarray(pad), jnp.asarray(table),
        jnp.int32(0), jnp.int32(T),
    )
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-4, atol=2e-4)

    # Decode step parity too (bias rides the scan's per-layer slices).
    with torch.no_grad():
        ref2 = hf(torch.tensor(np.concatenate([toks, [7]])[None].astype(np.int64))).logits[0, -1].numpy()
    l2, _ = M.decode_step(
        cfg, params, cache,
        jnp.asarray([7], jnp.int32), jnp.asarray([T], jnp.int32),
        jnp.asarray(table[None]), jnp.asarray([True]),
    )
    np.testing.assert_allclose(np.asarray(l2[0]), ref2, rtol=2e-4, atol=2e-4)
