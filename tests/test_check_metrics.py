"""Tier-1 wiring for tools/check_metrics.py: the metrics catalog must stay
clean — every registered metric carries help text, no name/type collisions
across scopes or process registries."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_metrics_catalog_is_clean():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_metrics.py")],
        cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    assert "check_metrics: OK" in proc.stdout
