"""Distributed runtime integration: serve, discover, route, cancel, fail over.

Mirrors the intent of the reference's lib/runtime/tests/ pipeline +
lifecycle suites, on the in-process memory store.
"""

import asyncio

import pytest

from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import TraceContext, current_trace
from dynamo_tpu.runtime.push_router import NoInstancesError, RouterMode


async def make_runtime(name="testcluster"):
    return await DistributedRuntime.create(store_url=f"memory://{name}")


def test_serve_and_call_roundtrip():
    async def run():
        rt = await make_runtime()
        ep = rt.namespace("ns").component("backend").endpoint("generate")

        async def handler(payload, ctx):
            for i in range(payload["n"]):
                yield {"token": i}

        handle = await ep.serve(handler)
        router = await ep.router()
        out = [item async for item in router.generate({"n": 3}, Context())]
        assert out == [{"token": 0}, {"token": 1}, {"token": 2}]
        await handle.close()
        await rt.shutdown()

    asyncio.run(run())


def test_round_robin_over_two_instances():
    async def run():
        rt1 = await make_runtime("rr")
        rt2 = await DistributedRuntime.create(store_url="memory://rr")
        seen = []

        def mk(tag):
            async def handler(payload, ctx):
                seen.append(tag)
                yield {"worker": tag}

            return handler

        ep1 = rt1.namespace("ns").component("c").endpoint("e")
        ep2 = rt2.namespace("ns").component("c").endpoint("e")
        await ep1.serve(mk("a"))
        await ep2.serve(mk("b"))

        router = await ep1.router(RouterMode.ROUND_ROBIN)
        await router.discovery.wait_for_instances(2, timeout=5)
        for _ in range(4):
            [_ async for _ in router.generate({}, Context())]
        assert sorted(seen) == ["a", "a", "b", "b"]
        await rt1.shutdown()
        await rt2.shutdown()

    asyncio.run(run())


def test_failover_marks_instance_down():
    async def run():
        rt1 = await make_runtime("fo")
        rt2 = await DistributedRuntime.create(store_url="memory://fo")

        async def good(payload, ctx):
            yield {"ok": True}

        ep1 = rt1.namespace("ns").component("c").endpoint("e")
        ep2 = rt2.namespace("ns").component("c").endpoint("e")
        h1 = await ep1.serve(good)
        await ep2.serve(good)

        router = await ep2.router(RouterMode.ROUND_ROBIN)
        await router.discovery.wait_for_instances(2, timeout=5)

        # Kill rt1's server abruptly (no deregistration) — simulates crash.
        await rt1._server.close()
        results = []
        for _ in range(4):
            out = [item async for item in router.generate({}, Context())]
            results.extend(out)
        assert all(r == {"ok": True} for r in results)
        # rt1's instance should now be marked down locally.
        assert len(router.discovery.available()) == 1
        await rt2.shutdown()
        await rt1.shutdown()

    asyncio.run(run())


def test_deregistration_via_handle_close():
    async def run():
        rt = await make_runtime("dereg")

        async def handler(payload, ctx):
            yield 1

        ep = rt.namespace("ns").component("c").endpoint("e")
        handle = await ep.serve(handler)
        client = await ep.client()
        await client.wait_for_instances(1, timeout=5)
        await handle.close()
        await asyncio.sleep(0.1)
        assert client.available() == []
        router = await ep.router()
        with pytest.raises(NoInstancesError):
            [_ async for _ in router.generate({}, Context())]
        await rt.shutdown()

    asyncio.run(run())


def test_cancellation_stops_worker_stream():
    async def run():
        rt = await make_runtime("cancel")
        progressed = {"n": 0}

        async def slow(payload, ctx):
            for i in range(1000):
                if ctx.cancelled:
                    return
                progressed["n"] = i
                yield {"i": i}
                await asyncio.sleep(0.01)

        ep = rt.namespace("ns").component("c").endpoint("e")
        await ep.serve(slow)
        router = await ep.router()
        ctx = Context()
        got = []
        async for item in router.generate({}, ctx):
            got.append(item)
            if len(got) == 3:
                ctx.cancel()
                break
        await asyncio.sleep(0.3)
        n_after = progressed["n"]
        await asyncio.sleep(0.2)
        assert progressed["n"] <= n_after + 1  # worker stopped advancing
        await rt.shutdown()

    asyncio.run(run())


def test_traceparent_propagates_to_handler():
    async def run():
        rt = await make_runtime("trace")
        seen = {}

        async def handler(payload, ctx):
            seen["trace"] = ctx.trace
            seen["logging_trace"] = current_trace()
            yield {}

        ep = rt.namespace("ns").component("c").endpoint("e")
        await ep.serve(handler)
        router = await ep.router()
        root = TraceContext.parse("00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01")
        [_ async for _ in router.generate({}, Context(trace=root))]
        assert seen["trace"].trace_id == "0af7651916cd43dd8448eb211c80319c"
        # span id was re-minted for the hop but trace id survived
        assert seen["logging_trace"].trace_id == seen["trace"].trace_id
        await rt.shutdown()

    asyncio.run(run())


def test_direct_mode_targets_specific_instance():
    async def run():
        rt = await make_runtime("direct")
        tags = {}

        def mk(tag):
            async def handler(payload, ctx):
                yield {"worker": tag}

            return handler

        rt2 = await DistributedRuntime.create(store_url="memory://direct")
        ep1 = rt.namespace("ns").component("c").endpoint("e")
        ep2 = rt2.namespace("ns").component("c").endpoint("e")
        h1 = await ep1.serve(mk("a"))
        h2 = await ep2.serve(mk("b"))
        router = await ep1.router(RouterMode.DIRECT)
        await router.discovery.wait_for_instances(2, timeout=5)
        target = h2.instance.instance_id
        out = [i async for i in router.generate({}, Context(), instance_id=target)]
        assert out == [{"worker": "b"}]
        await rt.shutdown()
        await rt2.shutdown()

    asyncio.run(run())


def test_system_http_server_health_live_metrics():
    """Every process can expose /health /live /metrics (reference:
    lib/runtime/src/http_server.rs:33-69) — VERDICT r3 weak #7: workers
    previously had no HTTP health surface."""
    import httpx

    from dynamo_tpu.runtime.config import Config
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    async def go():
        cfg = Config.from_env()
        cfg.system.enabled = True
        cfg.system.host = "127.0.0.1"
        cfg.system.port = 0
        rt = await DistributedRuntime.create(store_url="memory://sys1", config=cfg)
        comp = rt.namespace("sys").component("w")

        async def handler(payload, ctx):
            yield {"ok": True}

        await comp.endpoint("generate").serve(handler)
        port = rt._system_server.port
        async with httpx.AsyncClient(timeout=10) as client:
            h = await client.get(f"http://127.0.0.1:{port}/health")
            live = await client.get(f"http://127.0.0.1:{port}/live")
            metrics = await client.get(f"http://127.0.0.1:{port}/metrics")
        await rt.shutdown()
        return h, live, metrics

    h, live, metrics = asyncio.run(go())
    assert h.status_code == 200 and h.json()["status"] == "ready"
    assert any(v for v in h.json()["endpoints"].values())
    assert live.status_code == 200 and live.json()["live"] is True
    assert metrics.status_code == 200
