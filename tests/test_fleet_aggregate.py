"""Fleet aggregation units: exposition relabel/merge and the ledger
merge, plus supervisor helpers that don't need processes (backoff
policy shape, supervisor-flag stripping)."""

from dynamo_tpu.fleet.aggregate import merge_ledgers, merge_metrics, relabel_sample
from dynamo_tpu.fleet.supervisor import BackoffPolicy, strip_supervisor_flags


def test_relabel_sample_variants():
    assert (
        relabel_sample('m_total{a="x"} 3', "fleet_worker_id", "1")
        == 'm_total{fleet_worker_id="1",a="x"} 3'
    )
    assert (
        relabel_sample("m_total 3", "fleet_worker_id", "0")
        == 'm_total{fleet_worker_id="0"} 3'
    )
    assert relabel_sample("# HELP m_total x", "w", "0") is None
    assert relabel_sample("", "w", "0") is None
    # Histogram 'le' labels survive (injected label leads).
    out = relabel_sample('h_bucket{le="+Inf"} 7', "w", "2")
    assert out == 'h_bucket{w="2",le="+Inf"} 7'


def test_merge_metrics_groups_families_and_relabels():
    e0 = (
        "# HELP dt_req_total requests\n"
        "# TYPE dt_req_total counter\n"
        'dt_req_total{model="m"} 3\n'
        "# HELP dt_lat latency\n"
        "# TYPE dt_lat histogram\n"
        'dt_lat_bucket{le="+Inf"} 2\n'
        "dt_lat_sum 0.5\n"
        "dt_lat_count 2\n"
    )
    e1 = (
        "# HELP dt_req_total requests\n"
        "# TYPE dt_req_total counter\n"
        'dt_req_total{model="m"} 5\n'
    )
    merged = merge_metrics([("0", e0), ("1", e1)])
    lines = merged.splitlines()
    # One header per family, samples from both children contiguous.
    assert lines.count("# TYPE dt_req_total counter") == 1
    i0 = lines.index('dt_req_total{fleet_worker_id="0",model="m"} 3')
    i1 = lines.index('dt_req_total{fleet_worker_id="1",model="m"} 5')
    itype = lines.index("# TYPE dt_req_total counter")
    assert itype < i0 < i1
    # Histogram child samples land under the dt_lat family header, not
    # as their own families.
    assert 'dt_lat_bucket{fleet_worker_id="0",le="+Inf"} 2' in lines
    assert "# TYPE dt_lat histogram" in lines
    assert lines.index("# TYPE dt_lat histogram") < lines.index(
        'dt_lat_sum{fleet_worker_id="0"} 0.5'
    )


def test_merge_ledgers_tags_and_flags():
    merged = merge_ledgers([
        ("0", {"enabled": False, "requests": [{"trace_id": "a"}]}),
        ("1", {"enabled": True, "requests": [{"trace_id": "b"}]}),
    ])
    assert merged["enabled"] is True
    assert {r["fleet_worker_id"] for r in merged["requests"]} == {"0", "1"}


def test_backoff_policy_is_jittered_exponential_and_capped():
    import random

    bp = BackoffPolicy(base=0.5, max_delay=4.0, rng=random.Random(7))
    d1 = [bp.delay(1) for _ in range(50)]
    d4 = [bp.delay(4) for _ in range(50)]
    assert all(0.25 <= d < 0.75 for d in d1)  # base * [0.5, 1.5)
    assert all(2.0 <= d < 6.0 for d in d4)    # capped at max_delay, then jitter
    assert len(set(d1)) > 1  # actually jittered


def test_strip_supervisor_flags():
    argv = ["--fleet", "4", "--fleet-admin-port", "9", "--port", "8080",
            "--store-url", "tcp://h:1", "--fleet-id", "f", "--router-mode", "kv"]
    assert strip_supervisor_flags(argv) == [
        "--store-url", "tcp://h:1", "--fleet-id", "f", "--router-mode", "kv",
    ]
    assert strip_supervisor_flags(["--fleet=4", "--port=0", "--host", "h"]) == [
        "--host", "h",
    ]
