"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform *before* jax is imported so
multi-chip sharding (TP/DP/SP meshes) is exercised without TPU hardware.
Real-TPU benchmarking lives in bench.py, not the test suite.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# The TPU-tunnel sitecustomize imports jax at interpreter startup, so the
# env vars above are too late for platform selection — override via config
# (still before any backend is initialized).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402

import pytest  # noqa: E402

from dynamo_tpu.runtime import store as store_mod  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_memory_stores():
    store_mod.reset_memory_stores()
    yield
    store_mod.reset_memory_stores()


@pytest.fixture
def anyio_backend():
    return "asyncio"


def run_async(coro):
    """Run a coroutine in a fresh event loop (test helper)."""
    return asyncio.run(coro)
