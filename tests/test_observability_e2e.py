"""Observability slice e2e: distributed span tracing, lifecycle ledger,
/debug endpoints, and the new metric series across a real messaging hop.

In-process fleets (mocker workers + frontend over real framed TCP) share
the process-global SpanRecorder, so these tests see the full
frontend→router→worker span nesting that a single-host deployment sees.
"""

import asyncio
import logging

import httpx
import pytest

from dynamo_tpu.kv_router.publisher import KvEventBroadcaster, serve_kv_endpoints
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_model
from dynamo_tpu.llm.pipeline import RouterSettings
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.mocker.engine import MockerArgs, MockerEngine
from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.chaos import ChaosConfig
from dynamo_tpu.runtime.config import Config
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.push_router import RouterMode

TRACEPARENT = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"
TRACE_ID = "0af7651916cd43dd8448eb211c80319c"


@pytest.fixture
def fresh_recorder():
    rec = tracing.SpanRecorder(capacity=4096, ledger_capacity=256)
    prev = tracing.set_recorder(rec)
    yield rec
    tracing.set_recorder(prev)


def fast_config(chaos: ChaosConfig | None = None) -> Config:
    cfg = Config.from_env({})
    cfg.runtime.retry_backoff_base = 0.005
    cfg.runtime.retry_backoff_max = 0.05
    cfg.runtime.circuit_cooldown = 0.2
    if chaos is not None:
        cfg.chaos = chaos
    return cfg


async def start_worker(store_url, namespace="obs", chaos=None, migration_limit=0,
                       mocker: MockerArgs | None = None):
    rt = await DistributedRuntime.create(store_url=store_url, config=fast_config(chaos))
    # delta_max_tokens=0: per-window frames. The chaos/migration assertions
    # need multi-frame streams (a mid-stream cut only exists between
    # frames); emit coalescing would ship a whole fast burst in one frame.
    engine = MockerEngine(
        mocker or MockerArgs(block_size=4, num_kv_blocks=256, speedup=1000.0,
                             delta_max_tokens=0)
    )
    broadcaster = KvEventBroadcaster(engine.pool)
    engine.pool.set_event_sink(broadcaster.publish)
    comp = rt.namespace(namespace).component("backend")

    async def gen_handler(payload, ctx):
        async for item in engine.generate(payload, ctx):
            yield item

    await comp.endpoint("generate").serve(gen_handler)
    await serve_kv_endpoints(comp, broadcaster, engine.metrics)
    card = ModelDeploymentCard(
        name="obs-model", kv_cache_block_size=4,
        eos_token_ids=[ByteTokenizer.EOS], context_length=512,
        migration_limit=migration_limit,
    )
    await register_model(rt, namespace, card)
    return rt, engine


async def start_frontend(store_url):
    rt = await DistributedRuntime.create(store_url=store_url, config=fast_config())
    manager = ModelManager(rt, RouterSettings(mode=RouterMode.ROUND_ROBIN))
    watcher = await ModelWatcher(rt, manager).start()
    http = await HttpService(
        manager, rt.metrics, health=rt.health, host="127.0.0.1", port=0
    ).start()
    return rt, manager, watcher, http


def body(text="observe me", max_tokens=8, **kw):
    out = {
        "model": "obs-model",
        "messages": [{"role": "user", "content": text}],
        "max_tokens": max_tokens,
    }
    out.update(kw)
    return out


async def wait_model(client, base):
    for _ in range(100):
        r = await client.get(f"{base}/v1/models")
        if r.json()["data"]:
            return
        await asyncio.sleep(0.05)
    raise AssertionError("model never appeared")


def span_index(trace_json):
    """Chrome-trace JSON → {span_id: event} for complete events."""
    return {
        e["args"]["span_id"]: e
        for e in trace_json["traceEvents"]
        if e["ph"] == "X"
    }


def ancestors(spans, event):
    """Names of the event's ancestor chain (nearest first)."""
    chain = []
    parent = event["args"]["parent_id"]
    while parent is not None and parent in spans:
        event = spans[parent]
        chain.append(event["name"])
        parent = event["args"]["parent_id"]
    return chain


def test_inbound_traceparent_to_worker_spans_ledger_and_flame(fresh_recorder):
    """A request with an inbound traceparent yields same-trace-id spans on
    both sides of a real messaging hop, a /debug/requests ledger entry with
    non-zero phases, and a /debug/traces flame whose spans nest
    frontend→router→worker."""

    captured = []

    class Capture(logging.Handler):
        def emit(self, record):
            captured.append(record)

    handler = Capture()
    logging.getLogger("dynamo_tpu.ledger").addHandler(handler)

    async def go():
        url = "memory://obs_trace"
        wrt, _eng = await start_worker(url)
        frt, manager, watcher, http = await start_frontend(url)
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=20) as client:
                await wait_model(client, base)
                r = await client.post(
                    f"{base}/v1/chat/completions", json=body(),
                    headers={"traceparent": TRACEPARENT},
                )
                assert r.status_code == 200

                # ledger entry via /debug/requests, filtered by trace id
                r = await client.get(
                    f"{base}/debug/requests", params={"trace_id": TRACE_ID}
                )
                assert r.status_code == 200
                records = r.json()["requests"]
                assert len(records) == 1, records
                rec = records[0]
                assert rec["trace_id"] == TRACE_ID
                assert rec["model"] == "obs-model"
                assert rec["status"] == "200"
                assert rec["completion_tokens"] == 8
                assert rec["ttft_s"] > 0
                for phase in ("admission_wait", "preprocess", "route", "wire",
                              "queue_wait", "prefill", "decode"):
                    assert rec["phases"].get(phase, 0) > 0, (phase, rec["phases"])

                # worker-side spans carry the inbound trace id (the hop is
                # real framed TCP — the id crossed the wire)
                names = {s.name for s in fresh_recorder.spans(TRACE_ID)}
                assert {"wire.serve", "engine.queue", "engine.prefill",
                        "engine.decode"} <= names, names

                # flame export nests frontend→router→worker
                r = await client.get(f"{base}/debug/traces/{TRACE_ID}")
                assert r.status_code == 200
                spans = span_index(r.json())
                decodes = [e for e in spans.values() if e["name"] == "engine.decode"]
                assert decodes, spans
                chain = ancestors(spans, decodes[0])
                assert chain[:4] == ["wire.serve", "wire.call", "router.attempt",
                                     "http.request"], chain
                assert decodes[0]["args"]["tokens"] == 8
                assert decodes[0]["dur"] > 0

                # unknown trace → 404
                r = await client.get(f"{base}/debug/traces/{'0' * 32}")
                assert r.status_code == 404

                # ledger also rode the logging layer with structured fields
                ledger_records = [
                    c for c in captured
                    if getattr(c, "event", None) == "request_ledger"
                    and getattr(c, "trace_id", None) == TRACE_ID
                ]
                assert ledger_records, "no ledger log line"
                assert ledger_records[0].phases["decode"] > 0
        finally:
            logging.getLogger("dynamo_tpu.ledger").removeHandler(handler)
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await wrt.shutdown()

    asyncio.run(asyncio.wait_for(go(), timeout=60))


def test_chaos_run_ledger_counts_retries_and_migrations(fresh_recorder):
    """Acceptance: a chaos-run request (mocker path) yields a ledger entry
    with non-zero phase durations and retry/migration counts, plus the new
    metric series in /metrics text exposition."""

    async def go():
        url = "memory://obs_chaos"
        # Frame drops cut the transport mid-stream (after payload flowed),
        # which is what forces Migration re-dispatch; truncation at the
        # final frame alone is absorbed by the over-delivery guard.
        chaos = ChaosConfig(enabled=True, seed=7, frame_drop_p=0.08, truncate_p=0.2)
        w1 = await start_worker(url, chaos=chaos, migration_limit=20)
        w2 = await start_worker(
            url, chaos=ChaosConfig(enabled=True, seed=8, frame_drop_p=0.08, truncate_p=0.2),
            migration_limit=20,
        )
        frt, manager, watcher, http = await start_frontend(url)
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=30) as client:
                await wait_model(client, base)
                migrated = None
                for _ in range(25):
                    r = await client.post(
                        f"{base}/v1/chat/completions", json=body(max_tokens=24),
                        headers={"X-Request-Timeout": "30"},
                    )
                    assert r.status_code == 200, r.text
                    r = await client.get(f"{base}/debug/requests", params={"limit": "1"})
                    rec = r.json()["requests"][0]
                    if rec["migrations"] > 0:
                        migrated = rec
                        break
                assert migrated is not None, "chaos never forced a migration in 25 runs"
                assert migrated["status"] == "200"
                assert migrated["completion_tokens"] == 24
                assert migrated["phases"]["decode"] > 0
                assert migrated["phases"]["prefill"] > 0

                # /metrics text exposition: phase histograms + admission series
                r = await client.get(f"{base}/metrics")
                text = r.text
                assert "dynamo_tpu_phase_duration_seconds_bucket" in text
                assert 'phase="http.request"' in text
                assert 'phase="router.attempt"' in text
                assert "dynamo_tpu_admission_queue_depth" in text
                assert "dynamo_tpu_admission_wait_seconds_bucket" in text
                assert "dynamo_tpu_http_requests_total" in text

                # worker registries: engine phases + chaos injections
                wtext = w1[0].metrics.render() + w2[0].metrics.render()
                assert 'phase="engine.decode"' in wtext
                assert "dynamo_tpu_chaos_injections_total" in wtext
                assert 'kind="frame_drop"' in wtext or 'kind="truncate"' in wtext
        finally:
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await w1[0].shutdown()
            await w2[0].shutdown()

    asyncio.run(asyncio.wait_for(go(), timeout=120))


def test_deadline_breaker_retry_series_and_shed_ledger(fresh_recorder):
    """deadline_expired_total / router_retries_total / circuit_breaker_state
    appear once their paths fire; shed requests get ledger entries too."""
    from dynamo_tpu.runtime.admission import AdmissionController

    async def go():
        url = "memory://obs_series"
        wrt, _eng = await start_worker(
            url, mocker=MockerArgs(block_size=4, num_kv_blocks=256, itl_ms=50.0)
        )
        frt = await DistributedRuntime.create(store_url=url, config=fast_config())
        manager = ModelManager(frt, RouterSettings(mode=RouterMode.ROUND_ROBIN))
        watcher = await ModelWatcher(frt, manager).start()
        http = await HttpService(
            manager, frt.metrics, health=frt.health, host="127.0.0.1", port=0,
            admission=AdmissionController(max_inflight=1, retry_after=1.0),
        ).start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=30) as client:
                await wait_model(client, base)

                # deadline → 504 + counter
                r = await client.post(
                    f"{base}/v1/chat/completions", json=body(max_tokens=100),
                    headers={"X-Request-Timeout": "0.3"},
                )
                assert r.status_code == 504
                text = (await client.get(f"{base}/metrics")).text
                assert 'dynamo_tpu_deadline_expired_total{' in text
                assert 'scope="http"' in text

                # shed → 429 with its own ledger record
                slow = asyncio.ensure_future(client.post(
                    f"{base}/v1/chat/completions", json=body(max_tokens=30)
                ))
                while http.admission.inflight == 0:
                    await asyncio.sleep(0.01)
                r = await client.post(f"{base}/v1/chat/completions", json=body())
                assert r.status_code == 429
                await slow
                r = await client.get(f"{base}/debug/requests", params={"limit": "10"})
                statuses = [rec["status"] for rec in r.json()["requests"]]
                assert "429" in statuses, statuses

                # breaker: mark the instance down → gauge series appears
                pipe = manager.get("obs-model")
                disc = pipe.discovery
                iid = disc.instances()[0].instance_id
                disc.report_instance_down(iid)
                text = frt.metrics.render()
                assert "dynamo_tpu_circuit_breaker_state" in text
                assert f'instance="{iid:x}"' in text
                disc.report_instance_up(iid)
                assert 'dynamo_tpu_circuit_breaker_state{' in frt.metrics.render()
        finally:
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await wrt.shutdown()

    asyncio.run(asyncio.wait_for(go(), timeout=60))


def test_debug_endpoints_when_tracing_disabled():
    prev = tracing.set_recorder(None)

    async def go():
        url = "memory://obs_off"
        wrt, _eng = await start_worker(url)
        frt, manager, watcher, http = await start_frontend(url)
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=20) as client:
                await wait_model(client, base)
                # serving still works with the no-op fast path
                r = await client.post(f"{base}/v1/chat/completions", json=body())
                assert r.status_code == 200
                r = await client.get(f"{base}/debug/requests")
                assert r.json() == {"enabled": False, "requests": []}
                r = await client.get(f"{base}/debug/traces/{'0' * 32}")
                assert r.status_code == 404
        finally:
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await wrt.shutdown()

    try:
        asyncio.run(asyncio.wait_for(go(), timeout=60))
    finally:
        tracing.set_recorder(prev)


# ---------------------------------------------------------------------------
# Fleet stitching: store-backed span export, the pure merge, and the
# supervisor's /debug/fleet/traces endpoint (PR 17).
# ---------------------------------------------------------------------------


def _span_dict(span_id, parent_id, name, proc, start_ts, trace_id=TRACE_ID):
    return {
        "name": name, "trace_id": trace_id, "span_id": span_id,
        "parent_id": parent_id, "start_ts": start_ts, "duration_s": 0.01,
        "status": "ok", "proc": proc, "attrs": {}, "events": [],
    }


def test_merge_traces_relabels_dedups_and_renders_byte_stable():
    """The pure fleet stitch (fleet/aggregate.py): scraped child bodies get
    the metrics-merge relabel convention (``<worker_id>/<lane>``),
    store-exported spans keep their own lane, duplicates collapse by
    span_id, and repeated assembly of the same fragment set is
    byte-identical."""
    import json

    from dynamo_tpu.fleet.aggregate import merge_traces

    root = _span_dict("aaaa", None, "http.request", "frontend-0", 1.0)
    child = _span_dict("bbbb", "aaaa", "wire.serve", "decode-1", 1.002)
    # The same worker span arrives twice: scraped from child 1 AND via the
    # store export (its own lane). Exactly one survives.
    exported_child = dict(child)
    exported_only = _span_dict("cccc", "bbbb", "engine.decode", "decode-1", 1.004)
    parts = [("0", {"spans": [root]}), ("1", {"spans": [child]})]
    merged = merge_traces(TRACE_ID, parts,
                          extra_spans=[exported_child, exported_only])

    by_id = {d["span_id"]: d for d in merged["spans"]}
    assert len(by_id) == 3
    assert by_id["aaaa"]["proc"] == "0/frontend-0"  # scraped → relabeled
    assert by_id["bbbb"]["proc"] == "1/decode-1"    # scrape wins the dedup
    assert by_id["cccc"]["proc"] == "decode-1"      # export keeps its lane
    lanes = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert lanes == {"0/frontend-0", "1/decode-1", "decode-1"}

    again = merge_traces(TRACE_ID, parts,
                         extra_spans=[exported_only, exported_child])
    assert json.dumps(merged, sort_keys=True) == json.dumps(again, sort_keys=True)

    # Bodies without a spans list (older children) reconstruct from the
    # Chrome "X" events — the merge accepts its own output as a part.
    legacy = {k: v for k, v in merged.items() if k != "spans"}
    relegacy = merge_traces(TRACE_ID, [("2", legacy)])
    assert {d["span_id"] for d in relegacy["spans"]} == set(by_id)


def test_trace_exporter_roundtrip_is_bounded_batched_and_lease_scoped(fresh_recorder):
    """TraceExporter ships finished spans to ``fleet/<id>/trace/…`` keys a
    prefix scan reassembles; every key rides the exporter's lease so a dead
    process's fragments age out with it."""
    from dynamo_tpu.runtime.logging import TraceContext
    from dynamo_tpu.runtime.store import connect_store
    from dynamo_tpu.runtime.trace_export import (
        TraceExporter,
        load_fleet_trace,
        trace_prefix,
    )

    async def go():
        store = await connect_store("memory://obs_export")
        exporter = await TraceExporter(
            store, "f1", recorder=fresh_recorder, lane="w0", interval_s=30.0
        ).start()
        trace = TraceContext.parse(TRACEPARENT)
        with tracing.start_span("wire.serve", parent=trace) as outer:
            with tracing.start_span("engine.decode",
                                    parent=outer.trace_context()):
                pass
        assert await exporter.flush() == 2

        entries = await store.get_prefix(trace_prefix("f1"))
        assert [e.key for e in entries] == [
            f"fleet/f1/trace/{TRACE_ID}/w0/00000001"
        ]
        spans = await load_fleet_trace(store, "f1", TRACE_ID)
        assert {d["name"] for d in spans} == {"wire.serve", "engine.decode"}
        assert all(d["trace_id"] == TRACE_ID for d in spans)
        assert await load_fleet_trace(store, "f1", "0" * 32) == []

        # close() revokes the lease → the fragments die with the process.
        await exporter.close()
        assert await load_fleet_trace(store, "f1", TRACE_ID) == []
        await store.close()

    asyncio.run(asyncio.wait_for(go(), timeout=30))


def test_chaos_injection_stamps_victim_trace_into_ledger(fresh_recorder):
    """A chaos fault that fires inside a traced request lands the injection
    kind in that request's ledger record (``chaos_injections``)."""
    from dynamo_tpu.runtime.chaos import ChaosInjector
    from dynamo_tpu.runtime.logging import (
        TraceContext,
        reset_current_trace,
        set_current_trace,
    )

    inj = ChaosInjector(ChaosConfig(enabled=True, seed=3, truncate_p=1.0))
    inj.bind_metrics(__import__("dynamo_tpu.runtime.metrics",
                                fromlist=["MetricsRegistry"]).MetricsRegistry())
    token = set_current_trace(TraceContext.parse(TRACEPARENT))
    try:
        assert inj.should_truncate()
    finally:
        reset_current_trace(token)
    assert fresh_recorder.injections(TRACE_ID) == ["truncate"]

    rec = tracing.build_ledger(
        TRACE_ID, request_id="r1", model="m", endpoint="chat",
        status="200", duration_s=0.5, spans=[],
    )
    assert rec["chaos_injections"] == ["truncate"]


def test_fleet_stitched_trace_for_remote_prefill_plus_live_migration(fresh_recorder):
    """PR 17 acceptance: ONE trace id for a request that prefills remotely
    (disagg) and is then live-migrated between decode engines yields a
    single connected cross-process span tree with a lane per process
    (frontend, source decode, destination decode, prefill — ≥4), served
    byte-stable from the supervisor's ``/debug/fleet/traces`` endpoint via
    BOTH stitch paths (store export and per-child scrape), with the ledger
    record's phase durations decomposing wall TTFT / E2E within tolerance.

    Real TpuEngines on CPU (the mocker has no migration cutover); each
    DistributedRuntime gets its own ``proc_label`` so the in-process fleet
    records the same lanes a multi-process deployment would."""
    import json
    import time

    from aiohttp import ClientSession, ClientTimeout

    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.fleet.supervisor import FleetSupervisor, frontends_prefix
    from dynamo_tpu.llm.disagg import (
        DisaggConfig,
        DisaggDecodeHandler,
        PrefillHandler,
    )
    from dynamo_tpu.runtime.engine import Context
    from dynamo_tpu.runtime.store import connect_store
    from dynamo_tpu.runtime.trace_export import TraceExporter
    from dynamo_tpu.worker.migrate import (
        MigrationCoordinator,
        MigrationReceiver,
        register_migration_metrics,
    )

    NS = "obsfleet"
    FLEET = "obsfleet"
    url = "memory://obs_fleet_stitch"

    def engine_args():
        return EngineArgs(
            model=ModelConfig(), block_size=4, num_kv_blocks=128,
            max_num_seqs=4, max_model_len=256, max_prefill_tokens=128,
            dtype="float32", decode_steps=4,
        )

    class DecodeWorker:
        def __init__(self, rt, engine, disagg, receiver, coordinator, iid):
            self.rt = rt
            self.engine = engine
            self.disagg = disagg
            self.receiver = receiver
            self.coordinator = coordinator
            self.instance_id = iid

        async def stop(self):
            await self.receiver.close()
            await self.engine.stop()
            await self.rt.shutdown()

    async def start_decode(label):
        rt = await DistributedRuntime.create(
            store_url=url, config=fast_config(), proc_label=label
        )
        engine = await TpuEngine(engine_args(), seed=0).start()
        metrics = register_migration_metrics(rt.metrics)
        receiver = MigrationReceiver(rt, NS, metrics=metrics)
        pcomp = rt.namespace(NS).component("prefill")
        disagg = DisaggDecodeHandler(
            engine,
            await pcomp.endpoint("generate").router(RouterMode.ROUND_ROBIN),
            await pcomp.endpoint("kv_fetch").router(RouterMode.DIRECT),
            DisaggConfig(max_local_prefill_length=8),
        )
        comp = rt.namespace(NS).component("backend")

        async def gen_handler(payload, ctx):
            if isinstance(payload, dict):
                mr = (payload.get("kv_transfer_params") or {}).get(
                    "migration_resume")
                if isinstance(mr, dict) and mr.get("handle"):
                    staged = receiver.take(mr["handle"])
                    if staged is not None:
                        payload = dict(payload)
                        ktp = dict(payload.get("kv_transfer_params") or {})
                        ktp["inject"] = staged
                        payload["kv_transfer_params"] = ktp
                    # Resume leg: the KV just arrived via migration — no
                    # disagg detour for the carried prompt.
                    async for item in engine.generate(payload, ctx):
                        yield item
                    return
            async for item in disagg.generate(payload, ctx):
                yield item

        gh = await comp.endpoint("generate").serve(gen_handler)
        await comp.endpoint("kv_fetch").serve(PrefillHandler(engine).kv_fetch)

        acomp = rt.namespace(NS).component("workerctl")
        coordinator = MigrationCoordinator(
            engine,
            await acomp.endpoint("admin").router(RouterMode.DIRECT),
            "backend", gh.instance.instance_id, metrics=metrics,
        )

        async def admin(payload, ctx):
            # The roles.py admin verbs this test needs — including the
            # traceparent forward on migrate_in_start that stitches the
            # destination's KV pull into the migrating request's trace.
            payload = payload or {}
            cmd = payload.get("cmd")
            try:
                if cmd == "migrate_out":
                    yield await coordinator.migrate_out(
                        payload.get("request_id", ""),
                        int(payload.get("dest_instance") or 0),
                    )
                elif cmd == "migrate_in_start":
                    yield await receiver.start_pull(
                        payload.get("handle", ""),
                        payload.get("source_component", ""),
                        int(payload.get("source_instance") or 0),
                        traceparent=payload.get("traceparent"),
                    )
                elif cmd == "migrate_in_commit":
                    yield await receiver.commit(
                        payload.get("handle", ""),
                        int(payload.get("kv_blocks") or 0),
                    )
                elif cmd == "migrate_in_abort":
                    yield await receiver.abort(payload.get("handle", ""))
                else:
                    yield {"error": f"unknown admin cmd {cmd!r}"}
            except Exception as e:  # noqa: BLE001 — shim answers typed like roles.py
                yield {"error": f"{type(e).__name__}: {e}"}

        await acomp.endpoint("admin").serve(admin)
        return DecodeWorker(rt, engine, disagg, receiver, coordinator,
                            gh.instance.instance_id)

    async def go():
        w1 = await start_decode("decode-1")
        w2 = await start_decode("decode-2")

        prt = await DistributedRuntime.create(
            store_url=url, config=fast_config(), proc_label="prefill-0"
        )
        pengine = await TpuEngine(engine_args(), seed=0).start()
        ph = PrefillHandler(pengine)
        pcomp = prt.namespace(NS).component("prefill")
        await pcomp.endpoint("generate").serve(ph.generate)
        await pcomp.endpoint("kv_fetch").serve(ph.kv_fetch)

        frt = await DistributedRuntime.create(
            store_url=url, config=fast_config(), proc_label="frontend-0"
        )
        manager = ModelManager(frt, RouterSettings(mode=RouterMode.ROUND_ROBIN))
        watcher = await ModelWatcher(frt, manager).start()
        http = await HttpService(
            manager, frt.metrics, health=frt.health, host="127.0.0.1",
            port=0, proc_label="frontend-0",
        ).start()
        base = f"http://127.0.0.1:{http.port}"

        card = ModelDeploymentCard(
            name="fleet-model", kv_cache_block_size=4,
            eos_token_ids=[ByteTokenizer.EOS], context_length=256,
            migration_limit=3,
        )
        await register_model(frt, NS, card)

        # Store-backed export off the shared recorder: the push half of
        # the supervisor's stitch.
        store = await connect_store(url)
        exporter = await TraceExporter(
            store, FLEET, recorder=fresh_recorder, lane="export",
            interval_s=0.1, max_buffer=8192,
        ).start()

        admin = await frt.namespace(NS).component("workerctl") \
            .endpoint("admin").router(RouterMode.DIRECT)

        async def migrate_running():
            for w, other in ((w1, w2), (w2, w1)):
                running = w.engine.list_running()
                if running:
                    last = {}
                    async for frame in admin.generate(
                        {"cmd": "migrate_out", "request_id": running[0],
                         "dest_instance": other.instance_id},
                        Context(), instance_id=w.instance_id,
                    ):
                        if isinstance(frame, dict):
                            last = frame
                    return last
            return None

        async def one_request(client, attempt):
            """Stream one chat completion; fire migrate_out mid-stream.
            → (trace_id, migrate reply, wall ttft, wall e2e)."""
            tid = f"{0xfeedc0de + attempt:032x}"
            tp = f"00-{tid}-b7ad6b7169203331-01"
            # Fresh prompt text per attempt: a repeated prompt would
            # prefix-hit the decode engine and skip the remote prefill
            # this test must observe.
            text = f"stitch across the fleet please, attempt {attempt}"
            reply = None
            t0 = time.perf_counter()
            t_first = None
            chunks = 0
            async with client.stream(
                "POST", f"{base}/v1/chat/completions",
                json=body(text=text, max_tokens=48, model="fleet-model",
                          stream=True),
                headers={"traceparent": tp},
            ) as resp:
                assert resp.status_code == 200
                async for line in resp.aiter_lines():
                    if not line.startswith("data: ") or "[DONE]" in line:
                        continue
                    if t_first is None:
                        t_first = time.perf_counter()
                    chunks += 1
                    if reply is None and chunks >= 2:
                        reply = await migrate_running()
            assert t_first is not None and chunks > 2
            return tid, reply, t_first - t0, time.perf_counter() - t0

        sup = None
        try:
            async with httpx.AsyncClient(timeout=60) as client:
                for _ in range(200):
                    r = await client.get(f"{base}/v1/models")
                    if r.json()["data"]:
                        break
                    await asyncio.sleep(0.05)

                # The engines race the migrate trigger; retry with a fresh
                # trace id until a migration actually lands (CI timing).
                tid = reply = None
                for attempt in range(4):
                    tid, reply, wall_ttft, wall_e2e = await one_request(
                        client, attempt)
                    if reply is not None and reply.get("ok"):
                        break
                assert reply is not None and reply.get("ok"), reply
                assert (w1.disagg.remote_prefills
                        + w2.disagg.remote_prefills) >= 1

                # -- one CONNECTED span tree, ≥4 process lanes ------------
                spans = fresh_recorder.spans(tid)
                idx = {s.span_id: s for s in spans}
                roots = [s for s in spans if s.parent_id not in idx]
                assert len(roots) == 1, [(s.name, s.proc) for s in roots]
                assert roots[0].name == "http.request"
                assert roots[0].parent_id == "b7ad6b7169203331"  # inbound
                lanes = {s.proc for s in spans}
                assert {"frontend-0", "decode-1", "decode-2",
                        "prefill-0"} <= lanes, lanes
                names = {s.name for s in spans}
                assert {"disagg.remote_prefill", "transfer.kv_pull",
                        "migration.out", "migration.resume",
                        "engine.prefill", "engine.decode"} <= names, names
                # The migration KV pull is distinguishable from the disagg
                # one and runs on the DESTINATION lane.
                mig_pulls = [s for s in spans if s.name == "transfer.kv_pull"
                             and s.attrs.get("kind") == "migration"]
                assert mig_pulls and all(
                    s.proc in ("decode-1", "decode-2") for s in mig_pulls)

                # -- ledger v2: phases decompose wall TTFT / E2E ----------
                r = await client.get(f"{base}/debug/requests",
                                     params={"trace_id": tid})
                recs = r.json()["requests"]
                assert len(recs) == 1
                rec = recs[0]
                assert rec["schema"] == 2
                ph = rec["phases"]
                for key in ("remote_prefill", "transfer", "decode",
                            "migration_freeze"):
                    assert ph.get(key, 0) > 0, (key, ph)
                # TTFT-side serial phases (the disagg window covers the
                # remote prefill dispatch + pull + inject; route is NOT in
                # this set — router.attempt wraps the whole streamed leg)
                # stay bounded by the wall TTFT; generous slack for CPU
                # scheduling noise.
                ttft_side = sum(ph.get(k, 0) for k in
                                ("admission_wait", "preprocess",
                                 "remote_prefill"))
                assert rec["ttft_s"] <= wall_ttft + 0.05
                assert 0.2 * rec["ttft_s"] < ttft_side <= 1.2 * rec["ttft_s"] + 0.25, \
                    (ttft_side, rec["ttft_s"], ph)
                # Decode-budget phases (decode legs + the client-visible
                # freeze gap) account for the post-TTFT window.
                stream_wall = rec["duration_s"] - rec["ttft_s"]
                decode_side = ph["decode"] + ph["migration_freeze"] \
                    + ph.get("redispatch", 0)
                assert 0.3 * stream_wall < decode_side <= 2.0 * stream_wall + 0.25, \
                    (decode_side, stream_wall, ph)
                assert rec["duration_s"] <= wall_e2e + 0.05

                # -- the supervisor endpoint, both stitch paths ----------
                await exporter.flush()
                sup = FleetSupervisor(
                    1, [], "127.0.0.1", 0, fleet_id=FLEET,
                    store_url="tcp://unused:1",
                )
                sup._store = store
                sup._http = ClientSession(timeout=ClientTimeout(total=5.0))
                await sup._start_admin()
                sup_base = f"http://127.0.0.1:{sup.admin_port}"

                # (a) store-export path alone: no children registered yet.
                r = await client.get(f"{sup_base}/debug/fleet/traces/{tid}")
                assert r.status_code == 200
                exported_lanes = {
                    e["args"]["name"] for e in r.json()["traceEvents"]
                    if e.get("ph") == "M" and e.get("name") == "process_name"
                }
                assert {"frontend-0", "decode-1", "decode-2",
                        "prefill-0"} <= exported_lanes, exported_lanes

                # (b) register the frontend as fleet child 0 → the scrape
                # path joins; lanes adopt the <worker_id>/<lane> relabel
                # convention and the body pins byte-stable across GETs.
                await store.put(
                    frontends_prefix(FLEET) + "0",
                    json.dumps({"pid": 0, "admin": base}).encode(),
                )
                r1 = await client.get(f"{sup_base}/debug/fleet/traces/{tid}")
                r2 = await client.get(f"{sup_base}/debug/fleet/traces/{tid}")
                assert r1.status_code == r2.status_code == 200
                assert r1.content == r2.content  # byte-stable
                merged = r1.json()
                merged_lanes = {
                    e["args"]["name"] for e in merged["traceEvents"]
                    if e.get("ph") == "M" and e.get("name") == "process_name"
                }
                assert {"0/frontend-0", "0/decode-1", "0/decode-2",
                        "0/prefill-0"} <= merged_lanes, merged_lanes
                # Complete: every span the recorder holds for this trace
                # made it into the assembled body exactly once.
                assert {d["span_id"] for d in merged["spans"]} == set(idx)

                # unknown trace → 404 from the fleet endpoint too
                r = await client.get(
                    f"{sup_base}/debug/fleet/traces/{'0' * 32}")
                assert r.status_code == 404
        finally:
            if sup is not None:
                if sup._runner is not None:
                    await sup._runner.cleanup()
                await sup._http.close()
            await exporter.close()
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await prt.shutdown()
            await pengine.stop()
            await w1.stop()
            await w2.stop()

    asyncio.run(asyncio.wait_for(go(), timeout=300))
