"""Observability slice e2e: distributed span tracing, lifecycle ledger,
/debug endpoints, and the new metric series across a real messaging hop.

In-process fleets (mocker workers + frontend over real framed TCP) share
the process-global SpanRecorder, so these tests see the full
frontend→router→worker span nesting that a single-host deployment sees.
"""

import asyncio
import logging

import httpx
import pytest

from dynamo_tpu.kv_router.publisher import KvEventBroadcaster, serve_kv_endpoints
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_model
from dynamo_tpu.llm.pipeline import RouterSettings
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.mocker.engine import MockerArgs, MockerEngine
from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.chaos import ChaosConfig
from dynamo_tpu.runtime.config import Config
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.push_router import RouterMode

TRACEPARENT = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"
TRACE_ID = "0af7651916cd43dd8448eb211c80319c"


@pytest.fixture
def fresh_recorder():
    rec = tracing.SpanRecorder(capacity=4096, ledger_capacity=256)
    prev = tracing.set_recorder(rec)
    yield rec
    tracing.set_recorder(prev)


def fast_config(chaos: ChaosConfig | None = None) -> Config:
    cfg = Config.from_env({})
    cfg.runtime.retry_backoff_base = 0.005
    cfg.runtime.retry_backoff_max = 0.05
    cfg.runtime.circuit_cooldown = 0.2
    if chaos is not None:
        cfg.chaos = chaos
    return cfg


async def start_worker(store_url, namespace="obs", chaos=None, migration_limit=0,
                       mocker: MockerArgs | None = None):
    rt = await DistributedRuntime.create(store_url=store_url, config=fast_config(chaos))
    # delta_max_tokens=0: per-window frames. The chaos/migration assertions
    # need multi-frame streams (a mid-stream cut only exists between
    # frames); emit coalescing would ship a whole fast burst in one frame.
    engine = MockerEngine(
        mocker or MockerArgs(block_size=4, num_kv_blocks=256, speedup=1000.0,
                             delta_max_tokens=0)
    )
    broadcaster = KvEventBroadcaster(engine.pool)
    engine.pool.set_event_sink(broadcaster.publish)
    comp = rt.namespace(namespace).component("backend")

    async def gen_handler(payload, ctx):
        async for item in engine.generate(payload, ctx):
            yield item

    await comp.endpoint("generate").serve(gen_handler)
    await serve_kv_endpoints(comp, broadcaster, engine.metrics)
    card = ModelDeploymentCard(
        name="obs-model", kv_cache_block_size=4,
        eos_token_ids=[ByteTokenizer.EOS], context_length=512,
        migration_limit=migration_limit,
    )
    await register_model(rt, namespace, card)
    return rt, engine


async def start_frontend(store_url):
    rt = await DistributedRuntime.create(store_url=store_url, config=fast_config())
    manager = ModelManager(rt, RouterSettings(mode=RouterMode.ROUND_ROBIN))
    watcher = await ModelWatcher(rt, manager).start()
    http = await HttpService(
        manager, rt.metrics, health=rt.health, host="127.0.0.1", port=0
    ).start()
    return rt, manager, watcher, http


def body(text="observe me", max_tokens=8, **kw):
    out = {
        "model": "obs-model",
        "messages": [{"role": "user", "content": text}],
        "max_tokens": max_tokens,
    }
    out.update(kw)
    return out


async def wait_model(client, base):
    for _ in range(100):
        r = await client.get(f"{base}/v1/models")
        if r.json()["data"]:
            return
        await asyncio.sleep(0.05)
    raise AssertionError("model never appeared")


def span_index(trace_json):
    """Chrome-trace JSON → {span_id: event} for complete events."""
    return {
        e["args"]["span_id"]: e
        for e in trace_json["traceEvents"]
        if e["ph"] == "X"
    }


def ancestors(spans, event):
    """Names of the event's ancestor chain (nearest first)."""
    chain = []
    parent = event["args"]["parent_id"]
    while parent is not None and parent in spans:
        event = spans[parent]
        chain.append(event["name"])
        parent = event["args"]["parent_id"]
    return chain


def test_inbound_traceparent_to_worker_spans_ledger_and_flame(fresh_recorder):
    """A request with an inbound traceparent yields same-trace-id spans on
    both sides of a real messaging hop, a /debug/requests ledger entry with
    non-zero phases, and a /debug/traces flame whose spans nest
    frontend→router→worker."""

    captured = []

    class Capture(logging.Handler):
        def emit(self, record):
            captured.append(record)

    handler = Capture()
    logging.getLogger("dynamo_tpu.ledger").addHandler(handler)

    async def go():
        url = "memory://obs_trace"
        wrt, _eng = await start_worker(url)
        frt, manager, watcher, http = await start_frontend(url)
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=20) as client:
                await wait_model(client, base)
                r = await client.post(
                    f"{base}/v1/chat/completions", json=body(),
                    headers={"traceparent": TRACEPARENT},
                )
                assert r.status_code == 200

                # ledger entry via /debug/requests, filtered by trace id
                r = await client.get(
                    f"{base}/debug/requests", params={"trace_id": TRACE_ID}
                )
                assert r.status_code == 200
                records = r.json()["requests"]
                assert len(records) == 1, records
                rec = records[0]
                assert rec["trace_id"] == TRACE_ID
                assert rec["model"] == "obs-model"
                assert rec["status"] == "200"
                assert rec["completion_tokens"] == 8
                assert rec["ttft_s"] > 0
                for phase in ("admission_wait", "preprocess", "route", "wire",
                              "queue_wait", "prefill", "decode"):
                    assert rec["phases"].get(phase, 0) > 0, (phase, rec["phases"])

                # worker-side spans carry the inbound trace id (the hop is
                # real framed TCP — the id crossed the wire)
                names = {s.name for s in fresh_recorder.spans(TRACE_ID)}
                assert {"wire.serve", "engine.queue", "engine.prefill",
                        "engine.decode"} <= names, names

                # flame export nests frontend→router→worker
                r = await client.get(f"{base}/debug/traces/{TRACE_ID}")
                assert r.status_code == 200
                spans = span_index(r.json())
                decodes = [e for e in spans.values() if e["name"] == "engine.decode"]
                assert decodes, spans
                chain = ancestors(spans, decodes[0])
                assert chain[:4] == ["wire.serve", "wire.call", "router.attempt",
                                     "http.request"], chain
                assert decodes[0]["args"]["tokens"] == 8
                assert decodes[0]["dur"] > 0

                # unknown trace → 404
                r = await client.get(f"{base}/debug/traces/{'0' * 32}")
                assert r.status_code == 404

                # ledger also rode the logging layer with structured fields
                ledger_records = [
                    c for c in captured
                    if getattr(c, "event", None) == "request_ledger"
                    and getattr(c, "trace_id", None) == TRACE_ID
                ]
                assert ledger_records, "no ledger log line"
                assert ledger_records[0].phases["decode"] > 0
        finally:
            logging.getLogger("dynamo_tpu.ledger").removeHandler(handler)
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await wrt.shutdown()

    asyncio.run(asyncio.wait_for(go(), timeout=60))


def test_chaos_run_ledger_counts_retries_and_migrations(fresh_recorder):
    """Acceptance: a chaos-run request (mocker path) yields a ledger entry
    with non-zero phase durations and retry/migration counts, plus the new
    metric series in /metrics text exposition."""

    async def go():
        url = "memory://obs_chaos"
        # Frame drops cut the transport mid-stream (after payload flowed),
        # which is what forces Migration re-dispatch; truncation at the
        # final frame alone is absorbed by the over-delivery guard.
        chaos = ChaosConfig(enabled=True, seed=7, frame_drop_p=0.08, truncate_p=0.2)
        w1 = await start_worker(url, chaos=chaos, migration_limit=20)
        w2 = await start_worker(
            url, chaos=ChaosConfig(enabled=True, seed=8, frame_drop_p=0.08, truncate_p=0.2),
            migration_limit=20,
        )
        frt, manager, watcher, http = await start_frontend(url)
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=30) as client:
                await wait_model(client, base)
                migrated = None
                for _ in range(25):
                    r = await client.post(
                        f"{base}/v1/chat/completions", json=body(max_tokens=24),
                        headers={"X-Request-Timeout": "30"},
                    )
                    assert r.status_code == 200, r.text
                    r = await client.get(f"{base}/debug/requests", params={"limit": "1"})
                    rec = r.json()["requests"][0]
                    if rec["migrations"] > 0:
                        migrated = rec
                        break
                assert migrated is not None, "chaos never forced a migration in 25 runs"
                assert migrated["status"] == "200"
                assert migrated["completion_tokens"] == 24
                assert migrated["phases"]["decode"] > 0
                assert migrated["phases"]["prefill"] > 0

                # /metrics text exposition: phase histograms + admission series
                r = await client.get(f"{base}/metrics")
                text = r.text
                assert "dynamo_tpu_phase_duration_seconds_bucket" in text
                assert 'phase="http.request"' in text
                assert 'phase="router.attempt"' in text
                assert "dynamo_tpu_admission_queue_depth" in text
                assert "dynamo_tpu_admission_wait_seconds_bucket" in text
                assert "dynamo_tpu_http_requests_total" in text

                # worker registries: engine phases + chaos injections
                wtext = w1[0].metrics.render() + w2[0].metrics.render()
                assert 'phase="engine.decode"' in wtext
                assert "dynamo_tpu_chaos_injections_total" in wtext
                assert 'kind="frame_drop"' in wtext or 'kind="truncate"' in wtext
        finally:
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await w1[0].shutdown()
            await w2[0].shutdown()

    asyncio.run(asyncio.wait_for(go(), timeout=120))


def test_deadline_breaker_retry_series_and_shed_ledger(fresh_recorder):
    """deadline_expired_total / router_retries_total / circuit_breaker_state
    appear once their paths fire; shed requests get ledger entries too."""
    from dynamo_tpu.runtime.admission import AdmissionController

    async def go():
        url = "memory://obs_series"
        wrt, _eng = await start_worker(
            url, mocker=MockerArgs(block_size=4, num_kv_blocks=256, itl_ms=50.0)
        )
        frt = await DistributedRuntime.create(store_url=url, config=fast_config())
        manager = ModelManager(frt, RouterSettings(mode=RouterMode.ROUND_ROBIN))
        watcher = await ModelWatcher(frt, manager).start()
        http = await HttpService(
            manager, frt.metrics, health=frt.health, host="127.0.0.1", port=0,
            admission=AdmissionController(max_inflight=1, retry_after=1.0),
        ).start()
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=30) as client:
                await wait_model(client, base)

                # deadline → 504 + counter
                r = await client.post(
                    f"{base}/v1/chat/completions", json=body(max_tokens=100),
                    headers={"X-Request-Timeout": "0.3"},
                )
                assert r.status_code == 504
                text = (await client.get(f"{base}/metrics")).text
                assert 'dynamo_tpu_deadline_expired_total{' in text
                assert 'scope="http"' in text

                # shed → 429 with its own ledger record
                slow = asyncio.ensure_future(client.post(
                    f"{base}/v1/chat/completions", json=body(max_tokens=30)
                ))
                while http.admission.inflight == 0:
                    await asyncio.sleep(0.01)
                r = await client.post(f"{base}/v1/chat/completions", json=body())
                assert r.status_code == 429
                await slow
                r = await client.get(f"{base}/debug/requests", params={"limit": "10"})
                statuses = [rec["status"] for rec in r.json()["requests"]]
                assert "429" in statuses, statuses

                # breaker: mark the instance down → gauge series appears
                pipe = manager.get("obs-model")
                disc = pipe.discovery
                iid = disc.instances()[0].instance_id
                disc.report_instance_down(iid)
                text = frt.metrics.render()
                assert "dynamo_tpu_circuit_breaker_state" in text
                assert f'instance="{iid:x}"' in text
                disc.report_instance_up(iid)
                assert 'dynamo_tpu_circuit_breaker_state{' in frt.metrics.render()
        finally:
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await wrt.shutdown()

    asyncio.run(asyncio.wait_for(go(), timeout=60))


def test_debug_endpoints_when_tracing_disabled():
    prev = tracing.set_recorder(None)

    async def go():
        url = "memory://obs_off"
        wrt, _eng = await start_worker(url)
        frt, manager, watcher, http = await start_frontend(url)
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=20) as client:
                await wait_model(client, base)
                # serving still works with the no-op fast path
                r = await client.post(f"{base}/v1/chat/completions", json=body())
                assert r.status_code == 200
                r = await client.get(f"{base}/debug/requests")
                assert r.json() == {"enabled": False, "requests": []}
                r = await client.get(f"{base}/debug/traces/{'0' * 32}")
                assert r.status_code == 404
        finally:
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await wrt.shutdown()

    try:
        asyncio.run(asyncio.wait_for(go(), timeout=60))
    finally:
        tracing.set_recorder(prev)
