"""Golden-equivalence suite for speculative decoding.

The speculative path (n-gram prompt-lookup drafts + single-pass batched
verification, engine/drafter.py + model.spec_verify) may change HOW
tokens are produced but never WHAT is produced at greedy: for any
workload, spec-on streams (tokens, logprobs, top_logprobs, finish
reasons) must be byte-identical to the dense path across draft lengths,
pipeline depths, stops landing mid-draft, max_tokens boundaries inside
an accepted run, and preemption during an in-flight verify. Sampled
rows keep their exact output distribution (rejection sampling); rows
that never draft ride the dense RNG stream, so they too are
byte-identical. CPU, test-tiny model, every request explicitly seeded
(PR 4 lesson: unseeded requests perturb the global RNG stream and flip
downstream sampling-dependent tests).

Stop STRINGS are a backend concern (jail scan over decoded text); the
engine-level stop is the eos token id, exercised here mid-draft — the
backend sees the same truncated token stream either way.
"""

import asyncio

import pytest

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.drafter import NgramDrafter
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols import PreprocessedRequest
from dynamo_tpu.runtime.engine import Context

CFG = ModelConfig()  # test-tiny

# Tiled patterns make the PROMPT n-gram-rich; acceptance then comes from
# the model's own repetitive generation (greedy decode of the tiny
# random-weight model settles into loops the drafter predicts).
LOOPY = ([1, 2, 3] * 6, [7, 8, 9, 4] * 4, [5, 6] * 8)


def spec_args(S: int, depth: int = 0, gate: float = 0.0, fused: bool = False,
              **kw) -> EngineArgs:
    # fused=False by default: the stepwise verify is bitwise identical to
    # the dense path BY CONSTRUCTION (same compiled decode step body), so
    # the byte-identity goldens hold on every backend — including this
    # suite's 8-virtual-device CPU platform, where the fused forward's
    # batched matmul reductions differ from the dense step's at the last
    # ulp. The fused path gets its own tokens-exact/logprobs-close test.
    defaults = dict(
        model=CFG, block_size=4, num_kv_blocks=256, max_num_seqs=8,
        max_model_len=128, max_prefill_tokens=64, dtype="float32",
        decode_steps=4, spec_tokens=S, spec_gate=gate, spec_fused=fused,
        pipeline_depth=depth, pipeline_windows=depth > 0,
    )
    defaults.update(kw)
    return EngineArgs(**defaults)


def request(prompt, max_tokens, temperature=0.0, seed=0, logprobs=False,
            top_logprobs=0, eos=()) -> PreprocessedRequest:
    req = PreprocessedRequest(model="t", token_ids=list(prompt))
    req.sampling.temperature = temperature
    req.sampling.seed = seed
    req.sampling.logprobs = logprobs
    req.sampling.top_logprobs = top_logprobs
    req.stop.max_tokens = max_tokens
    req.stop.ignore_eos = not eos
    req.stop.stop_token_ids = list(eos)
    return req


async def run_stream(engine, req):
    toks, lps, tops = [], [], []
    finish = None
    async for item in engine.generate(req, Context()):
        toks.extend(item.get("token_ids") or [])
        lps.extend(item.get("log_probs") or [])
        tops.extend(item.get("top_log_probs") or [])
        if item.get("finish_reason"):
            finish = item["finish_reason"]
    return toks, lps, tops, finish


def mixed_workload():
    """Loopy and incompressible prompts side by side, logprobs and
    ranked alternatives, a prefill-only row, and stops at/inside window
    and draft boundaries."""
    return [
        request(LOOPY[0], 24),
        request(LOOPY[1], 17, logprobs=True),
        request(LOOPY[2], 21, logprobs=True, top_logprobs=3),
        request([11, 13, 17, 19, 23, 29, 31, 37], 20),   # incompressible
        request([2, 4, 8], 1),                           # prefill-only
        request(list(range(40, 70)), 9),                 # odd bucket fit
    ]


async def run_workload(eargs: EngineArgs, reqs=None):
    engine = await TpuEngine(eargs).start()
    try:
        out = await asyncio.gather(
            *(run_stream(engine, r) for r in (reqs or mixed_workload()))
        )
        stats = {
            "rows": engine.total_spec_rows,
            "proposed": engine.total_spec_proposed,
            "accepted": engine.total_spec_accepted,
            "emitted": engine.total_spec_emitted,
        }
        return out, stats
    finally:
        await engine.stop()


@pytest.mark.parametrize("S", [1, 2, 4, 8])
def test_spec_greedy_byte_identity(S):
    """Token, logprob and top-logprob streams must be identical with
    speculation on at every draft length — and the spec runs must have
    actually speculated (non-vacuous)."""

    async def go():
        dense, _ = await run_workload(spec_args(0))
        spec, stats = await run_workload(spec_args(S))
        assert spec == dense, f"S={S} diverged from the dense path"
        assert stats["rows"] > 0, f"S={S}: no verify pass ever dispatched"
        assert stats["accepted"] <= stats["proposed"]
        # Every live row-pass emits its accepted run plus one token.
        assert stats["emitted"] == stats["rows"] + stats["accepted"]
        for toks, _lps, _tops, finish in dense:
            assert finish == "length"

    asyncio.run(go())


@pytest.mark.parametrize("depth", [1, 2])
def test_spec_composes_with_pipeline(depth):
    """Speculation must ride the FIFO drain-order invariant alongside
    pipelined dense windows: a _Spec pass is a barrier, but before/after
    it the window pipeline runs at full depth — streams stay identical
    to the unpipelined dense engine."""

    async def go():
        dense, _ = await run_workload(spec_args(0))
        spec, stats = await run_workload(spec_args(4, depth=depth))
        assert spec == dense, f"S=4 depth={depth} diverged"
        assert stats["rows"] > 0

    asyncio.run(go())


def test_spec_stop_token_mid_draft():
    """An eos landing inside an accepted draft run must truncate the
    stream exactly where the dense path stops it (tokens past the stop
    are wasted device work, never surfaced)."""

    async def go():
        dense, _ = await run_workload(spec_args(0), [request(LOOPY[0], 24, seed=3)])
        toks = dense[0][0]
        assert len(toks) == 24
        # Stop on a token the dense stream emits mid-run (and mid-draft
        # for the spec engine, whose loop drafts run 8 deep).
        eos = toks[13]
        reqs = [request(LOOPY[0], 24, seed=3, eos=(eos,))]
        dense_stop, _ = await run_workload(spec_args(0), reqs)
        reqs = [request(LOOPY[0], 24, seed=3, eos=(eos,))]
        spec_stop, _ = await run_workload(spec_args(8), reqs)
        assert spec_stop == dense_stop
        assert spec_stop[0][3] == "stop"
        assert spec_stop[0][0][-1] == eos
        assert len(spec_stop[0][0]) < 24

    asyncio.run(go())


def test_spec_max_tokens_inside_accepted_run():
    """max_tokens boundaries landing anywhere inside an accepted run
    must truncate identically to the dense path."""

    async def go():
        for mt in (1, 2, 3, 5, 7, 10, 13):
            reqs = [request(LOOPY[0], mt, seed=1), request(LOOPY[2], mt, seed=2)]
            dense, _ = await run_workload(spec_args(0), reqs)
            reqs = [request(LOOPY[0], mt, seed=1), request(LOOPY[2], mt, seed=2)]
            spec, _ = await run_workload(spec_args(8), reqs)
            assert spec == dense, f"max_tokens={mt} diverged"
            assert all(len(s[0]) == mt for s in spec)
            assert all(s[3] == "length" for s in spec)

    asyncio.run(go())


def test_spec_preemption_golden():
    """KV pressure forces preemption-by-recompute while verifies are in
    flight; drained passes must land every kept token first and streams
    stay identical across spec on/off."""

    async def collect(S):
        engine = await TpuEngine(spec_args(
            S, max_num_seqs=2, num_kv_blocks=24, max_model_len=64,
        )).start()
        try:
            return await asyncio.gather(
                run_stream(engine, request(LOOPY[0][:4], 20, logprobs=True)),
                run_stream(engine, request(LOOPY[1][:4], 20, logprobs=True)),
            )
        finally:
            await engine.stop()

    async def go():
        base = await collect(0)
        for toks, lps, _tops, finish in base:
            assert len(toks) == 20 and len(lps) == 20 and finish == "length"
        for S in (2, 8):
            assert await collect(S) == base, f"S={S} diverged under preemption"

    asyncio.run(go())


def test_spec_sampled_rows():
    """Sampled rows: (a) seeded spec runs are deterministic; (b) rows
    that never draft ride the dense RNG stream byte-identically even
    inside a speculating engine; (c) drafted sampled rows may diverge
    from dense token-wise (different RNG stream) but the run completes
    with full-length streams — the distribution-preservation argument
    is rejection-sampling math, determinism is what's testable."""

    async def go():
        incompressible = [37, 11, 29, 5, 17, 2, 23, 41]
        reqs = lambda: [  # noqa: E731
            request(incompressible, 15, temperature=0.9, seed=11, logprobs=True),
            request(LOOPY[0], 15, temperature=0.7, seed=12),
            request(LOOPY[1], 15, seed=13),  # greedy row in the same batch
        ]
        dense, _ = await run_workload(spec_args(0), reqs())
        spec1, _ = await run_workload(spec_args(4), reqs())
        spec2, _ = await run_workload(spec_args(4), reqs())
        assert spec1 == spec2, "seeded speculative sampling must be deterministic"
        # The incompressible sampled row never drafts → exact dense match.
        assert spec1[0] == dense[0]
        # Greedy rows are byte-identical regardless of batch mode.
        assert spec1[2] == dense[2]
        assert all(len(s[0]) == 15 and s[3] == "length" for s in spec1)

    asyncio.run(go())


def test_spec_fused_tokens_exact_logprobs_close():
    """The fused single-pass verify (the production bandwidth path) must
    reproduce the dense GREEDY TOKEN stream exactly; its reported
    logprob values may differ from the stepwise dense kernel's at the
    last ulp (batched-matmul reduction order), so they are compared
    within tolerance rather than byte-for-byte."""

    async def go():
        dense, _ = await run_workload(spec_args(0))
        fused, stats = await run_workload(spec_args(8, fused=True))
        assert stats["rows"] > 0
        for (dt, dl, _dtop, df), (ft, fl, _ftop, ff) in zip(dense, fused):
            assert ft == dt and ff == df
            assert len(fl) == len(dl)
            for a, b in zip(dl, fl):
                assert abs(a - b) < 1e-4

    asyncio.run(go())


def test_spec_gate_disables_speculation():
    """An unattainable dispatch gate must keep the engine on the pure
    dense path (no verify ever dispatched) with identical output — the
    adaptive degradation endpoint for adversarial workloads."""

    async def go():
        dense, _ = await run_workload(spec_args(0))
        gated, stats = await run_workload(spec_args(8, gate=1e9))
        assert gated == dense
        assert stats["rows"] == 0

    asyncio.run(go())


def test_ngram_drafter():
    d = NgramDrafter(3)
    st = d.new_state()
    # No match on fresh history.
    assert d.draft([1, 2, 3, 4], st, 4) == []
    # Tail (2, 3, 4) matches the earlier occurrence; continuation + the
    # self-extending copy cycles the loop to the full requested length.
    toks = [1, 2, 3, 4, 9, 1, 2, 3, 4]
    st = d.new_state()
    assert d.draft(toks, st, 3) == [9, 1, 2]
    assert d.draft(toks, st, 8) == [9, 1, 2, 3, 4, 9, 1, 2]
    # Period-1 loop drafts max_len copies.
    st = d.new_state()
    assert d.draft([5, 6, 7, 7, 7, 7], st, 5) == [7] * 5
    # Incremental absorb: appending tokens keeps the index consistent.
    st = d.new_state()
    seq = [1, 2, 3, 4, 9]
    assert d.draft(seq, st, 4) == []
    seq += [1, 2, 3]
    assert d.draft(seq, st, 2) == [4, 9]
    # max_len=0 and short histories are safe no-ops.
    assert d.draft(seq, st, 0) == []
    assert d.draft([1, 2], d.new_state(), 4) == []
