"""Frontend e2e for structured output: /v1/chat/completions with
``response_format`` (and the Responses API ``text.format`` mapping)
served end to end by a REAL TpuEngine worker — the full request path
(HTTP parse → preprocessor validation → wire → engine token-mask FSM →
detokenized response) returns parseable, schema-valid JSON; malformed
schemas 400 at the frontend with a typed OpenAI error body."""

import asyncio
import json

import pytest

from dynamo_tpu.kv_router.publisher import KvEventBroadcaster, serve_kv_endpoints
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_model
from dynamo_tpu.llm.pipeline import RouterSettings
from dynamo_tpu.llm.protocols import OpenAIError, ResponsesRequest
from dynamo_tpu.llm.client import OpenAIClient, OpenAIClientError
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.push_router import RouterMode

SCHEMA = {"type": "object", "properties": {
    "name": {"type": "string", "maxLength": 8},
    "ok": {"type": "boolean"},
}}
RESPONSE_FORMAT = {"type": "json_schema",
                   "json_schema": {"name": "extract", "schema": SCHEMA}}


def _assert_schema_valid(text: str):
    obj = json.loads(text)
    assert set(obj) == {"name", "ok"}
    assert isinstance(obj["name"], str) and len(obj["name"]) <= 8
    assert isinstance(obj["ok"], bool)


async def _start_stack(url: str):
    from dynamo_tpu.engine.config import EngineArgs, ModelConfig
    from dynamo_tpu.engine.engine import TpuEngine

    rt = await DistributedRuntime.create(store_url=url)
    engine = await TpuEngine(EngineArgs(
        model=ModelConfig(), block_size=4, num_kv_blocks=320, max_num_seqs=8,
        max_model_len=256, max_prefill_tokens=128, dtype="float32",
        decode_steps=4, spec_tokens=8, spec_tree_width=2, spec_gate=0.0,
    )).start()
    broadcaster = KvEventBroadcaster(engine.pool)
    engine.pool.set_event_sink(broadcaster.publish)
    comp = rt.namespace("e2e").component("backend")

    async def gen_handler(payload, ctx):
        async for item in engine.generate(payload, ctx):
            yield item

    await comp.endpoint("generate").serve(gen_handler)
    await serve_kv_endpoints(comp, broadcaster, engine.metrics)
    card = ModelDeploymentCard(
        name="tiny", kv_cache_block_size=4,
        eos_token_ids=[ByteTokenizer.EOS], context_length=256,
    )
    await register_model(rt, "e2e", card)

    frt = await DistributedRuntime.create(store_url=url)
    manager = ModelManager(frt, RouterSettings(mode=RouterMode.ROUND_ROBIN))
    watcher = await ModelWatcher(frt, manager).start()
    http = await HttpService(
        manager, frt.metrics, health=frt.health, host="127.0.0.1", port=0
    ).start()
    return rt, engine, frt, manager, watcher, http


def test_chat_response_format_returns_schema_valid_json():
    async def go():
        rt, engine, frt, manager, watcher, http = await _start_stack(
            "memory://fe_grammar"
        )
        try:
            async with OpenAIClient(f"http://127.0.0.1:{http.port}",
                                    default_model="tiny") as client:
                # json_schema: the completion must parse AND validate
                resp = await client.chat(
                    [{"role": "user", "content": "extract the record"}],
                    max_tokens=160, temperature=0.0, seed=0,
                    response_format=RESPONSE_FORMAT,
                )
                choice = resp["choices"][0]
                assert choice["finish_reason"] == "stop"
                _assert_schema_valid(choice["message"]["content"])

                # json_object mode: any parseable JSON object
                resp2 = await client.chat(
                    [{"role": "user", "content": "give me json"}],
                    max_tokens=200, temperature=0.0, seed=1,
                    response_format={"type": "json_object"},
                )
                obj = json.loads(resp2["choices"][0]["message"]["content"])
                assert isinstance(obj, dict)

                # streaming path: concatenated deltas are schema-valid too
                parts = []
                finish = None
                async for chunk in client.chat_stream(
                    [{"role": "user", "content": "extract again"}],
                    max_tokens=160, temperature=0.0, seed=2,
                    response_format=RESPONSE_FORMAT,
                ):
                    d = chunk["choices"][0]["delta"]
                    if d.get("content"):
                        parts.append(d["content"])
                    if chunk["choices"][0].get("finish_reason"):
                        finish = chunk["choices"][0]["finish_reason"]
                assert finish == "stop"
                _assert_schema_valid("".join(parts))

                # malformed schema → 400 with a typed OpenAI error body
                with pytest.raises(OpenAIClientError) as ei:
                    await client.chat(
                        [{"role": "user", "content": "x"}],
                        response_format={"type": "json_schema",
                                         "json_schema": {"schema": {"type": "zzz"}}},
                    )
                assert ei.value.status == 400
                assert "response_format" in ei.value.body["error"]["message"]

                # malformed wire shape → 400 too
                with pytest.raises(OpenAIClientError) as ei2:
                    await client.chat(
                        [{"role": "user", "content": "x"}],
                        response_format={"type": "json_schema"},
                    )
                assert ei2.value.status == 400

                # Responses API: text.format maps to response_format
                # instead of the old 501 rejection
                r3 = await client.responses(
                    "extract the record", max_output_tokens=160,
                    temperature=0.0, seed=3,
                    text={"format": {"type": "json_schema", "name": "extract",
                                     "schema": SCHEMA}},
                )
                assert r3["status"] == "completed"
                _assert_schema_valid(r3["output"][0]["content"][0]["text"])
        finally:
            await http.close()
            await engine.stop()
            await frt.shutdown()
            await rt.shutdown()

    asyncio.run(go())


def test_responses_text_format_protocol_mapping():
    base = {"model": "m", "input": "hi"}
    # noop forms
    assert ResponsesRequest.parse(base).response_format is None
    assert ResponsesRequest.parse(
        {**base, "text": {"format": {"type": "text"}}}
    ).response_format is None
    # json_object
    assert ResponsesRequest.parse(
        {**base, "text": {"format": {"type": "json_object"}}}
    ).response_format == {"type": "json_object"}
    # json_schema flattens name/schema/strict into format
    req = ResponsesRequest.parse(
        {**base, "text": {"format": {"type": "json_schema", "name": "n",
                                     "schema": SCHEMA, "strict": True}}}
    )
    assert req.response_format == {
        "type": "json_schema",
        "json_schema": {"schema": SCHEMA, "name": "n", "strict": True},
    }
    assert req.to_chat().response_format == req.response_format
    # malformed format type is a 400, not a 501
    with pytest.raises(OpenAIError) as ei:
        ResponsesRequest.parse({**base, "text": {"format": {"type": "bogus"}}})
    assert ei.value.status == 400
    # unimplemented text.* options keep their explicit 501 (they were
    # never silently droppable)
    with pytest.raises(OpenAIError) as ei2:
        ResponsesRequest.parse({**base, "text": {"verbosity": "low"}})
    assert ei2.value.status == 501
