"""leader_worker_barrier tests (reference: lib/runtime/src/utils/
leader_worker_barrier.rs semantics: data publication + N check-ins +
joint release + timeout on missing participants)."""

import asyncio

import pytest

from dynamo_tpu.runtime.barrier import BarrierTimeout, leader_barrier, worker_barrier
from dynamo_tpu.runtime.store import connect_store


def test_barrier_releases_all_with_data():
    async def go():
        store = await connect_store("memory://b1")

        async def worker(i):
            return await worker_barrier(store, "boot", f"w{i}", timeout=5)

        results = await asyncio.gather(
            leader_barrier(store, "boot", 3, data=b"mesh-config", timeout=5),
            worker(0), worker(1), worker(2),
        )
        return results[1:]

    assert asyncio.run(go()) == [b"mesh-config"] * 3


def test_barrier_leader_times_out_on_missing_worker():
    async def go():
        store = await connect_store("memory://b2")
        task = asyncio.create_task(worker_barrier(store, "boot", "w0", timeout=1.0))
        with pytest.raises(BarrierTimeout):
            await leader_barrier(store, "boot", 2, timeout=0.3)
        with pytest.raises(BarrierTimeout):
            await task
        return True

    assert asyncio.run(go())


def test_barrier_worker_joining_late_still_releases():
    async def go():
        store = await connect_store("memory://b3")

        async def late_worker():
            await asyncio.sleep(0.1)
            return await worker_barrier(store, "boot", "late", timeout=5)

        _, data = await asyncio.gather(
            leader_barrier(store, "boot", 1, data=b"d", timeout=5),
            late_worker(),
        )
        return data

    assert asyncio.run(go()) == b"d"
