"""Fleet supervisor e2e: real child processes behind one shared port.

Covers the process-lifecycle contract: both children serve through one
port and the aggregation endpoint merges their observability; a
SIGKILLed child is restarted with backoff while sibling in-flight
streams are unaffected and its leased admission budget returns; SIGHUP
rolls a drain through the fleet one process at a time without dropping
requests; SIGTERM drains the whole fleet and leaves no shared state
behind in the store."""

import asyncio
import threading
import json
import signal
import socket
import time

import httpx
import pytest

from procutil import ManagedProcess

pytestmark = pytest.mark.e2e

GRACE_ENV = {
    "JAX_PLATFORMS": "cpu",
    # Fast drains + fast restart backoff so the suite stays quick.
    "DYNTPU_RUNTIME_GRACEFUL_SHUTDOWN_TIMEOUT": "10",
    "DYNTPU_FLEET_RESTART_BACKOFF_BASE": "0.2",
    "DYNTPU_FLEET_RESTART_BACKOFF_MAX": "1.0",
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FleetHarness:
    """store + mocker worker + a --fleet N frontend, with teardown."""

    def __init__(self, n: int = 2, extra_args: list | None = None,
                 extra_env: dict | None = None, itl_ms: str = "1"):
        self.n = n
        self.store_port = _free_port()
        self.store_url = f"tcp://127.0.0.1:{self.store_port}"
        self.procs: list[ManagedProcess] = []
        self.extra_args = extra_args or []
        self.extra_env = extra_env or {}
        self.itl_ms = itl_ms
        self.base = self.admin = None

    def __enter__(self) -> "FleetHarness":
        store = ManagedProcess(
            ["-m", "dynamo_tpu.runtime.store_server",
             "--host", "127.0.0.1", "--port", str(self.store_port)],
            name="store", env=GRACE_ENV,
        )
        self.procs.append(store)
        store.wait_for(r"store server: tcp://")
        worker = ManagedProcess(
            ["-m", "dynamo_tpu.worker", "--store-url", self.store_url,
             "--engine", "mocker", "--mocker-speedup", "1",
             "--mocker-ttft-ms", "1", "--mocker-itl-ms", self.itl_ms,
             "--max-num-seqs", "128"],
            name="worker", env=GRACE_ENV,
        )
        self.procs.append(worker)
        worker.wait_for(r"serving mock-model")
        fleet = ManagedProcess(
            ["-m", "dynamo_tpu.frontend", "--store-url", self.store_url,
             "--host", "127.0.0.1", "--port", "0", "--router-mode", "kv",
             "--fleet", str(self.n), "--fleet-id", f"t{self.store_port}",
             *self.extra_args],
            name="fleet", env={**GRACE_ENV, **self.extra_env},
        )
        self.procs.append(fleet)
        self.fleet = fleet
        m = fleet.wait_for(
            r"fleet: http://127\.0\.0\.1:(\d+) admin http://127\.0\.0\.1:(\d+)"
        )
        self.base = f"http://127.0.0.1:{m.group(1)}"
        self.admin = f"http://127.0.0.1:{m.group(2)}"
        fleet.wait_for(r"fleet ready", timeout=60)
        # Model discovery on every child.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            r = httpx.get(f"{self.base}/v1/models", timeout=5)
            if r.json()["data"]:
                return self
            time.sleep(0.2)
        raise TimeoutError("model never discovered")

    def __exit__(self, *exc):
        for p in reversed(self.procs):
            p.terminate()
        return False

    def status(self) -> dict:
        return httpx.get(f"{self.admin}/fleet", timeout=5).json()

    def chat(self, text: str, max_tokens: int = 4, **kw) -> httpx.Response:
        return httpx.post(
            f"{self.base}/v1/chat/completions",
            json={"model": "mock-model", "max_tokens": max_tokens,
                  "messages": [{"role": "user", "content": text}], **kw},
            # One fresh connection per request: SO_REUSEPORT balances
            # connections, not requests.
            headers={"Connection": "close"}, timeout=30,
        )


def test_fleet_serves_both_children_and_aggregates():
    with FleetHarness(n=2) as h:
        for i in range(24):
            r = h.chat(f"hello {i}")
            assert r.status_code == 200, r.text
        m = httpx.get(f"{h.admin}/metrics", timeout=10).text
        served = {}
        for line in m.splitlines():
            if line.startswith("dynamo_tpu_http_requests_total{") and 'status="200"' in line:
                wid = line.split('fleet_worker_id="')[1].split('"')[0]
                served[wid] = served.get(wid, 0) + float(line.rsplit(" ", 1)[1])
        assert set(served) == {"0", "1"}, f"not all children served: {served}"
        assert sum(served.values()) == 24
        # Supervisor's own series ride the merge too.
        assert 'dynamo_tpu_fleet_workers_alive{fleet_worker_id="supervisor"} 2' in m
        # Per-child budget/decision series exist (children register them).
        assert "dynamo_tpu_fleet_decision_cache_entries" in m
        h_resp = httpx.get(f"{h.admin}/health", timeout=5)
        assert h_resp.status_code == 200 and h_resp.json()["status"] == "ready"
        st = h.status()
        assert st["socket_mode"] in ("reuseport", "inherit")
        assert [w["alive"] for w in st["workers"]] == [True, True]


def test_kill_child_restarts_with_backoff_and_reclaims_budget():
    """SIGKILL one child mid-stream: the supervisor restarts it (counted,
    after backoff), sibling in-flight streams finish unaffected, and the
    dead process's budget chunks are reclaimable (TCP store revokes
    connection-owned leases on disconnect; TTL is the fallback)."""
    with FleetHarness(
        n=2, extra_args=["--global-max-inflight", "16", "--budget-chunk", "4"],
        itl_ms="50",
    ) as h:
        # Long streams across several fresh connections: with 8
        # connections the chance one child holds none is 2^-8 per side —
        # retried via more streams below if needed.
        async def drive():
            async with httpx.AsyncClient(timeout=60) as client:
                async def one(i):
                    toks = 0
                    try:
                        async with client.stream(
                            "POST", f"{h.base}/v1/chat/completions",
                            json={"model": "mock-model", "max_tokens": 40,
                                  "stream": True, "ignore_eos": True,
                                  "messages": [{"role": "user", "content": f"s{i}"}]},
                            headers={"Connection": "close"},
                        ) as resp:
                            assert resp.status_code == 200
                            async for line in resp.aiter_lines():
                                if line.startswith("data: ") and '"usage"' in line:
                                    u = json.loads(line[6:]).get("usage")
                                    if u:
                                        toks = u["completion_tokens"]
                        return ("ok", toks)
                    except (httpx.HTTPError, OSError) as e:
                        return (type(e).__name__, toks)

                streams = [asyncio.create_task(one(i)) for i in range(10)]
                # Streams at ~50ms/token for 40 tokens ≈ 2s: kill child 0
                # while they're all mid-flight.
                await asyncio.sleep(0.6)
                victim_pid = next(
                    w["pid"] for w in h.status()["workers"] if w["worker_id"] == 0
                )
                import os

                os.kill(victim_pid, signal.SIGKILL)
                return await asyncio.gather(*streams), victim_pid

        results, victim_pid = asyncio.run(drive())
        oks = [r for r in results if r[0] == "ok" and r[1] == 40]
        cut = [r for r in results if r[0] != "ok"]
        # The sibling's streams all completed with full token counts;
        # only streams pinned to the killed process were cut.
        assert len(oks) >= 1, results
        assert len(oks) + len(cut) == 10
        for r in results:
            assert not (r[0] == "ok" and r[1] != 40), f"silent truncation: {r}"

        # Supervisor restarts the slot with a fresh pid.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st = h.status()
            w0 = next(w for w in st["workers"] if w["worker_id"] == 0)
            if w0["alive"] and w0["registered"] and w0["pid"] != victim_pid:
                break
            time.sleep(0.2)
        else:
            raise AssertionError(f"child 0 never restarted: {st}")
        assert w0["restarts"] >= 1
        # Budget sanity after the crash+restart settles: claimed chunks
        # never exceed the chunk count and serving still works.
        assert h.status()["budget_chunks_claimed"] <= 4
        r = h.chat("post-restart")
        assert r.status_code == 200


def test_sighup_rolls_drain_without_dropping_requests():
    with FleetHarness(n=2) as h:
        st0 = h.status()
        pids0 = {w["worker_id"]: w["pid"] for w in st0["workers"]}
        h.fleet.proc.send_signal(signal.SIGHUP)
        # Keep issuing requests through the roll. A draining child leaves
        # the accept group FIRST, so new connections land on siblings —
        # but a connection the kernel handed it just before the listener
        # closed can still see the typed drain 503 + Retry-After, which
        # clients retry. The contract under test: one retry always
        # succeeds, and nothing ever fails at the transport level.
        failures = 0
        deadline = time.monotonic() + 45
        rolled = False
        while time.monotonic() < deadline:
            r = h.chat("during roll", max_tokens=2)
            if r.status_code == 503:
                assert "Retry-After" in r.headers
                r = h.chat("during roll retry", max_tokens=2)
            if r.status_code != 200:
                failures += 1
            st = h.status()
            pids = {w["worker_id"]: w["pid"] for w in st["workers"]}
            if (
                all(w["alive"] and w["registered"] for w in st["workers"])
                and all(pids[k] != pids0[k] for k in pids0)
            ):
                rolled = True
                break
            time.sleep(0.1)
        assert rolled, f"rolling restart never completed: {h.status()}"
        assert failures == 0, f"{failures} requests failed (post-retry) during the roll"
        r = h.chat("after roll")
        assert r.status_code == 200


def test_sigterm_drains_fleet_and_clears_shared_state():
    with FleetHarness(
        n=2, extra_args=["--global-max-inflight", "16", "--budget-chunk", "4"]
    ) as h:
        for i in range(4):
            assert h.chat(f"warm {i}").status_code == 200
        h.fleet.proc.send_signal(signal.SIGTERM)
        h.fleet.proc.wait(40)
        assert h.fleet.proc.returncode == 0
        # Shared state is handed back at drain, not left to TTL: no
        # budget chunks, no decision entries, no registrations.
        async def probe():
            from dynamo_tpu.runtime.store import connect_store

            store = await connect_store(h.store_url)
            try:
                fid = f"t{h.store_port}"
                assert await store.get_prefix(f"fleet/{fid}/budget/") == []
                assert await store.get_prefix(f"fleet/{fid}/route/") == []
                assert await store.get_prefix(f"fleet/{fid}/frontends/") == []
            finally:
                await store.close()

        asyncio.run(probe())


def test_fleet_resize_rpc_grows_and_shrinks_without_failures():
    """POST /fleet/resize — the autoscaler's frontend actuation: grow
    1 → 2 (new child registers and serves), shrink 2 → 1 through the
    zero-failure drain, with traffic flowing throughout."""
    with FleetHarness(n=1) as h:
        ok = [0]
        stop = threading.Event()
        errors: list[str] = []

        def hammer():
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    r = h.chat(f"resize {i}")
                    if r.status_code == 200:
                        ok[0] += 1
                    elif r.status_code not in (429, 503):
                        errors.append(f"status {r.status_code}")
                except Exception as e:  # noqa: BLE001 — a transport error during resize IS the failure signal
                    errors.append(f"{type(e).__name__}: {e}")
                time.sleep(0.02)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            r = httpx.post(f"{h.admin}/fleet/resize", json={"n": 2}, timeout=90)
            assert r.status_code == 200, r.text
            assert r.json()["fleet_size"] == 2 and r.json()["grew"] == 1
            # The operator's actuator reads the size off GET /fleet —
            # regression: the key must exist there, not only on /health.
            assert h.status()["fleet_size"] == 2

            async def via_actuator():
                from dynamo_tpu.planner.actuate import FleetHttpActuator

                return await FleetHttpActuator(h.admin).fleet_size()

            assert asyncio.run(via_actuator()) == 2
            st = h.status()
            assert len(st["workers"]) == 2
            # Both children must end up serving (registration-backed).
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                st = h.status()
                if all(w["registered"] and w["alive"] for w in st["workers"]):
                    break
                time.sleep(0.2)
            else:
                raise AssertionError(f"grown fleet never converged: {st}")

            r = httpx.post(f"{h.admin}/fleet/resize", json={"n": 1}, timeout=90)
            assert r.status_code == 200, r.text
            assert r.json()["fleet_size"] == 1 and r.json()["shrank"] == 1
            st = h.status()
            assert len(st["workers"]) == 1 and st["workers"][0]["alive"]
            # A few post-shrink requests prove the survivor serves.
            for i in range(6):
                assert h.chat(f"after {i}").status_code == 200
        finally:
            stop.set()
            t.join(10)
        assert not errors, errors[:5]
        assert ok[0] > 0
        # Bad bodies are typed 400s, never crashes.
        assert httpx.post(f"{h.admin}/fleet/resize", json={"n": 0}, timeout=10).status_code == 400
        assert httpx.post(f"{h.admin}/fleet/resize", json={}, timeout=10).status_code == 400
