from dynamo_tpu.runtime.metrics import InflightGuard, MetricsRegistry


def test_counter_and_labels():
    reg = MetricsRegistry()
    c = reg.child("ns1").child("comp1").counter("requests_total", "total requests")
    c.inc(model="m1")
    c.inc(2, model="m1")
    c.inc(model="m2")
    assert c.value(model="m1") == 3
    text = reg.render()
    assert 'dynamo_tpu_requests_total{dynamo_component="comp1",dynamo_namespace="ns1",model="m1"} 3' in text
    assert "# TYPE dynamo_tpu_requests_total counter" in text


def test_gauge_inflight_guard():
    reg = MetricsRegistry()
    g = reg.gauge("inflight", "in-flight requests")
    with InflightGuard(g, model="m"):
        assert g.value(model="m") == 1
    assert g.value(model="m") == 0


def test_histogram_buckets():
    reg = MetricsRegistry()
    h = reg.histogram("latency_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = reg.render()
    assert 'le="0.1"} 1' in text
    assert 'le="1"} 2' in text
    assert 'le="+Inf"} 3' in text
    assert "latency_seconds_count 3" in text


def test_same_name_returns_same_metric():
    reg = MetricsRegistry()
    a = reg.counter("x")
    b = reg.counter("x")
    assert a is b


def test_same_name_in_two_scopes_is_two_series():
    # ADVICE r1: metrics must be keyed by (name, const_labels), not name alone.
    reg = MetricsRegistry()
    a = reg.child("ns1").child("compA").counter("reqs")
    b = reg.child("ns2").child("compB").counter("reqs")
    assert a is not b
    a.inc()
    b.inc(3)
    out = reg.render()
    assert 'dynamo_component="compA"' in out
    assert 'dynamo_component="compB"' in out
    # but only one HELP/TYPE header per metric name
    assert out.count("# TYPE dynamo_tpu_reqs counter") == 1
