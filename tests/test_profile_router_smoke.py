"""Tier-1 guard for tools/profile_router.py: the placement-latency
profiler runs its --quick sweep (64-engine fleet, full-scan vs pruned)
and asserts its internal invariants itself — candidate counts, fallback
rate, nonzero latency percentiles — so the tool can't bit-rot between
perf rounds.

No timing assertions: --quick makes no latency claims.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_profile_router_quick_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profile_router.py"),
         "--quick"],
        capture_output=True, text=True, timeout=240,
        env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    # QUICK-OK prints only after the tool's own asserts (full scan scores
    # the whole fleet, pruning scores strictly fewer, bounded fallback).
    assert "QUICK-OK" in proc.stdout, proc.stdout + proc.stderr[-2000:]
    cells = [json.loads(l) for l in proc.stdout.splitlines() if l.startswith("{")]
    assert {c["shortlist_k"] for c in cells} == {0, 8}
    for c in cells:
        assert c["requests"] == 200 and c["place_p99_us"] > 0
