"""/v1/responses endpoint (OpenAI Responses API).

Reference parity: lib/llm/src/http/service/openai.rs:584-850 serves
responses by converting to chat completions (unary only there; here the
typed event stream is served too).
"""

import asyncio
import json

import httpx
import pytest

from dynamo_tpu.llm.protocols import OpenAIError, ResponsesRequest

from test_frontend_e2e import start_frontend, start_worker

pytestmark = pytest.mark.integration


# -- request parsing (pure) -------------------------------------------------


def test_parse_string_input_and_instructions():
    req = ResponsesRequest.parse({
        "model": "m", "input": "hi there",
        "instructions": "be brief", "max_output_tokens": 9,
        "temperature": 0.5,
    })
    assert [m.role for m in req.messages] == ["system", "user"]
    assert req.messages[1].content == "hi there"
    chat = req.to_chat()
    assert chat.max_tokens == 9
    assert chat.temperature == 0.5
    assert chat.messages[0].content == "be brief"


def test_parse_message_list_with_parts_and_developer_role():
    req = ResponsesRequest.parse({
        "model": "m",
        "input": [
            {"role": "developer", "content": "rules"},
            {"role": "user", "content": [
                {"type": "input_text", "text": "a"},
                {"type": "input_text", "text": "b"},
            ]},
        ],
    })
    assert [m.role for m in req.messages] == ["system", "user"]
    assert req.messages[1].content == "ab"


@pytest.mark.parametrize("body,status", [
    ({"model": "m", "input": "x", "tools": [{"type": "function"}]}, 501),
    ({"model": "m", "input": "x", "previous_response_id": "r"}, 501),
    ({"model": "m", "input": "x", "background": True}, 501),
    ({"model": "m", "input": "x", "store": True}, 501),
    ({"model": "m", "input": [{"role": "user", "content": [
        {"type": "input_image", "image_url": "u"}]}]}, 501),
    ({"model": "m"}, 400),
    ({"model": "m", "input": []}, 400),
    ({"input": "x"}, 400),
])
def test_parse_rejections(body, status):
    with pytest.raises(OpenAIError) as ei:
        ResponsesRequest.parse(body)
    assert ei.value.status == status


def test_parse_tolerates_explicit_null_and_empty_unsupported():
    req = ResponsesRequest.parse({
        "model": "m", "input": "x",
        "tools": [], "previous_response_id": None, "background": False,
    })
    assert req.messages[0].content == "x"


def test_parse_tolerates_documented_defaults():
    """A response's own echoed fields must round-trip into a request."""
    req = ResponsesRequest.parse({
        "model": "m", "input": "x",
        "truncation": "disabled", "tool_choice": "none",
        "service_tier": "auto", "text": {"format": {"type": "text"}},
        "store": False,
    })
    assert req.messages[0].content == "x"
    with pytest.raises(OpenAIError):
        ResponsesRequest.parse({"model": "m", "input": "x", "truncation": "auto"})


# -- served endpoint (in-process mocker fleet) ------------------------------


def test_responses_unary_and_streaming():
    async def go():
        url = "memory://resp1"
        wrt, _eng = await start_worker(url)
        frt, manager, watcher, http = await start_frontend(url)
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=20) as client:
                # unary
                r = await client.post(f"{base}/v1/responses", json={
                    "model": "mock-model", "input": "hello responses",
                    "max_output_tokens": 8,
                })
                assert r.status_code == 200
                body = r.json()
                assert body["object"] == "response"
                assert body["status"] in ("completed", "incomplete")
                item = body["output"][0]
                assert item["type"] == "message" and item["role"] == "assistant"
                assert item["content"][0]["type"] == "output_text"
                assert len(item["content"][0]["text"]) > 0
                assert body["usage"]["output_tokens"] == 8
                assert body["usage"]["input_tokens"] > 0
                assert body["usage"]["total_tokens"] == (
                    body["usage"]["input_tokens"] + body["usage"]["output_tokens"]
                )

                # streaming: typed event sequence
                events = []
                async with client.stream(
                    "POST", f"{base}/v1/responses",
                    json={"model": "mock-model", "input": "hello responses",
                          "max_output_tokens": 8, "stream": True},
                ) as resp:
                    assert resp.status_code == 200
                    raw = b"".join([c async for c in resp.aiter_bytes()])
                for frame in raw.split(b"\n\n"):
                    ev = data = None
                    for line in frame.split(b"\n"):
                        if line.startswith(b"event: "):
                            ev = line[7:].decode()
                        elif line.startswith(b"data: "):
                            data = json.loads(line[6:])
                    if ev is not None:
                        events.append((ev, data))
                names = [e for e, _ in events]
                assert names[:4] == [
                    "response.created", "response.in_progress",
                    "response.output_item.added", "response.content_part.added",
                ]
                assert "response.output_text.delta" in names
                assert names[-4:] == [
                    "response.output_text.done", "response.content_part.done",
                    "response.output_item.done", names[-1],
                ]
                assert names[-1] in ("response.completed", "response.incomplete")
                # sequence numbers are contiguous and payload types match
                for i, (ev, data) in enumerate(events):
                    assert data["sequence_number"] == i
                    assert data["type"] == ev
                # deltas concatenate to the final text
                text = "".join(d["delta"] for e, d in events
                               if e == "response.output_text.delta")
                final = events[-1][1]["response"]
                assert final["output"][0]["content"][0]["text"] == text
                assert final["usage"]["output_tokens"] == 8

                # 404 on unknown model
                r = await client.post(f"{base}/v1/responses", json={
                    "model": "nope", "input": "x"})
                assert r.status_code == 404
                # 501 on unsupported field
                r = await client.post(f"{base}/v1/responses", json={
                    "model": "mock-model", "input": "x",
                    "previous_response_id": "resp_1"})
                assert r.status_code == 501
        finally:
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await wrt.shutdown()

    asyncio.run(go())
