"""Tier-1 guard for ``bench.py --workload migrate``: the live-migration
robustness bench must run end to end at smoke shapes, complete forced
relocations, keep migrated output byte-identical to the unmigrated
reference in BOTH arms (clean + chaos), and report the accounting keys
the BENCH_MIGRATE_* trajectory depends on.

No timing assertions: --quick makes no gap-latency claims.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_migrate_quick_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--workload", "migrate", "--quick"],
        capture_output=True, text=True, timeout=420,
        env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, proc.stdout + proc.stderr[-2000:]
    result = json.loads(lines[-1])
    assert "error" not in result, result
    # Migrated ≡ unmigrated greedy bytes on every stream, both arms.
    assert result["parity"] is True
    # The clean arm actually relocated sequences, with KV on the wire.
    assert result["migrations_ok"] > 0
    assert result["kv_bytes_moved"] > 0
    # The chaos arm injected cuts and every cut degraded to a completed
    # stream (fallback), never a client error (parity covers output).
    assert result["chaos_injected_cuts"] > 0
    # The trajectory keys bench rounds compare.
    for key in ("cutover_gap_p50_ms", "cutover_gap_p99_ms",
                "chaos_fallback_rate", "kv_bytes_per_migration"):
        assert key in result, key
