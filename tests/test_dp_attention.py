"""DP-attention: per-rank worker processes behind the KV router.

Reference behaviour being matched: one dynamo worker per engine dp rank
with coordinated ports (reference: components/backends/vllm/launch/
dsr1_dep.sh:86-105, args.py:170-203). Here `worker --dp-size N` spawns N
independent rank processes of the same model; the KV router does the
cross-rank load balancing the reference's DP load balancer does.
"""

import asyncio
import socket

import pytest

from dynamo_tpu.kv_router.router import KvPushRouter, KvRouterConfig
from dynamo_tpu.llm.protocols import PreprocessedRequest
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.push_router import RouterMode
from dynamo_tpu.worker.__main__ import dp_rank_ports

from procutil import ManagedProcess


def test_dp_rank_ports_disjoint_and_deterministic():
    blocks = [dp_rank_ports(29600, r) for r in range(8)]
    # Rank blocks must not overlap: each rank's [system, reserved-end).
    spans = [(b["system"], b["reserved"][1]) for b in blocks]
    for (lo1, hi1), (lo2, hi2) in zip(spans, spans[1:]):
        assert hi1 <= lo2
    assert blocks[0]["system"] == 29600
    assert blocks[1]["system"] == 29604
    assert dp_rank_ports(29600, 3) == dp_rank_ports(29600, 3)


@pytest.mark.e2e
def test_dp_spawner_ranks_serve_and_route_across():
    """`--dp-size 2` spawns two rank processes; the KV router spreads
    distinct concurrent prompts over BOTH ranks; SIGTERM tears the whole
    group down cleanly."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        store_port = s.getsockname()[1]
    store_url = f"tcp://127.0.0.1:{store_port}"

    with ManagedProcess(
        ["-m", "dynamo_tpu.runtime.store_server", "--host", "127.0.0.1",
         "--port", str(store_port)], name="store",
    ) as store:
        store.wait_for(r"store server: tcp://")
        with ManagedProcess(
            ["-m", "dynamo_tpu.worker", "--store-url", store_url,
             "--engine", "mocker", "--model-name", "dp-model",
             "--mocker-speedup", "1000", "--dp-size", "2"],
            name="dp-group",
        ) as group:
            # Both ranks announce through the spawner's inherited stdout.
            group.wait_for(r"dp rank \d/2", timeout=60)
            group.wait_for(r"dp rank \d/2", timeout=60)
            ranks = {
                m for ln in group.lines
                for m in __import__("re").findall(r"dp rank (\d)/2", ln)
            }
            assert ranks == {"0", "1"}

            async def drive():
                rt = await DistributedRuntime.create(store_url=store_url)
                try:
                    ep = rt.namespace("dynamo").component("backend").endpoint("generate")
                    push = await ep.router(RouterMode.DIRECT)
                    await push.discovery.wait_for_instances(2)
                    router = await KvPushRouter(push, KvRouterConfig(block_size=4)).start()
                    try:
                        async def one(i):
                            r = PreprocessedRequest(
                                model="dp-model",
                                token_ids=[100 * i + j for j in range(1, 13)],
                            )
                            r.stop.max_tokens = 8
                            ctx = Context()
                            out = [x async for x in router.generate(r.to_dict(), ctx)]
                            assert out[-1].get("finish_reason")
                            return ctx.metadata["worker_instance_id"]

                        placed = await asyncio.gather(*(one(i) for i in range(1, 9)))
                        assert len(set(placed)) == 2  # both ranks served traffic
                    finally:
                        await router.close()
                finally:
                    await rt.shutdown()

            asyncio.run(drive())
            # Clean group teardown: SIGTERM to the spawner stops all ranks.
            group.terminate()
            assert group.proc.returncode in (0, -15)
