"""Token block hashing semantics (mirrors reference tokens.rs test intent)."""

from dynamo_tpu.tokens import (
    TokenBlockSequence,
    chain_hash,
    compute_block_hashes,
    hash_tokens,
)


def test_hash_deterministic():
    assert hash_tokens([1, 2, 3]) == hash_tokens([1, 2, 3])
    assert hash_tokens([1, 2, 3]) != hash_tokens([3, 2, 1])


def test_chain_depends_on_parent():
    local = hash_tokens([7, 8])
    assert chain_hash(None, local) == local
    assert chain_hash(123, local) != chain_hash(456, local)


def test_compute_block_hashes_prefix_property():
    toks = list(range(64))
    h_full = compute_block_hashes(toks, 16)
    h_prefix = compute_block_hashes(toks[:32], 16)
    assert len(h_full) == 4
    assert h_full[:2] == h_prefix  # shared prefix ⇒ shared hashes
    # Divergence in the first block changes every downstream hash.
    toks2 = [999] + toks[1:]
    h_div = compute_block_hashes(toks2, 16)
    assert all(a != b for a, b in zip(h_full, h_div))


def test_compute_block_hashes_ignores_partial_tail():
    toks = list(range(40))
    assert compute_block_hashes(toks, 16) == compute_block_hashes(toks[:32], 16)


def test_token_block_sequence_matches_batch_hashing():
    toks = list(range(50))
    seq = TokenBlockSequence(block_size=16)
    completed = seq.extend(toks)
    assert len(completed) == 3
    assert seq.partial_tokens == tuple(range(48, 50))
    assert seq.sequence_hashes() == compute_block_hashes(toks, 16)
    assert seq.all_tokens() == toks
    assert seq.total_tokens == 50


def test_append_returns_block_only_on_boundary():
    seq = TokenBlockSequence(block_size=4)
    assert seq.append(1) is None
    assert seq.append(2) is None
    assert seq.append(3) is None
    block = seq.append(4)
    assert block is not None
    assert block.tokens == (1, 2, 3, 4)
    assert block.parent_sequence_hash is None
