"""top_logprobs: ranked alternatives end to end (engine → OpenAI API).

Reference surface: OpenAI chat `top_logprobs` / completions `logprobs=N`
(reference serves these via vLLM; analysis consumer is
lib/llm/src/perf/logprobs.rs — our llm/logprobs.py)."""

import asyncio
import math

import numpy as np

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols import PreprocessedRequest
from dynamo_tpu.runtime.engine import Context

CFG = ModelConfig()


def make_engine(**kw):
    args = EngineArgs(
        model=CFG, block_size=4, num_kv_blocks=64, max_num_seqs=4,
        max_model_len=128, dtype="float32", decode_steps=4, **kw,
    )
    return TpuEngine(args)


def make_request(n_top=3, max_tokens=6):
    r = PreprocessedRequest(model="tiny", token_ids=[5, 9, 13, 17, 21])
    r.sampling.temperature = 0.0
    r.sampling.seed = 0  # greedy, but unseeded requests draw global RNG (DT004)
    r.sampling.logprobs = True
    r.sampling.top_logprobs = n_top
    r.stop.max_tokens = max_tokens
    r.stop.ignore_eos = True
    return r


def test_engine_emits_ranked_alternatives():
    async def go():
        engine = await make_engine().start()
        try:
            toks, lps, tops = [], [], []
            async for item in engine.generate(make_request(), Context()):
                toks += item.get("token_ids") or []
                lps += item.get("log_probs") or []
                tops += item.get("top_log_probs") or []
            assert len(toks) == len(lps) == len(tops) == 6
            for chosen, chosen_lp, top in zip(toks, lps, tops):
                assert len(top) == 3
                vals = [lp for _tid, lp in top]
                assert vals == sorted(vals, reverse=True)  # ranked
                # Greedy: the chosen token IS the top-1 alternative, with
                # the same raw-distribution logprob.
                assert top[0][0] == chosen
                assert math.isclose(top[0][1], chosen_lp, rel_tol=1e-5, abs_tol=1e-5)
                # Distribution sanity: probabilities <= 1 and descending.
                assert all(lp <= 1e-6 for lp in vals)
        finally:
            await engine.stop()

    asyncio.run(go())


def test_engine_clamps_to_top_logprobs_max():
    async def go():
        engine = await make_engine(top_logprobs_max=4).start()
        try:
            tops = []
            async for item in engine.generate(make_request(n_top=20, max_tokens=3), Context()):
                tops += item.get("top_log_probs") or []
            assert tops and all(len(t) == 4 for t in tops)
        finally:
            await engine.stop()

    asyncio.run(go())


def test_top_logprobs_mixed_batch_and_parity():
    """A batch mixing top-requesting and plain requests: plain streams see
    no alternatives, and tokens are unchanged by the extra outputs."""

    async def go():
        engine = await make_engine().start()
        try:
            async def run(req):
                toks, tops = [], []
                async for item in engine.generate(req, Context()):
                    toks += item.get("token_ids") or []
                    tops += item.get("top_log_probs") or []
                return toks, tops

            plain = make_request(n_top=0)
            plain.sampling.top_logprobs = 0
            (t1, p1), (t2, p2) = await asyncio.gather(
                run(make_request()), run(plain)
            )
            assert p1 and not p2
            # Same greedy continuation regardless of top emission.
            solo = await run(make_request(n_top=0))
            assert t2 == solo[0]
        finally:
            await engine.stop()

    asyncio.run(go())


def test_http_surface_top_logprobs():
    """Chat with top_logprobs=2 and completions with logprobs=2 over a
    REAL engine through the frontend."""
    import httpx

    from dynamo_tpu.kv_router.publisher import KvEventBroadcaster, serve_kv_endpoints
    from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_model
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    from test_frontend_e2e import start_frontend

    async def go():
        url = "memory://toplp"
        rt = await DistributedRuntime.create(store_url=url)
        engine = await make_engine().start()
        broadcaster = KvEventBroadcaster(engine.pool)
        engine.pool.set_event_sink(broadcaster.publish)
        comp = rt.namespace("e2e").component("backend")

        async def gen_handler(payload, ctx):
            async for item in engine.generate(payload, ctx):
                yield item

        await comp.endpoint("generate").serve(gen_handler)
        await serve_kv_endpoints(comp, broadcaster, engine.metrics)
        await register_model(rt, "e2e", ModelDeploymentCard(
            name="tiny", kv_cache_block_size=4,
            eos_token_ids=[ByteTokenizer.EOS], context_length=128,
        ))
        frt, manager, watcher, http = await start_frontend(url)
        base = f"http://127.0.0.1:{http.port}"
        try:
            async with httpx.AsyncClient(timeout=30) as client:
                r = await client.post(f"{base}/v1/chat/completions", json={
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "abc"}],
                    "max_tokens": 4, "logprobs": True, "top_logprobs": 2,
                })
                assert r.status_code == 200
                content = r.json()["choices"][0]["logprobs"]["content"]
                assert len(content) == 4
                for entry in content:
                    assert len(entry["top_logprobs"]) == 2
                    assert isinstance(entry["top_logprobs"][0]["logprob"], float)

                # top_logprobs without logprobs: OpenAI 400.
                r = await client.post(f"{base}/v1/chat/completions", json={
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "abc"}],
                    "max_tokens": 2, "top_logprobs": 2,
                })
                assert r.status_code == 400

                # Completions: logprobs=2 → per-token {token: lp} maps.
                r = await client.post(f"{base}/v1/completions", json={
                    "model": "tiny", "prompt": "xy", "max_tokens": 3, "logprobs": 2,
                })
                assert r.status_code == 200
                lp = r.json()["choices"][0]["logprobs"]
                assert len(lp["token_logprobs"]) == 3
                # Maps hold up to N+1 entries (the chosen token joins when
                # sampled outside the top-N, OpenAI semantics) and may
                # collapse below N when distinct token ids decode to the
                # same text (byte-tokenizer "�"s).
                assert lp["top_logprobs"] and all(
                    isinstance(m, dict) and 1 <= len(m) <= 3
                    and all(isinstance(v, float) for v in m.values())
                    for m in lp["top_logprobs"]
                )
        finally:
            await http.close()
            await watcher.close()
            await manager.close()
            await frt.shutdown()
            await engine.stop()
            await rt.shutdown()

    asyncio.run(go())
