"""Tier-1 guard for tools/profile_planner.py: the closed-loop smoke
drives ONE REAL SCALE-UP and ONE REAL POOL MOVE through the live
observe→decide→actuate stack (in-process workers + RuntimeActuator +
SlaAutoscaler) with traffic streaming throughout, and asserts itself:
both actions ok, zero failed/short streams, the planner_* metric series
present, and no leaked autoscaler/model/instance keys after teardown —
so the actuation path can't bit-rot between perf rounds."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_profile_planner_quick_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "profile_planner.py"),
         "--quick"],
        capture_output=True, text=True, timeout=240,
        env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-4000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    assert result["ok"], result
    assert result["scale_up_ok"] and result["pool_move_ok"]
    assert result["streams_failed"] == 0 and result["streams_ok"] > 0
    assert result["metrics"]["replica_scale_ok"] >= 1
    assert result["metrics"]["pool_move_ok"] >= 1
    assert result["leaked_keys"] == []
