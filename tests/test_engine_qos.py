"""Engine-side multi-tenant QoS: (class, age)-ordered admission,
class-aware preemption victim selection, per-class preemption counters,
and the byte-identity guarantees (no-priority traffic identical with
qos_scheduling on/off; a preempted-then-readmitted batch request still
streams byte-identical to a solo run)."""

import asyncio

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols import PreprocessedRequest
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.metrics import MetricsRegistry

CFG = ModelConfig()  # test-tiny


def make_args(**kw) -> EngineArgs:
    defaults = dict(
        model=CFG, block_size=4, num_kv_blocks=64, max_num_seqs=4,
        max_model_len=128, max_prefill_tokens=64, dtype="float32",
    )
    defaults.update(kw)
    return EngineArgs(**defaults)


def qos_request(prompt, max_tokens=8, priority=None, seed=0) -> PreprocessedRequest:
    req = PreprocessedRequest(model="t", token_ids=list(prompt), priority=priority)
    req.sampling.temperature = 0.0
    req.sampling.seed = seed  # greedy, but unseeded requests draw global RNG (DT004)
    req.stop.max_tokens = max_tokens
    req.stop.ignore_eos = True
    return req


async def run_one(engine, req, ctx=None):
    outs = []
    async for item in engine.generate(req, ctx or Context()):
        outs.append(item)
    return outs


def collect_tokens(outs):
    return [t for o in outs for t in o.get("token_ids", [])]


def test_waiting_interactive_admits_before_earlier_batch():
    """One decode slot: while a standard request runs, a batch request
    queues FIRST and an interactive request second — the interactive
    one must be admitted (and so finish) first. This is also the
    preemption hand-back property: a preempted batch request re-enters
    _waiting with its original class, so a waiting interactive request
    takes the freed capacity ahead of it."""

    async def go():
        engine = await TpuEngine(make_args(max_num_seqs=1)).start()
        order: list[str] = []
        try:
            async def run(tag, req, delay):
                await asyncio.sleep(delay)
                outs = await run_one(engine, req)
                order.append(tag)
                return outs

            await asyncio.gather(
                run("first", qos_request([1, 2, 3], 24), 0.0),
                run("batch", qos_request([4, 5, 6], 8, priority="batch"), 0.05),
                run("interactive",
                    qos_request([7, 8, 9], 8, priority="interactive"), 0.1),
            )
            assert order == ["first", "interactive", "batch"]
        finally:
            await engine.stop()

    asyncio.run(go())


def test_preemption_evicts_lowest_class_and_batch_still_finishes_identical():
    """KV pressure with a batch + an interactive long generation
    running: the victim must be the BATCH sequence (lowest class) even
    though the interactive one was admitted later (the pre-QoS rule
    would evict newest = interactive). The preempted batch request
    recomputes and still streams byte-identical to a solo run, and
    engine_preemptions_total{class="batch"} counts it."""

    async def go():
        # 12 blocks: a solo 32-token run fits (8 blocks + decode
        # lookahead ≤ 11) but ANY meaningful overlap of the two
        # sequences (15 blocks combined at peak) forces preemption even
        # when host load staggers their admissions by a window or two.
        engine = await TpuEngine(
            make_args(num_kv_blocks=12, max_model_len=32, max_num_seqs=2)
        ).start()
        registry = MetricsRegistry()
        engine.bind_metrics(registry)
        try:
            pb, pi = [1, 2, 3, 4, 5, 6], [9, 8, 7, 6, 5, 4]

            async def staggered(req, delay):
                await asyncio.sleep(delay)
                return await run_one(engine, req)

            # The stagger makes batch the OLDER running sequence (the
            # legacy newest-first rule would then evict interactive); a
            # loaded host can stretch the gap until batch finishes solo,
            # so retry the race a few times — the class assertions hold
            # on every attempt, the preemption only needs to fire once.
            rb = ri = None
            for _attempt in range(4):
                rb, ri = await asyncio.gather(
                    staggered(qos_request(pb, 26, priority="batch"), 0.0),
                    staggered(qos_request(pi, 20, priority="interactive"), 0.002),
                )
                assert engine.total_preemptions_by.get("interactive", 0) == 0, (
                    "interactive was evicted while a batch victim ran"
                )
                if engine.total_preemptions_by.get("batch", 0) >= 1:
                    break
            assert engine.total_preemptions_by.get("batch", 0) >= 1, (
                "KV pressure never preempted in 4 attempts (geometry regressed?)"
            )
            # Preempted-then-readmitted batch stream is byte-identical.
            solo_b = await run_one(engine, qos_request(pb, 26, priority="batch"))
            assert collect_tokens(rb) == collect_tokens(solo_b)
            assert len(collect_tokens(ri)) == 20
            exposition = registry.render()
            assert 'dynamo_tpu_engine_preemptions_total{class="batch"}' in exposition
        finally:
            await engine.stop()

    asyncio.run(go())


def test_no_priority_traffic_byte_identical_with_qos_on_and_off():
    """The no-QoS guarantee: requests without a priority produce the
    SAME streams whether class-aware scheduling is on (default) or
    off — uniform ranks make the (class, age) order exactly FIFO and
    the victim rule exactly newest-first, including through a
    preemption cycle."""

    async def go():
        geo = dict(num_kv_blocks=14, max_model_len=32, max_num_seqs=2)
        prompts = ([1, 2, 3, 4, 5, 6], [9, 8, 7, 6, 5, 4])
        streams = {}
        for mode in (True, False):
            engine = await TpuEngine(
                make_args(qos_scheduling=mode, **geo)
            ).start()
            try:
                r1, r2 = await asyncio.gather(
                    run_one(engine, qos_request(prompts[0], 20)),
                    run_one(engine, qos_request(prompts[1], 20)),
                )
                streams[mode] = (collect_tokens(r1), collect_tokens(r2))
            finally:
                await engine.stop()
        assert streams[True] == streams[False]
        assert all(len(s) == 20 for s in streams[True])

    asyncio.run(go())


def test_qos_scheduling_off_ignores_wire_priority():
    """--qos-sched off pins one class: priorities on the wire no longer
    reorder admission (FIFO by arrival, the pre-QoS contract)."""

    async def go():
        engine = await TpuEngine(
            make_args(max_num_seqs=1, qos_scheduling=False)
        ).start()
        order: list[str] = []
        try:
            async def run(tag, req, delay):
                await asyncio.sleep(delay)
                outs = await run_one(engine, req)
                order.append(tag)
                return outs

            await asyncio.gather(
                run("first", qos_request([1, 2, 3], 24), 0.0),
                run("batch", qos_request([4, 5, 6], 8, priority="batch"), 0.05),
                run("interactive",
                    qos_request([7, 8, 9], 8, priority="interactive"), 0.1),
            )
            assert order == ["first", "batch", "interactive"]
        finally:
            await engine.stop()

    asyncio.run(go())


def test_unknown_wire_priority_never_crashes_engine():
    """A stale/newer frontend may stamp a class this engine doesn't
    know: it must serve as the default class, not crash."""

    async def go():
        engine = await TpuEngine(make_args()).start()
        try:
            req = qos_request([1, 2, 3], 4)
            req.priority = "hyperspeed"  # junk straight on the wire
            outs = await run_one(engine, req)
            assert len(collect_tokens(outs)) == 4
        finally:
            await engine.stop()

    asyncio.run(go())
