"""Planner tests: predictors, interpolators, and the adjustment loop
driving replica counts up/down under synthetic load (VERDICT r2 next #6;
reference: planner_core.py:189-341)."""

import asyncio

import numpy as np

from dynamo_tpu.planner import (
    DecodeInterpolator,
    Planner,
    PlannerConfig,
    PlannerObservation,
    PrefillInterpolator,
    RecordingConnector,
    load_profile,
    make_predictor,
    save_profile,
)
from dynamo_tpu.planner.core import HttpMetricsSource


def test_predictors_track_level_and_trend():
    const = make_predictor("constant")
    for v in (1, 5, 3):
        const.observe(v)
    assert const.predict() == 3

    ma = make_predictor("moving-average", window=4)
    for v in (2, 4, 6, 8):
        ma.observe(v)
    assert ma.predict() == 5

    ar = make_predictor("ar", window=24)
    for t in range(12):
        ar.observe(10 + 2 * t)  # rising ramp
    assert ar.predict() > 30  # extrapolates the trend past the last value


def test_seasonal_predictor_learns_cycle():
    # Arbitrary repeating daily pattern + slow drift. (A sine would be
    # unfair to compare on: sinusoids satisfy an exact AR(2) recurrence,
    # so the AR baseline is perfect there; real diurnal load is not a
    # sinusoid.)
    period = 24
    rng = np.random.default_rng(0)
    pattern = rng.uniform(20, 150, period)

    def load(t):
        return 100 + 0.2 * t + pattern[t % period]

    sp = make_predictor("seasonal", window=240)
    ar = make_predictor("ar", window=24)
    errs_sp, errs_ar = [], []
    for t in range(6 * period):
        if t >= 4 * period:  # score after warm history exists
            errs_sp.append(abs(sp.predict() - load(t)))
            errs_ar.append(abs(ar.predict() - load(t)))
        sp.observe(load(t))
        ar.observe(load(t))
    # Season auto-discovered and exploited: seasonal beats AR clearly.
    assert sum(errs_sp) < 0.5 * sum(errs_ar)
    assert sp._fitted_m in (period - 1, period, period + 1)

    # Aperiodic series: falls back to AR-quality behaviour, no phantom
    # seasonality (predict stays near the ramp).
    sp2 = make_predictor("seasonal", window=96)
    for t in range(60):
        sp2.observe(10 + 2 * t)
    assert abs(sp2.predict() - 130) < 20


def test_interpolators_and_roundtrip(tmp_path):
    dec = DecodeInterpolator(
        np.array([8, 32, 128]), np.array([10.0, 20.0, 80.0]), np.array([800.0, 1600.0, 3200.0])
    )
    assert dec.itl_at(32) == 20.0
    assert 800 < dec.throughput_at(20) < 1600
    assert dec.max_batch_under_itl(20.0) >= 31.5
    assert dec.best_throughput_under_itl(10.0) <= 810

    pre = PrefillInterpolator(
        np.array([64, 512]), np.array([50.0, 300.0]), np.array([1280.0, 1700.0])
    )
    assert 50 < pre.ttft_at(256) < 300

    path = str(tmp_path / "prof.npz")
    save_profile(path, decode=dec, prefill=pre, meta={"model": "t"})
    d2, p2 = load_profile(path)
    assert d2.itl_at(32) == 20.0 and p2.ttft_at(64) == 50.0


def _make_planner(conn, rates, cfg=None):
    it = iter(rates)

    async def source():
        return PlannerObservation(request_rate=next(it))

    cfg = cfg or PlannerConfig(
        component="backend", predictor="constant", min_replicas=1, max_replicas=8,
        replica_tok_s=1000.0, mean_output_tokens=100.0, scale_down_headroom=1.0,
    )
    return Planner(cfg, conn, source)


def test_planner_scales_up_and_down_with_load():
    async def go():
        conn = RecordingConnector({"backend": 1})
        # rate 5 req/s x 100 tok = 500 tok/s → 1 replica; 35 → 4; 62 → 7; back down.
        planner = _make_planner(conn, [5, 35, 62, 8, 8])
        targets = [await planner.step() for _ in range(5)]
        return targets, conn.calls

    targets, calls = asyncio.run(go())
    assert targets == [1, 4, 7, 1, 1]
    assert ("backend", 4) in calls and ("backend", 7) in calls and ("backend", 1) in calls


def test_planner_respects_bounds_and_hysteresis():
    async def go():
        conn = RecordingConnector({"backend": 4})
        cfg = PlannerConfig(
            component="backend", predictor="constant", min_replicas=2, max_replicas=5,
            replica_tok_s=1000.0, mean_output_tokens=100.0, scale_down_headroom=1.5,
        )
        planner = _make_planner(conn, [100, 33, 0], cfg)
        burst = await planner.step()       # 10000 tok/s → clamped to max 5
        hyst = await planner.step()        # 3300 tok/s fits 4 but x1.5 headroom keeps 5... 3300*1.5=4950 > 4*1000 → holds
        idle = await planner.step()        # 0 → min_replicas
        return burst, hyst, idle

    burst, hyst, idle = asyncio.run(go())
    assert burst == 5
    assert hyst == 5
    assert idle == 2


def test_planner_sla_correction_scales_up_on_slow_itl():
    async def go():
        conn = RecordingConnector({"backend": 2})

        async def source():
            return PlannerObservation(request_rate=20.0, itl_ms=100.0)  # 2x over SLA

        cfg = PlannerConfig(
            component="backend", predictor="constant", min_replicas=1, max_replicas=16,
            replica_tok_s=1000.0, mean_output_tokens=100.0, itl_sla_ms=50.0,
        )
        planner = Planner(cfg, conn, source)
        return await planner.step()

    # base need = 2000/1000 = 2 → ITL correction x2 → 4
    assert asyncio.run(go()) == 4


def test_planner_uses_decode_interpolator_capacity():
    dec = DecodeInterpolator(
        np.array([8, 64]), np.array([10.0, 50.0]), np.array([500.0, 2000.0])
    )

    async def go():
        conn = RecordingConnector({"backend": 1})

        async def source():
            return PlannerObservation(request_rate=30.0)

        cfg = PlannerConfig(
            component="backend", predictor="constant", min_replicas=1, max_replicas=16,
            replica_tok_s=99999.0, mean_output_tokens=100.0, itl_sla_ms=30.0,
            scale_down_headroom=1.0,
        )
        planner = Planner(cfg, conn, source, decode_interp=dec)
        return await planner.step()

    # ITL SLA 30ms → max batch ~36.3 → capacity ~1258 tok/s (not 99999):
    # 3000 tok/s / 1258 → 3 replicas.
    assert asyncio.run(go()) == 3


def test_local_process_connector_scales_real_processes():
    from dynamo_tpu.planner import LocalProcessConnector

    conn = LocalProcessConnector({"backend": ["-c", "import time; time.sleep(60)"]})
    try:
        conn.set_replicas("backend", 3)
        assert conn.get_replicas("backend") == 3
        pids = [p.pid for p in conn._procs["backend"]]
        conn.set_replicas("backend", 1)
        import time

        deadline = time.monotonic() + 5
        while conn.get_replicas("backend") != 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert conn.get_replicas("backend") == 1
        assert conn._procs["backend"][0].pid == pids[0]  # oldest survives
    finally:
        conn.shutdown()
    assert conn.get_replicas("backend") == 0


def test_profile_sweep_cpu(tmp_path):
    """The sweep tool produces a loadable profile on the CPU engine."""
    import subprocess
    import sys
    import os

    out = str(tmp_path / "prof.npz")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, "tools/profile_sweep.py", "--cpu", "--out", out,
         "--batches", "2,4", "--prompt-lens", "16,32", "--gen-len", "8",
         "--decode-steps", "2"],
        cwd=root, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    dec, pre = load_profile(out)
    assert dec is not None and pre is not None
    assert dec.throughput_at(3) > 0 and pre.ttft_at(20) > 0


def test_http_metrics_source_parses_and_rates():
    src = HttpMetricsSource("http://unused")
    text1 = (
        "# TYPE dynamo_tpu_http_requests_total counter\n"
        'dynamo_tpu_http_requests_total{model="m",status="200"} 10\n'
        'dynamo_tpu_http_output_tokens_total{model="m"} 1000\n'
        'dynamo_tpu_http_time_to_first_token_seconds_sum{model="m"} 1.0\n'
        'dynamo_tpu_http_time_to_first_token_seconds_count{model="m"} 10\n'
    )
    parsed = src._parse(text1)
    assert parsed["dynamo_tpu_http_requests_total"] == 10
    # Label-split series sum into one value per name.
    text2 = text1 + 'dynamo_tpu_http_requests_total{model="n",status="200"} 5\n'
    assert src._parse(text2)["dynamo_tpu_http_requests_total"] == 15


def test_disagg_planner_itl_scales_decode_prefill_holds():
    """ITL-SLA breach must scale the decode component while prefill holds
    (reference: planner_core.py:241-276 computes them separately)."""

    async def go():
        conn = RecordingConnector({"backend": 2, "prefill": 2})
        obs = iter([
            PlannerObservation(request_rate=10.0, itl_ms=10.0, ttft_ms=100.0),
            PlannerObservation(request_rate=10.0, itl_ms=40.0, ttft_ms=100.0),  # ITL breach
        ])

        async def source():
            return next(obs)

        cfg = PlannerConfig(
            component="backend", prefill_component="prefill",
            predictor="constant", min_replicas=1, max_replicas=8,
            replica_tok_s=1000.0, mean_output_tokens=100.0,
            mean_input_tokens=200.0, prefill_tok_s=1000.0,
            itl_sla_ms=20.0, ttft_sla_ms=500.0, scale_down_headroom=1.0,
        )
        planner = Planner(cfg, conn, source)
        await planner.step()   # healthy: 1000 tok/s → 1; prefill 2000/1000 → 2
        first = (conn.get_replicas("backend"), conn.get_replicas("prefill"))
        await planner.step()   # ITL 40 > 20 → decode need x2; prefill unchanged
        second = (conn.get_replicas("backend"), conn.get_replicas("prefill"))
        return first, second

    first, second = asyncio.run(go())
    assert first == (1, 2)
    assert second[0] == 2, f"decode should scale on ITL breach, got {second}"
    assert second[1] == 2, f"prefill must hold on ITL breach, got {second}"


def test_disagg_planner_ttft_scales_prefill_decode_holds():
    async def go():
        conn = RecordingConnector({"backend": 1, "prefill": 1})
        obs = iter([
            PlannerObservation(request_rate=5.0, itl_ms=10.0, ttft_ms=100.0),
            PlannerObservation(request_rate=5.0, itl_ms=10.0, ttft_ms=1500.0),  # TTFT breach
        ])

        async def source():
            return next(obs)

        cfg = PlannerConfig(
            component="backend", prefill_component="prefill",
            predictor="constant", min_replicas=1, max_replicas=8,
            replica_tok_s=1000.0, mean_output_tokens=100.0,
            mean_input_tokens=200.0, prefill_tok_s=1000.0,
            itl_sla_ms=50.0, ttft_sla_ms=500.0, scale_down_headroom=1.0,
        )
        planner = Planner(cfg, conn, source)
        await planner.step()
        first = (conn.get_replicas("backend"), conn.get_replicas("prefill"))
        await planner.step()   # TTFT 1500 > 500 → prefill x3; decode holds
        second = (conn.get_replicas("backend"), conn.get_replicas("prefill"))
        return first, second

    first, second = asyncio.run(go())
    assert first == (1, 1)
    assert second[0] == 1, f"decode must hold on TTFT breach, got {second}"
    assert second[1] == 3, f"prefill should scale on TTFT breach, got {second}"


def test_http_metrics_source_parses_itl():
    import time as _time

    src = HttpMetricsSource("http://unused")
    base = (
        'dynamo_tpu_http_requests_total{model="m"} 10\n'
        'dynamo_tpu_http_inter_token_latency_seconds_sum{model="m"} 0.5\n'
        'dynamo_tpu_http_inter_token_latency_seconds_count{model="m"} 10\n'
    )
    later = (
        'dynamo_tpu_http_requests_total{model="m"} 20\n'
        'dynamo_tpu_http_inter_token_latency_seconds_sum{model="m"} 1.1\n'
        'dynamo_tpu_http_inter_token_latency_seconds_count{model="m"} 30\n'
    )
    src._last, src._last_t = src._parse(base), _time.monotonic() - 1.0
    cur = src._parse(later)
    # Reuse the internal delta logic by calling __call__'s math inline:
    ditl_n = cur["dynamo_tpu_http_inter_token_latency_seconds_count"] - 10
    ditl_s = cur["dynamo_tpu_http_inter_token_latency_seconds_sum"] - 0.5
    assert abs(ditl_s / ditl_n * 1000 - 30.0) < 1e-6  # 0.6s over 20 obs = 30ms


def test_kubernetes_connector_scale_calls(monkeypatch):
    """KubernetesConnector issues GET/PATCH on the scale subresource
    (reference: planner/kubernetes_connector.py + kube.py)."""
    import httpx

    from dynamo_tpu.planner.connector import KubernetesConnector

    calls = []

    def fake_get(url, headers=None, verify=None, timeout=None):
        calls.append(("GET", url))
        return httpx.Response(200, json={"spec": {"replicas": 3}},
                              request=httpx.Request("GET", url))

    def fake_patch(url, headers=None, content=None, verify=None, timeout=None):
        calls.append(("PATCH", url, content))
        return httpx.Response(200, json={},
                              request=httpx.Request("PATCH", url))

    monkeypatch.setattr(httpx, "get", fake_get)
    monkeypatch.setattr(httpx, "patch", fake_patch)
    conn = KubernetesConnector(
        namespace="serving", deployment_of={"backend": "dynamo-tpu-worker"},
        api_base="https://api", token="tok", verify=False,
    )
    assert conn.get_replicas("backend") == 3
    conn.set_replicas("backend", 5)
    assert calls[0][1].endswith("/namespaces/serving/deployments/dynamo-tpu-worker/scale")
    method, url, content = calls[1]
    assert method == "PATCH" and '"replicas": 5' in content


def test_plan_disagg_pools_goodput_split():
    """DistServe-style static split: the integer allocation equalizes
    per-pool REQUEST rates under the profiled SLA operating points."""
    from dynamo_tpu.planner.interpolate import plan_disagg_pools

    # Decode: flat 10ms ITL up to batch 32, 2000 tok/s there.
    dec = DecodeInterpolator(
        np.array([1, 16, 32]), np.array([5.0, 8.0, 10.0]),
        np.array([100.0, 1200.0, 2000.0]),
    )
    # Prefill: 8000 tok/s at 512-token prompts, 60ms TTFT.
    pre = PrefillInterpolator(
        np.array([128, 512, 2048]), np.array([20.0, 60.0, 200.0]),
        np.array([6000.0, 8000.0, 9000.0]),
    )
    plan = plan_disagg_pools(
        10, dec, pre, prompt_len=512, gen_len=128,
        itl_sla_ms=10.0, ttft_sla_ms=100.0,
    )
    assert plan["prefill_workers"] + plan["decode_workers"] == 10
    assert plan["prefill_workers"] >= 1 and plan["decode_workers"] >= 1
    # decode worker serves 2000/128 = 15.6 rps; prefill 8000/512 = 15.6
    # rps -> even split maximizes min() goodput.
    assert plan["prefill_workers"] == 5
    assert plan["goodput_rps"] > 0
    assert plan["ttft_feasible"] is True
    # A decode-heavy workload (short prompts, long generations) shifts
    # the split toward decode.
    plan2 = plan_disagg_pools(
        10, dec, pre, prompt_len=128, gen_len=512, itl_sla_ms=10.0,
    )
    assert plan2["decode_workers"] > plan2["prefill_workers"]

    import pytest as _pytest

    with _pytest.raises(ValueError):
        plan_disagg_pools(1, dec, pre, prompt_len=128, gen_len=128, itl_sla_ms=10.0)


def test_planner_initial_pool_split():
    dec = DecodeInterpolator(
        np.array([1, 32]), np.array([5.0, 10.0]), np.array([100.0, 2000.0])
    )
    pre = PrefillInterpolator(
        np.array([128, 2048]), np.array([20.0, 200.0]),
        np.array([6000.0, 9000.0]),
    )
    cfg = PlannerConfig(
        component="backend", prefill_component="prefill",
        mean_input_tokens=512.0, mean_output_tokens=128.0, itl_sla_ms=10.0,
    )
    conn = RecordingConnector({"backend": 1, "prefill": 1})

    async def source():
        return PlannerObservation()

    planner = Planner(cfg, conn, source, decode_interp=dec, prefill_interp=pre)
    split = planner.initial_pool_split(8)
    assert split["prefill_workers"] + split["decode_workers"] == 8
    import pytest as _pytest

    bare = Planner(PlannerConfig(), conn, source)
    with _pytest.raises(ValueError):
        bare.initial_pool_split(8)
