"""Tier-1 guard for ``bench.py --disagg``: the A/B harness (aggregated
engine vs prefill+decode pair over the streaming KV data plane) must run
end to end at smoke shapes, keep byte-identical output streams, actually
send every long prompt remote, and report the transfer accounting keys
the BENCH_* trajectory depends on.

No timing assertions: --quick makes no throughput claims.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_disagg_quick_smoke():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--disagg", "--quick"],
        capture_output=True, text=True, timeout=420,
        env=dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu"),
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, proc.stdout + proc.stderr[-2000:]
    result = json.loads(lines[-1])
    assert "error" not in result, result
    # Chunked-streaming output pinned byte-identical to aggregated.
    assert result["parity"] is True
    # The A/B measured the disagg path, not an all-fallback run.
    assert result["remote_prefills"] > 0
    assert result["transfer_bytes"] > 0
    # The trajectory keys bench rounds compare.
    for key in ("aggregated_tok_s", "disagg_vs_aggregated",
                "ttft_p99_ms_disagg", "transfer_overlap_frac"):
        assert key in result, key
