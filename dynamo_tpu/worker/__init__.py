"""Worker: hosts an inference engine (TPU or mocker) on the runtime.

Reference analogue: the engine worker CLIs — ``python -m dynamo.vllm`` /
``dynamo.mocker`` (reference: components/backends/vllm/src/dynamo/vllm/
main.py:65-88, components/backends/mocker/src/dynamo/mocker/main.py) —
except the engine is in-repo, so one worker hosts either the real
TpuEngine or the CPU mocker.
"""
