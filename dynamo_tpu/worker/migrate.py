"""Live sequence migration: worker-side coordinator + receiver.

Relocates an IN-FLIGHT decode between two engines with zero client
impact: the source keeps decoding while its KV streams in chunks over
the PR 8 credit-flow transfer plane (the same ``kv_fetch`` windowed
pull disagg uses — int8 scales ride along per chunk), then a bounded
cutover window freezes the sequence, ships the delta pages plus the
full resume identity (tokens, sampler seed/step, spec EMA, grammar
state, adapter, prompt boundary), and the destination resumes the SAME
client stream byte-identically (the Migration operator consumes the
handoff marker and re-dispatches pinned to the destination).

Three phases, each with its own failure fallback — every failure mode
degrades to a COMPLETED stream, never a client-visible error:

- **streaming** — source publishes full KV blocks as the decode writes
  them; destination pulls concurrently. Source/dest/store death here
  aborts the migration and the source just keeps decoding.
- **cutover** — source freezes the sequence (out of the batch, slot and
  KV retained), force-drains pending device tokens, publishes the delta
  since the stream cursor and seals. If the destination never confirms
  the commit inside the freeze window, the source unfreezes and decodes
  on; if the coordinator itself dies, the engine's freeze deadline
  unfreezes the sequence autonomously.
- **rebind** — the source posts the ``{"migration": ...}`` marker; the
  frontend's Migration operator re-dispatches pinned to the
  destination and the router rebinds stickiness atomically on the
  destination's first frame. A dead store pins with ``rebind: False``
  (no decision-cache write against a store that can't take it); a
  destination that dies after committing simply misses its staged
  inject — the resume identity rides the request, so ANY worker can
  serve the leg by re-prefilling, still byte-identical.

``chaos.maybe_cut_migration(phase)`` (runtime/chaos.py) injects a
seeded victim — source, dest, or store — at each phase boundary, which
is how tests/test_migration_live.py pins every cell of the failure
matrix (docs/robustness.md).
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from typing import Any

from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import TraceContext, get_logger
from dynamo_tpu.transfer.stream import (
    DEFAULT_CREDIT_BYTES,
    CreditBudget,
    inject_payload_from_chunks,
    process_credit_budget,
    pull_kv_stream,
)

log = get_logger("worker.migrate")

# Streaming is "caught up" when the stream cursor trails the KV write
# head by at most this many blocks — the cutover delta stays tiny.
DEFAULT_LAG_BLOCKS = 2
# How long the source waits for the stream to catch up before giving up
# (the sequence keeps decoding the whole time, so this only bounds the
# migration attempt, never the request).
DEFAULT_STREAM_TIMEOUT_S = 30.0
# Staged injects the destination holds for a resume leg that never
# arrives (frontend died between commit and re-dispatch) are reaped
# after this long.
DEFAULT_STAGE_TTL_S = 120.0
# Bandwidth pacing (ISSUE 19 tentpole (c)): at most this many outbound
# migrations may stream concurrently per engine. The balancer issues
# one move per cycle, but pool moves/retirement fan out over the whole
# running batch — without the cap those N concurrent streams contend
# with the disagg KV plane for the same egress.
DEFAULT_MAX_OUTBOUND = 2


class MigrationError(Exception):
    """Typed failure of one migration attempt. Never propagates to a
    client: the coordinator aborts engine-side (the sequence resumes
    decoding locally) and answers ``{"ok": False, "reason"}``."""


def register_migration_metrics(registry) -> dict:
    """The live-migration observability series (DT006-cataloged) —
    registered by the worker runtime and by the catalog guard."""
    return {
        "attempts": registry.counter(
            "migration_attempts_total",
            "Live migration attempts by outcome (ok | fallback | noop | paced)",
        ),
        "fallbacks": registry.counter(
            "migration_fallback_total",
            "Live migrations abandoned to in-place decode, by reason",
        ),
        "bytes": registry.counter(
            "migration_kv_bytes_total",
            "KV bytes received by migration destinations over the stream plane",
        ),
        "cutover_gap": registry.histogram(
            "migration_cutover_gap_seconds",
            "Source freeze to destination commit-ack wall time per migration",
        ),
        "inflight": registry.gauge(
            "migration_inflight",
            "Migrations this worker is currently driving as the source",
        ),
        "outbound_inflight": registry.gauge(
            "migration_outbound_inflight",
            "Outbound migrations currently STREAMING from this worker "
            "(the bandwidth-pacing cap applies to this gauge)",
        ),
    }


class MigrationCoordinator:
    """Source-side driver of one worker's outbound migrations.

    ``engine`` is the local TpuEngine (all engine mutations ship to the
    scheduler thread via ``run_on_engine_thread``); ``admin_router`` is
    a DIRECT PushRouter on ``workerctl/admin`` (the same RPC surface the
    autoscaler actuates through); ``component`` / ``source_instance``
    tell the destination where to pull our ``kv_fetch`` endpoint."""

    def __init__(self, engine, admin_router, component: str,
                 source_instance: int, chaos=None, metrics: dict | None = None,
                 lag_blocks: int = DEFAULT_LAG_BLOCKS,
                 stream_timeout_s: float = DEFAULT_STREAM_TIMEOUT_S,
                 max_outbound: int = DEFAULT_MAX_OUTBOUND):
        self.engine = engine
        self.admin_router = admin_router
        self.component = component
        self.source_instance = source_instance
        self.chaos = chaos
        self.metrics = metrics
        self.lag_blocks = lag_blocks
        self.stream_timeout_s = stream_timeout_s
        # Bandwidth pacing: concurrent outbound migrations beyond the
        # cap answer typed {"ok": False, "reason": "paced"} instead of
        # opening another stream (callers retry or keep the sequence).
        self.max_outbound = max(int(max_outbound), 1)
        self._outbound = 0
        # In-process ledgers (tests/bench assert against these; the
        # metrics dict mirrors them when bound).
        self.outcomes: dict[str, int] = {}
        self.fallback_reasons: dict[str, int] = {}

    # -- bookkeeping --------------------------------------------------------

    def _outcome(self, outcome: str) -> None:
        self.outcomes[outcome] = self.outcomes.get(outcome, 0) + 1
        if self.metrics is not None:
            self.metrics["attempts"].inc(outcome=outcome)

    def _fallback(self, reason: str) -> None:
        self.fallback_reasons[reason] = self.fallback_reasons.get(reason, 0) + 1
        if self.metrics is not None:
            self.metrics["fallbacks"].inc(reason=reason)

    def _chaos_victim(self, phase: str, trace: TraceContext | None = None) -> str | None:
        if self.chaos is None:
            return None
        victim = self.chaos.maybe_cut_migration(phase)
        if victim is not None and trace is not None:
            # Bind the injection to the MIGRATING request's trace (the
            # admin RPC's ambient trace is the planner's, not the
            # victim's) so its ledger record carries the fault.
            with contextlib.suppress(Exception):
                tracing.recorder().note_injection(
                    trace.trace_id, f"migration_cut:{phase}:{victim}"
                )
        return victim

    # -- the protocol -------------------------------------------------------

    async def migrate_out(self, request_id: str, dest_instance: int) -> dict:
        """Relocate one running decode to ``dest_instance``. Always
        answers typed — ``{"ok": True, "handle"}`` on a completed
        handoff, ``{"ok": False, "reason"}`` on any fallback (the
        sequence then simply keeps decoding here)."""
        eng = self.engine
        if dest_instance == self.source_instance:
            self._outcome("noop")
            return {"ok": False, "reason": "self"}
        if self._outbound >= self.max_outbound:
            # Pacing cap: refuse typed rather than queue — a queued move
            # would actuate against stale load scores, and the caller
            # (balancer, pool move loop) re-plans from live state anyway.
            self._outcome("paced")
            return {"ok": False, "reason": "paced"}
        self._outbound += 1
        if self.metrics is not None:
            self.metrics["inflight"].add(1)
            self.metrics["outbound_inflight"].set(self._outbound)
        begun = False
        trace: TraceContext | None = None
        mspan = tracing.NOOP_SPAN
        try:
            # -- phase: streaming -------------------------------------------
            victim = self._chaos_victim("streaming")
            if victim is not None:
                raise MigrationError(f"chaos:streaming:{victim}")
            res = await eng.run_on_engine_thread(
                lambda: eng.migration_begin(request_id)
            )
            if res.get("error"):
                self._outcome("noop")
                return {"ok": False, "reason": res["error"]}
            begun = True
            handle = res["handle"]
            # Join the CLIENT REQUEST's trace, not a coordinator-local
            # root: the engine stamps every running sequence with its
            # traceparent at submit and hands it back from
            # migration_begin, so the source's admin RPCs and the
            # destination's pull all stitch into the original tree.
            if res.get("traceparent"):
                with contextlib.suppress(Exception):
                    trace = TraceContext.parse(str(res["traceparent"]))
            mspan = tracing.start_span_if(
                trace, "migration.out",
                request_id=request_id, dest=str(dest_instance),
            )
            if mspan.recording:
                trace = mspan.trace_context()
            start_payload = {
                "cmd": "migrate_in_start",
                "handle": handle,
                "source_component": self.component,
                "source_instance": self.source_instance,
            }
            if trace is not None:
                start_payload["traceparent"] = trace.traceparent()
            await self._admin(dest_instance, start_payload, trace=trace)
            await self._await_caught_up(request_id)

            # -- phase: cutover ---------------------------------------------
            victim = self._chaos_victim("cutover", trace)
            if victim == "source":
                raise MigrationError("chaos:cutover:source")
            cut = await eng.run_on_engine_thread(
                lambda: eng.migration_cutover(request_id)
            )
            if cut.get("error"):
                if cut["error"] == "done":
                    # The force-drain finished the sequence in place —
                    # the client has its complete stream; nothing to move.
                    begun = False
                    self._outcome("noop")
                    mspan.set_attrs(outcome="finished")
                    mspan.end()
                    return {"ok": False, "reason": "finished"}
                raise MigrationError(f"cutover:{cut['error']}")
            t_freeze = time.monotonic()
            if victim is not None and victim != "source":
                # dest/store died mid-cutover: the commit can never
                # confirm — unfreeze and decode on.
                raise MigrationError(f"chaos:cutover:{victim}")
            ack = await self._admin(dest_instance, {
                "cmd": "migrate_in_commit",
                "handle": handle,
                "kv_blocks": cut["kv_blocks"],
            }, trace=trace)
            gap = time.monotonic() - t_freeze

            # -- phase: rebind ----------------------------------------------
            rebind = True
            victim = self._chaos_victim("rebind", trace)
            if victim == "source":
                # Source dying here would truncate the client stream —
                # the Migration operator's re-dispatch completes it. The
                # injected stand-in keeps the sequence alive locally
                # (same client outcome, no stream cut to engineer).
                raise MigrationError("chaos:rebind:source")
            if victim == "store":
                # No decision-cache write against a dead store: the
                # destination pin rides the request itself.
                rebind = False
            if victim == "dest":
                # Destination died after committing: its staged inject
                # is gone, but the resume identity rides the request —
                # the pinned leg falls through to any live worker and
                # re-prefills, still byte-identical.
                with contextlib.suppress(MigrationError):
                    await self._admin(dest_instance, {
                        "cmd": "migrate_in_abort", "handle": handle,
                    }, trace=trace)
            marker: dict[str, Any] = {
                "handle": handle,
                "dest_instance": dest_instance,
                "request": cut["request"],
            }
            if not rebind:
                marker["rebind"] = False
            fin = await eng.run_on_engine_thread(
                lambda: eng.migration_finish(request_id, marker)
            )
            if fin.get("error"):
                # The freeze deadline (or a racing finish) already tore
                # the migration down — the sequence is decoding locally.
                raise MigrationError(f"finish:{fin['error']}")
            if self.metrics is not None:
                self.metrics["cutover_gap"].observe(gap)
            mspan.set_attrs(outcome="ok", kv_blocks=cut["kv_blocks"],
                            cutover_gap_ms=round(gap * 1e3, 3))
            mspan.end()
            self._outcome("ok")
            log.info(
                "migrated %s → %x (%d KV blocks, cutover gap %.1f ms)",
                request_id, dest_instance, cut["kv_blocks"], gap * 1e3,
            )
            return {"ok": True, "handle": handle,
                    "kv_blocks": cut["kv_blocks"],
                    "kv_bytes": int(ack.get("total_bytes", 0)),
                    "cutover_gap_s": gap}
        except MigrationError as e:
            reason = str(e)
            mspan.set_attrs(outcome="fallback", reason=reason)
            mspan.end(status="error")
            if begun:
                await eng.run_on_engine_thread(
                    lambda: eng.migration_abort(request_id, reason)
                )
            self._outcome("fallback")
            self._fallback(reason)
            log.warning(
                "migration of %s → %x fell back (%s); decoding in place",
                request_id, dest_instance, reason,
            )
            return {"ok": False, "reason": reason}
        finally:
            mspan.end()  # idempotent — closes the span on surprise exits
            self._outbound -= 1
            if self.metrics is not None:
                self.metrics["inflight"].add(-1)
                self.metrics["outbound_inflight"].set(self._outbound)

    async def _await_caught_up(self, request_id: str) -> None:
        """Poll until the stream cursor trails the KV write head by at
        most ``lag_blocks`` — the cutover delta is then bounded."""
        eng = self.engine
        deadline = time.monotonic() + self.stream_timeout_s
        while True:
            st = await eng.run_on_engine_thread(
                lambda: eng.migration_status(request_id)
            )
            if st.get("error"):
                raise MigrationError(f"stream:{st['error']}")
            if st.get("aborted"):
                raise MigrationError(f"stream:{st['aborted']}")
            if st["written"] - st["published"] <= self.lag_blocks:
                return
            if time.monotonic() >= deadline:
                raise MigrationError("stream_lag")
            await asyncio.sleep(0.01)

    async def _admin(self, instance_id: int, payload: dict,
                     trace: TraceContext | None = None) -> dict:
        """One admin RPC to the destination; transport faults and error
        frames both become the typed MigrationError fallback. ``trace``
        stitches the hop into the migrating request's span tree."""
        last: dict = {}
        try:
            async for frame in self.admin_router.generate(
                dict(payload), Context(trace=trace), instance_id=instance_id
            ):
                if isinstance(frame, dict):
                    last = frame
        except Exception as e:  # noqa: BLE001 — a dead/unreachable destination is an expected fallback, surfaced typed
            raise MigrationError(
                f"dest_rpc:{payload.get('cmd')}:{type(e).__name__}"
            ) from e
        if last.get("error"):
            raise MigrationError(f"dest:{payload.get('cmd')}:{last['error']}")
        return last


class MigrationReceiver:
    """Destination-side: pulls the source's KV chunk stream while the
    source still decodes, then stages the assembled inject payload under
    the migration handle for the resume leg to claim at admission."""

    def __init__(self, rt, namespace: str, chaos=None, metrics: dict | None = None,
                 credit_bytes: int = DEFAULT_CREDIT_BYTES,
                 stall_timeout_s: float = 20.0, window_wait_s: float = 2.0,
                 stage_ttl_s: float = DEFAULT_STAGE_TTL_S,
                 fetch_endpoint: str = "kv_fetch",
                 budget: CreditBudget | None = None):
        self.rt = rt
        self.namespace = namespace
        self.chaos = chaos
        self.metrics = metrics
        self.credit_bytes = credit_bytes
        # Migration pulls ride the BACKGROUND tier of the shared credit
        # budget: each window's credit shrinks while disagg prefill
        # pulls (the priority tier) hold credit, so rebalancing never
        # starves the TTFT-critical plane.
        self.budget = process_credit_budget() if budget is None else budget
        self.stall_timeout_s = stall_timeout_s
        self.window_wait_s = window_wait_s
        self.stage_ttl_s = stage_ttl_s
        self.fetch_endpoint = fetch_endpoint
        self._pulls: dict[str, asyncio.Task] = {}
        self._staged: dict[str, tuple[dict, float]] = {}
        self._routers: dict[str, Any] = {}

    async def _fetch_router(self, component: str):
        router = self._routers.get(component)
        if router is None:
            from dynamo_tpu.runtime.push_router import RouterMode

            router = await (
                self.rt.namespace(self.namespace)
                .component(component)
                .endpoint(self.fetch_endpoint)
                .router(RouterMode.DIRECT)
            )
            self._routers[component] = router
        return router

    async def start_pull(self, handle: str, source_component: str,
                         source_instance: int,
                         traceparent: str | None = None) -> dict:
        """Begin pulling the migration stream in the background (the
        source is still decoding — this overlaps the transfer with the
        remaining generation, the same push-on-ready shape as disagg).
        ``traceparent`` joins the pull's spans to the migrating
        request's trace (the coordinator forwards it from the source
        engine's sequence stamp)."""
        self._reap()
        if handle in self._pulls or handle in self._staged:
            return {"ok": True}
        router = await self._fetch_router(source_component)
        trace: TraceContext | None = None
        if traceparent:
            with contextlib.suppress(Exception):
                trace = TraceContext.parse(str(traceparent))

        def window_call(cursor: int, credit: int, wait_s: float):
            return router.generate(
                {"handle": handle, "stream": True, "cursor": cursor,
                 "credit_bytes": credit, "wait_s": wait_s},
                Context(trace=trace), instance_id=source_instance,
            )

        async def pull():
            # The destination lane's transfer phase: same span name the
            # disagg KV pull records, so the stitched timeline shows
            # migration transfers with identical semantics.
            span = tracing.start_span_if(trace, "transfer.kv_pull",
                                         handle=handle, kind="migration")
            try:
                pulled = await pull_kv_stream(
                    window_call,
                    credit_bytes=self.credit_bytes,
                    stall_timeout_s=self.stall_timeout_s,
                    window_wait_s=self.window_wait_s,
                    budget=self.budget,
                    budget_kind="migration",
                )
            except BaseException:
                span.end(status="error")
                raise
            span.set_attrs(blocks=pulled.num_blocks, bytes=pulled.total_bytes)
            span.end()
            return pulled

        self._pulls[handle] = asyncio.get_running_loop().create_task(pull())
        return {"ok": True}

    async def commit(self, handle: str, kv_blocks: int) -> dict:
        """Cutover confirm: the stream is sealed — finish the pull,
        verify full coverage, and stage the inject. An error answer here
        makes the SOURCE unfreeze and keep the sequence (the commit is
        the migration's point of no return)."""
        task = self._pulls.pop(handle, None)
        if task is None:
            return {"error": f"unknown migration handle {handle!r}"}
        try:
            pulled = await asyncio.wait_for(task, self.stall_timeout_s)
        except asyncio.TimeoutError:
            task.cancel()
            with contextlib.suppress(BaseException):
                await task
            return {"error": "pull_timeout"}
        except Exception as e:  # noqa: BLE001 — any data-plane failure (abort, stall, truncation) answers typed; the source keeps the sequence
            return {"error": f"pull:{type(e).__name__}: {e}"}
        if pulled.num_blocks < int(kv_blocks or 0):
            # A short stream would leave a KV gap at admission — refuse,
            # the source decodes on.
            return {"error": f"short_stream:{pulled.num_blocks}<{kv_blocks}"}
        if self.metrics is not None:
            self.metrics["bytes"].inc(pulled.total_bytes)
        self._staged[handle] = (
            inject_payload_from_chunks(pulled),
            time.monotonic() + self.stage_ttl_s,
        )
        return {"ok": True, "num_blocks": pulled.num_blocks,
                "total_bytes": pulled.total_bytes}

    async def abort(self, handle: str) -> dict:
        task = self._pulls.pop(handle, None)
        if task is not None:
            task.cancel()
            with contextlib.suppress(BaseException):
                await task
        self._staged.pop(handle, None)
        return {"ok": True}

    def take(self, handle: str) -> dict | None:
        """Claim the staged inject for a resume leg at admission (one
        shot). None when unknown/expired — the leg then re-prefills from
        its own tokens, which is correct on any worker."""
        self._reap()
        item = self._staged.pop(handle, None)
        return item[0] if item is not None else None

    def _reap(self) -> None:
        now = time.monotonic()
        for h in [h for h, (_, dl) in self._staged.items() if dl < now]:
            log.warning("staged migration inject %s expired unclaimed", h)
            self._staged.pop(h, None)

    async def close(self) -> None:
        for h in list(self._pulls):
            await self.abort(h)
        self._staged.clear()
