"""Worker CLI: `python -m dynamo_tpu.worker`.

Boots the engine, serves the ``generate`` endpoint plus the KV-event and
load-metrics endpoints, and registers the model card — the frontend
discovers the model via the store watch
(reference worker startup flow: components/backends/vllm/src/dynamo/vllm/
main.py:65-223).
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import os
import signal

from dynamo_tpu.kv_router.publisher import KvEventBroadcaster, serve_kv_endpoints
from dynamo_tpu.llm.model_card import ModelDeploymentCard, register_model
from dynamo_tpu.llm.tokenizer import ByteTokenizer, load_tokenizer
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("worker")


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="dynamo_tpu.worker")
    p.add_argument("--store-url", default=None)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--model-name", default=None, help="served model name (defaults to preset name)")
    p.add_argument("--engine", choices=["tpu", "mocker"], default="tpu")
    p.add_argument("--preset", default="llama-1b", help="model preset (engine=tpu, random weights)")
    p.add_argument(
        "--model-path", default=None,
        help="local HF checkpoint dir (config.json + *.safetensors + tokenizer.json); "
             "overrides --preset with real weights",
    )
    p.add_argument("--tokenizer", default="byte", help='"byte" or "hf:<path>" (defaults to hf:<model-path> when --model-path is set)')
    p.add_argument("--context-length", type=int, default=None)
    p.add_argument("--migration-limit", type=int, default=0)
    # disaggregated prefill/decode (reference: --is-prefill-worker,
    # components/backends/vllm/src/dynamo/vllm/main.py:65-88)
    p.add_argument("--is-prefill-worker", action="store_true",
                   help="serve prefill-only + kv_fetch; no model card (run with --component prefill)")
    p.add_argument("--disagg", choices=["auto", "on", "off"], default="auto",
                   help="disaggregated prefill/decode as the serving shape: "
                        "auto (default) wires the decode-side disagg handler on "
                        "every TPU worker — with no prefill fleet discovered it "
                        "costs one set lookup per long prompt and serves "
                        "aggregated; off restores the bare engine")
    p.add_argument("--remote-prefill", action="store_true",
                   help="alias for --disagg on (kept for compatibility)")
    p.add_argument("--no-disagg-stream", action="store_true",
                   help="legacy one-shot KV pull after prefill instead of the "
                        "streaming data plane (dynamo_tpu/transfer)")
    p.add_argument("--prefill-component", default="prefill")
    p.add_argument("--max-local-prefill-length", type=int, default=512,
                   help="prompts with more uncached tokens than this prefill remotely")
    p.add_argument("--prefill-dispatch", choices=["queue", "push"], default="queue",
                   help="queue = competing-consumer work queue (reference behaviour); "
                        "push = round-robin RPC to a prefill worker")
    # Closed-loop autoscaler (docs/autoscaler.md): "on" hands endpoint/
    # card wiring to the WorkerRoleManager so the operator can MOVE this
    # engine between the prefill and decode pools at runtime (admin RPC,
    # drain-ordered) and retire it with zero downtime. "off" (default)
    # is the exact pre-autoscaler wiring — serving is byte-identical.
    p.add_argument("--autoscaler", choices=["on", "off"], default="off",
                   help="register with the closed-loop SLA autoscaler: "
                        "live pool moves + zero-downtime retirement via "
                        "the workerctl admin endpoint")
    p.add_argument("--autoscaler-role", choices=["decode", "prefill"], default=None,
                   help="initial pool under --autoscaler on (default: decode, "
                        "or prefill when --is-prefill-worker is set)")
    p.add_argument("--sla-profile", default=None,
                   help="profiled SLA npz (tools/profile_sweep.py) shipped "
                        "inside this worker's model card so frontends and "
                        "the planner discover the latency curves instead of "
                        "needing a --qos-profile path")
    # engine shape knobs
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--num-kv-blocks", type=int, default=2048)
    p.add_argument("--max-num-seqs", type=int, default=16)
    p.add_argument("--max-model-len", type=int, default=2048)
    p.add_argument("--decode-steps", type=int, default=8)
    # Decode-window pipelining: max windows dispatched-but-unfetched (0 =
    # unpipelined; fetches still start async). Stops are discovered up to
    # this many windows late (≤ depth × decode-steps wasted tokens).
    p.add_argument("--pipeline-depth", type=int, default=2)
    # Prefill T-bucket ladder: "fine" (1.5x midpoints ≤512), "coarse"
    # (legacy 2x/4x, fewest compiles) or an explicit comma list.
    p.add_argument("--prefill-buckets", default="fine")
    p.add_argument("--no-prefill-tail-split", action="store_true",
                   help="disable splitting padded prefill tails into smaller buckets")
    # Streaming delta coalescing (both engines): cap on tokens merged into
    # one wire frame when a stream's consumer lags (0 = one frame per
    # decode window), and an optional bounded gather wait in ms (adds up
    # to that much ITL; keep <= one decode step).
    p.add_argument("--delta-max-tokens", type=int, default=64)
    p.add_argument("--delta-max-ms", type=float, default=0.0)
    # Speculative decoding: n-gram prompt-lookup drafts verified in one
    # batched forward per pass (engine/drafter.py + model.spec_verify).
    # 0 = off. Greedy output is byte-identical to the dense path; sampled
    # requests keep their exact distribution via rejection sampling. A
    # per-sequence acceptance EMA auto-disables speculation on
    # incompressible streams.
    p.add_argument("--spec-tokens", type=int, default=0,
                   help="max draft tokens verified per speculative pass (0 = off)")
    p.add_argument("--spec-ngram", type=int, default=3,
                   help="n-gram match length for the prompt-lookup drafter")
    p.add_argument("--spec-stepwise", action="store_true",
                   help="verify drafts with a stepwise scan (bitwise parity "
                        "with the dense path; forfeits the single-weight-"
                        "stream win) instead of the fused single-pass forward")
    p.add_argument("--spec-tree-width", type=int, default=1,
                   help="max draft-tree branching factor (1 = linear drafts; "
                        ">= 2 enables SpecInfer-style tree verification with "
                        "the topology-masked kernel + Lookahead Jacobi pool)")
    p.add_argument("--spec-tree-depth", type=int, default=0,
                   help="max draft-tree path depth (0 = spec-tokens)")
    p.add_argument("--spec-budget", choices=["adaptive", "uniform"],
                   default="adaptive",
                   help="per-pass draft-node allocation: adaptive moves nodes "
                        "from acceptance-EMA-cold rows to hot ones under the "
                        "fixed batch budget (rows x spec-tokens); uniform = "
                        "every row gets spec-tokens (the pre-r11 behavior)")
    # Multi-LoRA multiplexing (engine/lora.py): serve MANY fine-tunes of
    # the base model on this one engine. --lora-slots sizes the HBM
    # adapter bank (0 = off); --lora registers adapters (repeatable,
    # NAME[:RANK[:SEED]]), each published as its own served model whose
    # requests decode under the adapter — base and adapter rows share
    # every batch via the gathered LoRA matmul. More adapters than slots
    # page through the G2/G3 tier economy on demand.
    p.add_argument("--qos-sched", choices=["on", "off"], default="on",
                   help="class-aware engine scheduling: admission and "
                        "KV-pressure preemption ordered by (priority "
                        "class, age). No-priority traffic is byte-"
                        "identical either way; off pins one class "
                        "(docs/qos.md)")
    p.add_argument("--lora-slots", type=int, default=0,
                   help="device-resident LoRA adapter slots (0 = LoRA off)")
    p.add_argument("--lora-rank", type=int, default=8,
                   help="static adapter bank rank (max over registered adapters)")
    p.add_argument("--lora", action="append", default=[], metavar="NAME[:RANK[:SEED]]",
                   help="register one adapter served as model NAME (repeatable)")
    p.add_argument("--attn-impl", choices=["auto", "xla", "pallas", "pallas_interpret"],
                   default="auto", help="attention backend (ops/paged_attention.py)")
    p.add_argument("--quant", choices=["none", "int8"], default="none",
                   help="weight format (int8 = weight-only quantization, engine/quant.py)")
    p.add_argument("--kv-quant", choices=["none", "int8"], default="none",
                   help="paged KV cache storage (int8 = quantized pages + "
                        "per-position-per-head scales; ~2x num_kv_blocks in "
                        "the same HBM, half the tier/transfer bytes)")
    p.add_argument("--host-kv-blocks", type=int, default=0,
                   help="G2 host-RAM KV tier capacity in blocks (0 = off)")
    p.add_argument("--disk-kv-dir", default=None, help="G3 disk KV tier directory")
    p.add_argument("--disk-kv-blocks", type=int, default=4096)
    p.add_argument("--fleet-kv-dir", default=None,
                   help="G4 fleet-SHARED KV pool directory (mounted by "
                        "every engine; salted-hash-keyed files dedup "
                        "across the fleet, block_manager/tiers.py)")
    p.add_argument("--fleet-kv-blocks", type=int, default=16384)
    p.add_argument("--kv-pressure-offer", type=float, default=0.0,
                   help="pool-usage fraction above which the engine "
                        "proactively OFFERS its cheapest running sequence "
                        "for migration before preemption is forced "
                        "(0 = off; the offer reuses the same "
                        "migration_offer hook as the preemption-boundary "
                        "grace window, docs/autoscaler.md#fleet-balancer)")
    p.add_argument("--kv-directory", choices=["on", "off"], default="off",
                   help="publish this engine's KV block residency to the "
                        "global prefix directory (fleet/directory.py) so "
                        "frontends can price transfer-vs-recompute and "
                        "the autoscaler sees cache heat")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--seed", type=int, default=0)
    # DP-attention: one worker PROCESS per dp rank, all serving the same
    # model behind the router — rank separation is process separation, so
    # no collective spans ranks and a dead rank loses only its own KV
    # (reference: one dynamo worker per vLLM dp_rank,
    # components/backends/vllm/launch/dsr1_dep.sh:86-105; per-rank port
    # math args.py:170-203). `--dp-size N` alone spawns and supervises N
    # rank processes; `--dp-rank i` marks one rank (set by the spawner).
    p.add_argument("--dp-size", type=int, default=1)
    p.add_argument("--dp-rank", type=int, default=None)
    p.add_argument("--dp-base-port", type=int, default=29600,
                   help="first port of the per-rank port blocks (dp_rank_ports)")
    p.add_argument("--dp-chips-per-rank", type=int, default=0,
                   help="pin TPU_VISIBLE_CHIPS=[r*k, (r+1)*k) per rank (0 = no pinning)")
    p.add_argument("--dp-restart", action="store_true",
                   help="restart a crashed dp rank with jittered exponential "
                        "backoff (fleet supervision hygiene, "
                        "dynamo_tpu/fleet/supervisor.py) instead of letting "
                        "the slot stay down until the spawner exits")
    # multi-host: ONE logical worker spanning several processes/hosts.
    # Launch one process per host; process 0 serves the endpoint, the
    # rest replay its dispatch stream (engine/runner.py). All processes
    # need identical model/shape flags; --tp counts GLOBAL devices.
    # (reference analogue: per-node engine ranks under NCCL/MPI --
    # components/backends/sglang/slurm_jobs/submit_job_script.py)
    p.add_argument("--dist-num-processes", type=int, default=1)
    p.add_argument("--dist-process-id", type=int, default=0)
    p.add_argument("--dist-coordinator", default="127.0.0.1:29500",
                   help="jax.distributed coordinator host:port (process 0's host)")
    p.add_argument("--dist-step-addr", default=None,
                   help="leader step-stream addr (default: coordinator host, port+1)")
    # mocker timing
    p.add_argument("--mocker-ttft-ms", type=float, default=20.0)
    p.add_argument("--mocker-itl-ms", type=float, default=5.0)
    p.add_argument("--mocker-speedup", type=float, default=1.0)
    p.add_argument("--mocker-delta-tokens", type=int, default=1,
                   help="tokens per simulated decode window (mirror engine decode_steps)")
    args = p.parse_args(argv)
    if args.remote_prefill:
        args.disagg = "on"
    if args.lora and args.lora_slots <= 0:
        p.error("--lora requires --lora-slots > 0")
    if args.lora and args.engine == "mocker":
        p.error("--lora requires --engine tpu (the mocker has no adapter bank)")
    try:
        # Parsed ONCE here (argparse-grade error UX); consumers read
        # args.lora_specs instead of re-parsing.
        args.lora_specs = parse_lora_specs(args.lora, args.lora_rank)
    except ValueError as e:
        p.error(str(e))
    if args.engine == "mocker" and (args.disagg == "on" or args.is_prefill_worker):
        # The disagg handlers drive the real engine's KV extract/inject
        # surface (prefix_hit_length, kv pages); the mocker has neither.
        # (--disagg auto silently stays aggregated on a mocker.)
        p.error("--engine mocker cannot combine with --disagg on/--is-prefill-worker")
    if (args.dp_rank is not None or args.dp_size > 1) and args.dist_num_processes > 1:
        # A dp rank is a self-contained JAX world; spanning hosts within a
        # rank would need per-rank coordinator port blocks — run multi-host
        # workers as independent fleet replicas instead. Checked for the
        # spawner too so the parent fails fast instead of every child.
        p.error("--dp-size/--dp-rank cannot combine with --dist-num-processes > 1")
    if args.dp_rank is not None and not 0 <= args.dp_rank < args.dp_size:
        p.error("--dp-rank must be in [0, --dp-size)")
    return args


def parse_lora_specs(entries: list[str], default_rank: int) -> list[tuple[str, int, int]]:
    """--lora NAME[:RANK[:SEED]] entries → [(name, rank, seed)]."""
    out = []
    for e in entries:
        parts = e.split(":")
        name = parts[0]
        if not name:
            raise ValueError(f"--lora entry {e!r}: empty adapter name")
        try:
            rank = int(parts[1]) if len(parts) > 1 and parts[1] else default_rank
            seed = int(parts[2]) if len(parts) > 2 and parts[2] else 0
        except ValueError:
            raise ValueError(
                f"--lora entry {e!r}: RANK and SEED must be integers"
            ) from None
        out.append((name, rank, seed))
    return out


def adapter_cards(card, lora_specs) -> list:
    """One ModelDeploymentCard per --lora adapter, derived from the base
    card — shared by the plain serving path and the role manager so
    both publish identical adapter metadata."""
    import dataclasses as _dc

    return [
        _dc.replace(
            card, name=lname,
            lora={"adapter_id": lname, "base": card.name,
                  "rank": lrank, "resident_tier": "G2"},
        )
        for lname, lrank, _lseed in lora_specs
    ]


def dp_rank_ports(base_port: int, dp_rank: int, stride: int = 4) -> dict:
    """Deterministic per-rank port block (reference analogue: vLLM
    dp_rank port math, components/backends/vllm/src/dynamo/vllm/
    args.py:170-203): rank r owns [base + r*stride, base + (r+1)*stride).
    Only the ``system`` slot (status HTTP when DYNTPU_SYSTEM_ENABLED) is
    consumed today — per-rank multi-host is rejected in parse_args, so no
    coordinator/step ports are needed; the rest of the block is reserved
    for rank-local services so external launchers can rely on the
    stride."""
    b = base_port + dp_rank * stride
    return {"system": b, "reserved": (b + 1, b + stride)}


from dynamo_tpu.llm.tokenizer import parse_tokenizer_spec as tokenizer_spec


async def build_engine(args, config=None):
    """→ (engine, model_card). Engine exposes .generate/.metrics/.pool."""
    if args.model_path:
        # Hub names (`org/repo`) and .gguf files resolve to local paths
        # up front (engine/hub.py; reference: hub.rs:126) so every later
        # consumer (tokenizer, loader, card) sees a concrete path.
        from dynamo_tpu.engine.hub import is_gguf, resolve_model

        args.model_path = resolve_model(args.model_path)
        if args.tokenizer == "byte":
            prefix = "gguf:" if is_gguf(args.model_path) else "hf:"
            args.tokenizer = prefix + args.model_path
    tok_spec = tokenizer_spec(args.tokenizer)
    tokenizer = load_tokenizer(tok_spec)
    eos_ids = list(tokenizer.eos_token_ids)
    if args.engine == "mocker":
        from dynamo_tpu.mocker.engine import MockerArgs, MockerEngine
        from dynamo_tpu.runtime.chaos import ChaosInjector
        from dynamo_tpu.runtime.config import Config

        cfg = config or Config.from_env()
        engine = MockerEngine(
            MockerArgs(
                block_size=args.block_size,
                num_kv_blocks=args.num_kv_blocks,
                max_num_seqs=args.max_num_seqs,
                ttft_ms=args.mocker_ttft_ms,
                itl_ms=args.mocker_itl_ms,
                speedup=args.mocker_speedup,
                delta_tokens=args.mocker_delta_tokens,
                delta_max_tokens=args.delta_max_tokens,
                delta_max_ms=args.delta_max_ms,
                # Env-driven fault injection (DYNTPU_CHAOS_*): engine-level
                # kill draws; the messaging layer reads the same section.
                chaos=ChaosInjector.from_config(cfg.chaos),
            )
        )
        name = args.model_name or "mock-model"
        context_length = args.context_length or args.max_model_len
    else:
        from dynamo_tpu.engine.config import EngineArgs, ModelConfig
        from dynamo_tpu.engine.engine import TpuEngine

        params = None
        sharding = None
        if args.model_path:
            from dynamo_tpu.engine.loader import load_config, load_model

            if args.tp > 1:
                from dynamo_tpu.parallel.mesh import ModelSharding, build_mesh

                hf_cfg = load_config(args.model_path)
                sharding = ModelSharding(build_mesh(tp=args.tp, cfg=hf_cfg), hf_cfg)
            model, params = await asyncio.to_thread(
                load_model, args.model_path, args.dtype, sharding, args.quant
            )
        else:
            model = ModelConfig.preset(args.preset)
        eargs = _engine_args(args, model)
        runner = None
        if args.dist_num_processes > 1:
            from dynamo_tpu.engine.runner import LeaderRunner

            host, port = _step_addr(args).rsplit(":", 1)
            runner = LeaderRunner(
                eargs, params=params, seed=args.seed, sharding=sharding,
                listen_addr=f"0.0.0.0:{port}",
                num_followers=args.dist_num_processes - 1,
            )
        engine = await TpuEngine(
            eargs, params=params, seed=args.seed, sharding=sharding, runner=runner
        ).start()
        name = args.model_name or model.name
        context_length = args.context_length or min(args.max_model_len, model.max_position)
    card = ModelDeploymentCard(
        name=name,
        tokenizer=tok_spec,
        context_length=context_length,
        kv_cache_block_size=args.block_size,
        migration_limit=args.migration_limit,
        eos_token_ids=eos_ids or [ByteTokenizer.EOS],
        component=args.component,
        endpoint=args.endpoint,
        max_batch_size=args.max_num_seqs,
        total_kv_blocks=args.num_kv_blocks,
    )
    if getattr(args, "sla_profile", None):
        # Ship the profiled latency curves inside the model card so
        # frontends (admission-time TTFT prediction) and the planner
        # pick them up via discovery instead of a --qos-profile CLI
        # path copied to every box (ROADMAP 2c).
        from dynamo_tpu.planner.interpolate import load_profile, profile_as_card_dict

        prof_decode, prof_prefill = load_profile(args.sla_profile)
        card.sla_profile = profile_as_card_dict(
            decode=prof_decode, prefill=prof_prefill
        )
        log.info("sla profile %s embedded in model card", args.sla_profile)
    return engine, card


async def async_main(args) -> None:
    from dynamo_tpu.runtime import tracing

    # Trace-lane identity: role-named lane (DYNTPU_PROC_LANE wins) so the
    # stitched fleet timeline shows "prefill-…"/"worker-…" rows, not PIDs
    # of indistinct processes.
    lane = os.environ.get("DYNTPU_PROC_LANE")
    if not lane:
        lane = f"{'prefill' if args.is_prefill_worker else 'worker'}-{os.getpid()}"
        tracing.set_default_lane(lane)
    rt = await DistributedRuntime.create(store_url=args.store_url, proc_label=lane)
    trace_exporter = None
    if tracing.enabled() and os.environ.get("DYNTPU_TRACE_EXPORT", "") not in ("", "0"):
        from dynamo_tpu.runtime.trace_export import TraceExporter

        trace_exporter = await TraceExporter(
            rt.store, os.environ.get("DYNTPU_FLEET_ID", "default"), lane=lane
        ).start()
    engine, card = await build_engine(args, config=rt.config)
    # Multi-LoRA: register every --lora adapter on the engine (paged
    # into the tier economy now; device slots fill on first request).
    # Prefill workers register them too — a remote prefill carries the
    # request's adapter_id and must resolve it.
    lora_specs = args.lora_specs
    for lname, lrank, lseed in lora_specs:
        engine.register_adapter(lname, rank=lrank, seed=lseed)
    # Engine-level chaos draws (mocker kill_p) count on this process's
    # /metrics alongside the messaging-layer injector's.
    engine_chaos = getattr(getattr(engine, "args", None), "chaos", None)
    if engine_chaos is not None:
        engine_chaos.bind_metrics(rt.metrics)
    # TPU engine hot-loop gauges (in-flight windows, pending first-sample
    # fetches, prefill pad ratio); catalog-guarded by tools/check_metrics.py.
    if hasattr(engine, "bind_metrics"):
        engine.bind_metrics(rt.metrics)

    broadcaster = KvEventBroadcaster(engine.pool)
    publisher = None
    if args.kv_directory == "on":
        # Global prefix directory (fleet/directory.py): mirror this
        # engine's block residency — G1 from the pool event stream, the
        # host/disk/fleet tiers from the TierStack sink — so frontends
        # can price transfer-vs-recompute and the autoscaler sees heat.
        from dynamo_tpu.fleet.directory import DirectoryPublisher

        publisher = await DirectoryPublisher(
            rt.store, args.namespace, await rt.primary_lease()
        ).start()
        engine.pool.set_event_sink(
            lambda ev: (broadcaster.publish(ev), publisher.pool_sink(ev))
        )
        tiers = getattr(engine, "tiers", None)
        if tiers is not None and hasattr(tiers, "set_event_sink"):
            tiers.set_event_sink(publisher.tier_sink)
    else:
        engine.pool.set_event_sink(broadcaster.publish)

    manager = None
    if args.autoscaler == "on":
        from dynamo_tpu.planner.actions import POOL_DECODE, POOL_PREFILL
        from dynamo_tpu.runtime.chaos import ChaosInjector
        from dynamo_tpu.worker.roles import WorkerRoleManager

        cards = [card] + adapter_cards(card, lora_specs)
        role = (
            POOL_PREFILL
            if args.is_prefill_worker or args.autoscaler_role == "prefill"
            else POOL_DECODE
        )
        manager = await WorkerRoleManager(
            rt, engine, cards, args, broadcaster,
            chaos=ChaosInjector.from_config(rt.config.chaos),
        ).start(role)
        role = f"autoscaled {manager.role} worker"
        print(
            f"dynamo_tpu {role}: serving {card.name} in namespace "
            f"{args.namespace} (workerctl/admin live)",
            flush=True,
        )
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            with contextlib.suppress(NotImplementedError):
                loop.add_signal_handler(sig, stop.set)
        stop_task = loop.create_task(stop.wait())
        retired_task = loop.create_task(manager.retired.wait())
        await asyncio.wait(
            (stop_task, retired_task), return_when=asyncio.FIRST_COMPLETED
        )
        for t in (stop_task, retired_task):
            t.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await t
        log.info("worker shutting down")
        await manager.close()
        if publisher is not None:
            with contextlib.suppress(Exception):
                await publisher.close()
        if trace_exporter is not None:
            with contextlib.suppress(Exception):
                await trace_exporter.close()
        stop_fn = getattr(engine, "stop", None)
        if stop_fn is not None:
            await stop_fn()
        await rt.shutdown()
        return

    comp = rt.namespace(args.namespace).component(args.component)

    # G4 cross-worker reuse: every real engine answers peer prefix
    # fetches from its host tiers (llm/peer_kv.py; no-op without tiers).
    if args.engine == "tpu":
        from dynamo_tpu.llm.peer_kv import KV_PREFIX_ENDPOINT, make_kv_prefix_handler

        await comp.endpoint(KV_PREFIX_ENDPOINT).serve(make_kv_prefix_handler(engine))

    if args.is_prefill_worker:
        from dynamo_tpu.llm.disagg import DisaggConfig, PrefillHandler, PrefillPuller
        from dynamo_tpu.runtime.chaos import ChaosInjector
        from dynamo_tpu.runtime.queue import WorkQueue

        dcfg = DisaggConfig()
        # Env-driven kill-mid-transfer faults (DYNTPU_CHAOS_TRANSFER_CUT_P)
        # ride the same [chaos] section as the messaging-layer injector.
        handler = PrefillHandler(
            engine, frame_bytes=dcfg.frame_bytes,
            chaos=ChaosInjector.from_config(rt.config.chaos),
        )
        gen_handle = await comp.endpoint(args.endpoint).serve(handler.generate)
        await comp.endpoint("kv_fetch").serve(handler.kv_fetch)
        await serve_kv_endpoints(comp, broadcaster, engine.metrics)
        # Pull queued prefill jobs too (competing consumer across the
        # prefill fleet) — push and queue dispatch both work.
        PrefillPuller(
            engine,
            WorkQueue(rt.store, dcfg.queue_name),
            rt.store,
            gen_handle.instance.instance_id,
            lane=lane,
        ).start()
        # No model card: the frontend must route only to decode workers.
        role = "prefill worker"
    else:
        # Disaggregated prefill/decode is the DEFAULT serving shape for
        # TPU decode workers (--disagg auto): the handler costs one
        # discovery-set lookup per long prompt when no prefill fleet
        # exists and serves aggregated, so wiring it is free — a prefill
        # component joining the namespace starts taking long prefills
        # with no decode-worker restart.
        if args.engine == "tpu" and args.disagg != "off":
            from dynamo_tpu.llm.disagg import DisaggConfig, DisaggDecodeHandler
            from dynamo_tpu.runtime.push_router import RouterMode

            from dynamo_tpu.runtime.queue import WorkQueue

            pcomp = rt.namespace(args.namespace).component(args.prefill_component)
            cfg = DisaggConfig(
                max_local_prefill_length=args.max_local_prefill_length,
                prefill_component=args.prefill_component,
                stream=not args.no_disagg_stream,
            )
            handler = DisaggDecodeHandler(
                engine,
                await pcomp.endpoint(cfg.prefill_endpoint).router(RouterMode.ROUND_ROBIN),
                await pcomp.endpoint(cfg.fetch_endpoint).router(RouterMode.DIRECT),
                cfg,
                queue=(
                    None if args.prefill_dispatch == "push"
                    else WorkQueue(rt.store, cfg.queue_name)
                ),
                store=rt.store,
            )
            # disagg_remote_prefill_total / disagg_fallback_total{reason}
            # + transfer bytes/inflight/overlap on this process's /metrics.
            handler.bind_metrics(rt.metrics)
        else:
            handler = engine

        if args.engine == "tpu":
            # Resolve router peer_prefix hints (G4) ahead of disagg/admission.
            from dynamo_tpu.llm.peer_kv import KV_PREFIX_ENDPOINT, PeerPrefixFetcher
            from dynamo_tpu.runtime.push_router import RouterMode

            handler = PeerPrefixFetcher(
                engine,
                await comp.endpoint(KV_PREFIX_ENDPOINT).router(RouterMode.DIRECT),
                inner=handler,
            )

        async def gen_handler(payload, ctx):
            async for item in handler.generate(payload, ctx):
                yield item

        await comp.endpoint(args.endpoint).serve(gen_handler)
        await serve_kv_endpoints(comp, broadcaster, engine.metrics)
        if hasattr(engine, "embed"):
            async def embed_handler(payload, ctx):
                try:
                    vec = await engine.embed((payload or {}).get("token_ids") or [])
                    yield {"embedding": vec}
                except Exception as e:  # noqa: BLE001 — per-request failure
                    yield {"error": str(e)}

            await comp.endpoint("embed").serve(embed_handler)
        if hasattr(engine, "clear_kv_blocks"):
            async def clear_handler(payload, ctx):
                yield {"cleared": engine.clear_kv_blocks()}

            await comp.endpoint("clear_kv").serve(clear_handler)
        await register_model(rt, args.namespace, card)
        # One model card per adapter: the frontend lists each fine-tune
        # as its own served model (/v1/models carries the lora metadata),
        # the preprocessor stamps adapter_id from the card, and routing
        # lands on the same component/endpoint this engine serves —
        # adapters start cold in the tiers (resident_tier G2) and page
        # into G1 on first request.
        for acard in adapter_cards(card, lora_specs):
            await register_model(rt, args.namespace, acard)
        role = "worker"
    rank = "" if args.dp_rank is None else f" [dp rank {args.dp_rank}/{args.dp_size}]"
    print(
        f"dynamo_tpu {role}: serving {card.name} as "
        f"{args.namespace}/{args.component}/{args.endpoint}{rank}",
        flush=True,
    )

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        with contextlib.suppress(NotImplementedError):
            loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    log.info("worker shutting down")
    if publisher is not None:
        with contextlib.suppress(Exception):
            await publisher.close()
    if trace_exporter is not None:
        with contextlib.suppress(Exception):
            await trace_exporter.close()
    stop_fn = getattr(engine, "stop", None)
    if stop_fn is not None:
        await stop_fn()
    await rt.shutdown()


def _step_addr(args) -> str:
    if args.dist_step_addr:
        return args.dist_step_addr
    host, port = args.dist_coordinator.rsplit(":", 1)
    return f"{host}:{int(port) + 1}"


def _engine_args(args, model):
    from dynamo_tpu.engine.config import EngineArgs
    from dynamo_tpu.llm.tokenizer import parse_tokenizer_spec as tokenizer_spec

    return EngineArgs(
        model=model,
        block_size=args.block_size,
        num_kv_blocks=args.num_kv_blocks,
        max_num_seqs=args.max_num_seqs,
        max_model_len=args.max_model_len,
        dtype=args.dtype,
        tp=args.tp,
        decode_steps=args.decode_steps,
        pipeline_depth=args.pipeline_depth,
        pipeline_windows=args.pipeline_depth > 0,
        prefill_buckets_spec=args.prefill_buckets,
        prefill_tail_split=not args.no_prefill_tail_split,
        delta_max_tokens=args.delta_max_tokens,
        delta_max_ms=args.delta_max_ms,
        spec_tokens=args.spec_tokens,
        spec_ngram=args.spec_ngram,
        spec_fused=not args.spec_stepwise,
        spec_tree_width=args.spec_tree_width,
        spec_tree_depth=args.spec_tree_depth,
        spec_budget_adaptive=args.spec_budget == "adaptive",
        lora_slots=args.lora_slots,
        lora_rank=max([args.lora_rank] + [r for _, r, _ in args.lora_specs]),
        qos_scheduling=args.qos_sched == "on",
        # Grammar token-mask FSMs compile over the SERVING tokenizer's
        # vocabulary (engine/grammar.py) — response_format masks must
        # legalize exactly the ids the detokenizer can render.
        grammar_tokenizer=tokenizer_spec(args.tokenizer),
        attn_impl=args.attn_impl,
        quant=args.quant,
        kv_quant=args.kv_quant,
        kv_pressure_offer=args.kv_pressure_offer,
        host_kv_blocks=args.host_kv_blocks,
        disk_kv_dir=args.disk_kv_dir,
        disk_kv_blocks=args.disk_kv_blocks,
        fleet_kv_dir=args.fleet_kv_dir,
        fleet_kv_blocks=args.fleet_kv_blocks,
    )


def run_follower(args) -> None:
    '''Multi-host follower: no store, no endpoint; replays the leader
    dispatch stream against this host\'s shard of the mesh.'''
    from dynamo_tpu.engine.config import ModelConfig
    from dynamo_tpu.engine.runner import follower_loop

    params = None
    sharding = None
    if args.model_path:
        from dynamo_tpu.engine.loader import load_config, load_model
        from dynamo_tpu.parallel.mesh import ModelSharding, build_mesh

        model = load_config(args.model_path)
        if args.tp > 1:
            sharding = ModelSharding(build_mesh(tp=args.tp, cfg=model), model)
        model, params = load_model(args.model_path, args.dtype, sharding, args.quant)
    else:
        model = ModelConfig.preset(args.preset)
    eargs = _engine_args(args, model)
    print(f"dynamo_tpu follower {args.dist_process_id}/{args.dist_num_processes}", flush=True)
    follower_loop(eargs, _step_addr(args), params=params, seed=args.seed, sharding=sharding)


def run_dp_spawner(args, argv) -> int:
    """Spawn and supervise one worker process per dp rank (reference:
    dsr1_dep.sh launches one dynamo worker per vLLM dp_rank). Ranks are
    independent replicas of the same model: a dead rank loses only its
    own KV and lease — the rest keep serving, so the spawner does not
    gang-kill on a single failure; it forwards SIGINT/SIGTERM and exits
    with the worst child code once all ranks are done. With
    ``--dp-restart`` a dead rank is respawned after jittered exponential
    backoff (the frontend fleet's supervision hygiene,
    fleet/supervisor.py:BackoffPolicy) — the replacement re-registers
    under a fresh lease and the router folds it back in."""
    import os
    import signal as sig
    import subprocess
    import sys
    import time

    base = [a for a in (argv if argv is not None else sys.argv[1:])]
    procs: list[subprocess.Popen] = []
    stopping = False

    def forward(signum, _frame):
        nonlocal stopping
        stopping = True  # mid-launch: abort spawning further ranks too
        for p in procs:
            if p.poll() is None:
                p.send_signal(signum)

    # Installed BEFORE spawning: a signal mid-launch must still reach the
    # ranks already running, or they orphan with chips and leases held.
    sig.signal(sig.SIGTERM, forward)
    sig.signal(sig.SIGINT, forward)
    def spawn_rank(r: int) -> subprocess.Popen:
        env = dict(os.environ)
        if args.dp_chips_per_rank > 0:
            k = args.dp_chips_per_rank
            env["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in range(r * k, (r + 1) * k))
        if env.get("DYNTPU_SYSTEM_ENABLED"):
            env["DYNTPU_SYSTEM_PORT"] = str(
                dp_rank_ports(args.dp_base_port, r)["system"]
            )
        return subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker", *base, "--dp-rank", str(r)],
            env=env,
        )

    try:
        for r in range(args.dp_size):
            if stopping:
                break
            procs.append(spawn_rank(r))
    except Exception:
        # A failed spawn must not leave earlier ranks orphaned (they hold
        # chips and store leases with nobody to signal them).
        for p in procs:
            if p.poll() is None:
                p.terminate()
        raise
    if stopping:
        # A rank spawned while the handler ran may have missed the signal.
        for p in procs:
            if p.poll() is None:
                p.terminate()
    print(f"dynamo_tpu dp spawner: {args.dp_size} ranks launched", flush=True)
    if args.dp_restart and not stopping:
        # Fleet supervision hygiene for dp ranks: respawn a dead rank
        # after jittered exponential backoff instead of serving degraded
        # until an operator notices. A rank is an independent replica, so
        # the restart is invisible to its siblings.
        from dynamo_tpu.fleet.backoff import BackoffPolicy
        from dynamo_tpu.runtime.config import Config

        # Same knobs as the frontend fleet's restarts: an operator tuning
        # DYNTPU_FLEET_RESTART_BACKOFF_* tunes BOTH supervision paths.
        fcfg = Config.from_env().fleet
        backoff = BackoffPolicy(
            fcfg.restart_backoff_base,
            fcfg.restart_backoff_max,
            fcfg.restart_reset_after,
        )
        failures = [0] * len(procs)
        started = [time.monotonic()] * len(procs)
        restart_at = [0.0] * len(procs)
        while not stopping:
            now = time.monotonic()
            for r, p in enumerate(procs):
                # rc=0 is a deliberate exit (operator SIGTERMed the rank
                # directly, or it finished): leave the slot down — only
                # CRASHED ranks restart, as the flag advertises.
                if p.poll() is None or p.returncode == 0:
                    continue
                if restart_at[r] == 0.0:
                    if now - started[r] > backoff.reset_after:
                        failures[r] = 0
                    failures[r] += 1
                    restart_at[r] = now + backoff.delay(failures[r])
                    print(
                        f"dynamo_tpu dp spawner: rank {r} exited rc={p.returncode}, "
                        f"restart in {restart_at[r] - now:.2f}s", flush=True,
                    )
                elif now >= restart_at[r] and not stopping:
                    try:
                        procs[r] = spawn_rank(r)
                    except Exception:
                        # Same rule as the startup loop: a failed spawn
                        # must not leave live ranks orphaned with chips
                        # and leases held and nobody to signal them.
                        for q in procs:
                            if q.poll() is None:
                                q.terminate()
                        raise
                    started[r] = now
                    restart_at[r] = 0.0
            if all(p.poll() is not None and p.returncode == 0 for p in procs):
                break  # every rank exited cleanly: nothing left to supervise
            time.sleep(0.25)
        for p in procs:
            if p.poll() is None:
                p.send_signal(sig.SIGTERM)
    rcs = [p.wait() for p in procs]
    return max((abs(rc) for rc in rcs), default=0)


def main(argv=None) -> int:
    import os

    # CPU dev/e2e-testing of the real engine CLI: JAX_PLATFORMS in the env
    # is ignored when a sitecustomize pre-imports jax (TPU tunnels), but
    # the config update still works before backend init.
    plat = os.environ.get("DYNTPU_JAX_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    # Persistent compile cache: restart MTTR drops from minutes of XLA
    # compiles to seconds once the lattice has been warmed (AOT warm via
    # `python bench.py --precompile-only` pointed at the same dir).
    cache_dir = os.environ.get("DYNTPU_COMPILE_CACHE")
    if cache_dir:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    args = parse_args(argv)
    if args.dp_size > 1 and args.dp_rank is None:
        return run_dp_spawner(args, argv)
    if args.dist_num_processes > 1:
        import jax

        jax.distributed.initialize(
            coordinator_address=args.dist_coordinator,
            num_processes=args.dist_num_processes,
            process_id=args.dist_process_id,
        )
        if args.dist_process_id > 0:
            run_follower(args)
            return 0
    asyncio.run(async_main(args))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
