"""WorkerRoleManager: live prefill↔decode pool membership for one worker.

PR 8 made disaggregated prefill/decode the default serving shape and
PR 9 taught the fleet zero-failure drains; this module composes them so
the autoscaler can MOVE an engine between the pools at runtime without
restarting the process (and without losing its warm KV tiers — the
engine object survives every transition):

- **decode role** — the worker serves ``<component>/generate`` behind
  the conditional-disagg decode handler, publishes its model card(s),
  and answers KV events/load metrics, exactly like a ``--disagg auto``
  worker today.
- **prefill role** — the worker serves ``<prefill_component>/generate``
  + ``kv_fetch`` and pulls queued prefill jobs, exactly like an
  ``--is-prefill-worker`` today (no model card: frontends must route
  only to decode workers).

A transition is drain-ordered so no stream can fail: the old role's
instances DEREGISTER first (the router stops picking this worker
within one discovery event), in-flight streams then drain to
completion (``ServeHandle.close``), the prefill puller finishes its
current job, and only then do the new role's endpoints register. The
lease-backed registration key ``autoscaler/<ns>/workers/<lease>``
always names the worker's CURRENT role — the level-converging operator
reads it as ground truth, and it dies with the process, so a killed
worker can never leak a stale pool entry.

The manager also serves the ``workerctl/admin`` endpoint (DIRECT
instance routing): ``{"cmd": "set_role"|"retire"|"status"}`` — the
autoscaler's actuation RPC surface.
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any

from dynamo_tpu.kv_router.publisher import serve_kv_endpoints
from dynamo_tpu.llm.model_card import register_model
from dynamo_tpu.planner.actions import POOL_DECODE, POOL_PREFILL
from dynamo_tpu.planner.actuate import worker_key
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("worker.roles")

ADMIN_COMPONENT = "workerctl"
ADMIN_ENDPOINT = "admin"


class WorkerRoleError(Exception):
    """Typed failure of a role transition (bad role name, transition
    already in flight at shutdown, …) — surfaced to the operator as the
    admin RPC's error frame."""


class WorkerRoleManager:
    """Owns which pool this worker serves and performs the zero-failure
    transitions between them. ``args`` is the parsed worker CLI
    namespace (component names + disagg knobs); ``cards`` is the model
    card list the decode role publishes (base card first)."""

    #: Max blocks a retiring replica pushes to survivors (drain-on-retire,
    #: docs/performance.md "Fleet KV economy"). Bounds the retirement
    #: latency the autoscaler observes: the drain is an optimization, not
    #: a durability guarantee — anything past the budget re-enters the
    #: fleet through G4 or recompute.
    DRAIN_BUDGET_BLOCKS = 256

    def __init__(self, rt, engine, cards, args, broadcaster, chaos=None):
        self.rt = rt
        self.engine = engine
        self.cards = list(cards)
        self.args = args
        self.broadcaster = broadcaster
        self.chaos = chaos
        self.namespace = args.namespace
        self.role: str | None = None
        self.retired = asyncio.Event()
        self._lock = asyncio.Lock()
        self._handles: list = []          # current role's ServeHandles
        self._card_keys: list[str] = []   # published model-card store keys
        self._puller = None
        self._admin_handle = None
        self._peer_handle = None
        # Live migration (worker/migrate.py): outbound coordinator +
        # inbound receiver, wired in start() when the engine has the
        # migration surface. None on control-plane-only engines.
        self.migrator = None
        self.receiver = None
        self._peer_rr = 0

    # -- lifecycle ----------------------------------------------------------

    async def start(self, role: str) -> "WorkerRoleManager":
        if role not in (POOL_DECODE, POOL_PREFILL):
            raise WorkerRoleError(f"unknown role {role!r}")
        comp = self.rt.namespace(self.namespace).component(ADMIN_COMPONENT)
        self._admin_handle = await comp.endpoint(ADMIN_ENDPOINT).serve(self._admin)
        if hasattr(self.engine, "migration_begin"):
            from dynamo_tpu.runtime.push_router import RouterMode
            from dynamo_tpu.worker.migrate import (
                MigrationCoordinator,
                MigrationReceiver,
                register_migration_metrics,
            )

            metrics = register_migration_metrics(self.rt.metrics)
            self.receiver = MigrationReceiver(
                self.rt, self.namespace, chaos=self.chaos, metrics=metrics
            )
            self.migrator = MigrationCoordinator(
                self.engine,
                await comp.endpoint(ADMIN_ENDPOINT).router(RouterMode.DIRECT),
                self.args.component,
                await self.rt.primary_lease(),
                chaos=self.chaos,
                metrics=metrics,
            )
            # QoS defrag: the engine offers a relocation before killing
            # a preemption victim. Called from the scheduler thread →
            # bounce onto the event loop.
            loop = asyncio.get_running_loop()
            self.engine.migration_offer = lambda rid: loop.call_soon_threadsafe(
                lambda: loop.create_task(self._offer_migration(rid))
            )
        # G4 peer prefix serving is role-agnostic (host-tier reads):
        # registered once, survives every transition.
        if self.args.engine == "tpu":
            from dynamo_tpu.llm.peer_kv import KV_PREFIX_ENDPOINT, make_kv_prefix_handler

            wcomp = self.rt.namespace(self.namespace).component(self.args.component)
            self._peer_handle = await wcomp.endpoint(KV_PREFIX_ENDPOINT).serve(
                make_kv_prefix_handler(self.engine)
            )
        async with self._lock:
            await self._activate(role)
        return self

    async def set_role(self, role: str, relocate: bool = True) -> dict:
        if role not in (POOL_DECODE, POOL_PREFILL):
            raise WorkerRoleError(f"unknown role {role!r}")
        async with self._lock:
            if self.retired.is_set():
                raise WorkerRoleError("worker is retiring")
            if role == self.role:
                return self.status()
            log.info("pool move: %s → %s", self.role, role)
            if relocate:
                await self._relocate_running()
            await self._deactivate()
            await self._activate(role)
            return self.status()

    async def retire(self, relocate: bool = True) -> None:
        """Drain + deregister everything and signal the process to
        exit — the scale-down half of zero-downtime replica scaling.
        New work stops the moment the instances deregister; in-flight
        streams complete inside the drain (running decodes RELOCATE to
        peers first when possible, so retirement usually drains an
        already-empty batch)."""
        async with self._lock:
            if self.retired.is_set():
                return
            log.info("retiring (%s)", self.role)
            if relocate:
                await self._relocate_running()
            await self._drain_hot_kv()
            await self._deactivate()
            try:
                await self.rt.store.delete(
                    worker_key(self.namespace, await self.rt.primary_lease())
                )
            except Exception:  # noqa: BLE001 — the lease reaps the key anyway; retire must not fail on a flaky store
                pass
            self.retired.set()

    async def close(self) -> None:
        await self.retire()
        if self.receiver is not None:
            await self.receiver.close()
        for h in (self._peer_handle, self._admin_handle):
            if h is not None:
                await h.close()
        self._peer_handle = self._admin_handle = None

    # -- live migration -----------------------------------------------------

    async def _peers(self) -> list[int]:
        """Live decode-pool peer instance ids (relocation targets),
        excluding this worker."""
        from dynamo_tpu.planner.actuate import read_pools

        me = await self.rt.primary_lease()
        pools = await read_pools(self.rt.store, self.namespace)
        return [
            w.instance_id for w in pools.get(POOL_DECODE, [])
            if w.instance_id != me
        ]

    async def _relocate_running(self) -> dict:
        """Best-effort relocation of every running decode to peer decode
        workers — pool moves and retirement RELOCATE instead of drain.
        Any failure just leaves that sequence to the drain (the
        fallback); this must never raise."""
        if self.migrator is None or self.role != POOL_DECODE:
            return {}
        if not hasattr(self.engine, "list_running"):
            return {}
        try:
            peers = await self._peers()
        except Exception as e:  # noqa: BLE001 — a degraded store only disables relocation; the drain still runs
            log.warning("relocation peer discovery failed (%s); draining", e)
            return {}
        if not peers:
            return {}
        moved = kept = 0
        for i, rid in enumerate(self.engine.list_running()):
            res = await self.migrator.migrate_out(rid, peers[i % len(peers)])
            if res.get("ok"):
                moved += 1
            else:
                kept += 1
        if moved or kept:
            log.info("relocation: %d moved, %d left to drain", moved, kept)
        return {"relocated": moved, "kept": kept}

    # -- drain-on-retire KV handoff -----------------------------------------

    def _hot_chains(self) -> list[list[int]]:
        """Root→leaf block-hash chains from the radix pool snapshot,
        deepest first, each truncated to its tier-resident leading run
        (``kv_prefix`` serves from the tiers, not HBM — but write-through
        offload keeps the tiers current for sealed blocks)."""
        snap = self.engine.pool.snapshot()
        parent = {h: p for h, p in snap}
        inner = {p for _, p in snap if p is not None}
        chains: list[list[int]] = []
        for leaf in (h for h in parent if h not in inner):
            chain: list[int] = []
            h: int | None = leaf
            while h is not None and h in parent:
                chain.append(h)
                h = parent[h]
            chain.reverse()
            run = self.engine.tiers.peek_run_len(chain)
            if run:
                chains.append(chain[:run])
        chains.sort(key=len, reverse=True)
        return chains

    async def _drain_hot_kv(self) -> dict:
        """Push this worker's warm prefixes to surviving decode peers
        before the endpoints deregister — the retirement half of the
        fleet KV economy: a scale-down must not cold-start the very
        prefixes that made this replica the victim's *survivors* hot.

        Each survivor PULLS the pages itself (``kv_adopt`` admin RPC →
        our still-registered ``kv_prefix`` endpoint), so the transfer
        rides the same bounded-frame data plane as routed peer fetches,
        and the survivor's tier puts republish directory residency.
        Best-effort throughout: any failure (peer gone, RPC timeout,
        this process dying mid-drain) degrades to a plain retire."""
        try:
            tiers = getattr(self.engine, "tiers", None)
            pool = getattr(self.engine, "pool", None)
            if (tiers is None or not getattr(tiers, "enabled", False)
                    or pool is None or not hasattr(pool, "snapshot")):
                return {}
            peers = await self._peers()
            if not peers:
                return {}
            from dynamo_tpu.runtime.engine import Context
            from dynamo_tpu.runtime.push_router import RouterMode

            admin = await (
                self.rt.namespace(self.namespace).component(ADMIN_COMPONENT)
                .endpoint(ADMIN_ENDPOINT).router(RouterMode.DIRECT)
            )
            me = await self.rt.primary_lease()
            budget = self.DRAIN_BUDGET_BLOCKS
            sent: set[int] = set()
            drained = 0
            for i, chain in enumerate(self._hot_chains()):
                if budget <= 0:
                    break
                hashes = [h for h in chain if h not in sent][:budget]
                if not hashes:
                    continue
                peer = peers[i % len(peers)]
                res: dict = {}
                try:
                    async for item in admin.generate(
                        {"cmd": "kv_adopt", "hashes": hashes,
                         "source_component": self.args.component,
                         "source_instance": me},
                        Context(), instance_id=peer,
                    ):
                        res = item or {}
                except Exception as e:  # noqa: BLE001 — a dead survivor just forfeits its share of the drain
                    log.debug("kv drain to %x failed: %s", peer, e)
                    continue
                n = int(res.get("adopted") or 0)
                if n:
                    sent.update(hashes[:n])
                    budget -= n
                    drained += n
            if drained:
                log.info(
                    "hot-KV drain: %d blocks adopted by %d survivor(s)",
                    drained, len(peers),
                )
            return {"drained": drained}
        except Exception as e:  # noqa: BLE001 — the drain is an optimization; retirement must proceed
            log.warning("hot-KV drain failed (%s); retiring without it", e)
            return {}

    async def _kv_adopt_cmd(self, payload: dict) -> dict:
        """``{"cmd": "kv_adopt", "hashes", "source_component",
        "source_instance"}`` — adopt a retiring peer's warm prefix run:
        pull the pages from its ``kv_prefix`` endpoint and store them in
        our own tiers (protected, so the adopted prefix survives the
        next one-off-prompt burst). → {"ok", "adopted": n}."""
        tiers = getattr(self.engine, "tiers", None)
        if tiers is None or not getattr(tiers, "enabled", False):
            return {"error": "no kv tiers on this worker"}
        hashes = [int(h) for h in payload.get("hashes") or []]
        source = int(payload.get("source_instance") or 0)
        component = payload.get("source_component") or self.args.component
        if not hashes or not source:
            return {"ok": True, "adopted": 0}
        from dynamo_tpu.engine.kv_transfer import split_page_run
        from dynamo_tpu.llm.peer_kv import KV_PREFIX_ENDPOINT
        from dynamo_tpu.runtime.engine import Context
        from dynamo_tpu.runtime.push_router import RouterMode
        from dynamo_tpu.transfer.stream import TransferError, read_kv_payload_frames

        router = await (
            self.rt.namespace(self.namespace).component(component)
            .endpoint(KV_PREFIX_ENDPOINT).router(RouterMode.DIRECT)
        )
        try:
            kv = await read_kv_payload_frames(
                router.generate({"hashes": hashes}, Context(), instance_id=source)
            )
        except TransferError as e:
            return {"ok": False, "reason": str(e)}
        if kv.num_tokens <= 0:
            return {"ok": True, "adopted": 0}
        pages = kv.pages()
        blocks = split_page_run(pages, pages[0].shape[1])
        pairs = [(h, *blk) for h, blk in zip(hashes, blocks)]
        step = tiers.MAX_OFFLOAD_PER_STEP
        adopted = 0
        for i in range(0, len(pairs), step):
            chunk = pairs[i : i + step]
            adopted += tiers.offload(chunk, protected=[True] * len(chunk))
        return {"ok": True, "adopted": adopted}

    async def _offer_migration(self, request_id: str) -> None:
        """Engine preemption-offer hook target: try to relocate the
        would-be preemption victim to a peer. Failure is fine — the
        engine's grace deadline expires and it preempts as before."""
        if self.migrator is None:
            return
        try:
            peers = await self._peers()
            if not peers:
                return
            self._peer_rr += 1
            await self.migrator.migrate_out(
                request_id, peers[self._peer_rr % len(peers)]
            )
        except Exception:  # noqa: BLE001 — the offer is advisory; the engine's preemption fallback owns correctness
            log.exception("preemption-relief migration of %s failed", request_id)

    # -- role wiring --------------------------------------------------------

    async def _publish_registration(self) -> None:
        lease = await self.rt.primary_lease()
        await self.rt.store.put(
            worker_key(self.namespace, lease),
            json.dumps({
                "role": self.role,
                "pid": os.getpid(),
                "instance_id": lease,
                "model": self.cards[0].name if self.cards else "",
            }).encode(),
            lease_id=lease,
        )

    async def _activate(self, role: str) -> None:
        if role == POOL_DECODE:
            await self._activate_decode()
        else:
            await self._activate_prefill()
        self.role = role
        await self._publish_registration()

    async def _deactivate(self) -> None:
        """Drain-ordered teardown of the current role. Model cards are
        deleted FIRST (frontends stop listing the model through this
        instance), then each ServeHandle deregisters its instance and
        drains its in-flight streams, then the prefill puller finishes
        its current job."""
        for key in self._card_keys:
            try:
                await self.rt.store.delete(key)
            except Exception:  # noqa: BLE001 — lease-backed; at worst the card lingers until TTL
                pass
        self._card_keys = []
        if self._puller is not None:
            await self._puller.drain()
            self._puller = None
        for h in self._handles:
            await h.close()
        self._handles = []
        self.role = None

    async def _activate_decode(self) -> None:
        args = self.args
        comp = self.rt.namespace(self.namespace).component(args.component)
        handler: Any = self.engine
        if args.engine == "tpu" and args.disagg != "off":
            from dynamo_tpu.llm.disagg import DisaggConfig, DisaggDecodeHandler
            from dynamo_tpu.llm.peer_kv import KV_PREFIX_ENDPOINT, PeerPrefixFetcher
            from dynamo_tpu.runtime.push_router import RouterMode
            from dynamo_tpu.runtime.queue import WorkQueue

            pcomp = self.rt.namespace(self.namespace).component(args.prefill_component)
            cfg = DisaggConfig(
                max_local_prefill_length=args.max_local_prefill_length,
                prefill_component=args.prefill_component,
                stream=not args.no_disagg_stream,
            )
            handler = DisaggDecodeHandler(
                self.engine,
                await pcomp.endpoint(cfg.prefill_endpoint).router(RouterMode.ROUND_ROBIN),
                await pcomp.endpoint(cfg.fetch_endpoint).router(RouterMode.DIRECT),
                cfg,
                queue=(
                    None if args.prefill_dispatch == "push"
                    else WorkQueue(self.rt.store, cfg.queue_name)
                ),
                store=self.rt.store,
            )
            handler.bind_metrics(self.rt.metrics)
            handler = PeerPrefixFetcher(
                self.engine,
                await comp.endpoint(KV_PREFIX_ENDPOINT).router(RouterMode.DIRECT),
                inner=handler,
            )
        gen = handler
        receiver = self.receiver

        async def gen_handler(payload, ctx):
            if receiver is not None and isinstance(payload, dict):
                # Migration resume leg: claim the staged KV inject for
                # this handle, if we are the destination that pulled it.
                # A miss (wrong worker after a pin fallback, expired
                # stage) is fine — the identity rides the request and
                # admission just re-prefills from the carried tokens.
                mr = (payload.get("kv_transfer_params") or {}).get("migration_resume")
                if isinstance(mr, dict) and mr.get("handle"):
                    staged = receiver.take(mr["handle"])
                    if staged is not None:
                        payload = dict(payload)
                        ktp = dict(payload.get("kv_transfer_params") or {})
                        ktp["inject"] = staged
                        payload["kv_transfer_params"] = ktp
            async for item in gen.generate(payload, ctx):
                yield item

        self._handles.append(await comp.endpoint(args.endpoint).serve(gen_handler))
        if hasattr(self.engine, "get_stream_export"):
            # Decode workers serve the same windowed kv_fetch surface as
            # prefill workers: a migration DESTINATION pulls the source's
            # chunk stream from here (PrefillHandler.kv_fetch is
            # handle-generic — any registered KvStreamExport serves).
            from dynamo_tpu.llm.disagg import DisaggConfig, PrefillHandler

            dcfg = DisaggConfig()
            fetch = PrefillHandler(
                self.engine, frame_bytes=dcfg.frame_bytes, chaos=self.chaos
            )
            self._handles.append(
                await comp.endpoint(dcfg.fetch_endpoint).serve(fetch.kv_fetch)
            )
        self._handles.extend(
            await serve_kv_endpoints(comp, self.broadcaster, self.engine.metrics)
        )
        if hasattr(self.engine, "embed"):
            engine = self.engine

            async def embed_handler(payload, ctx):
                try:
                    vec = await engine.embed((payload or {}).get("token_ids") or [])
                    yield {"embedding": vec}
                except Exception as e:  # noqa: BLE001 — per-request failure
                    yield {"error": str(e)}

            self._handles.append(await comp.endpoint("embed").serve(embed_handler))
        if hasattr(self.engine, "clear_kv_blocks"):
            engine = self.engine

            async def clear_handler(payload, ctx):
                yield {"cleared": engine.clear_kv_blocks()}

            self._handles.append(await comp.endpoint("clear_kv").serve(clear_handler))
        for card in self.cards:
            self._card_keys.append(
                await register_model(self.rt, self.namespace, card)
            )

    async def _activate_prefill(self) -> None:
        from dynamo_tpu.llm.disagg import DisaggConfig, PrefillHandler, PrefillPuller
        from dynamo_tpu.runtime.queue import WorkQueue

        args = self.args
        comp = self.rt.namespace(self.namespace).component(args.prefill_component)
        dcfg = DisaggConfig(prefill_component=args.prefill_component)
        handler = PrefillHandler(
            self.engine, frame_bytes=dcfg.frame_bytes, chaos=self.chaos
        )
        gen_handle = await comp.endpoint(args.endpoint).serve(handler.generate)
        self._handles.append(gen_handle)
        self._handles.append(
            await comp.endpoint(dcfg.fetch_endpoint).serve(handler.kv_fetch)
        )
        self._handles.extend(
            await serve_kv_endpoints(comp, self.broadcaster, self.engine.metrics)
        )
        self._puller = PrefillPuller(
            self.engine,
            WorkQueue(self.rt.store, dcfg.queue_name),
            self.rt.store,
            gen_handle.instance.instance_id,
        ).start()

    # -- admin RPC ----------------------------------------------------------

    def status(self) -> dict:
        return {
            "ok": True,
            "role": self.role,
            "pid": os.getpid(),
            "retiring": self.retired.is_set(),
        }

    async def _migrate_out_cmd(self, payload: dict) -> dict:
        """``{"cmd": "migrate_out", "request_id"?, "dest_instance"?}`` —
        the planner/operator + fleet-balancer verb. Without a
        destination, round-robins the live decode peers. Without a
        request_id (the balancer's shape — it reasons about ENGINES, not
        sequences), the worker auto-picks the cheapest victim: the
        newest running sequence, which has accumulated the least KV and
        therefore streams fastest."""
        if self.migrator is None:
            return {"error": "migration unsupported on this engine"}
        request_id = payload.get("request_id", "")
        if not request_id:
            running = (
                list(self.engine.list_running())
                if hasattr(self.engine, "list_running") else []
            )
            if not running:
                return {"ok": False, "reason": "no_running"}
            request_id = running[-1]
        dest = payload.get("dest_instance")
        if dest is None:
            peers = await self._peers()
            if not peers:
                return {"ok": False, "reason": "no_peer"}
            self._peer_rr += 1
            dest = peers[self._peer_rr % len(peers)]
        return await self.migrator.migrate_out(request_id, int(dest))

    async def _admin(self, payload: Any, ctx):
        payload = payload or {}
        cmd = payload.get("cmd")
        relocate = payload.get("relocate") is not False
        try:
            if cmd == "status":
                yield self.status()
            elif cmd == "set_role":
                yield await self.set_role(payload.get("role", ""), relocate=relocate)
            elif cmd == "retire":
                # Ack first, retire in the background: the drain may
                # outlive the RPC's own deadline, and the operator
                # converges on the registration key vanishing anyway.
                yield {"ok": True, "retiring": True}
                asyncio.get_running_loop().create_task(self.retire(relocate=relocate))
            elif cmd == "migrate_out":
                yield await self._migrate_out_cmd(payload)
            elif cmd == "kv_adopt":
                yield await self._kv_adopt_cmd(payload)
            elif cmd == "migrate_in_start":
                if self.receiver is None:
                    yield {"error": "no migration receiver"}
                else:
                    yield await self.receiver.start_pull(
                        payload.get("handle", ""),
                        payload.get("source_component", ""),
                        int(payload.get("source_instance") or 0),
                        traceparent=payload.get("traceparent"),
                    )
            elif cmd == "migrate_in_commit":
                if self.receiver is None:
                    yield {"error": "no migration receiver"}
                else:
                    yield await self.receiver.commit(
                        payload.get("handle", ""),
                        int(payload.get("kv_blocks") or 0),
                    )
            elif cmd == "migrate_in_abort":
                if self.receiver is None:
                    yield {"error": "no migration receiver"}
                else:
                    yield await self.receiver.abort(payload.get("handle", ""))
            else:
                yield {"error": f"unknown admin cmd {cmd!r}"}
        except WorkerRoleError as e:
            yield {"error": str(e)}
        except Exception as e:  # noqa: BLE001 — an admin RPC must answer typed, never hang the operator on an unexpected transition failure
            log.exception("admin cmd %s failed", cmd)
            yield {"error": f"{type(e).__name__}: {e}"}
