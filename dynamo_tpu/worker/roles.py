"""WorkerRoleManager: live prefill↔decode pool membership for one worker.

PR 8 made disaggregated prefill/decode the default serving shape and
PR 9 taught the fleet zero-failure drains; this module composes them so
the autoscaler can MOVE an engine between the pools at runtime without
restarting the process (and without losing its warm KV tiers — the
engine object survives every transition):

- **decode role** — the worker serves ``<component>/generate`` behind
  the conditional-disagg decode handler, publishes its model card(s),
  and answers KV events/load metrics, exactly like a ``--disagg auto``
  worker today.
- **prefill role** — the worker serves ``<prefill_component>/generate``
  + ``kv_fetch`` and pulls queued prefill jobs, exactly like an
  ``--is-prefill-worker`` today (no model card: frontends must route
  only to decode workers).

A transition is drain-ordered so no stream can fail: the old role's
instances DEREGISTER first (the router stops picking this worker
within one discovery event), in-flight streams then drain to
completion (``ServeHandle.close``), the prefill puller finishes its
current job, and only then do the new role's endpoints register. The
lease-backed registration key ``autoscaler/<ns>/workers/<lease>``
always names the worker's CURRENT role — the level-converging operator
reads it as ground truth, and it dies with the process, so a killed
worker can never leak a stale pool entry.

The manager also serves the ``workerctl/admin`` endpoint (DIRECT
instance routing): ``{"cmd": "set_role"|"retire"|"status"}`` — the
autoscaler's actuation RPC surface.
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any

from dynamo_tpu.kv_router.publisher import serve_kv_endpoints
from dynamo_tpu.llm.model_card import register_model
from dynamo_tpu.planner.actions import POOL_DECODE, POOL_PREFILL
from dynamo_tpu.planner.actuate import worker_key
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("worker.roles")

ADMIN_COMPONENT = "workerctl"
ADMIN_ENDPOINT = "admin"


class WorkerRoleError(Exception):
    """Typed failure of a role transition (bad role name, transition
    already in flight at shutdown, …) — surfaced to the operator as the
    admin RPC's error frame."""


class WorkerRoleManager:
    """Owns which pool this worker serves and performs the zero-failure
    transitions between them. ``args`` is the parsed worker CLI
    namespace (component names + disagg knobs); ``cards`` is the model
    card list the decode role publishes (base card first)."""

    def __init__(self, rt, engine, cards, args, broadcaster, chaos=None):
        self.rt = rt
        self.engine = engine
        self.cards = list(cards)
        self.args = args
        self.broadcaster = broadcaster
        self.chaos = chaos
        self.namespace = args.namespace
        self.role: str | None = None
        self.retired = asyncio.Event()
        self._lock = asyncio.Lock()
        self._handles: list = []          # current role's ServeHandles
        self._card_keys: list[str] = []   # published model-card store keys
        self._puller = None
        self._admin_handle = None
        self._peer_handle = None

    # -- lifecycle ----------------------------------------------------------

    async def start(self, role: str) -> "WorkerRoleManager":
        if role not in (POOL_DECODE, POOL_PREFILL):
            raise WorkerRoleError(f"unknown role {role!r}")
        comp = self.rt.namespace(self.namespace).component(ADMIN_COMPONENT)
        self._admin_handle = await comp.endpoint(ADMIN_ENDPOINT).serve(self._admin)
        # G4 peer prefix serving is role-agnostic (host-tier reads):
        # registered once, survives every transition.
        if self.args.engine == "tpu":
            from dynamo_tpu.llm.peer_kv import KV_PREFIX_ENDPOINT, make_kv_prefix_handler

            wcomp = self.rt.namespace(self.namespace).component(self.args.component)
            self._peer_handle = await wcomp.endpoint(KV_PREFIX_ENDPOINT).serve(
                make_kv_prefix_handler(self.engine)
            )
        async with self._lock:
            await self._activate(role)
        return self

    async def set_role(self, role: str) -> dict:
        if role not in (POOL_DECODE, POOL_PREFILL):
            raise WorkerRoleError(f"unknown role {role!r}")
        async with self._lock:
            if self.retired.is_set():
                raise WorkerRoleError("worker is retiring")
            if role == self.role:
                return self.status()
            log.info("pool move: %s → %s", self.role, role)
            await self._deactivate()
            await self._activate(role)
            return self.status()

    async def retire(self) -> None:
        """Drain + deregister everything and signal the process to
        exit — the scale-down half of zero-downtime replica scaling.
        New work stops the moment the instances deregister; in-flight
        streams complete inside the drain."""
        async with self._lock:
            if self.retired.is_set():
                return
            log.info("retiring (%s)", self.role)
            await self._deactivate()
            try:
                await self.rt.store.delete(
                    worker_key(self.namespace, await self.rt.primary_lease())
                )
            except Exception:  # noqa: BLE001 — the lease reaps the key anyway; retire must not fail on a flaky store
                pass
            self.retired.set()

    async def close(self) -> None:
        await self.retire()
        for h in (self._peer_handle, self._admin_handle):
            if h is not None:
                await h.close()
        self._peer_handle = self._admin_handle = None

    # -- role wiring --------------------------------------------------------

    async def _publish_registration(self) -> None:
        lease = await self.rt.primary_lease()
        await self.rt.store.put(
            worker_key(self.namespace, lease),
            json.dumps({
                "role": self.role,
                "pid": os.getpid(),
                "instance_id": lease,
                "model": self.cards[0].name if self.cards else "",
            }).encode(),
            lease_id=lease,
        )

    async def _activate(self, role: str) -> None:
        if role == POOL_DECODE:
            await self._activate_decode()
        else:
            await self._activate_prefill()
        self.role = role
        await self._publish_registration()

    async def _deactivate(self) -> None:
        """Drain-ordered teardown of the current role. Model cards are
        deleted FIRST (frontends stop listing the model through this
        instance), then each ServeHandle deregisters its instance and
        drains its in-flight streams, then the prefill puller finishes
        its current job."""
        for key in self._card_keys:
            try:
                await self.rt.store.delete(key)
            except Exception:  # noqa: BLE001 — lease-backed; at worst the card lingers until TTL
                pass
        self._card_keys = []
        if self._puller is not None:
            await self._puller.drain()
            self._puller = None
        for h in self._handles:
            await h.close()
        self._handles = []
        self.role = None

    async def _activate_decode(self) -> None:
        args = self.args
        comp = self.rt.namespace(self.namespace).component(args.component)
        handler: Any = self.engine
        if args.engine == "tpu" and args.disagg != "off":
            from dynamo_tpu.llm.disagg import DisaggConfig, DisaggDecodeHandler
            from dynamo_tpu.llm.peer_kv import KV_PREFIX_ENDPOINT, PeerPrefixFetcher
            from dynamo_tpu.runtime.push_router import RouterMode
            from dynamo_tpu.runtime.queue import WorkQueue

            pcomp = self.rt.namespace(self.namespace).component(args.prefill_component)
            cfg = DisaggConfig(
                max_local_prefill_length=args.max_local_prefill_length,
                prefill_component=args.prefill_component,
                stream=not args.no_disagg_stream,
            )
            handler = DisaggDecodeHandler(
                self.engine,
                await pcomp.endpoint(cfg.prefill_endpoint).router(RouterMode.ROUND_ROBIN),
                await pcomp.endpoint(cfg.fetch_endpoint).router(RouterMode.DIRECT),
                cfg,
                queue=(
                    None if args.prefill_dispatch == "push"
                    else WorkQueue(self.rt.store, cfg.queue_name)
                ),
                store=self.rt.store,
            )
            handler.bind_metrics(self.rt.metrics)
            handler = PeerPrefixFetcher(
                self.engine,
                await comp.endpoint(KV_PREFIX_ENDPOINT).router(RouterMode.DIRECT),
                inner=handler,
            )
        gen = handler

        async def gen_handler(payload, ctx):
            async for item in gen.generate(payload, ctx):
                yield item

        self._handles.append(await comp.endpoint(args.endpoint).serve(gen_handler))
        self._handles.extend(
            await serve_kv_endpoints(comp, self.broadcaster, self.engine.metrics)
        )
        if hasattr(self.engine, "embed"):
            engine = self.engine

            async def embed_handler(payload, ctx):
                try:
                    vec = await engine.embed((payload or {}).get("token_ids") or [])
                    yield {"embedding": vec}
                except Exception as e:  # noqa: BLE001 — per-request failure
                    yield {"error": str(e)}

            self._handles.append(await comp.endpoint("embed").serve(embed_handler))
        if hasattr(self.engine, "clear_kv_blocks"):
            engine = self.engine

            async def clear_handler(payload, ctx):
                yield {"cleared": engine.clear_kv_blocks()}

            self._handles.append(await comp.endpoint("clear_kv").serve(clear_handler))
        for card in self.cards:
            self._card_keys.append(
                await register_model(self.rt, self.namespace, card)
            )

    async def _activate_prefill(self) -> None:
        from dynamo_tpu.llm.disagg import DisaggConfig, PrefillHandler, PrefillPuller
        from dynamo_tpu.runtime.queue import WorkQueue

        args = self.args
        comp = self.rt.namespace(self.namespace).component(args.prefill_component)
        dcfg = DisaggConfig(prefill_component=args.prefill_component)
        handler = PrefillHandler(
            self.engine, frame_bytes=dcfg.frame_bytes, chaos=self.chaos
        )
        gen_handle = await comp.endpoint(args.endpoint).serve(handler.generate)
        self._handles.append(gen_handle)
        self._handles.append(
            await comp.endpoint(dcfg.fetch_endpoint).serve(handler.kv_fetch)
        )
        self._handles.extend(
            await serve_kv_endpoints(comp, self.broadcaster, self.engine.metrics)
        )
        self._puller = PrefillPuller(
            self.engine,
            WorkQueue(self.rt.store, dcfg.queue_name),
            self.rt.store,
            gen_handle.instance.instance_id,
        ).start()

    # -- admin RPC ----------------------------------------------------------

    def status(self) -> dict:
        return {
            "ok": True,
            "role": self.role,
            "pid": os.getpid(),
            "retiring": self.retired.is_set(),
        }

    async def _admin(self, payload: Any, ctx):
        cmd = (payload or {}).get("cmd")
        try:
            if cmd == "status":
                yield self.status()
            elif cmd == "set_role":
                yield await self.set_role((payload or {}).get("role", ""))
            elif cmd == "retire":
                # Ack first, retire in the background: the drain may
                # outlive the RPC's own deadline, and the operator
                # converges on the registration key vanishing anyway.
                yield {"ok": True, "retiring": True}
                asyncio.get_running_loop().create_task(self.retire())
            else:
                yield {"error": f"unknown admin cmd {cmd!r}"}
        except WorkerRoleError as e:
            yield {"error": str(e)}
        except Exception as e:  # noqa: BLE001 — an admin RPC must answer typed, never hang the operator on an unexpected transition failure
            log.exception("admin cmd %s failed", cmd)
            yield {"error": f"{type(e).__name__}: {e}"}
