"""Mesh construction + model sharding rules (tensor / data parallel).

TP layout (Megatron-style column→row, expressed as shardings — XLA
derives the collectives; reference analogue is engine-internal NCCL TP,
SURVEY §2.6):

- attention: wq/wk/wv sharded on the head output dim ("column"), wo on
  the head input dim ("row") → one implicit all-reduce per attention
  block; KV cache sharded on the kv-head axis so paged reads/writes stay
  device-local.
- MLP: w_gate/w_up column-sharded on intermediate, w_down row-sharded →
  one all-reduce per MLP.
- embed / lm_head / norms replicated (logits land replicated; sampling
  is tiny). Vocab sharding is a later optimization.

DP: the engine batch dimension can additionally shard over a ``dp`` axis
(used by the multichip dryrun); production DP-attention runs one worker
process per dp rank, as the reference does (dsr1_dep.sh:86-105).
"""

from __future__ import annotations

from typing import Any

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import ModelConfig

DP_AXIS = "dp"
TP_AXIS = "tp"


def build_mesh(tp: int = 1, dp: int = 1, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    need = tp * dp
    if len(devices) < need:
        raise ValueError(f"mesh {dp}x{tp} needs {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(dp, tp)
    return Mesh(grid, (DP_AXIS, TP_AXIS))


class ModelSharding:
    """Sharding rules for one model on one mesh. Passed to TpuEngine;
    ``shard_params``/``shard_cache`` place arrays, ``batch_spec`` shards
    engine step inputs over dp."""

    def __init__(self, mesh: Mesh, cfg: ModelConfig):
        self.mesh = mesh
        self.cfg = cfg
        tp = mesh.shape[TP_AXIS]
        if cfg.num_heads % tp:
            raise ValueError(f"num_heads={cfg.num_heads} not divisible by tp={tp}")
        if cfg.num_kv_heads % tp:
            raise ValueError(f"num_kv_heads={cfg.num_kv_heads} not divisible by tp={tp}")
        if cfg.intermediate_size % tp:
            raise ValueError(f"intermediate_size={cfg.intermediate_size} not divisible by tp={tp}")

    def _ns(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def param_shardings(self) -> dict[str, Any]:
        rep = self._ns()
        col = self._ns(None, None, TP_AXIS)   # [L, D, out] — shard out
        row = self._ns(None, TP_AXIS, None)   # [L, in, D] — shard in
        shardings = {
            "embed": rep,
            "final_norm": rep,
            "layers": {
                "wq": col, "wk": col, "wv": col, "wo": row,
                "w_gate": col, "w_up": col, "w_down": row,
                "attn_norm": rep, "mlp_norm": rep,
            },
        }
        if not self.cfg.tie_embeddings:
            shardings["lm_head"] = rep
        return shardings

    def cache_spec(self) -> P:
        # [L, num_blocks, block_size, KVH, hd] — shard kv heads.
        return P(None, None, None, TP_AXIS, None)

    def batch_spec(self) -> P:
        return P(DP_AXIS)

    def shard_params(self, params: Any) -> Any:
        return jax.device_put(params, self.param_shardings())

    def shard_cache(self, cache) -> tuple[jax.Array, jax.Array]:
        ns = self._ns(*self.cache_spec())
        return jax.device_put(cache.k, ns), jax.device_put(cache.v, ns)
