"""Mesh construction + model sharding rules (tensor / data parallel).

TP layout (Megatron-style column→row, expressed as shardings — XLA
derives the collectives; reference analogue is engine-internal NCCL TP,
SURVEY §2.6):

- attention: wq/wk/wv sharded on the head output dim ("column"), wo on
  the head input dim ("row") → one implicit all-reduce per attention
  block; KV cache sharded on the kv-head axis so paged reads/writes stay
  device-local.
- MLP: w_gate/w_up column-sharded on intermediate, w_down row-sharded →
  one all-reduce per MLP.
- embed / lm_head sharded on the VOCAB dim over the full tp group (the
  logits matmul is the single largest matmul at decode; XLA all-gathers
  the tiny [B, D] activations instead), norms replicated.

**TP beyond num_kv_heads** (VERDICT r2 weak #4): the tp mesh axis is
internally split into ``tp_kv × tp_rep``. KV projections and the KV
cache shard over ``tp_kv`` only (and replicate over ``tp_rep``); query
heads and MLP shard over the combined ``("tp_kv", "tp_rep")`` axes. With
head index h = kvh·G + g (model.py's GQA reshape), row-major tuple
sharding maps device (i, j) to kv-head group i and query-subgroup j —
exactly the grouped layout the attention einsums expect. This expresses
llama-70b-class tp=16 over 8 kv heads (tp_kv=8, tp_rep=2).

DP: the engine batch dimension can additionally shard over a ``dp`` axis
(used by the multichip dryrun); production DP-attention runs one worker
process per dp rank, as the reference does (dsr1_dep.sh:86-105).
"""

from __future__ import annotations

from typing import Any

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.engine.config import ModelConfig

DP_AXIS = "dp"
EP_AXIS = "ep"
TP_KV_AXIS = "tp_kv"
TP_REP_AXIS = "tp_rep"
TP_AXES = (TP_KV_AXIS, TP_REP_AXIS)


def split_tp(tp: int, cfg: ModelConfig) -> tuple[int, int]:
    """tp → (tp_kv, tp_rep): shard kv heads as far as they divide, then
    replicate. Raises if the residue cannot split the query groups."""
    tp_kv = 1
    for cand in range(min(tp, cfg.num_kv_heads), 0, -1):
        if tp % cand == 0 and cfg.num_kv_heads % cand == 0:
            tp_kv = cand
            break
    tp_rep = tp // tp_kv
    G = cfg.num_heads // cfg.num_kv_heads
    if G % tp_rep:
        raise ValueError(
            f"tp={tp} needs query-group replication {tp_rep} but "
            f"G={G} query heads per kv head is not divisible by it"
        )
    return tp_kv, tp_rep


def build_mesh(tp: int = 1, dp: int = 1, ep: int = 1, devices=None,
               cfg: ModelConfig | None = None) -> Mesh:
    """dp × ep × tp mesh with the tp axis pre-split for kv replication.
    When ``cfg`` is None the split is (tp, 1) — fine for tp <=
    num_kv_heads. The ep axis shards MoE experts (wide-EP); dense models
    leave it at 1."""
    devices = list(devices if devices is not None else jax.devices())
    need = tp * dp * ep
    if len(devices) < need:
        raise ValueError(f"mesh {dp}x{ep}x{tp} needs {need} devices, have {len(devices)}")
    tp_kv, tp_rep = split_tp(tp, cfg) if cfg is not None else (tp, 1)
    grid = np.array(devices[:need]).reshape(dp, ep, tp_kv, tp_rep)
    return Mesh(grid, (DP_AXIS, EP_AXIS, TP_KV_AXIS, TP_REP_AXIS))


class ModelSharding:
    """Sharding rules for one model on one mesh. Passed to TpuEngine;
    ``shard_params``/``shard_cache`` place arrays, ``batch_spec`` shards
    engine step inputs over dp."""

    def __init__(self, mesh: Mesh, cfg: ModelConfig):
        self.mesh = mesh
        self.cfg = cfg
        tp_kv = mesh.shape[TP_KV_AXIS]
        tp_rep = mesh.shape[TP_REP_AXIS]
        tp = tp_kv * tp_rep
        ep = mesh.shape.get(EP_AXIS, 1)
        if cfg.num_experts and cfg.num_experts % ep:
            raise ValueError(f"num_experts={cfg.num_experts} not divisible by ep={ep}")
        if cfg.num_kv_heads % tp_kv:
            raise ValueError(f"num_kv_heads={cfg.num_kv_heads} not divisible by tp_kv={tp_kv}")
        if cfg.num_heads % tp:
            raise ValueError(f"num_heads={cfg.num_heads} not divisible by tp={tp}")
        if (cfg.num_heads // cfg.num_kv_heads) % tp_rep:
            raise ValueError(f"query groups not divisible by tp_rep={tp_rep}")
        if cfg.intermediate_size % tp:
            raise ValueError(f"intermediate_size={cfg.intermediate_size} not divisible by tp={tp}")
        if cfg.vocab_size % tp:
            # Vocab sharding falls back to replication on awkward sizes.
            self._vocab_spec = None
        else:
            self._vocab_spec = TP_AXES

    def _ns(self, *spec) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def param_shardings(self, params: Any | None = None) -> dict[str, Any]:
        """Pass the params pytree to include shardings for the optional
        int8 ``*_scale`` leaves (scales follow their weight's OUTPUT-dim
        sharding; row-sharded weights have replicated output dims)."""
        rep = self._ns()
        col = self._ns(None, None, TP_AXES)     # [L, D, out] — shard out
        row = self._ns(None, TP_AXES, None)     # [L, in, D] — shard in
        kv_col = self._ns(None, None, TP_KV_AXIS)  # kv heads: shard tp_kv, replicate tp_rep
        embed = self._ns(self._vocab_spec, None) if self._vocab_spec else rep
        layer_shardings: dict[str, Any] = {
            "wq": col, "wk": kv_col, "wv": kv_col, "wo": row,
            "attn_norm": rep, "mlp_norm": rep,
        }
        if self.cfg.attn_bias:
            # Biases follow their weight's OUTPUT-dim sharding.
            layer_shardings.update({
                "bq": self._ns(None, TP_AXES),
                "bk": self._ns(None, TP_KV_AXIS),
                "bv": self._ns(None, TP_KV_AXIS),
            })
        if self.cfg.num_experts:
            # Experts over ep, expert-FFN width over tp (wide-EP x TP):
            # the MoE einsums contract e locally and psum the combine.
            layer_shardings.update({
                "w_router": rep,
                "moe_gate": self._ns(None, EP_AXIS, None, TP_AXES),
                "moe_up": self._ns(None, EP_AXIS, None, TP_AXES),
                "moe_down": self._ns(None, EP_AXIS, TP_AXES, None),
            })
        else:
            layer_shardings.update({"w_gate": col, "w_up": col, "w_down": row})
        shardings = {
            "embed": embed,
            "final_norm": rep,
            "layers": layer_shardings,
        }
        if not self.cfg.tie_embeddings:
            # [D, V] — shard vocab (the logits matmul's big dim).
            shardings["lm_head"] = (
                self._ns(None, self._vocab_spec) if self._vocab_spec else rep
            )
        if params is not None:
            scale_of = {
                "wq": self._ns(None, TP_AXES), "wk": self._ns(None, TP_KV_AXIS),
                "wv": self._ns(None, TP_KV_AXIS), "wo": rep,
                "w_gate": self._ns(None, TP_AXES), "w_up": self._ns(None, TP_AXES),
                "w_down": rep,
            }
            for name, spec in scale_of.items():
                if name + "_scale" in params.get("layers", {}):
                    shardings["layers"][name + "_scale"] = spec
            vocab1d = self._ns(self._vocab_spec) if self._vocab_spec else rep
            if "embed_scale" in params:
                shardings["embed_scale"] = vocab1d
            if "lm_head_scale" in params:
                shardings["lm_head_scale"] = vocab1d
        return shardings

    def cache_spec(self) -> P:
        # [L, num_blocks, block_size, KVH*hd] — the merged head-dim splits
        # into tp_kv contiguous [KVH/tp_kv * hd] chunks, i.e. kv heads
        # grouped exactly as the attention einsums expect.
        return P(None, None, None, TP_KV_AXIS)

    def batch_spec(self) -> P:
        return P(DP_AXIS)

    def shard_params(self, params: Any) -> Any:
        if jax.process_count() > 1:
            # Cross-process device_put of committed device arrays is not
            # allowed; route through host. Every process holds the same
            # full value (same init seed / same checkpoint), so each can
            # supply its addressable shards. (Sharded-native loading is
            # the loader's job for models that exceed host RAM.)
            params = jax.tree.map(np.asarray, params)
        return jax.device_put(params, self.param_shardings(params))

    def shard_cache(self, cache) -> tuple:
        """→ the cache's arrays, sharded, in KVCache field order. int8
        caches carry [L, N, bs, KVH] scale arrays whose last axis is the
        kv-head axis — the same tp_kv split as the merged page lanes, so
        each shard dequantizes its own heads locally."""
        ns = self._ns(*self.cache_spec())
        out = [jax.device_put(cache.k, ns), jax.device_put(cache.v, ns)]
        k_scale = getattr(cache, "k_scale", None)
        if k_scale is not None:
            out += [
                jax.device_put(k_scale, ns),
                jax.device_put(cache.v_scale, ns),
            ]
        return tuple(out)
