"""Parallelism: device meshes + sharding rules.

The reference delegates intra-model parallelism to its engines' NCCL
(reference: components/backends/trtllm/src/dynamo/trtllm/utils/
trtllm_utils.py:131-143, SURVEY §2.6); here the engine is ours, so TP/DP
live in-repo the TPU way: a ``jax.sharding.Mesh`` with NamedShardings on
params/cache/batch, XLA inserting the collectives over ICI.
"""

from dynamo_tpu.parallel.mesh import ModelSharding, build_mesh

__all__ = ["build_mesh", "ModelSharding"]
