"""Mocker CLI: `python -m dynamo_tpu.mocker` — a worker hosting the fake
engine (reference: components/backends/mocker/src/dynamo/mocker/main.py).
Accepts every `dynamo_tpu.worker` flag; forces --engine mocker."""

import sys

from dynamo_tpu.worker.__main__ import main

if __name__ == "__main__":
    raise SystemExit(main(["--engine", "mocker", *sys.argv[1:]]))
