"""Mocker: a CPU-only fake engine with a real simulated KV manager.

Reference analogue: the Rust ``MockVllmEngine`` (reference: lib/llm/src/
mocker/engine.rs:49-60, mocker/kv_manager.rs:57-290) — the reference's
key testability trick: every serving/routing behaviour (KV events, load
metrics, prefix caching, continuous-batching timing) is exercised without
accelerator hardware, so router e2e tests run anywhere
(reference: tests/router/test_router_e2e_with_mockers.py:26-80).

This mocker reuses the production BlockPool for its KV simulation, so the
events it publishes are bit-identical in shape and hashing to the real
TPU engine's.
"""

from dynamo_tpu.mocker.engine import MockerArgs, MockerEngine

__all__ = ["MockerArgs", "MockerEngine"]
