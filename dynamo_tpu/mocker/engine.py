"""MockerEngine: streams deterministic tokens with simulated timing while
driving a real BlockPool (prefix caching, eviction, KV events, metrics).

Timing model (reference: mocker/scheduler.rs:252 — a batch/KV-pressure
cost model, not constants; VERDICT r3 weak #9):
  TTFT = ttft_ms + prefill_ms_per_token x uncached-prompt-tokens,
         scaled by (1 + prefill contention)
  ITL  = itl_ms x (1 + itl_batch_slope x (active-1))
             x (1 + itl_kv_pressure x usage^2)
so planner/router experiments against mocker fleets show realistic
saturation: ITL climbs with concurrent sequences (batch effect) and
blows up as the KV pool fills (paging pressure), instead of staying
flat until a cliff. A ``speedup`` divides everything for fast tests.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator

from dynamo_tpu.block_manager.pool import BlockPool, NoFreeBlocksError
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics, KvStats, WorkerStats
from dynamo_tpu.llm.protocols import FinishReason, LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.chaos import ChaosInjector
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.tokens import TokenBlockSequence, compute_block_hashes


@dataclass
class MockerArgs:
    block_size: int = 16
    num_kv_blocks: int = 512
    max_num_seqs: int = 64
    ttft_ms: float = 20.0
    prefill_ms_per_token: float = 0.05
    itl_ms: float = 5.0
    # Saturation model (reference: mocker/scheduler.rs:252):
    itl_batch_slope: float = 0.02    # +2% ITL per extra active sequence
    itl_kv_pressure: float = 1.0     # ITL multiplier at 100% KV usage: 1+this
    prefill_contention: float = 0.5  # TTFT multiplier at full slots: 1+this
    speedup: float = 1.0
    # Production window: the real engine samples K-token fused windows
    # (engine decode_steps), not single tokens — tokens become emittable in
    # groups of this size, so frontend-path costs are modeled per window.
    delta_tokens: int = 1
    # Emit coalescing (bounded-latency): when the stream is BEHIND its
    # simulated schedule (event loop congested — exactly when the Python
    # frontend path is the bottleneck), all due windows batch into one
    # frame up to this cap. 0 disables coalescing (one frame per window,
    # the legacy shape). Coalescing adds no latency: a frame always
    # flushes before the stream sleeps for the next not-yet-due token.
    delta_max_tokens: int = 64
    # Optional extra hold (simulated ms, scaled like all times): let a
    # complete window ride through sleeps this long to gather more windows
    # per frame. 0 = never hold across a sleep. Bounds added ITL.
    delta_max_ms: float = 0.0
    # Seeded fault injection (runtime/chaos.py): per-step worker-kill draws.
    chaos: ChaosInjector | None = None

    def scaled(self, ms: float) -> float:
        return ms / (1000.0 * self.speedup)


class MockerEngine:
    """AsyncEngine shape: PreprocessedRequest dict in → LLMEngineOutput
    dicts out. Echoes the prompt cyclically as its "generation"."""

    def __init__(self, args: MockerArgs | None = None, event_sink=None):
        self.args = args or MockerArgs()
        self.pool = BlockPool(
            self.args.num_kv_blocks, self.args.block_size, event_sink=event_sink
        )
        self._active = 0
        self._waiting = 0
        self._slots = asyncio.Semaphore(self.args.max_num_seqs)
        self.total_generated = 0

    def metrics(self) -> ForwardPassMetrics:
        return ForwardPassMetrics(
            worker=WorkerStats(
                request_active_slots=self._active,
                request_total_slots=self.args.max_num_seqs,
                num_requests_waiting=self._waiting,
            ),
            kv=KvStats(
                kv_active_blocks=self.pool.num_active,
                kv_total_blocks=self.pool.num_blocks - 1,
                gpu_cache_usage_perc=self.pool.usage,
                gpu_prefix_cache_hit_rate=self.pool.hit_rate,
            ),
        )

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        req = request if isinstance(request, PreprocessedRequest) else PreprocessedRequest.from_dict(request)
        if not req.token_ids:
            yield LLMEngineOutput(finish_reason=FinishReason.ERROR, error="empty prompt").to_dict()
            return
        self._waiting += 1
        acquired = False
        # Worker engine phase spans parent on the hop's wire.serve span
        # (messaging re-anchored context.trace on it).
        qspan = tracing.start_span_if(
            context.trace, "engine.queue", waiting=self._waiting
        )
        try:
            await self._slots.acquire()
            acquired = True
            qspan.end()
            self._waiting -= 1
            self._active += 1
            try:
                async for item in self._run(req, context):
                    yield item
            finally:
                self._active -= 1
        finally:
            qspan.end(status="abandoned")  # no-op once the slot was acquired
            if acquired:
                self._slots.release()
            else:
                self._waiting -= 1  # abandoned while queued

    async def _run(self, req: PreprocessedRequest, context: Context) -> AsyncIterator[dict]:
        a = self.args
        bs = a.block_size
        prompt = req.token_ids
        plen = len(prompt)
        max_hit = (plen - 1) // bs
        hashes = compute_block_hashes(prompt, bs)[:max_hit]
        total_blocks = (plen + bs - 1) // bs
        try:
            block_ids, n_hit = self.pool.allocate_sequence(hashes, total_blocks)
        except NoFreeBlocksError:
            yield LLMEngineOutput(
                finish_reason=FinishReason.ERROR, error="KV cache exhausted"
            ).to_dict()
            return
        block_seq = TokenBlockSequence(prompt, bs)
        dspan = tracing.NOOP_SPAN
        emitted = 0
        try:
            # Simulated prefill: cached prefix blocks are free; concurrent
            # occupancy inflates it (contending prefills share the chip).
            uncached = plen - n_hit * bs
            slot_frac = self._active / max(self.args.max_num_seqs, 1)
            ttft = (a.ttft_ms + a.prefill_ms_per_token * uncached) * (
                1.0 + a.prefill_contention * slot_frac
            )
            with tracing.start_span_if(
                context.trace, "engine.prefill",
                prompt_tokens=plen, uncached_tokens=uncached, cached_blocks=n_hit,
            ):
                await asyncio.sleep(a.scaled(ttft))
                for i, blk in enumerate(block_seq.blocks):
                    self.pool.register_block(block_ids[i], blk.sequence_hash, blk.parent_sequence_hash)
            dspan = tracing.start_span_if(context.trace, "engine.decode")

            max_tokens = req.stop.max_tokens or 64
            eos = set(req.eos_token_ids) | set(req.stop.stop_token_ids)
            want_lp = req.sampling.logprobs
            top_n = req.sampling.top_logprobs if want_lp else 0
            window = max(a.delta_tokens, 1)
            cap = max(a.delta_max_tokens, window) if a.delta_max_tokens > 0 else window
            hold_s = a.scaled(a.delta_max_ms) if a.delta_max_ms > 0 else 0.0
            burst: list[int] = []
            burst_lps: list[float] | None = [] if want_lp else None
            burst_tops: list | None = [] if top_n else None
            burst_t0 = 0.0

            def frame(finish: FinishReason | None = None) -> dict:
                # One delta for everything pending — a finish discovered
                # with a non-empty burst rides the SAME frame (never a
                # trailing finish-only frame + extra queue hop).
                nonlocal burst, burst_lps, burst_tops
                d = LLMEngineOutput(
                    token_ids=burst, finish_reason=finish,
                    log_probs=burst_lps or None, top_log_probs=burst_tops or None,
                ).to_dict()
                burst = []
                burst_lps = [] if want_lp else None
                burst_tops = [] if top_n else None
                return d

            # Per-token due times: token i is due itl_i after token i-1.
            # On schedule the stream sleeps between tokens and emits one
            # frame per production window; behind schedule (loop congested)
            # every already-due token batches into the current frame.
            next_due = time.perf_counter()
            while emitted < max_tokens:
                if emitted:
                    # Batch effect + KV paging pressure (superlinear near
                    # full) — the saturation curve planner sweeps see.
                    usage = self.pool.usage
                    itl = a.itl_ms * (
                        1.0 + a.itl_batch_slope * max(self._active - 1, 0)
                    ) * (1.0 + a.itl_kv_pressure * usage * usage)
                    next_due += a.scaled(itl)
                    now = time.perf_counter()
                    if next_due > now:
                        # About to sleep: flush completed windows unless the
                        # hold knob lets them gather (bounded by hold_s).
                        if len(burst) >= window and (
                            hold_s <= 0.0 or now - burst_t0 >= hold_s
                        ):
                            yield frame()
                        await asyncio.sleep(next_due - now)
                if context.cancelled:
                    # flush the pending burst so counted tokens are delivered
                    yield frame(FinishReason.CANCELLED)
                    return
                # Out of budget mid-generation: raise the typed error (the
                # messaging layer sends it as a "deadline" err frame) — the
                # worker stops burning slots on a request nobody can use.
                context.check_deadline()
                if a.chaos is not None:
                    a.chaos.maybe_kill()
                token = prompt[emitted % plen]  # deterministic echo
                if block_seq.total_tokens + 1 > len(block_ids) * bs:
                    try:
                        block_ids.append(self.pool.allocate_block())
                    except NoFreeBlocksError:
                        yield frame(FinishReason.LENGTH)
                        return
                sealed = block_seq.append(token)
                emitted += 1
                self.total_generated += 1
                if sealed is not None:
                    idx = len(block_seq.blocks) - 1
                    self.pool.register_block(
                        block_ids[idx], sealed.sequence_hash, sealed.parent_sequence_hash
                    )
                finish = None
                if token in eos and not req.stop.ignore_eos and emitted >= req.stop.min_tokens:
                    finish = FinishReason.STOP
                elif emitted >= max_tokens:
                    finish = FinishReason.LENGTH
                if not burst:
                    burst_t0 = time.perf_counter()
                burst.append(token)
                if want_lp:
                    # Deterministic fake logprobs: a pure function of the
                    # token id, so coalesced and per-token streams must
                    # attribute identically (frontend logprob-path tests).
                    lp = -((token % 13) + 1) / 16.0
                    burst_lps.append(lp)
                    if top_n:
                        burst_tops.append(
                            [[token + r, lp - 0.25 * r] for r in range(top_n)]
                        )
                if finish is not None:
                    yield frame(finish)
                    return
                if len(burst) >= cap:
                    yield frame()
                    # Behind schedule the production loop has no awaits:
                    # give other streams a scheduling slot per cap flush.
                    await asyncio.sleep(0)
        finally:
            dspan.set_attrs(tokens=emitted)
            dspan.end(status="cancelled" if context.cancelled else None)
            self.pool.free_sequence(block_ids)
