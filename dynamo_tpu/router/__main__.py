"""Standalone KV-router component: `python -m dynamo_tpu.router`.

Reference analogue: components/router/src/main.rs:27-115 — a router
service other components query for placement decisions (worker id +
overlap) without the frontend in the path. Serves two endpoints on its
own component:

- ``route``: one-shot placement — {token_ids} → {worker_instance_id,
  overlap_blocks} (the reference's `generate` returning the chosen
  worker id).
- ``generate``: full routed proxy — forwards the request to the chosen
  backend worker and relays its stream (so lightweight clients get
  KV-aware routing without running the scheduler themselves).
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import signal

from dynamo_tpu.kv_router.router import KvPushRouter, KvRouterConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.push_router import RouterMode

log = get_logger("router")


def parse_args(argv=None):
    p = argparse.ArgumentParser(prog="dynamo_tpu.router")
    p.add_argument("--store-url", default=None)
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="router", help="component THIS service registers as")
    p.add_argument("--backend-component", default="backend", help="worker component to route over")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--kv-overlap-score-weight", type=float, default=1.0)
    p.add_argument("--router-temperature", type=float, default=0.0)
    p.add_argument("--no-kv-events", action="store_true")
    p.add_argument("--index-shards", type=int, default=0,
                   help="KV index shard threads (0 = in-loop; reference: KvIndexerSharded)")
    p.add_argument("--shortlist-k", type=int, default=16,
                   help="candidate pruning: top-k holder shortlist + least-loaded "
                        "workers only (0 = legacy full scan)")
    return p.parse_args(argv)


async def async_main(args) -> None:
    rt = await DistributedRuntime.create(store_url=args.store_url)
    backend_ep = (
        rt.namespace(args.namespace).component(args.backend_component).endpoint(args.endpoint)
    )
    push = await backend_ep.router(RouterMode.DIRECT)
    kv = await KvPushRouter(
        push,
        KvRouterConfig(
            block_size=args.block_size,
            overlap_score_weight=args.kv_overlap_score_weight,
            router_temperature=args.router_temperature,
            use_kv_events=not args.no_kv_events,
            index_shards=args.index_shards,
            shortlist_k=args.shortlist_k,
        ),
    ).start()

    async def route(payload, ctx):
        from dynamo_tpu.runtime.push_router import NoInstancesError

        tokens = list((payload or {}).get("token_ids") or [])
        try:
            wid, overlap = kv.find_best_match(tokens)
        except NoInstancesError:
            yield {"error": "no available workers"}
            return
        yield {"worker_instance_id": wid, "overlap_blocks": overlap}

    async def generate(payload, ctx):
        async for item in kv.generate(payload, ctx):
            yield item

    try:
        comp = rt.namespace(args.namespace).component(args.component)
        await comp.endpoint("route").serve(route)
        await comp.endpoint(args.endpoint).serve(generate)
        print(
            f"dynamo_tpu router: {args.namespace}/{args.component} routing over "
            f"{args.backend_component}/{args.endpoint}",
            flush=True,
        )

        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            with contextlib.suppress(NotImplementedError):
                loop.add_signal_handler(sig, stop.set)
        await stop.wait()
    finally:
        # Cancellation must still tear down subscriptions + deregister.
        await kv.close()
        await rt.shutdown()


def main(argv=None) -> int:
    asyncio.run(async_main(parse_args(argv)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
