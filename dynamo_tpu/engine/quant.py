"""Weight-only int8 quantization.

Decode is weight-bandwidth-bound (2 bytes/param/step in bf16); storing
the big matmul weights as int8 with per-output-channel scales halves the
traffic, and XLA:TPU fuses the int8→bf16 dequant into the matmul operand
read (measured 2.4x on v5e decode-shaped matmuls, tools notes). This is
also what fits llama-8b on a single 16GB v5e chip.

Reference analogue: the quantized-serving configs the reference reaches
through its engines (vLLM/TRT-LLM int8/fp8 weight formats); here the
format is ours: ``w_int8 [in, out]`` + ``scale bf16 [out]`` per weight,
with ``<name>_scale`` leaves riding the same pytree (model._w dequants).

Quantized leaves: per-layer matmul weights, the embedding table, and the
untied lm_head. Norms stay high-precision.
"""

from __future__ import annotations

from typing import Any

import numpy as np

# Leaves quantized along their OUTPUT channel (last axis).
_LAYER_WEIGHTS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_np(w: np.ndarray, axis: int = -1) -> tuple[np.ndarray, np.ndarray]:
    """→ (int8 weights, float32 per-channel scales) with symmetric
    absmax scaling along ``axis``'s complement (scale per output slice)."""
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(i for i in range(w.ndim) if i != (axis % w.ndim))
    absmax = np.max(np.abs(w), axis=reduce_axes)
    scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    shape = [1] * w.ndim
    shape[axis % w.ndim] = -1
    q = np.clip(np.rint(w / scale.reshape(shape)), -127, 127).astype(np.int8)
    return q, scale


def quantize_layer_stacks_np(layers: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Quantize the stacked [L, in, out] layer weights in place-style:
    returns a new dict with int8 leaves + ``<name>_scale`` [L, out].
    MoE expert stacks are left unquantized (their einsum path has no
    int8 dequant fusion yet)."""
    out = dict(layers)
    for name in _LAYER_WEIGHTS:
        if name not in layers:
            continue
        w = np.asarray(layers[name], np.float32)  # [L, in, out]
        absmax = np.max(np.abs(w), axis=1)        # [L, out]
        scale = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
        out[name] = np.clip(
            np.rint(w / scale[:, None, :]), -127, 127
        ).astype(np.int8)
        out[name + "_scale"] = scale
    return out


def quantize_params_np(params: dict[str, Any]) -> dict[str, Any]:
    """Host-side quantization of a full (numpy) params pytree."""
    out = dict(params)
    out["layers"] = quantize_layer_stacks_np(
        {k: np.asarray(v) for k, v in params["layers"].items()}
    )
    emb_q, emb_s = quantize_np(np.asarray(params["embed"]), axis=0)  # scale per vocab row
    out["embed"] = emb_q
    out["embed_scale"] = emb_s
    if "lm_head" in params:
        q, s = quantize_np(np.asarray(params["lm_head"]), axis=-1)   # [D, V] → scale per V
        out["lm_head"] = q
        out["lm_head_scale"] = s
    return out


def _int8_layer_specs(cfg) -> dict[str, tuple[tuple, int]]:
    """name → (stacked shape, fan_in) for the quantized layer matmuls —
    the single source both random-init variants build from."""
    d, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    return {
        "wq": ((L, d, cfg.q_size), d), "wk": ((L, d, cfg.kv_size), d),
        "wv": ((L, d, cfg.kv_size), d), "wo": ((L, cfg.q_size, d), cfg.q_size),
        "w_gate": ((L, d, i), d), "w_up": ((L, d, i), d), "w_down": ((L, i, d), i),
    }


def random_int8_params(cfg, seed: int = 0, dtype: str = "bfloat16") -> dict[str, Any]:
    """Random int8 params generated host-side layer by layer — the bench
    path for geometries whose bf16 random init would not fit HBM (8B on
    one v5e). Values are benchmark-plausible (small scales keep the
    forward finite); decode timing is weight-value-independent."""
    if getattr(cfg, "num_experts", 0):
        raise NotImplementedError("int8 random init not wired for MoE configs")
    attn_bias = getattr(cfg, "attn_bias", False)
    import ml_dtypes

    # Norms define the activation compute dtype (model._embed_rows keys
    # off attn_norm.dtype): f32 norms would silently drag the whole
    # forward to f32 matmuls.
    ndt = ml_dtypes.bfloat16 if dtype == "bfloat16" else np.dtype(dtype)
    rng = np.random.default_rng(seed)
    d, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers

    def q(shape, fan_in):
        return (
            rng.integers(-127, 128, size=shape, dtype=np.int16).astype(np.int8),
            np.full(shape[-1], (fan_in ** -0.5) / 64.0, np.float32),
        )

    layers: dict[str, np.ndarray] = {}
    for name, (shape, fan) in _int8_layer_specs(cfg).items():
        w, s = q(shape, fan)
        layers[name] = w
        layers[name + "_scale"] = np.broadcast_to(
            s, (L, shape[-1])
        ).copy()
    layers["attn_norm"] = np.ones((L, d), ndt)
    layers["mlp_norm"] = np.ones((L, d), ndt)
    if attn_bias:
        # Biases stay float (never quantized), same as real checkpoints.
        layers["bq"] = (rng.standard_normal((L, cfg.q_size)) * 0.02).astype(ndt)
        layers["bk"] = (rng.standard_normal((L, cfg.kv_size)) * 0.02).astype(ndt)
        layers["bv"] = (rng.standard_normal((L, cfg.kv_size)) * 0.02).astype(ndt)
    params: dict[str, Any] = {
        "embed": rng.integers(-127, 128, size=(cfg.vocab_size, d), dtype=np.int16).astype(np.int8),
        "embed_scale": np.full((cfg.vocab_size,), (d ** -0.5) / 64.0, np.float32),
        "layers": layers,
        "final_norm": np.ones((d,), ndt),
    }
    if not cfg.tie_embeddings:
        w, s = q((d, cfg.vocab_size), d)
        params["lm_head"] = w
        params["lm_head_scale"] = s
    return params


def random_int8_params_device(cfg, seed: int = 0, dtype: str = "bfloat16") -> dict[str, Any]:
    """Device-side variant of ``random_int8_params``: every leaf is
    generated ON the accelerator, so an 8B bench engine start pays zero
    weight upload (the 8 GB host→device transfer through an axon tunnel
    measures ~25-30 MB/s ≈ 5 minutes — device threefry generates the
    same bytes in under a second). Same pytree shapes/dtypes as the host
    variant; single-device only (sharded multi-host init keeps the host
    path so every process materializes identical addressable shards)."""
    if getattr(cfg, "num_experts", 0):
        raise NotImplementedError("int8 random init not wired for MoE configs")
    import jax
    import jax.numpy as jnp

    ndt = jnp.bfloat16 if dtype == "bfloat16" else jnp.dtype(dtype)
    d, i, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers
    attn_bias = getattr(cfg, "attn_bias", False)

    @jax.jit
    def build():
        key = jax.random.PRNGKey(seed)

        def q(idx, shape, fan_in):
            w = jax.random.randint(
                jax.random.fold_in(key, idx), shape, -127, 128, jnp.int8
            )
            s = jnp.full((L, shape[-1]), (fan_in ** -0.5) / 64.0, jnp.float32)
            return w, s

        layers: dict[str, Any] = {}
        for idx, (name, (shape, fan)) in enumerate(_int8_layer_specs(cfg).items()):
            w, s = q(idx, shape, fan)
            layers[name] = w
            layers[name + "_scale"] = s
        layers["attn_norm"] = jnp.ones((L, d), ndt)
        layers["mlp_norm"] = jnp.ones((L, d), ndt)
        if attn_bias:
            bkey = jax.random.fold_in(key, 31)
            layers["bq"] = (jax.random.normal(bkey, (L, cfg.q_size)) * 0.02).astype(ndt)
            layers["bk"] = (jax.random.normal(jax.random.fold_in(bkey, 1), (L, cfg.kv_size)) * 0.02).astype(ndt)
            layers["bv"] = (jax.random.normal(jax.random.fold_in(bkey, 2), (L, cfg.kv_size)) * 0.02).astype(ndt)
        params: dict[str, Any] = {
            "embed": jax.random.randint(
                jax.random.fold_in(key, 90), (cfg.vocab_size, d), -127, 128, jnp.int8
            ),
            "embed_scale": jnp.full((cfg.vocab_size,), (d ** -0.5) / 64.0, jnp.float32),
            "layers": layers,
            "final_norm": jnp.ones((d,), ndt),
        }
        if not cfg.tie_embeddings:
            w, s = q(91, (d, cfg.vocab_size), d)
            params["lm_head"] = w
            params["lm_head_scale"] = s[0]
        return params

    return build()
