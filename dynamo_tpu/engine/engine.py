"""TpuEngine: continuous-batching inference engine over the jitted model.

Replaces what the reference delegates to vLLM's ``AsyncLLM``
(reference: components/backends/vllm/src/dynamo/vllm/main.py:90,
handlers.py:113): admission, paged-KV allocation with prefix caching,
prefill (chunked, prefix-skipping), batched decode, on-device sampling,
per-request streaming, cancellation, preemption-by-recompute, KV events
and load metrics.

Threading model: JAX dispatch is blocking, so the scheduler loop runs in a
dedicated thread; asyncio callers submit requests through a lock-guarded
queue and receive ``LLMEngineOutput`` dicts on per-request asyncio queues
via ``loop.call_soon_threadsafe``.

Host↔device sync budget (the latency cost model): one *fetch* per
``decode_steps``-token fused window (model.multi_decode feeds sampled
tokens back on device) and one per admission wave (all first tokens
sampled together) — and the host starts every fetch asynchronously at
dispatch time (``copy_to_host_async``), harvesting results from a FIFO
completion queue by readiness polling. The scheduler therefore blocks on
a fetch only when the window pipeline is full (``pipeline_depth``
windows in flight) or a consumer needs host-visible tokens (full
sampler, per-step path, preemption); admission, prefill dispatch and the
next window dispatch all proceed while fetches are in flight. Per-step
syncing (decode_steps=1) is the fallback for full-sampler batches and
near-max_model_len sequences.
"""

from __future__ import annotations

import asyncio
import collections
import random
import threading
import time
from typing import Any, AsyncIterator

import numpy as np

from dynamo_tpu.block_manager.adapters import AdapterSlotPool
from dynamo_tpu.block_manager.pool import BlockPool, NoFreeBlocksError
from dynamo_tpu.engine import kv_transfer
from dynamo_tpu.engine.config import EngineArgs
from dynamo_tpu.engine.lora import (
    LoraAdapterSpec,
    adapter_tier_hash,
    make_adapter_pages,
)
from dynamo_tpu.engine.drafter import (
    DraftConstraint,
    TreeDraft,
    build_drafter,
    constrain_chain,
)
from dynamo_tpu.engine.grammar import (
    GrammarError,
    build_compiler,
    mask_words,
    pack_token_ids,
)
from dynamo_tpu.engine.runner import host_ready, start_host_fetch
from dynamo_tpu.engine.sampler import needs_full, row_needs_full
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics, KvCacheEvent, KvStats, WorkerStats
from dynamo_tpu.llm.protocols import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
    coalesce_delta,
)
from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.qos import DEFAULT_CLASS, QOS_CLASSES, qos_rank
from dynamo_tpu.tokens import (
    TokenBlockSequence,
    adapter_hash_seed,
    compute_block_hashes,
)
from dynamo_tpu.transfer.stream import KvChunk, KvStreamExport

log = get_logger("engine")

_SENTINEL_DONE = object()

# Adaptive tree budgets: the per-row draft-node cap, as a multiple of
# spec_tokens. Bounding hot rows at 2x keeps the verify-shape lattice at
# two S1 values (S+1 and 2S+1) instead of one compile per allocation.
SPEC_BUDGET_MAX_MULT = 2


class RequestValidationError(Exception):
    """Client error (clean rejection, no stack trace)."""


def trim_spec_budgets(rows: list[tuple[float, int]], S: int) -> list[int]:
    """Batch-level draft-node reallocation (ROADMAP 6 fold-in), the trim
    half: rows drafted OPTIMISTICALLY (each up to min(cap, 2S) nodes —
    drafting is host dict probes, cheap), and this decides how many
    nodes each row KEEPS so the batch stays under the fixed uniform
    budget ``len(rows) * S``. ``rows`` = per-row (spec_ema,
    drafted_len).

    Rows that drafted short (no index/pool hit, cooldown, near model
    end) implicitly donate their unused allowance; when the total still
    exceeds the budget, EMA-cold rows are trimmed back toward their
    EMA-desired length — the SAME shrink the uniform path applies
    (S * ema / 0.5, floor 1) — coldest first.

    Invariants (pinned by tests):
    - sum(keep) <= len(rows) * S (never exceeds the uniform total);
    - keep_i >= min(drafted_i, 1) (a drafting row is never starved —
      its probe survives, so its EMA can re-heat);
    - keep_i >= min(drafted_i, desired_i) (no row keeps fewer nodes
      than the uniform path's EMA shrink would have drafted — per-row
      drafts dominate uniform's, so greedy batch tokens-per-weight-pass
      can only go up at equal total node budget);
    - keep_i <= drafted_i.

    Feasibility: sum(min(drafted, desired)) <= len(rows) * S always
    (desired <= S per row), so trimming to desired always lands under
    budget. Hot rows — grammar-constrained rows above all (near-perfect
    drafts: forced JSON structure runs past S) — keep their full 2S
    drafts whenever cold rows leave room, which is where the
    reallocation pays."""
    n = len(rows)
    keep = [d for _, d in rows]
    if n == 0 or S <= 0:
        return [0] * n
    total = sum(keep)
    limit = n * S
    if total <= limit:
        return keep
    order = sorted(range(n), key=lambda i: (rows[i][0], i))  # coldest first
    for i in order:
        if total <= limit:
            break
        desired = max(1, round(S * min(1.0, rows[i][0] / 0.5)))
        cut = min(keep[i] - min(keep[i], desired), total - limit)
        keep[i] -= cut
        total -= cut
    return keep


class _Seq:
    __slots__ = (
        "request_id", "tokens", "prompt_len", "sampling", "stop", "eos_ids",
        "block_ids", "block_seq", "registered_blocks", "queue", "emitted",
        "cancelled", "preempted", "prefix_hit_blocks", "sample_seed",
        "kv_written", "export", "export_meta", "inject", "dead",
        "slot", "first_pend", "t_admit",
        "spec_ema", "spec_cool", "draft_state",
        "export_handle", "export_stream", "export_pub_blocks",
        "grammar", "grammar_state", "grammar_eos_bits",
        "adapter_id", "adapter_slot", "hash_seed",
        "qos", "qos_rank", "arrival",
        "step_base", "mig", "offer_deadline", "traceparent",
    )

    def __init__(self, request_id: str, req: PreprocessedRequest, queue: asyncio.Queue):
        self.request_id = request_id
        self.tokens: list[int] = list(req.token_ids)
        self.prompt_len = len(req.token_ids)
        self.sampling = req.sampling
        self.stop = req.stop
        self.eos_ids = set(req.eos_token_ids) | set(req.stop.stop_token_ids)
        self.block_ids: list[int] = []
        self.block_seq: TokenBlockSequence | None = None
        self.registered_blocks = 0
        self.queue = queue
        self.emitted = 0
        self.cancelled = False
        self.preempted = False
        self.prefix_hit_blocks = 0
        # Seeded requests are reproducible; others get a per-request seed.
        self.sample_seed = (
            req.sampling.seed if req.sampling.seed is not None else random.getrandbits(31)
        ) & 0x7FFFFFFF
        # Number of positions whose KV is actually in the cache. Blocks may
        # only be registered for prefix reuse once fully *written* — a
        # just-sampled token's KV lands on the NEXT step (it is that step's
        # input), so sealing a block lags writing it.
        self.kv_written = 0
        # Tracing stamp (perf_counter, set by the scheduler thread when the
        # request wins admission): splits queue-wait from prefill in the
        # consumer coroutine's retroactive spans.
        self.t_admit: float | None = None
        # Finished/cancelled (set by _finish). In-flight decode windows
        # drain after the fact; dead rows' outputs are discarded.
        self.dead = False
        # Stable device chain slot (runner._last_toks index) while
        # running; first_pend = first token sampled on device but not yet
        # fetched/emitted (async admission).
        self.slot: int | None = None
        self.first_pend = False
        # Speculative decoding: per-sequence acceptance-rate EMA (starts
        # optimistic so new sequences get full drafts; a few rejected
        # passes decay it below the disable threshold), cooldown counter
        # of decode iterations before a disabled/draft-less row proposes
        # again, and the drafter's incremental n-gram index (built lazily
        # on the first draft call).
        self.spec_ema = 1.0
        self.spec_cool = 0
        self.draft_state = None
        # Grammar-constrained decoding (engine/grammar.py): the compiled
        # token-FSM shared by every request using the same schema, this
        # sequence's FSM state (advanced host-side per EMITTED token —
        # the prompt is unconstrained), and the packed EOS bitset OR-ed
        # into terminal-state masks. Attached by generate() before
        # submission; None = unconstrained.
        self.grammar = None
        self.grammar_state = 0
        self.grammar_eos_bits: np.ndarray | None = None
        # Multi-LoRA: the request's adapter identity (None = base), its
        # resident bank slot while admitted (-1 = none/base; the pin is
        # released at finish/preempt), and the adapter-salted hash seed
        # that partitions KV identity — block hashes, tier keys, KV
        # events and router stickiness all derive from it, so an
        # adapter's KV can never prefix-hit another identity's.
        self.adapter_id = getattr(req, "adapter_id", None)
        self.adapter_slot = -1
        self.hash_seed = adapter_hash_seed(self.adapter_id)
        # Multi-tenant QoS: the request's priority class name (metrics
        # label; unknown wire values fall back to the default class),
        # its scheduling rank (generate() zeroes it when
        # args.qos_scheduling is off), and the engine-assigned arrival
        # number — the (class, age) sort key for admission order and
        # preemption victim selection.
        self.qos = (
            getattr(req, "priority", None)
            if getattr(req, "priority", None) in QOS_CLASSES
            else DEFAULT_CLASS
        )
        self.qos_rank = qos_rank(getattr(req, "priority", None))
        self.arrival = 0
        # Disaggregation (engine side of llm/disagg.py):
        ktp = req.kv_transfer_params or {}
        self.export = bool(ktp.get("do_remote_decode"))  # prefill-only + export KV
        self.export_meta: dict | None = None             # filled at prefill time
        self.inject = ktp.get("inject")                  # KvPagePayload dict to pre-load
        # Streaming export (dynamo_tpu/transfer): with a decode-worker-
        # minted stream_handle, KV chunks publish DURING prefill instead
        # of one payload after it. export_pub_blocks tracks contiguous
        # published coverage.
        self.export_handle = ktp.get("stream_handle") if self.export else None
        self.export_stream: KvStreamExport | None = None
        self.export_pub_blocks = 0
        # Live migration: sampler step offset (a resumed sequence keeps
        # drawing the SOURCE's gumbel index sequence: same seed, steps
        # continue at step_base + emitted) and the outbound migration
        # state while this sequence is being relocated (engine-thread
        # owned, via _migrations).
        self.step_base = 0
        self.mig = None
        # W3C traceparent of the client request this sequence serves
        # (stamped by generate() from the wire context). Rides the
        # migration protocol so the source coordinator's admin RPCs and
        # the destination's resume leg all join the ORIGINAL trace.
        self.traceparent: str | None = None
        # Preemption-offer grace: when a migration offer hook fires for
        # this sequence as a preemption victim, the kill waits until
        # this deadline for the relocation to free the blocks instead.
        self.offer_deadline = 0.0
        # Resume identity (live migration / re-dispatch): the original
        # prompt boundary survives worker changes — penalties and grammar
        # replay key off it — and seed/step/EMA continue the source's.
        resume = ktp.get("resume")
        if isinstance(resume, dict):
            pl = resume.get("prompt_len")
            if isinstance(pl, int) and 1 <= pl <= len(self.tokens):
                self.prompt_len = pl
            if resume.get("sample_seed") is not None:
                self.sample_seed = int(resume["sample_seed"]) & 0x7FFFFFFF
            self.step_base = int(resume.get("sample_step") or 0)
            if resume.get("spec_ema") is not None:
                self.spec_ema = float(resume["spec_ema"])

    @property
    def next_write_pos(self) -> int:
        return len(self.tokens) - 1


class _Window:
    """One dispatched multi-step decode window (results not yet fetched)."""

    __slots__ = ("rows", "pos0", "K", "ref", "row_of", "top_n")

    def __init__(self, rows: list[_Seq], pos0: list[int], K: int, ref, top_n: int = 0):
        self.rows = rows
        self.pos0 = pos0
        self.K = K
        # StepRef: arrs = (toks [K,B], logps [K,B], tvals [K,B,top_n], tids)
        self.ref = ref
        self.row_of = {s: i for i, s in enumerate(rows)}
        self.top_n = top_n

    def fetch_arrays(self) -> list:
        a = [self.ref.arrs[0], self.ref.arrs[1]]
        if self.top_n:
            a += [self.ref.arrs[2], self.ref.arrs[3]]
        return a


class _MigSt:
    """Engine-thread state of one outbound live migration: the sequence
    keeps decoding while its sealed KV blocks publish as stream chunks
    (``pump``), until the coordinator freezes it for the bounded cutover
    window. ``fetches`` are this migration's in-flight page extracts
    (lo, hi, device arrays, bucket n), harvested strictly in dispatch
    order so the consumer's chunk coverage stays contiguous."""

    __slots__ = ("seq", "handle", "stream", "pub_blocks", "frozen",
                 "freeze_deadline", "fetches")

    def __init__(self, seq: "_Seq", handle: str, stream: KvStreamExport):
        self.seq = seq
        self.handle = handle
        self.stream = stream
        self.pub_blocks = 0
        self.frozen = False
        self.freeze_deadline = 0.0
        self.fetches: list = []


class _Spec:
    """One dispatched speculative verify pass (results not yet fetched).
    Unlike a _Window, the number of tokens a row will emit (1 + accepted
    drafts) is unknown until the fetch lands, so the scheduler never
    plans further decode work for these rows while a _Spec is queued —
    _decode_iteration force-drains any queued _Spec before planning.

    ``draft_lens`` counts proposed draft NODES per row (the token budget
    spent); ``potentials`` the max accepted run each proposal could
    yield — equal for a linear draft, the deepest path for a tree (the
    honest EMA denominator). ``node_tokens``/``node_parents`` keep the
    host-side tree views so the drain can feed the drafter's Jacobi
    pool without re-fetching anything."""

    __slots__ = ("rows", "pos0", "draft_lens", "potentials", "ref",
                 "top_n", "tree", "node_tokens", "node_parents")

    def __init__(self, rows: list[_Seq], pos0: list[int],
                 draft_lens: list[int], ref, top_n: int = 0,
                 potentials: list[int] | None = None, tree: bool = False,
                 node_tokens: list[list[int]] | None = None,
                 node_parents: list[list[int]] | None = None):
        self.rows = rows
        self.pos0 = pos0
        self.draft_lens = draft_lens
        self.potentials = potentials or draft_lens
        # StepRef: arrs = (out [B, S1], n_emit [B], logps [B, S1],
        # cand [B, S1], top_vals [B, S1, n], top_ids [B, S1, n])
        self.ref = ref
        self.top_n = top_n
        self.tree = tree
        self.node_tokens = node_tokens
        self.node_parents = node_parents

    def fetch_arrays(self) -> list:
        a = [self.ref.arrs[0], self.ref.arrs[1], self.ref.arrs[2],
             self.ref.arrs[3]]
        if self.top_n:
            a += [self.ref.arrs[4], self.ref.arrs[5]]
        return a


class _First:
    """One dispatched admission wave's first-token sample (not yet
    fetched). Entries: (seq, row) into the wave's padded sample batch."""

    __slots__ = ("entries", "out_d", "lps_d", "top_ref")

    def __init__(self, entries: list[tuple[_Seq, int]], out_d, lps_d, top_ref):
        self.entries = entries
        self.out_d = out_d
        self.lps_d = lps_d
        self.top_ref = top_ref

    def fetch_arrays(self) -> list:
        a = [self.out_d, self.lps_d]
        if self.top_ref is not None:
            a += [self.top_ref.arrs[0], self.top_ref.arrs[1]]
        return a


# Host-side phases during which the scheduler thread is (or may be)
# BLOCKED on a device fetch/sync — the bench.py host_blocked_frac
# numerator. drain_ready is included conservatively: is_ready() reflects
# device COMPUTE completion, not arrival of the async D2H copy, so a
# "ready" drain's np.asarray can still wait out the transfer tail on a
# slow link; counting it keeps the metric an honest upper bound (it is
# ~µs when overlap works, which is the claim being measured).
BLOCKING_PHASES = ("first_sample", "drain_sync", "drain_ready", "single_step")


def register_engine_metrics(registry):
    """Register the engine gauges/counters on a MetricsRegistry →
    (inflight windows, pending first fetches, prefill pad ratio,
    spec proposed counter, spec accepted counter, spec accept-rate gauge,
    tokens-per-weight-pass gauge). Shared by the worker (bind_metrics)
    and the tools/check_metrics.py catalog guard."""
    return (
        registry.gauge(
            "engine_inflight_windows",
            "Decode windows dispatched on device but not yet drained",
        ),
        registry.gauge(
            "engine_pending_first_fetches",
            "Admission first-token sample fetches in flight",
        ),
        registry.gauge(
            "engine_prefill_pad_ratio",
            "Cumulative dispatched/true prefill token ratio (bucket padding waste)",
        ),
        registry.counter(
            "engine_spec_proposed_total",
            "Draft tokens proposed to speculative verify passes",
        ),
        registry.counter(
            "engine_spec_accepted_total",
            "Proposed draft tokens accepted by speculative verification",
        ),
        registry.gauge(
            "engine_spec_accept_rate",
            "Cumulative accepted/proposed draft-token ratio",
        ),
        registry.gauge(
            "engine_tokens_per_weight_pass",
            "Decode tokens sampled per per-sequence weight stream "
            "(1.0 = dense; >1.0 = speculation paying off)",
        ),
        registry.gauge(
            "engine_kv_cache_bytes",
            "HBM bytes of the G1 paged KV pool (pages + quantization "
            "scales, num_kv_blocks x kv_bytes_per_block)",
        ),
        registry.gauge(
            "engine_kv_quant_enabled",
            "1 when the paged KV cache stores int8 pages (kv_quant), "
            "0 for full-precision storage",
        ),
        registry.counter(
            "engine_spec_tree_passes_total",
            "Speculative verify passes dispatched with a branched "
            "(non-chain) draft tree",
        ),
        registry.gauge(
            "engine_spec_tree_accept_depth",
            "Cumulative mean accepted root-path depth of tree verify "
            "passes (0 = every tree pass rejected at the root)",
        ),
        registry.counter(
            "tier_protected_evictions_total",
            "Host/disk KV tier eviction scans that SPARED a protected "
            "block (high prefix fan-out or recent hits) and evicted a "
            "colder one instead",
        ),
        registry.gauge(
            "tier_hit_rate",
            "Cumulative G2+G3 tier lookup hit rate (hits / (hits + "
            "misses)) — the churn-resistance signal for the "
            "frequency-aware eviction policy",
        ),
        registry.gauge(
            "engine_grammar_active_seqs",
            "Running sequences decoding under a grammar constraint "
            "(response_format token-mask FSMs)",
        ),
        registry.gauge(
            "engine_grammar_mask_seconds",
            "Cumulative host seconds spent building/packing grammar "
            "token masks (FSM walks + bitset gathers per verify slot)",
        ),
        registry.counter(
            "engine_spec_budget_reallocs_total",
            "Speculative verify passes whose batch-level draft-node "
            "budget was reallocated away from the uniform per-row split "
            "(EMA-hot rows drafting past spec_tokens)",
        ),
        registry.gauge(
            "engine_lora_resident_adapters",
            "LoRA adapters currently resident in the device (G1) bank "
            "slots (engine/lora.py; 0 when lora_slots is 0)",
        ),
        registry.counter(
            "engine_lora_swap_total",
            "LoRA adapter page-ins: uploads of adapter factor pages into "
            "a device bank slot (cold fetch through the G2/G3 tier "
            "economy; when slots are full each one evicts a colder "
            "resident)",
        ),
        registry.gauge(
            "engine_lora_gather_seconds",
            "Cumulative host seconds spent on LoRA multiplexing — "
            "resolving adapter slots at admission, uploading factor "
            "pages, and building per-dispatch adapter_slot operands",
        ),
        registry.counter(
            "engine_preemptions_total",
            "Recompute-preemptions under KV pressure by victim QoS "
            "class (victims are lowest-class/newest-first; a preempted "
            "request requeues and re-prefills, so its stream stays "
            "byte-identical under greedy sampling)",
        ),
        registry.counter(
            "tier_g4_hits_total",
            "G4 fleet-shared pool lookups that found the block file "
            "(possibly written by a PEER engine — the cross-engine "
            "dedup payoff)",
        ),
        registry.counter(
            "tier_g4_evictions_total",
            "G4 fleet-pool files pruned by this engine's oldest-mtime "
            "capacity sweep of the SHARED directory",
        ),
        registry.counter(
            "tier_g4_dedup_blocks_total",
            "G4 puts/spill-adoptions skipped because a peer engine "
            "already wrote the identical salted-hash block file",
        ),
    )


class TpuEngine:
    # Scheduler-state ownership manifest, machine-checked by DT001
    # (tools/analysis — keep the mirror in checkers/dt001 in sync). Every
    # attribute named here is owned by the scheduler thread (_run/_step):
    # async-side code may touch one ONLY under `with self._wakeup:` (the
    # handoff protocol for _submissions/_embed_jobs/_host_jobs and the
    # cancel flag) or by shipping a closure via run_on_engine_thread.
    # Deliberately NOT owned: spec_tokens + spec_budget_adaptive
    # (documented idle-engine toggles, read once per scheduler
    # iteration), the total_* counters incl. total_grammar_mask_s
    # (monotonic values read racily by bench/metrics — stale reads are
    # harmless, total_lora_s included), _stopping (always mutex-guarded),
    # pool/tiers/_lora_pool (internally consistent; acquire/release on
    # the scheduler thread, cross-thread readers get point-in-time
    # values), _lora_registry (always _lora_lock-guarded; registration
    # runs from setup/async contexts), and _grammar_compiler (built
    # under _grammar_lock from
    # generate() coroutines; the compiled FSMs it hands out are
    # internally locked, so scheduler-thread mask lookups race async
    # compiles safely).
    _SCHED_OWNED = frozenset({
        "_submissions", "_waiting", "_running", "_fetchq", "_free_slots",
        "_embed_jobs", "_host_jobs", "_offload_pending", "_exports",
        "_export_fetches", "_drafter", "_step_no", "_spec_ticked",
        "phase_s", "phase_n", "_ctr_pushed", "_spec_depth_hist",
        "_migrations",
    })

    def __init__(
        self,
        args: EngineArgs,
        params: Any | None = None,
        seed: int = 0,
        event_sink=None,
        sharding=None,  # dynamo_tpu.parallel.ModelSharding | None
        runner=None,    # engine.runner.ModelRunner | None (multi-host leader)
    ):
        from dynamo_tpu.engine.runner import LocalRunner

        self.args = args
        self.cfg = args.model
        self._runner = runner or LocalRunner(args, params=params, seed=seed, sharding=sharding)
        self._external_events = event_sink
        self.pool = BlockPool(
            args.num_kv_blocks,
            args.block_size,
            event_sink=self._on_pool_event,
            enable_prefix_caching=args.prefix_caching,
        )
        # G2/G3 KV tiers: sealed blocks write through to host (batched per
        # step); prefix misses in HBM onboard from the tiers instead of
        # recomputing (block_manager/tiers.py).
        self.tiers = self._build_tiers(args)
        self._offload_pending: list[tuple[int, int]] = []  # (block_id, seq_hash)

        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._mutex = threading.Lock()
        self._wakeup = threading.Condition(self._mutex)
        self._submissions: collections.deque[_Seq] = collections.deque()
        self._waiting: collections.deque[_Seq] = collections.deque()
        self._running: list[_Seq] = []
        self._stopping = False
        # FIFO completion queue of dispatched-but-unfetched device work:
        # _First admission samples and _Window decode windows, in
        # dispatch order. Every item's D2H fetch was started async at
        # dispatch (start_host_fetch); _drain_completed harvests ready
        # items from the front, and force-drains only when the pipeline
        # is full or host-visible tokens are required. FIFO order is the
        # per-sequence emission-order invariant: a seq's first sample is
        # always queued before any window containing it.
        self._fetchq: collections.deque[_First | _Window | _Spec] = collections.deque()
        self._free_slots: list[int] = list(range(args.max_num_seqs))
        # (tokens, future, loop) embedding jobs; served between scheduler
        # steps on the engine thread (device dispatch affinity).
        self._embed_jobs: collections.deque = collections.deque()
        # (fn, future, loop) host jobs run on the engine thread between
        # steps — the device-dispatch-affinity seam for out-of-band work
        # like AOT-warming the spec_verify compile lattice (bench).
        self._host_jobs: collections.deque = collections.deque()
        # Disagg exports: handle → (KvPagePayload | KvStreamExport,
        # deadline). Host copies, so they survive cache donation; reaped
        # after export_ttl_s (unsealed streams abort at reap time).
        self._exports: dict[str, tuple[Any, float]] = {}
        self.export_ttl_s = 60.0
        # Outbound live migrations: request_id → _MigSt. The scheduler
        # pumps each unfrozen migration's KV delta once per step; frozen
        # ones are auto-unfrozen (and the migration aborted) when the
        # coordinator misses the cutover deadline — a dead coordinator
        # can never wedge a stream.
        self._migrations: dict[str, _MigSt] = {}
        self.migration_freeze_ttl_s = 10.0
        # QoS defrag: when set (worker/roles.py wires it to the
        # migration coordinator), preemption under KV pressure OFFERS
        # the victim a relocation first — called from the scheduler
        # thread with the victim's request id, must be thread-safe —
        # and the kill waits a bounded grace for the offer to land.
        self.migration_offer = None
        self.preempt_offer_grace_s = 0.75
        # Proactive defrag (args.kv_pressure_offer): once pool usage
        # crosses the threshold, the offer hook fires for the cheapest
        # running sequence AHEAD of the preemption boundary, rate-limited
        # so one sustained pressure plateau yields one offer per window
        # rather than one per scheduler step.
        self.kv_pressure_offer = float(getattr(args, "kv_pressure_offer", 0.0) or 0.0)
        self.kv_pressure_offer_window_s = 2.0
        self._pressure_offer_next = 0.0
        self.pressure_offers = 0  # observability: proactive offers fired
        # Streaming-export page fetches in flight: (seq, lo, hi, device
        # arrays, bucket n). Dispatched per prefill chunk with async D2H
        # (start_host_fetch); harvested opportunistically between chunk
        # dispatches and in _step, forced at seal — so page copies and
        # wire sends overlap the remaining prefill chunks.
        self._export_fetches: list = []
        # Speculative decoding: host-side drafter + a runtime-togglable
        # draft length (initialized from args; bench/tests flip it on an
        # idle engine to compare dense vs speculative on one warmed
        # engine — it is read once per scheduler iteration, never mid-
        # dispatch).
        self._drafter = build_drafter(args)
        self.spec_tokens = args.spec_tokens
        # Batch-budget mode toggle: like spec_tokens, a documented
        # idle-engine runtime switch (bench A/Bs adaptive vs uniform on
        # one warmed engine); read once per _try_speculative call.
        self.spec_budget_adaptive = args.spec_budget_adaptive
        # Grammar-constrained decoding: the compiler (vocab + schema
        # cache) is built lazily on the first constrained request, OFF
        # the scheduler thread (generate() compiles via to_thread; the
        # compiled FSMs are internally locked, so scheduler-thread mask
        # lookups race compiles safely). Not scheduler-owned.
        self._grammar_compiler = None
        self._grammar_lock = threading.Lock()
        # Scheduler-step counter + last-ticked stamp: _decode_iteration
        # can re-enter _try_speculative within one step (drain → replan),
        # and probe cooldowns must tick once per STEP, not per attempt.
        self._step_no = 0
        self._spec_ticked = -1
        # Spec counters: proposed/accepted draft tokens, verify
        # dispatches, live row-passes and tokens they emitted — the
        # numerators/denominators for accept-rate and tokens-per-pass.
        self.total_spec_proposed = 0
        self.total_spec_accepted = 0
        self.total_spec_passes = 0
        self.total_spec_rows = 0
        self.total_spec_emitted = 0
        # Tree speculation: branched-pass dispatches, per-row accepted
        # depth sum + row count (mean accept depth), and a small
        # accepted-depth histogram {depth: rows} for the profiler.
        self.total_spec_tree_passes = 0
        self.total_spec_tree_rows = 0
        self.total_spec_tree_depth = 0
        self._spec_depth_hist: collections.Counter = collections.Counter()
        # Grammar + budget accounting (same racy-read contract as the
        # other total_* counters: monotonic, stale reads harmless).
        # total_grammar_mask_s: host seconds building/packing masks;
        # total_spec_budget_reallocs: passes dispatched with a
        # non-uniform node split; total_grammar_seqs: constrained
        # sequences admitted.
        self.total_grammar_mask_s = 0.0
        self.total_spec_budget_reallocs = 0
        self.total_grammar_seqs = 0
        # Multi-LoRA multiplexing (engine/lora.py): the G1 slot pool
        # (block_manager/adapters.py; acquire/release on the scheduler
        # thread, stats read racily — same contract as pool/tiers, so
        # deliberately NOT scheduler-owned) and the adapter registry
        # (adapter_id → LoraAdapterSpec; registered from setup/async
        # contexts under _lora_lock, read at admission). total_lora_s is
        # the engine_lora_gather_seconds feed (racy-total contract).
        self._lora_pool = (
            AdapterSlotPool(args.lora_slots) if args.lora_slots > 0 else None
        )
        self._lora_registry: dict[str, tuple[LoraAdapterSpec, tuple | None]] = {}
        self._lora_lock = threading.Lock()
        self.total_lora_s = 0.0
        # Tokens-per-weight-pass accounting: every (row, substep) of a
        # drained window or single step is one per-sequence weight pass
        # yielding one token; a spec row-pass is one weight pass yielding
        # n_emit tokens. Dense-only traffic sits at exactly 1.0.
        self.total_row_passes = 0
        self.total_row_tokens = 0
        # Multi-tenant QoS: monotone submission counter (the age half of
        # the (class, age) scheduling key; assigned under _wakeup at
        # submission, read by the scheduler thread afterwards) and
        # recompute-preemption counts by victim class (racy-total
        # contract like the other total_* counters; _preempt_pushed
        # tracks what _update_gauges already fed the labeled counter).
        self._arrival_no = 0
        self.total_preemptions_by: collections.Counter = collections.Counter()
        self._preempt_pushed: dict[str, int] = {}
        # Cumulative counters for metrics/bench.
        self.total_generated = 0
        self.total_prefilled = 0
        # Token-rows actually DISPATCHED for prefill (bucket padding and
        # padded rows included) — the denominator for padding-efficiency
        # accounting (bench.py roofline breakdown).
        self.total_prefill_padded = 0
        self.total_decode_steps = 0  # device substeps incl. padded/zombie work
        # Host-side phase accounting (bench.py --breakdown; VERDICT r4
        # weak #1: where the non-device half of the step time goes).
        # Keys: idle / admission / prefill_dispatch / first_sample /
        # decode_dispatch / drain_sync / emit / other.
        self.phase_s: dict[str, float] = collections.defaultdict(float)
        self.phase_n: dict[str, int] = collections.defaultdict(int)
        # Optional Prometheus gauges (worker bind_metrics): in-flight
        # windows / pending first fetches / prefill pad ratio / spec
        # series. _ctr_pushed tracks what the monotonic counters have
        # already been fed (engine keeps plain ints; registry counters
        # get the delta once per step).
        self._gauges = None
        # (proposed, accepted, tree passes, protected tier evictions,
        # budget reallocs, lora page-ins) already inc'd into the
        # registry counters.
        self._ctr_pushed = [0] * 9

    def bind_metrics(self, registry) -> None:
        """Attach the engine gauges to a MetricsRegistry; updated once
        per scheduler step (never per token)."""
        self._gauges = register_engine_metrics(registry)

    def _update_gauges(self) -> None:
        if self._gauges is None:
            return
        (g_win, g_first, g_pad, c_prop, c_acc, g_rate, g_tpp,
         g_kvb, g_kvq, c_tree, g_tree_depth, c_tier_prot, g_tier_hit,
         g_gram_seqs, g_gram_mask, c_budget,
         g_lora_res, c_lora_swap, g_lora_s, c_preempt,
         c_g4_hit, c_g4_evict, c_g4_dedup) = self._gauges
        g_kvb.set(self.args.kv_bytes_per_block() * self.args.num_kv_blocks)
        g_kvq.set(1 if self.args.kv_quant == "int8" else 0)
        g_win.set(sum(1 for it in self._fetchq if isinstance(it, _Window)))
        g_first.set(sum(1 for it in self._fetchq if isinstance(it, _First)))
        g_pad.set(self.total_prefill_padded / max(1, self.total_prefilled))
        if self.total_spec_proposed > self._ctr_pushed[0]:
            c_prop.inc(self.total_spec_proposed - self._ctr_pushed[0])
            self._ctr_pushed[0] = self.total_spec_proposed
        if self.total_spec_accepted > self._ctr_pushed[1]:
            c_acc.inc(self.total_spec_accepted - self._ctr_pushed[1])
            self._ctr_pushed[1] = self.total_spec_accepted
        g_rate.set(self.total_spec_accepted / max(1, self.total_spec_proposed))
        g_tpp.set(self.total_row_tokens / max(1, self.total_row_passes))
        if self.total_spec_tree_passes > self._ctr_pushed[2]:
            c_tree.inc(self.total_spec_tree_passes - self._ctr_pushed[2])
            self._ctr_pushed[2] = self.total_spec_tree_passes
        g_tree_depth.set(
            self.total_spec_tree_depth / max(1, self.total_spec_tree_rows)
        )
        prot = self.tiers.protected_evictions
        if prot > self._ctr_pushed[3]:
            c_tier_prot.inc(prot - self._ctr_pushed[3])
            self._ctr_pushed[3] = prot
        g_tier_hit.set(self.tiers.hit_rate)
        g_gram_seqs.set(sum(1 for s in self._running if s.grammar is not None))
        g_gram_mask.set(self.total_grammar_mask_s)
        if self.total_spec_budget_reallocs > self._ctr_pushed[4]:
            c_budget.inc(self.total_spec_budget_reallocs - self._ctr_pushed[4])
            self._ctr_pushed[4] = self.total_spec_budget_reallocs
        if self._lora_pool is not None:
            g_lora_res.set(self._lora_pool.resident)
            if self._lora_pool.pageins > self._ctr_pushed[5]:
                c_lora_swap.inc(self._lora_pool.pageins - self._ctr_pushed[5])
                self._ctr_pushed[5] = self._lora_pool.pageins
        g_lora_s.set(self.total_lora_s)
        if self.tiers.fleet is not None:
            fl = self.tiers.fleet
            for i, (ctr, cur) in enumerate(
                ((c_g4_hit, fl.hits), (c_g4_evict, fl.evictions),
                 (c_g4_dedup, fl.dedup_blocks)), start=6,
            ):
                if cur > self._ctr_pushed[i]:
                    ctr.inc(cur - self._ctr_pushed[i])
                    self._ctr_pushed[i] = cur
        for cls, n in self.total_preemptions_by.items():
            pushed = self._preempt_pushed.get(cls, 0)
            if n > pushed:
                c_preempt.inc(n - pushed, **{"class": cls})
                self._preempt_pushed[cls] = n

    def _phase(self, key: str, t0: float) -> float:
        """Accumulate perf_counter()-t0 into phase `key`; → new t0."""
        t1 = time.perf_counter()
        self.phase_s[key] += t1 - t0
        self.phase_n[key] += 1
        return t1

    @staticmethod
    def _build_tiers(args: EngineArgs):
        from dynamo_tpu.block_manager.tiers import (
            DiskBlockPool,
            FleetBlockPool,
            HostBlockPool,
            TierStack,
        )

        host = HostBlockPool(args.host_kv_blocks) if args.host_kv_blocks > 0 else None
        disk = (
            DiskBlockPool(args.disk_kv_dir, args.disk_kv_blocks)
            if args.disk_kv_dir
            else None
        )
        fleet = (
            FleetBlockPool(args.fleet_kv_dir, args.fleet_kv_blocks)
            if args.fleet_kv_dir
            else None
        )
        # unit_bytes makes NON-KV paged objects (LoRA adapters) charge
        # the blocks-denominated capacity by their byte size — a 34 MB
        # 8B-geometry adapter costs ~50 block units, not 1, so the
        # host/disk byte budget the capacity was sized for holds.
        return TierStack(host, disk, fleet, unit_bytes=args.kv_bytes_per_block())

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> "TpuEngine":
        self._loop = asyncio.get_running_loop()
        await asyncio.to_thread(self._runner.start)
        self._thread = threading.Thread(target=self._run, name="tpu-engine", daemon=True)
        self._thread.start()
        return self

    async def stop(self) -> None:
        with self._wakeup:
            self._stopping = True
            self._wakeup.notify()
        if self._thread is not None:
            await asyncio.to_thread(self._thread.join, 10.0)
        # Release runner resources (multi-host: sends followers the stop
        # op and closes the step-stream sockets).
        self._runner.stop()

    # -- events / metrics -------------------------------------------------

    def _on_pool_event(self, event: KvCacheEvent) -> None:
        if self._external_events is not None:
            self._external_events(event)

    def metrics(self) -> ForwardPassMetrics:
        with self._mutex:
            running, waiting = len(self._running), len(self._waiting) + len(self._submissions)
        return ForwardPassMetrics(
            worker=WorkerStats(
                request_active_slots=running,
                request_total_slots=self.args.max_num_seqs,
                num_requests_waiting=waiting,
            ),
            kv=KvStats(
                kv_active_blocks=self.pool.num_active,
                kv_total_blocks=self.pool.num_blocks - 1,
                gpu_cache_usage_perc=self.pool.usage,
                gpu_prefix_cache_hit_rate=self.pool.hit_rate,
            ),
        )

    # -- grammar-constrained decoding -------------------------------------

    def _compile_grammar(self, rf: dict):
        """response_format dict → CompiledGrammar (None = unconstrained).
        Called via to_thread from generate(); the compiler is built once
        per engine over the serving tokenizer's vocabulary and caches by
        schema hash, so structured traffic sharing a schema pays the DFA
        construction exactly once."""
        comp = self._grammar_compiler
        if comp is None:
            with self._grammar_lock:
                comp = self._grammar_compiler
                if comp is None:
                    comp = build_compiler(
                        self.args.grammar_tokenizer, self.cfg.vocab_size
                    )
                    self._grammar_compiler = comp
        return comp.compile(rf)

    def _grammar_row_masks(self, seqs: list[_Seq], B: int) -> np.ndarray | None:
        """Per-row packed grammar masks for a dense sampling dispatch
        (admission first tokens / single-step decode) → [B, W32] uint32,
        or None when no row is constrained (the unmasked jit variant —
        unconstrained traffic never pays the where()). Unconstrained
        rows in a mixed batch ride all-ones masks (bitwise identity)."""
        if not any(s.grammar is not None for s in seqs):
            return None
        t0 = time.perf_counter()
        masks = np.full(
            (B, mask_words(self.cfg.vocab_size)), 0xFFFFFFFF, np.uint32
        )
        for i, s in enumerate(seqs):
            if s.grammar is not None:
                masks[i] = s.grammar.mask(s.grammar_state, s.grammar_eos_bits)
        self.total_grammar_mask_s += time.perf_counter() - t0
        return masks

    # -- multi-LoRA adapter multiplexing ----------------------------------
    #
    # Serving shape (Punica BGMV + S-LoRA unified paging, engine/lora.py):
    # MANY per-tenant low-rank fine-tunes of the one base model share this
    # engine. The device bank holds args.lora_slots resident adapters;
    # the registry may hold far more — a cold adapter pages in at
    # admission (blocking only that request's admission, never the
    # running batch: in-flight windows keep executing and the upload is
    # device-ordered after them), its factor pages living in the SAME
    # G2/G3 tier pools as KV blocks under adapter_tier_hash keys, and a
    # cold resident pages out under the slot pool's second-chance
    # pressure. Batch rows carry adapter_slot (-1 = base) into every
    # prefill/decode/spec dispatch; base-only batches pass None and run
    # the exact pre-LoRA jit variant.

    def register_adapter(
        self,
        name: str,
        rank: int | None = None,
        seed: int = 0,
        scaling: float = 1.0,
        targets: str = "qkvo",
        pages: tuple | None = None,
    ) -> None:
        """Register one serveable adapter. ``pages`` = pre-materialized
        factor pages (checkpoint loaders); None = deterministic random
        factors from (name, seed) — the bench/test source. Write-through:
        pages land in the tier economy now, so later slot eviction is
        free and a cold re-page-in is a tier read, not a reload.
        Thread-safe; callable while serving (new tenants onboard live)."""
        if self._lora_pool is None:
            raise RequestValidationError(
                "engine has no adapter bank (lora_slots=0)"
            )
        spec = LoraAdapterSpec(
            name=name, rank=rank if rank is not None else self.args.lora_rank,
            seed=seed, scaling=scaling, targets=targets,
        )
        if spec.rank > self.args.lora_rank:
            raise RequestValidationError(
                f"adapter {name!r} rank {spec.rank} exceeds lora_rank="
                f"{self.args.lora_rank}"
            )
        if self.tiers.enabled:
            tier_pages = (
                pages if pages is not None
                else make_adapter_pages(self.cfg, spec, self.args.lora_rank)
            )
            self.tiers.put_object(adapter_tier_hash(name), *tier_pages)
        # Caller-provided pages (real checkpoints) are NOT rematerializable
        # from the spec, so they stay pinned in the registry even with
        # tiers enabled — the tiers are a cache (adapter objects compete
        # with KV blocks and CAN be evicted end to end), never the only
        # copy. Seed-generated adapters pin nothing (a tier miss
        # regenerates bit-identically).
        with self._lora_lock:
            self._lora_registry[name] = (spec, pages)

    def adapters(self) -> list[str]:
        """Registered adapter names (thread-safe)."""
        with self._lora_lock:
            return sorted(self._lora_registry)

    def lora_stats(self) -> dict:
        """Slot-pool residency/swap counters (racy snapshot)."""
        if self._lora_pool is None:
            return {}
        return self._lora_pool.stats()

    def _adapter_pages(self, spec: LoraAdapterSpec,
                       pinned: tuple | None) -> tuple:
        """Fetch one adapter's factor pages: tier hit (G2, promoting a G3
        hit — the unified-paging path), registry-pinned pages (real
        checkpoints — always retained), or rematerialize from the spec's
        seed source and write back through. Tier hit/miss counts feed
        tier_hit_rate, so adapter churn shows in the same signal KV
        churn does."""
        h = adapter_tier_hash(spec.name)
        if self.tiers.enabled:
            pages = self.tiers.get_object(h)
            if pages is not None:
                return pages
        if pinned is not None:
            if self.tiers.enabled:  # re-warm the cache for the next miss
                self.tiers.put_object(h, *pinned)
            return pinned
        pages = make_adapter_pages(self.cfg, spec, self.args.lora_rank)
        if self.tiers.enabled:
            self.tiers.put_object(h, *pages)
        return pages

    def _acquire_adapter(self, seq: _Seq) -> None:
        """Resolve seq.adapter_id → pinned bank slot, uploading on a cold
        miss. Raises RequestValidationError (unknown adapter) or
        NoFreeAdapterSlotsError (every slot pinned — admission requeues
        and retries when running sequences release pins)."""
        if self._lora_pool is None:
            raise RequestValidationError(
                f"request names adapter {seq.adapter_id!r} but this engine "
                "has no adapter bank (lora_slots=0)"
            )
        with self._lora_lock:
            entry = self._lora_registry.get(seq.adapter_id)
        if entry is None:
            raise RequestValidationError(f"unknown adapter {seq.adapter_id!r}")
        spec, pinned = entry
        t0 = time.perf_counter()
        slot, needs_upload, _evicted = self._lora_pool.acquire(seq.adapter_id)
        if needs_upload:
            try:
                self._runner.upload_adapter(
                    slot, self._adapter_pages(spec, pinned)
                )
            except BaseException:
                # The upload never landed: DROP the residency entry (not
                # just the pin) or the next acquire would skip the upload
                # and decode against a zero/partial bank slot.
                self._lora_pool.drop(seq.adapter_id)
                raise
        seq.adapter_slot = slot
        self.total_lora_s += time.perf_counter() - t0

    def _release_adapter(self, seq: _Seq) -> None:
        if seq.adapter_slot >= 0 and self._lora_pool is not None:
            self._lora_pool.release(seq.adapter_id)
        seq.adapter_slot = -1

    def _adapter_row_slots(self, seqs: list[_Seq], B: int) -> np.ndarray | None:
        """Per-row adapter_slot operand for one dispatch → [B] int32, or
        None when no row carries an adapter (the unadapted jit variant —
        base-only traffic pays nothing, byte-identical to a lora-disabled
        engine). Base rows in a mixed batch ride -1 (where-masked in
        model._lora_apply, bit-identical)."""
        if not any(s.adapter_slot >= 0 for s in seqs):
            return None
        t0 = time.perf_counter()
        slots = np.full((B,), -1, np.int32)
        for i, s in enumerate(seqs):
            slots[i] = s.adapter_slot
        self.total_lora_s += time.perf_counter() - t0
        return slots

    # -- async API --------------------------------------------------------

    async def generate(self, request: Any, context: Context) -> AsyncIterator[dict]:
        """AsyncEngine shape: PreprocessedRequest (or its dict) in →
        LLMEngineOutput dicts out (token deltas; no text — Backend's job)."""
        req = request if isinstance(request, PreprocessedRequest) else PreprocessedRequest.from_dict(request)
        # Validate wire input here (caller's coroutine) so malformed requests
        # error this stream instead of reaching the shared scheduler thread.
        if not req.token_ids:
            yield LLMEngineOutput(
                finish_reason=FinishReason.ERROR, error="empty prompt"
            ).to_dict()
            return
        vocab = self.cfg.vocab_size
        if any(not (0 <= int(t) < vocab) for t in req.token_ids):
            yield LLMEngineOutput(
                finish_reason=FinishReason.ERROR,
                error=f"token id out of range [0, {vocab})",
            ).to_dict()
            return
        # One static alternative-logprob width (compile-matrix bound);
        # requests beyond it are clamped, not rejected. top_logprobs
        # without logprobs would pay the top-k and emit nothing — zero it.
        if req.sampling.top_logprobs:
            req.sampling.top_logprobs = (
                min(req.sampling.top_logprobs, self.args.top_logprobs_max)
                if req.sampling.logprobs else 0
            )
        # Grammar-constrained decoding: compile (or cache-hit) the
        # token-mask FSM for this request's response_format OFF the
        # event loop and the scheduler thread. Malformed specs error
        # this stream only (the frontend already 400s them; engine-
        # direct callers get the typed message).
        grammar = None
        if req.response_format:
            try:
                grammar = await asyncio.to_thread(
                    self._compile_grammar, req.response_format
                )
            except GrammarError as e:
                yield LLMEngineOutput(
                    finish_reason=FinishReason.ERROR,
                    error=f"invalid response_format: {e}",
                ).to_dict()
                return
        queue: asyncio.Queue = asyncio.Queue()
        t_submit = time.perf_counter()
        seq = _Seq(context.id, req, queue)
        # Span lineage across relocation: a resume leg that arrives
        # without a live trace (engine-direct dispatch, staged-inject
        # claim path) re-anchors on the traceparent the cutover identity
        # carried, so destination spans join the original request trace
        # instead of minting a fresh root.
        resume_tp = ((req.kv_transfer_params or {}).get("resume") or {}).get("traceparent")
        if context.trace is None and resume_tp:
            from dynamo_tpu.runtime.logging import TraceContext

            try:
                context.trace = TraceContext.parse(str(resume_tp))
            except Exception:  # noqa: BLE001 — a malformed carried traceparent must never fail the resume leg
                pass
        seq.traceparent = (
            context.trace.traceparent() if context.trace is not None else None
        )
        if grammar is not None:
            seq.grammar = grammar
            seq.grammar_state = grammar.start
            seq.grammar_eos_bits = pack_token_ids(
                seq.eos_ids, self.cfg.vocab_size
            )
            self.total_grammar_seqs += 1
            if seq.prompt_len < len(seq.tokens):
                # Resumed (migrated/re-dispatched) constrained request:
                # the carried tokens past the original prompt boundary
                # were GENERATED under this grammar on the previous leg —
                # replay the FSM over them so masking continues from the
                # exact state the source reached (deterministic: the FSM
                # is a pure function of the emitted tokens).
                st = grammar.start
                for t in seq.tokens[seq.prompt_len:]:
                    if t in seq.eos_ids:
                        break
                    ns = grammar.advance(st, t)
                    if ns is None:
                        break  # desync-defensive, same stance as _emit_tokens
                    st = ns
                seq.grammar_state = st
        if not self.args.qos_scheduling:
            seq.qos_rank = 0  # one class: FIFO admission, newest-first preempt
        with self._wakeup:
            if self._stopping:
                raise RuntimeError("engine is stopping")
            self._arrival_no += 1
            seq.arrival = self._arrival_no
            self._submissions.append(seq)
            self._wakeup.notify()

        async def watch_cancel():
            await context.wait_cancelled()
            with self._wakeup:
                seq.cancelled = True
                self._wakeup.notify()

        watcher = asyncio.get_running_loop().create_task(watch_cancel())
        dspan = tracing.NOOP_SPAN
        first = True
        # Emit coalescing: merge the backlog of decode-window deltas
        # already sitting in the queue into one frame (bounded by
        # delta_max_tokens; optional delta_max_ms gather wait). The first
        # delta is never delayed (TTFT), and a finish delta terminates the
        # merge so it rides the same frame as its tokens.
        cap = self.args.delta_max_tokens
        gather_s = self.args.delta_max_ms / 1000.0
        pending: Any = None
        try:
            while True:
                item = pending if pending is not None else await queue.get()
                pending = None
                if cap > 0 and isinstance(item, dict) and not item.get("finish_reason"):
                    # Backlog merge first (free — deltas already queued),
                    # then the opt-in bounded gather to fill the frame
                    # further toward the cap (costs ≤ delta_max_ms of ITL;
                    # default 0 never waits; the first delta never waits).
                    deadline = (
                        time.monotonic() + gather_s
                        if gather_s > 0.0 and not first else None
                    )
                    while (
                        pending is None
                        and len(item.get("token_ids") or ()) < cap
                        and not item.get("finish_reason")
                    ):
                        if not queue.empty():
                            nxt = queue.get_nowait()
                        elif deadline is not None:
                            wait = deadline - time.monotonic()
                            if wait <= 0:
                                break
                            try:
                                nxt = await asyncio.wait_for(queue.get(), wait)
                            except asyncio.TimeoutError:
                                break
                        else:
                            break
                        if not isinstance(nxt, dict):
                            pending = nxt  # _SENTINEL_DONE: deliver after item
                            break
                        if (
                            len(item.get("token_ids") or ())
                            + len(nxt.get("token_ids") or ())
                        ) > cap:
                            pending = nxt  # merging would exceed the cap
                            break
                        merged = coalesce_delta(item, nxt)
                        if merged is None:
                            pending = nxt
                            break
                        item = merged
                if item is _SENTINEL_DONE:
                    return
                if first:
                    first = False
                    if tracing.enabled() and context.trace is not None:
                        # Queue/prefill phases from the scheduler thread's
                        # admission stamp, recorded retroactively at first
                        # delta; decode is live from here.
                        now = time.perf_counter()
                        t_admit = seq.t_admit or now
                        tracing.record_interval(
                            "engine.queue", context.trace,
                            start=t_submit, end=t_admit,
                        )
                        tracing.record_interval(
                            "engine.prefill", context.trace,
                            start=t_admit, end=now,
                            prompt_tokens=seq.prompt_len,
                            cached_blocks=seq.prefix_hit_blocks,
                        )
                        dspan = tracing.start_span(
                            "engine.decode", parent=context.trace
                        )
                yield item
                if isinstance(item, dict) and item.get("finish_reason"):
                    return
        finally:
            dspan.set_attrs(tokens=seq.emitted)
            dspan.end(status="cancelled" if seq.cancelled else None)
            watcher.cancel()
            with self._wakeup:
                seq.cancelled = True  # no-op if already finished

    # -- scheduler loop (engine thread) -----------------------------------

    def _run(self) -> None:
        crashed = False
        try:
            while True:
                t0 = time.perf_counter()
                with self._wakeup:
                    while (
                        not self._stopping
                        and not self._submissions
                        and not self._waiting
                        and not self._running
                        and not self._embed_jobs
                        and not self._host_jobs
                    ):
                        if self._migrations:
                            # A frozen cutover must still observe its
                            # deadline even on an otherwise-idle engine:
                            # bounded sleep, then run a (cheap) step.
                            self._wakeup.wait(timeout=0.02)
                            break
                        self._wakeup.wait()
                    if self._stopping:
                        break
                    while self._submissions:
                        self._waiting.append(self._submissions.popleft())
                self._phase("idle", t0)
                self._step()
        except Exception:  # noqa: BLE001 — engine death must not be silent
            crashed = True
            log.exception("engine loop crashed")
        finally:
            # Flip stopping FIRST so late generate() calls are rejected
            # instead of queueing onto a dead thread.
            self._fetchq.clear()  # drop; leftovers get terminal posts below
            self._export_fetches.clear()
            with self._mutex:
                exports = [item for item, _dl in self._exports.values()]
            for item in exports:
                if isinstance(item, KvStreamExport):
                    item.abort("engine_stopped")  # no-op when sealed
            with self._wakeup:
                self._stopping = True
                leftovers = list(self._running) + list(self._waiting) + list(self._submissions)
                # Frozen mid-cutover sequences live in no queue; without a
                # terminal post their client streams would hang forever.
                leftovers += [
                    m.seq for m in self._migrations.values()
                    if m.frozen and not m.seq.dead
                ]
                for m in self._migrations.values():
                    m.stream.abort("engine_stopped")
                    m.seq.mig = None
                self._migrations.clear()
                self._running.clear()
                self._waiting.clear()
                self._submissions.clear()
            reason = FinishReason.ERROR if crashed else FinishReason.CANCELLED
            err = "engine loop crashed" if crashed else None
            for seq in leftovers:
                self._post(seq, LLMEngineOutput(finish_reason=reason, error=err).to_dict())
                self._post_done(seq)
            # Pending embed/host-job futures must resolve too, or their
            # awaiters hang forever.
            while self._embed_jobs or self._host_jobs:
                if self._embed_jobs:
                    _toks, fut, floop = self._embed_jobs.popleft()
                else:
                    _fn, fut, floop = self._host_jobs.popleft()
                exc = RuntimeError(err or "engine stopped")
                floop.call_soon_threadsafe(
                    lambda f=fut, e=exc: f.set_exception(e) if not f.cancelled() else None
                )

    def _step(self) -> None:
        self._step_no += 1
        # Harvest whatever fetches completed while the host was away:
        # frees slots/KV and discovers stops as early as possible, and
        # costs nothing when the head of the queue is still in flight.
        self._drain_completed()
        if self._export_fetches:
            self._drain_export_fetches()
        self._reap_cancelled()
        while self._embed_jobs:
            self._serve_embed(*self._embed_jobs.popleft())
        while self._host_jobs:
            self._serve_host_job(*self._host_jobs.popleft())
        if self._exports:
            self._reap_exports()
        if self._migrations:
            self._service_migrations()
        if self.kv_pressure_offer > 0.0:
            self._maybe_pressure_offer()
        # Prefill-priority admission, two phases: (1) allocate KV for the
        # whole wave, (2) dispatch prefills PACKED by suffix bucket
        # (model.prefill_batch) — one-at-a-time prefill was the r3 TTFT
        # killer. The wave shares ONE first-token sampling fetch, and the
        # whole wave is dispatched while previously-dispatched decode
        # windows are still executing (prefill interleave: arrivals no
        # longer inherit a blocking drain's worth of queueing delay).
        # The wave is budgeted to ~one max_prefill_tokens chunk so running
        # decodes are not starved by a long burst of arrivals.
        t0 = time.perf_counter()
        allocated: list[tuple[_Seq, int]] = []  # (seq, suffix start)
        wave_budget = self.args.admission_budget_tokens or (1 << 62)
        # Frozen mid-cutover sequences are out of _running but still hold
        # their chain slot (and KV) until the handoff resolves — admission
        # must not oversubscribe the slot pool past them.
        frozen = sum(1 for m in self._migrations.values() if m.frozen)
        while (
            self._waiting
            and len(self._running) + len(allocated) + frozen < self.args.max_num_seqs
            and (wave_budget > 0 or not allocated)
        ):
            seq = self._pop_next_waiting()
            if seq.cancelled:
                self._post_done(seq)
                continue
            wave_budget -= len(seq.tokens)
            try:
                start = self._admit_alloc(seq)
            except NoFreeBlocksError:
                self._waiting.appendleft(seq)  # try again when blocks free up
                if not self._running and not allocated and not self._migrations:
                    # Deadlock: nothing to free. Fail the request.
                    # (A frozen migration is NOT a deadlock — its blocks
                    # free within the bounded cutover window either way.)
                    self._waiting.remove(seq)
                    self._finish(seq, FinishReason.ERROR,
                                 error="prompt does not fit in KV cache")
                break
            except RequestValidationError as e:
                self._finish(seq, FinishReason.ERROR, error=str(e))
                continue
            except Exception as e:  # noqa: BLE001 — contain per-request faults
                log.exception("admission failed for %s", seq.request_id)
                if seq.block_ids:
                    self.pool.free_sequence(seq.block_ids)
                    seq.block_ids = []
                self._finish(seq, FinishReason.ERROR, error=f"admission failed: {e}")
                continue
            seq.t_admit = time.perf_counter()
            allocated.append((seq, start))
        t0 = self._phase("admission", t0)
        admitted: list[tuple[_Seq, Any, int]] = []  # (seq, logits array, row)
        if allocated:
            try:
                admitted = self._dispatch_prefills(allocated)
            except Exception as e:  # noqa: BLE001 — contain wave faults
                log.exception("prefill dispatch failed")
                for seq, _ in allocated:
                    self.pool.free_sequence(seq.block_ids)
                    seq.block_ids = []
                    self._finish(seq, FinishReason.ERROR, error=f"prefill failed: {e}")
            t0 = self._phase("prefill_dispatch", t0)
        if admitted:
            # Async admission: sample first tokens ON DEVICE, fold them
            # into each sequence's chain slot, and enqueue the host fetch
            # on the completion queue (transfer started immediately) —
            # the fetch roundtrip overlaps window execution instead of
            # idling the device (r4 bench: these syncs were 68% of the
            # timed section). Waves padded to a decode bucket so sampling
            # compiles once per bucket.
            seqs = [s for s, _, _ in admitted]
            try:
                B = self.args.bucket_decode(len(admitted))
                srcs = [(ref, row) for _, ref, row in admitted]
                srcs += [srcs[0]] * (B - len(srcs))
                for s in seqs:
                    s.slot = self._free_slots.pop()
                slots = np.full((B,), self.args.max_num_seqs, np.int32)
                slots[: len(seqs)] = [s.slot for s in seqs]
                out_d, lps_d, top_ref = self._sample_rows_device(
                    srcs, seqs, slots,
                    top_n=(self.args.top_logprobs_max
                           if any(s.sampling.top_logprobs for s in seqs) else 0),
                )
            except Exception as e:  # noqa: BLE001 — admitted seqs are in no
                # collection yet; orphaning them would hang their streams.
                log.exception("first-token sampling failed")
                for seq in seqs:
                    self.pool.free_sequence(seq.block_ids)
                    seq.block_ids = []
                    self._finish(seq, FinishReason.ERROR, error=f"sampling failed: {e}")
                seqs = []
            t0 = self._phase("first_dispatch", t0)
            if seqs:
                for seq in seqs:
                    seq.first_pend = True
                    self._running.append(seq)
                first = _First(
                    [(s, i) for i, s in enumerate(seqs)], out_d, lps_d, top_ref
                )
                start_host_fetch(first.fetch_arrays())
                self._fetchq.append(first)
                # Prefill-only requests (disagg export, max_tokens=1)
                # finish at the first token — resolve JUST this wave's
                # sample now (its seqs ride no earlier queued item, so
                # draining it out of FIFO order is safe) so they never
                # ride a decode window as instant zombies and the rest of
                # the pipeline stays in flight.
                if any(s.stop.max_tokens == 1 for s in seqs):
                    self._fetchq.pop()  # == first, just appended
                    self._drain_one(first)
        if self._running:
            self._decode_iteration()
            self._flush_offloads()
        elif self._fetchq:
            # Every owner of the queued fetches died during a drain:
            # release them all (zombie rows; keeps StepRef/device arrays
            # from idling forever — the idle predicate ignores _fetchq —
            # and total_decode_steps honest).
            self._drain_completed(force=True)
        self._update_gauges()

    # -- embeddings (reference: http/service/openai.rs:302) ----------------

    async def embed(self, token_ids: list[int]) -> list[float]:
        """Mean-pooled final hidden state; serialized through the
        scheduler thread (device dispatch affinity)."""
        if not token_ids:
            raise RequestValidationError("empty input")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        with self._wakeup:
            if self._stopping:
                raise RuntimeError("engine is stopping")
            self._embed_jobs.append((list(token_ids), fut, loop))
            self._wakeup.notify()
        return await fut

    async def run_on_engine_thread(self, fn):
        """Run ``fn()`` on the scheduler thread between steps (device
        dispatch affinity) and await its result. Bench/warmup seam — not
        a serving-path API."""
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        with self._wakeup:
            if self._stopping:
                raise RuntimeError("engine is stopping")
            self._host_jobs.append((fn, fut, loop))
            self._wakeup.notify()
        return await fut

    def _serve_host_job(self, fn, fut, loop) -> None:
        try:
            result = fn()
            loop.call_soon_threadsafe(
                lambda: fut.set_result(result) if not fut.cancelled() else None
            )
        except Exception as e:  # noqa: BLE001 — surface to the caller
            err = e
            loop.call_soon_threadsafe(
                lambda: fut.set_exception(err) if not fut.cancelled() else None
            )

    async def warm_spec(self, modes: tuple[str, ...] = ("greedy",),
                        top_ns: tuple[int, ...] = (0,),
                        grammar: bool = False) -> int:
        """AOT-compile the REQUESTED subset of the spec_verify variant
        lattice: one inert dispatch (all rows inactive → KV writes land
        in garbage block 0) per (decode bucket x table bucket x mode x
        top_n x S1 shape). Drafts cannot be forced through real traffic
        — they depend on the model looping — so cold variants would
        otherwise compile mid-serving. The default covers the bench
        shape (greedy, no top_logprobs); a serving worker expecting
        sampled or top_logprobs traffic should pass modes=("greedy",
        "simple") and top_ns=(0, args.top_logprobs_max), or rely on the
        persistent compile cache (DYNTPU_COMPILE_CACHE) like every
        other variant family. Adaptive batch budgets add the 2S+1 shape
        (hot rows drafting past S); ``grammar=True`` adds the
        masked-tree variants constrained traffic dispatches. → number
        of variants dispatched."""
        S = self.spec_tokens
        if S <= 0:
            return 0
        args = self.args
        s1_list = [S + 1]
        if self.spec_budget_adaptive:
            s1_list.append(SPEC_BUDGET_MAX_MULT * S + 1)

        def _warm():
            count = 0
            for S1 in s1_list:
                # Tree lattice rides the same loop when tree drafting is
                # on: the topology arrays are traced by SHAPE only, so
                # one inert chain-shaped dispatch warms every tree a
                # real batch can produce at this (B, W, mode, top_n).
                # Grammar masks are one more shape-only operand: the
                # masked variant covers every schema.
                shapes: list[tuple[bool, bool]] = [(False, False)]
                if args.spec_tree_width > 1 or grammar:
                    shapes.append((True, False))
                if grammar:
                    shapes.append((True, True))
                chain_parents = np.maximum(
                    np.arange(S1, dtype=np.int32) - 1, 0
                )
                chain_anc = np.tril(np.ones((S1, S1), np.int8))
                chain_depth = np.arange(S1, dtype=np.int32)
                W32 = mask_words(self.cfg.vocab_size)
                # Adapter-slot operand is one more shape-only variant
                # axis (mixed-adapter batches dispatch with it; base
                # batches without).
                lora_opts = [False, True] if args.lora_slots > 0 else [False]
                for mode in modes:
                    for top_n in top_ns:
                        for B in args.decode_buckets:
                            for W in args.table_buckets:
                                for with_tree, with_mask in shapes:
                                    for with_lora in lora_opts:
                                        tree = masks = None
                                        if with_tree:
                                            tree = (
                                                np.broadcast_to(chain_parents, (B, S1)).copy(),
                                                np.broadcast_to(chain_anc, (B, S1, S1)).copy(),
                                                np.broadcast_to(chain_depth, (B, S1)).copy(),
                                            )
                                        if with_mask:
                                            masks = np.full(
                                                (B, S1, W32), 0xFFFFFFFF, np.uint32
                                            )
                                        aslots = (
                                            np.zeros((B,), np.int32)
                                            if with_lora else None
                                        )
                                        self._runner.spec_verify(
                                            S1, mode,
                                            np.zeros((B, S1), np.int32),
                                            np.zeros((B,), np.int32),
                                            np.full((B,), S1 - 1, np.int32),
                                            np.zeros((B, W), np.int32),
                                            np.zeros((B,), bool),
                                            np.ones((B,), np.float32),
                                            np.zeros((B,), np.uint32),
                                            np.zeros((B,), np.int32),
                                            None, top_n, tree, masks, aslots,
                                        )
                                        count += 1
            return count

        return await self.run_on_engine_thread(_warm)

    def _serve_embed(self, token_ids: list[int], fut, loop) -> None:
        try:
            if len(token_ids) > self.args.max_model_len:
                raise RequestValidationError(
                    f"input of {len(token_ids)} tokens exceeds max_model_len "
                    f"of {self.args.max_model_len}"
                )
            # Long inputs chunk-pool (VERDICT r4 weak #8): each
            # max_prefill_tokens chunk embeds independently and the
            # results token-weight-average — the standard long-input
            # recipe for mean-pooled embeddings (cross-chunk attention is
            # traded away; within-chunk context is exact).
            chunks = [
                token_ids[i : i + self.args.max_prefill_tokens]
                for i in range(0, len(token_ids), self.args.max_prefill_tokens)
            ]
            refs = []
            for chunk in chunks:
                t_pad = self.args.bucket_prefill(len(chunk))
                toks = np.zeros((t_pad,), np.int32)
                toks[: len(chunk)] = chunk
                refs.append(self._runner.embed(toks, len(chunk)))
            acc: np.ndarray | None = None
            for chunk, ref in zip(chunks, refs):
                v = np.asarray(ref.arrs[0], dtype=np.float64) * len(chunk)
                acc = v if acc is None else acc + v
            vec = [float(x) for x in acc / len(token_ids)]
            loop.call_soon_threadsafe(
                lambda: fut.set_result(vec) if not fut.cancelled() else None
            )
        except Exception as e:  # noqa: BLE001 — surface to the caller
            err = e
            loop.call_soon_threadsafe(
                lambda: fut.set_exception(err) if not fut.cancelled() else None
            )

    def clear_kv_blocks(self) -> int:
        """Admin: drop all idle cached blocks (reference:
        http/service/clear_kv_blocks.rs). → number of blocks dropped."""
        return self.pool.clear()

    def _flush_offloads(self) -> None:
        """Batch-extract queued sealed blocks to the host tiers: one DMA
        per step, bounded. Runs on the engine thread before the next
        donation can recycle the pages (blocks are referenced or at worst
        LRU-cached until the next allocation, which happens after)."""
        if not self._offload_pending:
            return
        batch = self._offload_pending[: self.tiers.MAX_OFFLOAD_PER_STEP]
        del self._offload_pending[: len(batch)]
        pages = self._runner.extract_pages([b for b, _ in batch])
        self.tiers.offload(
            [
                (h, *(a[:, i : i + 1] for a in pages))
                for i, (_, h) in enumerate(batch)
            ],
            # Radix protection hint: branch points / live-shared blocks
            # get eviction credit in the tiers so one-off prompt bursts
            # can't flush the hot shared system-prefix blocks.
            protected=[self.pool.hash_protected(h) for _, h in batch],
        )

    def _reap_cancelled(self) -> None:
        for seq in [s for s in self._running if s.cancelled]:
            self._finish(seq, FinishReason.CANCELLED)
        for seq in [s for s in self._waiting if s.cancelled]:
            self._waiting.remove(seq)
            self._post_done(seq)

    # -- admission / prefill ----------------------------------------------

    def _pop_next_waiting(self) -> _Seq:
        """(class, age)-ordered admission: the highest-rank class first,
        oldest arrival within it — a waiting interactive request admits
        ahead of queued batch work, including into blocks a batch
        preemption just freed. Uniform-rank traffic (no-QoS, or
        qos_scheduling off) reduces to EXACT FIFO: _waiting is
        arrival-ordered (appendleft re-queues — preempted or
        blocks-starved seqs — are always the oldest arrivals, since
        admission itself drains oldest-first), so min arrival IS the
        leftmost element and this selection is byte-identical to the
        popleft it replaces."""
        best = max(self._waiting, key=lambda s: (s.qos_rank, -s.arrival))
        self._waiting.remove(best)
        return best

    def _admit_alloc(self, seq: _Seq) -> int:
        """Phase 1 of admission: allocate KV blocks, resolve prefix hits
        (local cache, disagg inject, tier onboard). Returns the suffix
        start position. Raises on resource/validation failure; no model
        dispatch happens here."""
        # Flush queued offloads BEFORE allocating: allocation may evict and
        # recycle exactly the pages still waiting to be copied out.
        self._flush_offloads()
        # Adapter residency first (before any block allocation, so a
        # failure here has nothing to unwind): resolve adapter_id → a
        # pinned bank slot, paging the adapter in on a cold miss. Only
        # THIS request's admission blocks on the fetch — decode windows
        # already in flight keep executing, and the upload is device-
        # stream-ordered after them.
        acquired = False
        if seq.adapter_id is not None and seq.adapter_slot < 0:
            self._acquire_adapter(seq)
            acquired = True
        try:
            return self._admit_alloc_blocks(seq)
        except BaseException:
            if acquired:
                self._release_adapter(seq)
            raise

    def _admit_alloc_blocks(self, seq: _Seq) -> int:
        bs = self.args.block_size
        prompt = seq.tokens
        plen = len(prompt)
        if plen > self.args.max_model_len - 1:
            raise RequestValidationError("prompt exceeds max_model_len")
        # KV identity is (tokens, adapter): the hash seed is salted by
        # the adapter id (tokens.adapter_hash_seed), so adapter KV never
        # prefix-hits base/other-adapter blocks — in the G1 radix tree,
        # the G2/G3 tiers, KV events, and peer fetches alike.
        hashes = compute_block_hashes(prompt, bs, seq.hash_seed)
        # Never reuse the *entire* prompt: at least one suffix token must be
        # computed to produce logits (vLLM rule).
        max_hit = (plen - 1) // bs
        hashes_matchable = hashes[:max_hit]
        total_blocks = (plen + bs - 1) // bs
        block_ids, n_hit = self.pool.allocate_sequence(hashes_matchable, total_blocks)
        seq.block_ids = block_ids
        seq.prefix_hit_blocks = n_hit
        seq.block_seq = TokenBlockSequence(prompt, bs, seq.hash_seed)
        start = n_hit * bs

        # G2/G3 onboard: blocks evicted from HBM but still host-resident
        # re-enter as a prefix hit instead of being recomputed
        # (reference: block_manager/offload.rs onboard path). Runs BEFORE
        # a remote inject: a peer payload may start past the local tiers'
        # coverage (llm/peer_kv.py delta fetch).
        if self.tiers.enabled and n_hit < max_hit:
            run = self.tiers.lookup_run(hashes_matchable[n_hit:])
            if run:
                # Per-block page tuples → one batched inject; int8 pages
                # carry their scale sidecars through the same stack, and
                # blocks a persistent disk dir stored under a different
                # kv_quant setting are bridged to the current format.
                pages = kv_transfer.concat_page_run(
                    run,
                    quantized=self.args.kv_quant == "int8",
                    num_kv_heads=self.args.model.num_kv_heads,
                    dtype=self.args.dtype,
                )
                n_onb = n_hit + len(run)
                self._runner.inject_pages(seq.block_ids[n_hit:n_onb], *pages)
                n_hit = n_onb
                start = n_hit * bs
                seq.prefix_hit_blocks = n_hit

        # Disagg / peer fetch: pre-load remotely-prefilled pages as a
        # materialized prefix hit — the suffix (< 2 blocks) is recomputed
        # locally, which also regenerates the first-token logits (no logit
        # shipping).
        if seq.inject is not None:
            start, n_hit = self._inject_kv(seq, n_hit, max_hit)
            seq.prefix_hit_blocks = n_hit

        # Streaming disagg export: register the stream at ADMISSION so
        # the decode worker's kv_fetch can start pulling while prefill
        # is still running; locally prefix-hit blocks are already in
        # cache, so they publish as chunk 0 right now.
        if seq.export and seq.export_handle:
            exp = KvStreamExport(
                seq.export_handle,
                max_buffer_bytes=self.args.transfer_buffer_bytes,
            )
            seq.export_stream = exp
            with self._mutex:
                self._exports[seq.export_handle] = (
                    exp, time.monotonic() + self.export_ttl_s
                )
            n_exp = (plen - 1) // bs  # full blocks only, like _export_kv
            hit = min(start // bs, n_exp)
            if hit > 0:
                self._start_export_extract(seq, 0, hit)
        return start

    def _dispatch_prefills(
        self, allocated: list[tuple[_Seq, int]]
    ) -> list[tuple[_Seq, Any, int]]:
        """Phase 2 of admission: run the wave's prefills. Suffixes that fit
        one chunk are PACKED by (T bucket) into prefill_batch dispatches;
        longer prompts fall back to per-sequence chunked prefill, and
        suffixes whose bucket pad is large split into [bucket chunk,
        re-bucketed tail] chunked dispatches (plan_prefill_chunks) so the
        remainder packs a small bucket instead of padding a whole row.
        Returns (seq, logits array, row index) triples (logits not
        synced)."""
        out: list[tuple[_Seq, Any, int]] = []
        singles: list[tuple[_Seq, int, list[int] | None]] = []
        groups: dict[int, list[tuple[_Seq, int]]] = {}
        for seq, start in allocated:
            sfx = len(seq.tokens) - start
            if sfx > self.args.max_prefill_tokens:
                singles.append((seq, start, None))
                continue
            plan = self.args.plan_prefill_chunks(sfx)
            if len(plan) > 1:
                singles.append((seq, start, plan))
            else:
                groups.setdefault(self.args.bucket_prefill(sfx), []).append((seq, start))

        for seq, start, plan in singles:
            # row=None: chunked prefill yields [V] logits, not a batch row.
            out.append((seq, self._prefill_chunked(seq, start, plan), None))

        bmax = max(1, self.args.prefill_batch_max)
        for t_pad, members in sorted(groups.items()):
            # Greedy pow2 packs (5 → 4+1): every dispatch exactly fills
            # its row bucket, so no padded row ever runs the model.
            i = 0
            while i < len(members):
                take = min(bmax, len(members) - i)
                p = 1
                while p * 2 <= take:
                    p *= 2
                sub = members[i : i + p]
                i += p
                arr = self._prefill_packed(sub, t_pad)
                for row, (seq, start) in enumerate(sub):
                    out.append((seq, arr, row))
        return out

    def _prefill_packed(
        self, members: list[tuple[_Seq, int]], t_pad: int
    ) -> Any:
        """One packed prefill dispatch for same-bucket suffixes. Returns
        logits [Bp, V] (not synced)."""
        Bp = self.args.bucket_prefill_rows(len(members))
        W = self.args.bucket_table(max(len(s.block_ids) for s, _ in members))
        toks = np.zeros((Bp, t_pad), np.int32)
        tables = np.zeros((Bp, W), np.int32)
        starts = np.zeros((Bp,), np.int32)
        tlens = np.zeros((Bp,), np.int32)  # padding rows: true_len 0 → inactive
        for r, (seq, start) in enumerate(members):
            sfx = seq.tokens[start:]
            toks[r, : len(sfx)] = sfx
            tables[r, : len(seq.block_ids)] = seq.block_ids
            starts[r] = start
            tlens[r] = len(seq.tokens)
        aslots = self._adapter_row_slots([s for s, _ in members], Bp)
        ref = self._runner.prefill_batch(toks, tables, starts, tlens, aslots)
        self.total_prefill_padded += Bp * t_pad
        for seq, start in members:
            self._finish_prefill_bookkeeping(seq, start)
        return ref

    def _prefill_chunked(self, seq: _Seq, start: int,
                         chunks: list[int] | None = None) -> Any:
        """Per-sequence chunked prefill: suffix > max_prefill_tokens, or
        an explicit tail-split ``chunks`` plan (true lengths; every chunk
        but the last is bucket-sized, hence block-aligned, so each chunk
        starts on a block boundary). Returns last-token logits [V] (not
        synced)."""
        prompt = seq.tokens
        plen = len(prompt)
        W = self.args.bucket_table(len(seq.block_ids))
        table = np.zeros((W,), np.int32)
        table[: len(seq.block_ids)] = seq.block_ids
        logits = None
        pos = start
        max_chunk = self.args.max_prefill_tokens
        ci = 0
        while pos < plen:
            if chunks is not None:
                n = chunks[ci]
                ci += 1
            else:
                n = min(max_chunk, plen - pos)
            chunk = prompt[pos : pos + n]
            t_pad = self.args.bucket_prefill(len(chunk))
            toks = np.zeros((t_pad,), np.int32)
            toks[: len(chunk)] = chunk
            logits = self._runner.prefill_chunk(
                toks, table, pos, min(pos + len(chunk), plen),
                seq.adapter_slot if seq.adapter_slot >= 0 else None,
            )
            self.total_prefill_padded += t_pad
            pos += len(chunk)
            # Streaming export: the blocks this chunk completed can ship
            # while the NEXT chunks compute — dispatch their gather with
            # an async D2H now, and harvest whatever earlier gathers
            # already landed (non-blocking), so the data plane overlaps
            # the remaining prefill instead of serializing after it.
            if (seq.export_stream is not None
                    and seq.export_stream.abort_reason is None):
                bs = self.args.block_size
                done = min(pos // bs, (plen - 1) // bs)
                if done > seq.export_pub_blocks:
                    self._start_export_extract(seq, seq.export_pub_blocks, done)
                self._drain_export_fetches()
        self._finish_prefill_bookkeeping(seq, start)
        assert logits is not None  # plen >= 1 → at least one chunk ran
        return logits

    def _finish_prefill_bookkeeping(self, seq: _Seq, start: int) -> None:
        plen = len(seq.tokens)
        self.total_prefilled += plen - start
        # Prompt positions are now resident in HBM; register their blocks.
        seq.kv_written = plen
        self._register_written_blocks(seq)
        # Disagg: copy the full prompt blocks to host for the decode
        # worker to fetch (reference: prefill returning kv_transfer_params,
        # handlers.py:149-158 — here device→host DMA replaces NIXL).
        if seq.export:
            self._export_kv(seq, plen)

    def _inject_kv(self, seq: _Seq, n_hit: int, max_hit: int) -> tuple[int, int]:
        """Scatter fetched pages into this sequence's blocks beyond the
        locally-hit prefix. The payload's first page corresponds to prompt
        block ``block_offset`` (0 for disagg exports; >0 for peer delta
        fetches, llm/peer_kv.py). → (new start position, new hit count)."""
        payload = seq.inject
        if isinstance(payload, dict) and payload.get("chunks") is not None:
            return self._inject_kv_chunks(seq, payload["chunks"], n_hit, max_hit)
        off = 0
        if isinstance(payload, dict):
            off = int(payload.get("block_offset") or 0)
            payload = kv_transfer.KvPagePayload.from_dict(payload)
        bs = self.args.block_size
        n_inj = min(off + payload.num_tokens // bs, max_hit, off + payload.k.shape[1])
        if n_inj <= n_hit or off > n_hit:
            # Already covered locally, or the payload starts past what the
            # cache holds (blocks evicted between fetch and admission) —
            # injecting would leave a KV gap, so recompute instead.
            return n_hit * bs, n_hit
        self._runner.inject_pages(
            seq.block_ids[n_hit:n_inj],
            *(a[:, n_hit - off : n_inj - off] for a in payload.pages()),
        )
        seq.inject = None  # free host pages promptly
        return n_inj * bs, n_inj

    def _inject_kv_chunks(
        self, seq: _Seq, chunks: list, n_hit: int, max_hit: int
    ) -> tuple[int, int]:
        """Incremental inject of a streamed chunk list (dynamo_tpu/
        transfer): each contiguous page run scatters separately — no
        monolithic host concat — and format bridging (adapt_pages)
        happens per chunk, so a float-prefill → int8-decode stream
        quantizes run by run. Coverage must stay contiguous from the
        local hit boundary; a gap stops injection (the rest recomputes)."""
        bs = self.args.block_size
        n_cur = n_hit
        for ch in chunks:
            off = int(ch.get("block_offset") or 0)
            payload = kv_transfer.KvPagePayload.from_dict(ch)
            end = min(off + payload.k.shape[1], max_hit)
            if end <= n_cur:
                continue  # fully covered locally already
            if off > n_cur:
                break  # gap — injecting past it would leave a KV hole
            self._runner.inject_pages(
                seq.block_ids[n_cur:end],
                *(a[:, n_cur - off : end - off] for a in payload.pages()),
            )
            n_cur = end
        seq.inject = None  # free host chunk buffers promptly
        return n_cur * bs, n_cur

    def _export_kv(self, seq: _Seq, plen: int) -> None:
        bs = self.args.block_size
        n_exp = (plen - 1) // bs  # full blocks only; suffix recomputed remotely
        if seq.export_stream is not None:
            # Streaming export: publish the remainder (everything for a
            # single-dispatch packed prefill; the final partial run for a
            # chunked one), drain this stream's in-flight page fetches
            # (blocking is fine — prefill is done, nothing left to
            # overlap) and seal.
            meta = {"remote_handle": seq.export_handle, "stream": True,
                    "num_tokens": n_exp * bs, "num_blocks": n_exp}
            if (n_exp > seq.export_pub_blocks
                    and seq.export_stream.abort_reason is None):
                self._start_export_extract(seq, seq.export_pub_blocks, n_exp)
            self._drain_export_fetches(force_seq=seq)
            seq.export_stream.seal(num_blocks=n_exp, num_tokens=n_exp * bs)
            seq.export_meta = meta
            return
        meta = {"remote_handle": seq.request_id, "num_tokens": n_exp * bs, "num_blocks": n_exp}
        if n_exp > 0:
            pages = self._runner.extract_pages(seq.block_ids[:n_exp])
            # int8 KV: scale sidecars ride the same payload.
            payload = kv_transfer.KvPagePayload.from_pages(pages, n_exp * bs)
            with self._mutex:
                self._exports[seq.request_id] = (payload, time.monotonic() + self.export_ttl_s)
        seq.export_meta = meta

    def _start_export_extract(self, seq: _Seq, lo: int, hi: int) -> None:
        """Dispatch the gather for blocks [lo, hi) of a streaming export
        and start its async D2H copy; harvested by _drain_export_fetches."""
        arrs, n = self._runner.start_extract_pages(seq.block_ids[lo:hi])
        start_host_fetch(arrs)
        self._export_fetches.append((seq, lo, hi, arrs, n))
        seq.export_pub_blocks = hi

    def _drain_export_fetches(self, force_seq: _Seq | None = None) -> None:
        """Harvest streaming-export page fetches whose D2H copy landed
        (never blocking), publishing each as one chunk. ``force_seq``
        additionally block-drains THAT sequence's fetches (seal time).
        Fetches whose stream died (abort/preempt) are dropped."""
        keep: list = []
        blocked: set[int] = set()
        bs = self.args.block_size
        for item in self._export_fetches:
            seq, lo, hi, arrs, n = item
            exp = seq.export_stream
            if exp is None or exp.abort_reason is not None:
                continue  # stream gone — release the device arrays
            # Publish strictly in dispatch order per sequence: host_ready
            # is per-array, and a later run landing before an earlier one
            # would punch a gap in the consumer's contiguous chunk stream
            # (its injector stops at the first gap and recomputes).
            if id(seq) in blocked or (
                seq is not force_seq and not host_ready(arrs)
            ):
                keep.append(item)
                blocked.add(id(seq))
                continue
            pages = self._runner.finish_extract_pages(arrs, n)
            exp.publish(KvChunk(
                block_offset=lo, pages=pages, num_tokens=(hi - lo) * bs,
            ))
        self._export_fetches = keep

    def prefix_hit_length(self, token_ids: list[int],
                          adapter_id: str | None = None) -> int:
        """Tokens of this prompt already resident in the local prefix
        cache (whole blocks), probed in the request's (model, adapter)
        identity domain. Used by the disagg decision: a locally-cached
        prompt should not prefill remotely. Thread-safe."""
        bs = self.args.block_size
        max_hit = (len(token_ids) - 1) // bs
        hashes = compute_block_hashes(
            token_ids, bs, adapter_hash_seed(adapter_id)
        )[:max_hit]
        return len(self.pool.match_prefix(hashes)) * bs

    def take_export(self, handle: str):
        """→ KvPagePayload | None. One-shot: the caller owns the pages.
        Streaming exports are not served here (get_stream_export)."""
        with self._mutex:
            item = self._exports.get(handle)
            if item is not None and isinstance(item[0], KvStreamExport):
                return None
            item = self._exports.pop(handle, None)
        return item[0] if item else None

    def get_stream_export(self, handle: str) -> KvStreamExport | None:
        """→ the live streaming export for ``handle`` (non-popping — the
        consumer pulls windows against it), or None. Each lookup refreshes
        the reap deadline: the TTL bounds time since the consumer LAST
        pulled, not the whole transfer — a healthy long prefill + many-GB
        pull must outlive any fixed total budget (mirrors the puller's
        stall-not-total timeout). Thread-safe."""
        with self._mutex:
            item = self._exports.get(handle)
            if item is not None and isinstance(item[0], KvStreamExport):
                exp = item[0]
                self._exports[handle] = (exp, time.monotonic() + self.export_ttl_s)
                return exp
        return None

    def release_stream_export(self, handle: str) -> None:
        """Drop a fully-delivered streaming export (the consumer saw
        kv_eos); frees any remaining host pages. Thread-safe."""
        with self._mutex:
            item = self._exports.pop(handle, None)
        if item is not None and isinstance(item[0], KvStreamExport):
            item[0].ack(item[0].chunk_count())

    def _reap_exports(self) -> None:
        now = time.monotonic()
        with self._mutex:
            dead = [h for h, (_, dl) in self._exports.items() if dl < now]
            reaped = [self._exports.pop(h) for h in dead]
        for item, _dl in reaped:
            if isinstance(item, KvStreamExport):
                # An unsealed reaped stream means the consumer never
                # finished pulling — tell any late puller it is gone,
                # and free whatever pages are still buffered.
                item.abort("expired")
                item.ack(item.chunk_count())

    # -- live migration (engine side) --------------------------------------
    #
    # Protocol (worker/migrate.py drives it; every entry point below runs
    # on the scheduler thread via run_on_engine_thread):
    #   begin    — register a KvStreamExport for a RUNNING decode; the
    #              sequence keeps decoding while each step's newly-sealed
    #              full blocks publish as chunks (the PR 8 credit-flow
    #              plane serves them to the destination, int8 scales
    #              riding along).
    #   cutover  — force-drain pending device tokens, FREEZE the sequence
    #              (out of _running; slot/KV retained), publish the delta
    #              blocks since the stream cursor, seal, and return the
    #              full resume identity (tokens, seed, sampler step,
    #              spec EMA, grammar state, adapter, next_write_pos).
    #   finish   — the destination committed: release resources and post
    #              a {"migration": marker} frame; the Migration operator
    #              consumes it and re-dispatches the SAME client stream
    #              pinned to the destination. Byte-identity: after the
    #              force-drain, kv_written == len(tokens)-1, so the sealed
    #              full blocks equal the destination's admission hit
    #              ceiling exactly — it recomputes only the <block_size
    #              suffix and continues sampling at (seed, step_base).
    #   abort    — any failure (or the freeze deadline passing with no
    #              coordinator): unfreeze, re-enter _running, keep
    #              decoding locally. The client never notices.

    def migration_begin(self, request_id: str) -> dict:
        """Start streaming a running decode's KV. → {"ok", "handle",
        "published"} or {"error"}. Scheduler thread only."""
        seq = next(
            (s for s in self._running if s.request_id == request_id), None
        )
        if seq is None or seq.dead or seq.cancelled:
            return {"error": "not_running"}
        if seq.mig is not None:
            return {"error": "already_migrating"}
        if seq.export or seq.export_stream is not None:
            return {"error": "exporting"}  # disagg export seqs finish at token 1
        handle = f"mig-{request_id}-{self._step_no}"
        stream = KvStreamExport(handle)
        with self._mutex:
            self._exports[handle] = (stream, time.monotonic() + self.export_ttl_s)
        mig = _MigSt(seq, handle, stream)
        seq.mig = mig
        self._migrations[request_id] = mig
        self._pump_migration(mig)
        return {"ok": True, "handle": handle, "published": mig.pub_blocks,
                "traceparent": seq.traceparent}

    def migration_status(self, request_id: str) -> dict:
        """Cutover-lag probe: how far the stream cursor trails the KV
        actually written. Scheduler thread only."""
        mig = self._migrations.get(request_id)
        if mig is None:
            return {"error": "no_migration"}
        return {
            "ok": True,
            "published": mig.pub_blocks,
            "written": mig.seq.kv_written // self.args.block_size,
            "frozen": mig.frozen,
            "sealed": mig.stream.sealed,
            "aborted": mig.stream.abort_reason,
        }

    def migration_cutover(self, request_id: str) -> dict:
        """Freeze the sequence, ship the delta, seal the stream, and
        return the resume identity. Scheduler thread only."""
        mig = self._migrations.get(request_id)
        if mig is None:
            return {"error": "no_migration"}
        seq = mig.seq
        if mig.stream.abort_reason is not None:
            reason = mig.stream.abort_reason
            self._abort_migration(mig, reason)
            return {"error": f"stream_aborted:{reason}"}
        # Every device-pending token must be host-visible before the
        # identity snapshots: the handoff carries exactly the tokens the
        # client will have seen. The drain may FINISH the sequence (stop
        # condition in flight) or a preemption may have raced us — both
        # tear the migration down via the _finish/_preempt hooks.
        self._drain_completed(force=True)
        if self._migrations.get(request_id) is not mig:
            return {"error": "done" if seq.dead else "preempted"}
        if seq.dead or seq not in self._running:
            self._abort_migration(mig, "finished")
            return {"error": "done"}
        self._running.remove(seq)
        mig.frozen = True
        mig.freeze_deadline = time.monotonic() + self.migration_freeze_ttl_s
        self._pump_migration(mig, force=True)
        if mig.stream.abort_reason is not None:
            reason = mig.stream.abort_reason
            self._abort_migration(mig, reason)
            return {"error": f"stream_aborted:{reason}"}
        bs = self.args.block_size
        mig.stream.seal(
            num_blocks=mig.pub_blocks, num_tokens=mig.pub_blocks * bs
        )
        return {
            "ok": True,
            "handle": mig.handle,
            "kv_blocks": mig.pub_blocks,
            "emitted": seq.emitted,
            "adapter_id": seq.adapter_id,
            "request": {
                "token_ids": list(seq.tokens),
                "resume": {
                    "prompt_len": seq.prompt_len,
                    "sample_seed": seq.sample_seed,
                    "sample_step": seq.step_base + seq.emitted,
                    "spec_ema": seq.spec_ema,
                    "grammar_state": seq.grammar_state,
                    "next_write_pos": seq.next_write_pos,
                    "traceparent": seq.traceparent,
                },
            },
        }

    def migration_finish(self, request_id: str, marker: dict) -> dict:
        """Destination committed: hand the client stream off by posting
        the migration marker, then release this side's resources. The KV
        already lives in the sealed stream's host pages (and the
        destination's staged inject), so freeing device blocks is safe.
        Scheduler thread only."""
        mig = self._migrations.get(request_id)
        if mig is None or not mig.frozen:
            return {"error": "not_frozen"}
        seq = mig.seq
        self._migrations.pop(request_id, None)
        seq.mig = None
        seq.dead = True
        if seq.slot is not None:
            self._free_slots.append(seq.slot)
            seq.slot = None
        self._release_adapter(seq)
        if self._offload_pending:
            freed = set(seq.block_ids)
            self._offload_pending = [
                (b, h) for b, h in self._offload_pending if b not in freed
            ]
        self.pool.free_sequence(seq.block_ids)
        seq.block_ids = []
        self._post(seq, {"token_ids": [], "migration": marker})
        self._post_done(seq)
        return {"ok": True}

    def migration_abort(self, request_id: str, reason: str) -> dict:
        """Coordinator-initiated teardown: the sequence resumes decoding
        locally (if frozen) and the stream aborts. Scheduler thread only."""
        mig = self._migrations.get(request_id)
        if mig is None:
            return {"error": "no_migration"}
        self._abort_migration(mig, reason)
        return {"ok": True}

    def _abort_migration(self, mig: _MigSt, reason: str) -> None:
        seq = mig.seq
        self._migrations.pop(seq.request_id, None)
        seq.mig = None
        mig.fetches = []  # drop in-flight extracts (device arrays released)
        mig.stream.abort(reason)  # no-op when sealed
        mig.stream.ack(mig.stream.chunk_count())  # free buffered host pages
        with self._mutex:
            self._exports.pop(mig.handle, None)
        if mig.frozen and not seq.dead:
            # Unfreeze: re-enter the running batch exactly where it left
            # off (slot and KV were retained) — zero client impact.
            mig.frozen = False
            self._running.append(seq)

    def _service_migrations(self) -> None:
        """Once per step: pump streaming migrations, reap finished ones,
        and enforce the cutover freeze deadline (a dead coordinator must
        never wedge a frozen stream)."""
        now = time.monotonic()
        for rid in list(self._migrations):
            mig = self._migrations.get(rid)
            if mig is None:
                continue
            seq = mig.seq
            if seq.dead or seq.cancelled:
                self._abort_migration(mig, "finished")
                continue
            if mig.stream.abort_reason is not None:
                # Overrun (slow consumer) or TTL reap ("expired" — the
                # consumer/store died). Either way the source just keeps
                # the stream: unfreeze if needed and decode on.
                self._abort_migration(mig, mig.stream.abort_reason)
                continue
            if mig.frozen:
                if now >= mig.freeze_deadline:
                    log.warning(
                        "migration %s cutover deadline exceeded; resuming locally",
                        rid,
                    )
                    self._abort_migration(mig, "cutover_deadline")
                continue
            self._pump_migration(mig)

    def _pump_migration(self, mig: _MigSt, force: bool = False) -> None:
        """Publish the KV block delta written since the stream cursor.
        Extract dispatch is async (start_host_fetch) and harvested
        strictly in dispatch order; ``force`` block-drains everything
        (cutover's final delta)."""
        if mig.stream.abort_reason is not None:
            return
        seq = mig.seq
        bs = self.args.block_size
        lo, hi = kv_transfer.delta_blocks(
            seq.kv_written, bs, mig.pub_blocks, len(seq.block_ids)
        )
        if hi > lo:
            arrs, n = self._runner.start_extract_pages(seq.block_ids[lo:hi])
            start_host_fetch(arrs)
            mig.fetches.append((lo, hi, arrs, n))
            mig.pub_blocks = hi
        keep: list = []
        for item in mig.fetches:
            flo, fhi, arrs, n = item
            if keep or (not force and not host_ready(arrs)):
                keep.append(item)
                continue
            pages = self._runner.finish_extract_pages(arrs, n)
            if not mig.stream.publish(KvChunk(
                block_offset=flo, pages=pages, num_tokens=(fhi - flo) * bs,
            )):
                break  # overrun — stream aborted; _service tears it down
        mig.fetches = keep

    def list_running(self) -> list[str]:
        """Request ids currently in the running batch — the relocation
        candidate set for pool moves/retirement. Thread-safe snapshot."""
        with self._wakeup:
            return [s.request_id for s in self._running if not s.dead]

    def _register_written_blocks(self, seq: _Seq) -> None:
        """Register sealed blocks whose KV is fully written. A block sealed
        by a just-sampled token must wait: that token's KV lands on the next
        decode step. Registering early would let another request prefix-hit
        a block with an unwritten tail slot."""
        if seq.block_seq is None:
            return
        bs = self.args.block_size
        while (
            seq.registered_blocks < len(seq.block_seq.blocks)
            and (seq.registered_blocks + 1) * bs <= seq.kv_written
        ):
            blk = seq.block_seq.blocks[seq.registered_blocks]
            bid = seq.block_ids[seq.registered_blocks]
            self.pool.register_block(bid, blk.sequence_hash, blk.parent_sequence_hash)
            # Write-through offload: queue the sealed block for the end-of-
            # step batched extract (bounded; duplicates in tiers skipped).
            if (
                self.tiers.enabled
                and len(self._offload_pending) < 256
                and not (self.tiers.host and self.tiers.host.contains(blk.sequence_hash))
            ):
                self._offload_pending.append((bid, blk.sequence_hash))
            seq.registered_blocks += 1

    # -- decode ------------------------------------------------------------

    def _ensure_block(self, seq: _Seq, lookahead: int = 1) -> bool:
        """Cover write positions [next_write_pos, next_write_pos+lookahead)
        with blocks; grow as needed."""
        last_pos = seq.next_write_pos + lookahead - 1
        while len(seq.block_ids) * self.args.block_size <= last_pos:
            try:
                seq.block_ids.append(self.pool.allocate_block())
            except NoFreeBlocksError:
                return False
        return True

    def _maybe_pressure_offer(self) -> None:
        """Proactive defrag (ISSUE 19 tentpole (d)): when KV pool usage
        crosses ``kv_pressure_offer``, fire the migration-offer hook for
        the CHEAPEST victim — fewest resident blocks, so the relocation
        streams the least KV — before allocation failure forces a
        recompute-preemption. Purely advisory: the hook's relocation
        either frees the blocks (migration_finish) or nothing changes
        and the preemption boundary still owns correctness. Scheduler
        thread only; rate-limited to one offer per pressure window."""
        cb = self.migration_offer
        if cb is None or not self._running:
            return
        now = time.monotonic()
        if now < self._pressure_offer_next:
            return
        if self.pool.usage < self.kv_pressure_offer:
            return
        victim: _Seq | None = None
        for s in self._running:
            if s.dead or s.mig is not None or s.export:
                continue
            if victim is None or len(s.block_ids) < len(victim.block_ids):
                victim = s
        if victim is None:
            return
        self._pressure_offer_next = now + self.kv_pressure_offer_window_s
        self.pressure_offers += 1
        # Reuse the preemption-offer grace stamp: if pressure keeps
        # climbing and this victim IS chosen for preemption inside the
        # grace, the kill waits for the already-running relocation.
        victim.offer_deadline = now + self.preempt_offer_grace_s
        try:
            cb(victim.request_id)
        except Exception:  # noqa: BLE001 — the proactive offer is advisory; a broken hook must never stall the scheduler
            log.exception("kv-pressure migration offer hook failed")

    def _offer_migration_grace(self, victim: _Seq) -> bool:
        """QoS preemption offers migration before killing: when an offer
        hook is wired, fire it once for the chosen victim and grant a
        bounded grace window for the relocation to free its blocks.
        False (kill now) when unwired, the hook fails, or the victim's
        grace already expired. Scheduler thread only."""
        cb = self.migration_offer
        if cb is None:
            return False
        now = time.monotonic()
        if victim.offer_deadline == 0.0:
            victim.offer_deadline = now + self.preempt_offer_grace_s
            try:
                cb(victim.request_id)
            except Exception:  # noqa: BLE001 — a broken offer hook must never block the preemption fallback
                log.exception("migration offer hook failed")
                return False
            return True
        return now < victim.offer_deadline

    def _preempt_victim(self) -> _Seq:
        """Class-aware victim selection: evict the LOWEST class first,
        newest admission within it — the newest victim has the least
        sunk prefill work, and a preempted batch request's freed blocks
        admit the waiting interactive request on the next step. Uniform
        ranks (no-QoS) select exactly ``self._running[-1]``, the
        pre-QoS newest-first rule."""
        best = self._running[-1]
        for s in self._running:  # later index = newer admission event
            if s.qos_rank <= best.qos_rank:
                best = s
        return best

    def _preempt(self, seq: _Seq) -> None:
        """Recompute-preemption: free blocks, requeue with all tokens as the
        new prompt (reference behaviour matches vLLM recompute mode)."""
        self._drain_completed(force=True)  # pending tokens must be host-visible
        if seq.dead or seq not in self._running:
            return  # resolution finished it (stop condition on token 1)
        # An outbound migration of the victim tears down first: its KV is
        # about to be freed, so the stream can never complete. (Frozen
        # sequences are not in _running, so they are immune to victim
        # selection — the bounded cutover window is never preempted.)
        if seq.mig is not None:
            self._abort_migration(seq.mig, "preempted")
        log.warning(
            "preempting request %s (KV pressure, class=%s)",
            seq.request_id, seq.qos,
        )
        self.total_preemptions_by[seq.qos] += 1
        seq.offer_deadline = 0.0  # a later re-admission can be offered again
        self._running.remove(seq)
        if seq.slot is not None:
            self._free_slots.append(seq.slot)
            seq.slot = None
        # Unpin the adapter: re-admission re-acquires (a still-resident
        # adapter is a free hit; an evicted one pages back in). The
        # serial device stream orders any later slot upload after this
        # sequence's already-dispatched work.
        self._release_adapter(seq)
        # Purge queued offloads of the freed blocks: they become evictable
        # now and could be recycled before the next flush.
        freed = set(seq.block_ids)
        self._offload_pending = [(b, h) for b, h in self._offload_pending if b not in freed]
        # A preempted streaming export aborts (the decode worker falls
        # back to local prefill) and the re-admission runs non-streamed:
        # re-registering the same handle under a fresh object would race
        # a consumer already waiting on this one. export must drop too —
        # otherwise re-admission runs a legacy one-shot extract under a
        # handle no consumer ever learned, parking the payload on the
        # heap until the TTL reap.
        if seq.export_stream is not None:
            seq.export_stream.abort("preempted")
            seq.export_stream = None
            seq.export_handle = None
            seq.export_pub_blocks = 0
            seq.export = False
        self.pool.free_sequence(seq.block_ids)
        seq.block_ids = []
        seq.registered_blocks = 0
        seq.kv_written = 0
        # prompt_len stays at the ORIGINAL prompt length: it delimits the
        # penalty token window (generated = tokens[prompt_len:]), which must
        # survive preemption; _prefill_seq re-runs over seq.tokens anyway.
        seq.block_seq = None
        seq.preempted = True
        self._waiting.appendleft(seq)

    # -- decode window pipeline -------------------------------------------
    #
    # With host↔device syncs costing a full tunnel roundtrip (~100 ms
    # measured), the engine keeps up to ``pipeline_depth`` decode windows
    # in flight: window w+1 is dispatched (chaining its input tokens from
    # w's on-device outputs via the per-slot fold buffer) BEFORE w's
    # results are fetched, and every fetch is started asynchronously at
    # dispatch, so the fetch roundtrips overlap later windows' device
    # execution. Consequences handled here:
    # - stops are discovered up to depth windows late; a stopped sequence
    #   rides the remaining in-flight windows as a zombie row whose
    #   output is discarded (waste bounded by depth × K tokens, same
    #   order as the fused window itself);
    # - zombie rows only write KV at positions beyond the drained
    #   boundary, and block registration is gated by complete kept-token
    #   blocks, so prefix reuse never sees junk;
    # - the device stream is serial and a sequence's blocks/slot are only
    #   freed after every window containing it has been DISPATCHED, so
    #   later prefills/samples reusing freed blocks or slots are ordered
    #   after all zombie writes and folds;
    # - the full sampler needs host-visible penalty windows, so sampler-
    #   heavy batches drain everything first and run unpipelined.

    def _pend(self, seq: _Seq) -> int:
        """Tokens already sampled on device for this sequence but not yet
        drained/emitted (its host-visible length lags by this many): K
        steps per in-flight window it rides plus an unfetched admission
        sample. _Spec items are invisible here BY INVARIANT: their
        pending count is data-dependent (1 + accepted), so
        _decode_iteration force-drains any queued _Spec before any
        planning that consults _pend."""
        p = 1 if seq.first_pend else 0
        for item in self._fetchq:
            if isinstance(item, _Window) and seq in item.row_of:
                p += item.K
        return p

    def _inflight_windows(self) -> int:
        return sum(1 for it in self._fetchq if isinstance(it, _Window))

    def _drain_completed(self, force: bool = False) -> None:
        """Harvest the completion queue from the front, strictly FIFO.
        Non-forced: pop only items whose async fetch already finished
        (free — the host never blocks). Forced: fetch-blocking drain of
        everything (needed when the pipeline is full, host-visible tokens
        are required, or all consumers died)."""
        while self._fetchq:
            if not force and not host_ready(self._fetchq[0].fetch_arrays()):
                break
            self._drain_one(self._fetchq.popleft())

    def _drain_one(self, item: "_First | _Window | _Spec") -> None:
        """Fetch + emit one queue item, attributing the fetch time by
        whether the host actually had to wait for it."""
        ready = host_ready(item.fetch_arrays())
        if isinstance(item, _First):
            self._drain_first(item, blocked=not ready)
        elif isinstance(item, _Spec):
            self._drain_spec(item, blocked=not ready)
        else:
            self._drain_window(item, blocked=not ready)

    def _drain_first(self, f: _First, blocked: bool = True) -> None:
        """Fetch + emit one admission wave's first-token samples."""
        t0 = time.perf_counter()
        toks = np.asarray(f.out_d)
        lps = np.asarray(f.lps_d)
        tvals_l = tids_l = None
        if f.top_ref is not None:
            tvals_l = np.asarray(f.top_ref.arrs[0]).tolist()
            tids_l = np.asarray(f.top_ref.arrs[1]).tolist()
        t0 = self._phase("first_sample" if blocked else "drain_ready", t0)
        toks_l, lps_l = toks.tolist(), lps.tolist()
        for seq, row in f.entries:
            seq.first_pend = False
            if seq.dead:
                continue  # cancelled while the sample was in flight
            tops = None
            if tids_l is not None and seq.sampling.top_logprobs:
                n = seq.sampling.top_logprobs
                tops = [[list(p) for p in zip(tids_l[row][:n], tvals_l[row][:n])]]
            self._emit_tokens(seq, [toks_l[row]], [lps_l[row]], tops)
        self._phase("emit", t0)

    def _plan_window(self) -> tuple[int, int]:
        """→ (K, depth). K=1 is the end-of-life tail near max_model_len;
        pipelining (depth > 0) needs K>1 and no full-sampler rows.
        Grammar rows also force K=1: their FSM advances host-side per
        emitted token and the NEXT token's mask depends on it, so the
        fused multi-step window (which samples K tokens on device) could
        only mask its first substep. The speculative tree path is the
        constrained fast path — there every node's mask is known at
        dispatch because the draft tokens are."""
        K = max(1, self.args.decode_steps)
        if K > 1:
            for s in self._running:
                if len(s.tokens) + self._pend(s) + K > self.args.max_model_len:
                    K = 1
                    break
        if K > 1 and any(s.grammar is not None for s in self._running):
            K = 1
        depth = self.args.effective_pipeline_depth
        if K == 1 or any(self._needs_full_sampler(s) for s in self._running):
            depth = 0
        return K, depth

    def _decode_iteration(self) -> None:
        # A queued _Spec hides an unknown number of pending tokens per
        # row (1 + accepted), so no decode work may be PLANNED past it:
        # positions, block lookahead and chain pends would all be wrong.
        # Its fetch has been in flight since dispatch (overlapping the
        # admission/prefill work _step did meanwhile); settle it first.
        if any(isinstance(it, _Spec) for it in self._fetchq):
            self._drain_completed(force=True)
        if not self._running:
            self._drain_completed(force=True)
            return
        if self._try_speculative():
            return
        K, depth = self._plan_window()
        if depth == 0 and self._fetchq:
            # Unpipelined plan (full sampler / K=1 tail): host-visible
            # tokens (penalty windows, per-step inputs) are required, so
            # everything pending drains first — then re-plan on the
            # drained state.
            self._drain_completed(force=True)
            return self._decode_iteration()
        # Grow block tables K ahead; under KV pressure drain the in-flight
        # windows first (their tokens must land before a preempted
        # sequence re-queues), then preempt newest-first. A lone sequence
        # that cannot grow is finished (cache physically too small).
        while self._running:
            blocked = next(
                (s for s in self._running
                 if not self._ensure_block(s, lookahead=K + self._pend(s))),
                None,
            )
            if blocked is None:
                break
            if self._fetchq:
                self._drain_completed(force=True)
                return self._decode_iteration()
            if len(self._running) == 1:
                self._finish(blocked, FinishReason.LENGTH)
            else:
                victim = self._preempt_victim()
                if self._offer_migration_grace(victim):
                    # Bounded grace: skip planning this step — either
                    # the offered relocation frees the victim's blocks
                    # (migration_finish) or the deadline expires and the
                    # next pass preempts for real.
                    self._drain_completed(force=True)
                    time.sleep(0.002)
                    return
                self._preempt(victim)
        if not self._running:
            self._drain_completed(force=True)
            return

        if K > 1:
            w = self._dispatch_window(K)
            self._fetchq.append(w)
            # Opportunistic harvest first (free), then enforce the depth
            # bound: block-draining the OLDEST window while the newest
            # executes is where the fetch roundtrip hides.
            self._drain_completed()
            while self._inflight_windows() > depth and self._fetchq:
                self._drain_one(self._fetchq.popleft())
            if not self._running:
                # Every sequence finished during the drains — remaining
                # queued windows are all zombie rows and nothing would
                # ever wake the loop to fetch them (the idle predicate
                # ignores _fetchq), so release them now.
                self._drain_completed(force=True)
        else:
            self._decode_single_step()

    def _dispatch_window(self, K: int) -> "_Window":
        """Enqueue one fused K-step window over the current running set.
        Rows with device-pending tokens (in-flight window output or an
        unfetched admission sample) chain their input from the per-slot
        buffer (no host sync)."""
        batch = list(self._running)
        B = self.args.bucket_decode(len(batch))
        # Table width = smallest bucket covering the longest sequence in
        # the batch (block growth for pend+K already happened): attention
        # cost tracks actual lengths, not max_model_len.
        W = self.args.bucket_table(max(len(s.block_ids) for s in batch))
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.zeros((B, W), np.int32)
        active = np.zeros((B,), bool)
        fold_slots = np.full((B,), self.args.max_num_seqs, np.int32)
        pos0: list[int] = []
        chain: list[tuple[int, int]] = []  # (this row, chain SLOT)
        for i, seq in enumerate(batch):
            pend = self._pend(seq)
            p0 = seq.next_write_pos + pend
            pos0.append(p0)
            positions[i] = p0
            tables[i, : len(seq.block_ids)] = seq.block_ids
            active[i] = True
            fold_slots[i] = seq.slot
            if pend:
                # Input rides the per-slot chain buffer: fed by the
                # in-flight window's fold and/or the admission sample.
                chain.append((i, seq.slot))
            else:
                tokens[i] = seq.tokens[-1]

        temps = np.ones((B,), np.float32)
        seeds = np.zeros((B,), np.uint32)
        steps0 = np.zeros((B,), np.int32)
        tks = np.zeros((B,), np.int32)
        tps = np.ones((B,), np.float32)
        freqs = np.zeros((B,), np.float32)
        press = np.zeros((B,), np.float32)
        for i, s in enumerate(batch):
            temps[i] = s.sampling.temperature
            seeds[i] = s.sample_seed
            steps0[i] = s.step_base + s.emitted + self._pend(s)
            tks[i] = s.sampling.top_k or 0
            tps[i] = s.sampling.top_p if s.sampling.top_p is not None else 1.0
            freqs[i] = s.sampling.frequency_penalty
            press[i] = s.sampling.presence_penalty
        if any(self._needs_full_sampler(s) for s in batch):
            # Only reachable unpipelined (chain is empty then).
            mode = "full"
            pen = self._penalty_window(batch, B)
        else:
            mode = "greedy" if all(t < 1e-5 for t in temps[: len(batch)]) else "simple"
            pen = np.full((B, 1), -1, np.int32)  # placeholder, untraced-const shape

        wchain = None
        if chain:
            wchain = ([d for d, _ in chain], [s for _, s in chain])
        top_n = (
            self.args.top_logprobs_max
            if any(s.sampling.top_logprobs for s in batch) else 0
        )
        aslots = self._adapter_row_slots(batch, B)
        t0 = time.perf_counter()
        ref = self._runner.multi_decode(
            K, mode, tokens, wchain, positions, tables, active,
            temps, seeds, steps0, tks, tps, freqs, press, pen, fold_slots,
            top_n, aslots,
        )
        w = _Window(batch, pos0, K, ref, top_n)
        start_host_fetch(w.fetch_arrays())
        self._phase("decode_dispatch", t0)
        return w

    def _drain_window(self, w: "_Window", blocked: bool = True) -> None:
        self.total_decode_steps += w.K
        t0 = time.perf_counter()
        toks_np = np.asarray(w.ref.arrs[0])  # [K, B] — the one host fetch
        logps_np = np.asarray(w.ref.arrs[1])
        tvals_l = tids_l = None
        if w.top_n:
            # transpose → [B, K, top_n]; bulk-converted once (per-element
            # int()/float() at K·B·n scale was measurable emit cost).
            tvals_l = np.asarray(w.ref.arrs[2]).transpose(1, 0, 2).tolist()
            tids_l = np.asarray(w.ref.arrs[3]).transpose(1, 0, 2).tolist()
        t0 = self._phase("drain_sync" if blocked else "drain_ready", t0)
        toks_l = toks_np.T.tolist()    # [B][K] python ints
        logps_l = logps_np.T.tolist()  # [B][K] python floats
        for i, seq in enumerate(w.rows):
            if seq.dead:
                continue  # finished/cancelled while this window was in flight
            # Dense accounting: K per-sequence weight passes, one token
            # each (the tokens-per-weight-pass denominator/numerator).
            self.total_row_passes += w.K
            self.total_row_tokens += w.K
            seq.kv_written = w.pos0[i] + w.K
            self._register_written_blocks(seq)
            tops = None
            if tids_l is not None and seq.sampling.top_logprobs:
                n = seq.sampling.top_logprobs
                tops = [
                    [list(p) for p in zip(tids_l[i][j][:n], tvals_l[i][j][:n])]
                    for j in range(w.K)
                ]
            self._emit_tokens(seq, toks_l[i], logps_l[i], tops)
        self._phase("emit", t0)

    # -- speculative decoding ---------------------------------------------
    #
    # Decode is weight-bandwidth-bound: a dense substep streams the full
    # weights for ONE token per sequence. A speculative pass streams them
    # once for up to spec_tokens+1 tokens per sequence: the host drafts
    # each row's likely continuation by n-gram prompt lookup (free), the
    # device scores draft_len+1 positions in one forward
    # (model.spec_verify — a decode-time prefill chunk over the same
    # paged-attention path), and on-device acceptance keeps the longest
    # prefix the target model agrees with plus one corrected/bonus token.
    # Greedy rows are byte-identical to the dense path (argmax match);
    # sampled rows use rejection sampling, leaving the output
    # distribution unchanged.
    #
    # Scheduling contract: drafting needs the full host-visible history
    # and the drain reveals how far each row advanced, so a speculative
    # pass is a pipeline BARRIER — everything pending drains before
    # dispatch, and the pass itself drains before the next decode plan
    # (admission + prefill dispatch still overlap it: the _Spec rides
    # _fetchq with its fetch in flight while _step admits new work).
    # Rows whose drafts keep being rejected (or that never match) decay
    # an acceptance EMA / enter a probe cooldown, so incompressible
    # workloads fall back to the dense window pipeline at full depth.

    def _row_draft(self, seq: _Seq, budget: int):
        """Propose a draft for one row — a token list (linear drafter)
        or a TreeDraft (tree drafter) — applying the adaptive controls.
        Empty ⇒ the row rides the pass with draft_len 0 (a plain
        next-token step) or, if no row drafts, the batch falls back to
        the dense path entirely. ``budget`` is this row's draft-node
        allowance: uniform spec_tokens, or 2S under adaptive batch
        budgets (drafting is optimistic there — the EMA shrink below
        still applies, scaled to the allowance, and trim_spec_budgets
        enforces the batch total afterwards)."""
        args = self.args
        # Never draft past the model length: the pass emits up to
        # potential+1 tokens and writes KV slots up to positions0 +
        # draft-node count (tree slots are slot-ordered, so the node
        # budget bounds the write extent for any shape).
        cap = min(budget, args.max_model_len - len(seq.tokens) - 1)
        if cap <= 0 or seq.spec_cool > 0:
            return []
        # EMA-proportional shrink: full drafts at ema >= 0.5, linearly
        # shorter below, floor 1 — a just-re-enabled low-EMA row
        # proposes a naturally short probe, and acceptance lifts the
        # EMA back up.
        eff = min(cap, max(1, round(budget * min(1.0, seq.spec_ema / 0.5))))
        if seq.draft_state is None:
            seq.draft_state = self._drafter.new_state()
        constraint = None
        if seq.grammar is not None:
            # Grammar-pruned drafting: candidates filtered to FSM-legal
            # continuations, forced states contributing their single
            # legal token (certainty) — constrained rows draft near-
            # perfect trees, which is where tree speculation pays
            # hardest on structured traffic.
            g, st = seq.grammar, seq.grammar_state
            constraint = DraftConstraint(st, g.advance, g.forced)
        if hasattr(self._drafter, "draft_tree"):
            return self._drafter.draft_tree(
                seq.tokens, seq.draft_state, eff, constraint=constraint
            )
        d = self._drafter.draft(seq.tokens, seq.draft_state, eff)
        if constraint is not None:
            d = constrain_chain(d, constraint, eff)
        return d

    @staticmethod
    def _draft_potential(d) -> int:
        """Best-case accepted run of one proposal: the whole draft for a
        chain, the deepest root path for a tree."""
        return d.max_depth if isinstance(d, TreeDraft) else len(d)

    def _spec_gate_passes(self, drafts: dict["_Seq", Any]) -> bool:
        """Batch-level dispatch decision: the EMA-weighted expected
        tokens per row-pass, mean(1 + ema_i * potential_i), must clear
        spec_gate — and at least one draft must exist at all."""
        if not drafts or not any(len(d) for d in drafts.values()):
            return False
        expected = sum(
            1.0 + s.spec_ema * self._draft_potential(d)
            for s, d in drafts.items()
        ) / len(drafts)
        return expected >= self.args.spec_gate

    def _try_speculative(self) -> bool:
        """Dispatch one speculative verify pass over the running set if
        it is eligible and at least one row has a draft. → True when a
        pass was dispatched (the caller's decode iteration is done).

        Two-phase drafting keeps the dense pipeline intact when there is
        nothing to verify: a cheap scan over the HOST-VISIBLE history
        (which may lag in-flight windows) decides whether draining the
        pipeline could pay off at all; only a scan hit forces the drain,
        after which rows re-draft on their complete histories for the
        actual dispatch. The drafter's incremental index makes the
        per-iteration scan O(newly visible tokens)."""
        S = self.spec_tokens
        if S <= 0:
            return False
        # Full-sampler rows need host-visible penalty windows stepwise;
        # same constraint that forces the dense path unpipelined.
        if any(self._needs_full_sampler(s) for s in self._running):
            return False
        # Tick rejection cooldowns once per scheduler STEP (this method
        # can run twice in a step when a drain forces a replan): a row
        # whose acceptance EMA collapsed proposes nothing until its
        # cooldown expires, then re-probes with an EMA-shortened draft.
        if self._spec_ticked != self._step_no:
            self._spec_ticked = self._step_no
            for s in self._running:
                if s.spec_cool > 0:
                    s.spec_cool -= 1
        t0 = time.perf_counter()
        drafts = self._draft_all(S)
        if not self._spec_gate_passes(drafts):
            self._phase("draft", t0)
            return False
        # The gate passed on the visible history: drafting positions +
        # inputs need COMPLETE histories, so settle everything in flight,
        # then re-draft rows whose histories just advanced.
        if self._fetchq:
            self._phase("draft", t0)
            self._drain_completed(force=True)
            if not self._running:
                return True
            t0 = time.perf_counter()
            drafts = self._draft_all(S)
        t0 = self._phase("draft", t0)
        if not self._spec_gate_passes(drafts):
            return False
        batch = list(self._running)
        # Cover writes at positions0 + draft-node-count; rows that
        # cannot grow fall back to the dense path's pressure handling
        # (drain/preempt).
        for seq in batch:
            if not self._ensure_block(seq, lookahead=len(drafts[seq]) + 1):
                return False
        B = self.args.bucket_decode(len(batch))
        # Verify-shape bucket: the uniform S+1 covers every draft at or
        # under the per-row allowance; an adaptive reallocation that let
        # a hot row draft past S (its only way past S) upgrades the pass
        # to the 2S+1 shape — two S1 buckets total, both AOT-warmable.
        max_nodes = max((len(d) for d in drafts.values()), default=0)
        S1 = S + 1 if max_nodes <= S else SPEC_BUDGET_MAX_MULT * S + 1
        if max_nodes > S:
            self.total_spec_budget_reallocs += 1
        W = self.args.bucket_table(max(len(s.block_ids) for s in batch))
        tokens = np.zeros((B, S1), np.int32)
        pos0_arr = np.zeros((B,), np.int32)
        dlen = np.zeros((B,), np.int32)
        tables = np.zeros((B, W), np.int32)
        active = np.zeros((B,), bool)
        fold_slots = np.full((B,), self.args.max_num_seqs, np.int32)
        temps = np.ones((B,), np.float32)
        seeds = np.zeros((B,), np.uint32)
        steps0 = np.zeros((B,), np.int32)
        pos0: list[int] = []
        draft_lens: list[int] = []
        potentials: list[int] = []
        node_tokens: list[list[int]] = []
        node_parents: list[list[int]] = []
        # A batch whose proposals are all CHAINS dispatches through the
        # PR 5 linear op (byte-for-byte that path, including stepwise
        # parity); any branched proposal upgrades the whole batch to the
        # topology-masked tree op (chains are trees too). Grammar rows
        # ALSO force the tree op: per-node masks ride only the tree
        # acceptance path (even a draft-less constrained row needs its
        # root mask for the bonus sample).
        any_gram = any(s.grammar is not None for s in batch)
        any_tree = any_gram or any(
            isinstance(d, TreeDraft) and not d.is_chain()
            for d in drafts.values()
        )
        for i, seq in enumerate(batch):
            d = drafts[seq]
            if isinstance(d, TreeDraft):
                toks, pars = d.tokens, d.parents
            else:
                toks, pars = list(d), list(range(len(d)))
            tokens[i, 0] = seq.tokens[-1]
            tokens[i, 1 : 1 + len(toks)] = toks
            p0 = seq.next_write_pos
            pos0.append(p0)
            pos0_arr[i] = p0
            dlen[i] = len(toks)
            draft_lens.append(len(toks))
            potentials.append(self._draft_potential(d))
            node_tokens.append([seq.tokens[-1]] + list(toks))
            node_parents.append([0] + list(pars))
            tables[i, : len(seq.block_ids)] = seq.block_ids
            active[i] = True
            fold_slots[i] = seq.slot
            temps[i] = seq.sampling.temperature
            seeds[i] = seq.sample_seed
            steps0[i] = seq.step_base + seq.emitted
        mode = "greedy" if all(t < 1e-5 for t in temps[: len(batch)]) else "simple"
        top_n = (
            self.args.top_logprobs_max
            if any(s.sampling.top_logprobs for s in batch) else 0
        )
        tree = None
        if any_tree:
            tree = self._build_tree_args(B, S1, node_parents)
        masks = None
        if any_gram:
            masks = self._build_tree_masks(batch, B, S1, node_tokens,
                                           node_parents)
        ref = self._runner.spec_verify(
            S1, mode, tokens, pos0_arr, dlen, tables, active,
            temps, seeds, steps0, fold_slots, top_n, tree, masks,
            self._adapter_row_slots(batch, B),
        )
        item = _Spec(
            batch, pos0, draft_lens, ref, top_n,
            potentials=potentials, tree=any_tree,
            node_tokens=node_tokens, node_parents=node_parents,
        )
        start_host_fetch(item.fetch_arrays())
        self._fetchq.append(item)
        self._phase("spec_dispatch", t0)
        return True

    def _draft_all(self, S: int) -> dict:
        """Draft every running row under the batch node budget. Uniform
        mode: each row proposes up to S (EMA-shrunk — PR 10 behavior,
        byte-for-byte). Adaptive mode (spec_budget_adaptive): every row
        drafts optimistically up to 2S (its EMA shrink still applies,
        scaled to the doubled allowance), then trim_spec_budgets
        enforces the FIXED batch total rows x S by trimming EMA-cold
        rows back toward their uniform-path draft length — rows with
        nothing to say donate their allowance, and the hot rows (above
        all grammar-constrained rows, whose forced JSON runs exceed S)
        spend it."""
        if not self.spec_budget_adaptive:
            return {s: self._row_draft(s, S) for s in self._running}
        rows = list(self._running)
        cap = SPEC_BUDGET_MAX_MULT * S
        drafts = {s: self._row_draft(s, cap) for s in rows}
        keep = trim_spec_budgets(
            [(s.spec_ema, len(drafts[s])) for s in rows], S
        )
        for s, k in zip(rows, keep):
            d = drafts[s]
            if len(d) <= k:
                continue
            if isinstance(d, TreeDraft):
                d.truncate(k)
            else:
                drafts[s] = d[:k]
        return drafts

    def _build_tree_masks(
        self, batch: list[_Seq], B: int, S1: int,
        node_tokens: list[list[int]], node_parents: list[list[int]],
    ) -> np.ndarray:
        """Per-(row, node) packed grammar masks for one tree verify
        dispatch → [B, S1, W32] uint32. Node j masks by ITS OWN FSM
        state — the state reached by walking the draft tokens from the
        sequence's current state along the tree's parent chain — because
        node j's logits are the distribution its children are checked
        against and its correction/bonus token samples from.
        Unconstrained rows (and dead slots) ride all-ones masks: bitwise
        identity under where(). Pruned drafting guarantees every walk
        step succeeds; the defensive parent-state fallback only matters
        for an illegal draft node, which acceptance can never reach
        anyway (its own edge probability is masked to zero)."""
        t0 = time.perf_counter()
        masks = np.full(
            (B, S1, mask_words(self.cfg.vocab_size)), 0xFFFFFFFF, np.uint32
        )
        for i, seq in enumerate(batch):
            g = seq.grammar
            if g is None:
                continue
            states = [seq.grammar_state]
            masks[i, 0] = g.mask(states[0], seq.grammar_eos_bits)
            toks_i, pars_i = node_tokens[i], node_parents[i]
            for j in range(1, len(toks_i)):
                st = g.advance(states[pars_i[j]], toks_i[j])
                states.append(st if st is not None else states[pars_i[j]])
                masks[i, j] = g.mask(states[j], seq.grammar_eos_bits)
        self.total_grammar_mask_s += time.perf_counter() - t0
        return masks

    @staticmethod
    def _build_tree_args(
        B: int, S1: int, node_parents: list[list[int]]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Host-side tree topology for one verify dispatch → (parents
        [B, S1], ancestor-or-self mask [B, S1, S1] int8, depth [B, S1]).
        Rows beyond the live batch stay all-zero (inactive)."""
        parents = np.zeros((B, S1), np.int32)
        anc = np.zeros((B, S1, S1), np.int8)
        depth = np.zeros((B, S1), np.int32)
        for i, pars in enumerate(node_parents):
            anc[i, 0, 0] = 1
            for j in range(1, len(pars)):
                p = pars[j]
                parents[i, j] = p
                anc[i, j] = anc[i, p]
                anc[i, j, j] = 1
                depth[i, j] = depth[i, p] + 1
        return parents, anc, depth

    def _drain_spec(self, sp: "_Spec", blocked: bool = True) -> None:
        self.total_spec_passes += 1
        if sp.tree:
            self.total_spec_tree_passes += 1
        t0 = time.perf_counter()
        out_l = np.asarray(sp.ref.arrs[0]).tolist()     # [B][S1]
        n_emit_l = np.asarray(sp.ref.arrs[1]).tolist()  # [B]
        logps_l = np.asarray(sp.ref.arrs[2]).tolist()   # [B][S1]
        cand_l = np.asarray(sp.ref.arrs[3]).tolist()    # [B][S1]
        tvals_l = tids_l = None
        if sp.top_n:
            tvals_l = np.asarray(sp.ref.arrs[4]).tolist()  # [B][S1][n]
            tids_l = np.asarray(sp.ref.arrs[5]).tolist()
        t0 = self._phase("drain_sync" if blocked else "drain_ready", t0)
        alpha = self.args.spec_ema_alpha
        for i, seq in enumerate(sp.rows):
            if seq.dead:
                continue  # finished/cancelled while the pass was in flight
            n = int(n_emit_l[i])
            a = n - 1
            S_i = sp.draft_lens[i]
            self.total_spec_rows += 1
            self.total_spec_emitted += n
            self.total_row_passes += 1
            self.total_row_tokens += n
            if S_i > 0:
                self.total_spec_proposed += S_i
                self.total_spec_accepted += a
                # EMA over ACHIEVABLE acceptance: a tree that branches 4
                # wide can only accept down its deepest path, so the
                # potential (max depth; == S_i for a chain) is the
                # honest denominator for the shrink/disable controls.
                pot = max(1, sp.potentials[i])
                seq.spec_ema = (1 - alpha) * seq.spec_ema + alpha * (a / pot)
                if seq.spec_ema < self.args.spec_ema_disable:
                    seq.spec_cool = self.args.spec_probe_every
                if sp.tree:
                    self.total_spec_tree_rows += 1
                    self.total_spec_tree_depth += a
                    self._spec_depth_hist[a] += 1
            # Jacobi-pool refresh for EVERY live row — including rows
            # that proposed nothing (their root-node cand is exactly the
            # zero-history-hit seed the Lookahead pool exists for):
            # every node's (context → argmax prediction) pair is free
            # drafting signal, rejected branches included. seq.tokens
            # still ends at this pass's root (emission happens below).
            if seq.draft_state is not None and sp.node_tokens is not None:
                self._drafter.observe(
                    seq.draft_state, seq.tokens, sp.node_tokens[i],
                    sp.node_parents[i], S_i + 1, cand_l[i],
                )
            # Positions p0..p0+a hold CORRECT KV ([last, accepted
            # drafts]); the correction/bonus token's KV lands on the next
            # dispatch, exactly like a dense window's last sample. Junk
            # KV past the boundary is never registered and gets rewritten
            # by the next dispatch (next_write_pos rolls back with the
            # emitted count).
            seq.kv_written = sp.pos0[i] + n
            self._register_written_blocks(seq)
            tops = None
            if tids_l is not None and seq.sampling.top_logprobs:
                tn = seq.sampling.top_logprobs
                tops = [
                    [list(p) for p in zip(tids_l[i][j][:tn], tvals_l[i][j][:tn])]
                    for j in range(n)
                ]
            self._emit_tokens(seq, out_l[i][:n], logps_l[i][:n], tops)
        self._phase("emit", t0)

    def _decode_single_step(self) -> None:
        # Per-step path needs host-visible tokens (inputs come from
        # seq.tokens[-1]); drain everything pending first.
        self._drain_completed(force=True)
        if not self._running:
            return
        t_start = time.perf_counter()
        batch = list(self._running)
        B = self.args.bucket_decode(len(batch))
        W = self.args.bucket_table(max(len(s.block_ids) for s in batch))
        tokens = np.zeros((B,), np.int32)
        positions = np.zeros((B,), np.int32)
        tables = np.zeros((B, W), np.int32)
        active = np.zeros((B,), bool)
        for i, seq in enumerate(batch):
            tokens[i] = seq.tokens[-1]
            positions[i] = seq.next_write_pos
            tables[i, : len(seq.block_ids)] = seq.block_ids
            active[i] = True
        ref = self._runner.decode_step(
            tokens, positions, tables, active,
            self._adapter_row_slots(batch, B),
        )
        self.total_decode_steps += 1
        self.total_row_passes += len(batch)
        self.total_row_tokens += len(batch)
        # The step just wrote each sequence's KV at `positions[i]`.
        for i, seq in enumerate(batch):
            seq.kv_written = int(positions[i]) + 1
            self._register_written_blocks(seq)
        srcs = [(ref, i) for i in range(len(batch))]
        srcs += [(ref, 0)] * (B - len(batch))
        sampled, logps, tref = self._sample_rows(
            srcs, batch,
            top_n=(self.args.top_logprobs_max
                   if any(s.sampling.top_logprobs for s in batch) else 0),
        )
        tvals = tids = None
        if tref is not None:
            tvals, tids = np.asarray(tref.arrs[0]), np.asarray(tref.arrs[1])
        for i, seq in enumerate(batch):
            tops = None
            if tvals is not None and seq.sampling.top_logprobs:
                n = seq.sampling.top_logprobs
                tops = [[[int(tids[i, r]), float(tvals[i, r])] for r in range(n)]]
            self._emit_tokens(seq, [int(sampled[i])], [float(logps[i])], tops)
        self._phase("single_step", t_start)

    @staticmethod
    def _needs_full_sampler(seq: _Seq) -> bool:
        s = seq.sampling
        return row_needs_full(s.top_k, s.top_p, s.frequency_penalty, s.presence_penalty)

    @staticmethod
    def _penalty_window(seqs: list[_Seq], B: int) -> np.ndarray:
        """[B, L] generated-so-far ids (-1 pad), L bucketed pow2 so the
        shape set stays small."""
        # Generated = everything past the prompt boundary — a resumed
        # (migrated) sequence's carried tokens count even though its
        # this-leg emitted does not include them.
        max_gen = max((len(s.tokens) - s.prompt_len for s in seqs), default=0)
        L = 16
        while L < max_gen:
            L *= 2
        pen = np.full((B, L), -1, np.int32)
        for i, s in enumerate(seqs):
            gen = s.tokens[s.prompt_len : s.prompt_len + L]
            pen[i, : len(gen)] = gen
        return pen

    def _sample_rows(self, srcs, seqs: list[_Seq], top_n: int = 0):
        """Sample one token per row for the first len(seqs) rows, synced.
        ``srcs``: list of (StepRef, row|None) logits sources (padded to a
        bucket). → (tokens [B], chosen logprobs [B], top_ref|None)."""
        out, logps, top_ref = self._sample_rows_device(srcs, seqs, None, top_n)
        return np.asarray(out), np.asarray(logps), top_ref  # the one host sync

    def _sample_rows_device(self, srcs, seqs: list[_Seq], fold_slots, top_n: int = 0):
        """Device-side sampling; with ``fold_slots`` the tokens also land
        in the chain buffer for the next window (async admission).
        → (tokens [B], logprobs [B], top_ref|None) unfetched."""
        B = len(srcs)
        temps = np.ones((B,), np.float32)
        tks = np.zeros((B,), np.int32)
        tps = np.ones((B,), np.float32)
        freqs = np.zeros((B,), np.float32)
        press = np.zeros((B,), np.float32)
        seeds = np.zeros((B,), np.uint32)
        steps = np.zeros((B,), np.int32)
        for i, s in enumerate(seqs):
            temps[i] = s.sampling.temperature
            tks[i] = s.sampling.top_k or 0
            tps[i] = s.sampling.top_p if s.sampling.top_p is not None else 1.0
            freqs[i] = s.sampling.frequency_penalty
            press[i] = s.sampling.presence_penalty
            seeds[i] = s.sample_seed
            steps[i] = s.step_base + s.emitted
        full = needs_full(tks.tolist(), tps.tolist(), freqs.tolist(), press.tolist())
        pen = (
            self._penalty_window(seqs, B) if full
            else np.full((B, 1), -1, np.int32)
        )
        # Grammar rows sample from their FSM state's masked vocabulary
        # (admission = the start state; single-step = the state after
        # every emitted token, host-visible because grammar batches
        # always run force-drained K=1).
        masks = self._grammar_row_masks(seqs, B)
        return self._runner.sample_rows(
            srcs, temps, tks, tps, pen, freqs, press, seeds, steps, full,
            fold_slots, top_n, masks,
        )

    # -- token emission / finish ------------------------------------------

    def _emit_tokens(self, seq: _Seq, toks: list[int], logps: list[float] | None = None,
                     tops: list | None = None) -> None:
        """Append sampled tokens (a multi-step window or a single token),
        truncating at the first stop condition. Posts ONE output delta with
        the kept tokens — tokens past a mid-window stop are wasted device
        work, never surfaced."""
        kept: list[int] = []
        finish: FinishReason | None = None
        for token in toks:
            token = int(token)  # numpy scalar → msgpack-able python int
            seq.tokens.append(token)
            seq.emitted += 1
            self.total_generated += 1
            kept.append(token)
            # Advance the grammar FSM per emitted token (EOS stops the
            # walk, it is not part of the match). Masked sampling makes
            # every emitted token legal by construction; the defensive
            # None check keeps a state-desync from cascading (the row
            # would just stop constraining instead of crashing the
            # scheduler thread).
            if seq.grammar is not None and token not in seq.eos_ids:
                ns = seq.grammar.advance(seq.grammar_state, token)
                if ns is not None:
                    seq.grammar_state = ns
            # Block-hash bookkeeping only; registration waits until the
            # sealed block's KV is fully written (_register_written_blocks).
            if seq.block_seq is not None:
                seq.block_seq.append(token)
            if (
                token in seq.eos_ids
                and not seq.stop.ignore_eos
                and seq.emitted >= seq.stop.min_tokens  # eos counts toward min (vLLM)
            ):
                finish = FinishReason.STOP
            elif seq.stop.max_tokens is not None and seq.emitted >= seq.stop.max_tokens:
                finish = FinishReason.LENGTH
            elif len(seq.tokens) >= self.args.max_model_len:
                finish = FinishReason.LENGTH
            if finish is not None:
                break
        self._post(
            seq,
            LLMEngineOutput(
                token_ids=kept,
                finish_reason=finish,
                log_probs=logps[: len(kept)] if logps and seq.sampling.logprobs else None,
                top_log_probs=(
                    tops[: len(kept)]
                    if tops and seq.sampling.logprobs and seq.sampling.top_logprobs
                    else None
                ),
                kv_transfer_params=seq.export_meta if finish is not None else None,
            ).to_dict(),
        )
        if finish is not None:
            self._finish(seq, finish, already_posted=True)

    def _finish(
        self,
        seq: _Seq,
        reason: FinishReason,
        error: str | None = None,
        already_posted: bool = False,
    ) -> None:
        if seq.mig is not None:
            # Finished (stop/cancel/error) while migrating out: the
            # destination's pull sees the abort and the coordinator's
            # cutover gets a typed "done" — the stream completed in place.
            self._abort_migration(seq.mig, "finished")
        seq.dead = True
        if seq.export_stream is not None and not seq.export_stream.sealed:
            # Error/cancel before the prefill sealed the stream: the
            # puller must not wait out its deadline on a dead export.
            seq.export_stream.abort("prefill_failed")
        if seq in self._running:
            self._running.remove(seq)
        if seq.slot is not None:
            self._free_slots.append(seq.slot)
            seq.slot = None
        self._release_adapter(seq)
        # Purge queued offloads of blocks about to become evictable (same
        # as _preempt): once freed they can be recycled by any allocation
        # before the next flush, and a late extract would snapshot the NEW
        # occupant's KV under the OLD sequence hash — poisoning the tier.
        if self._offload_pending:
            freed = set(seq.block_ids)
            self._offload_pending = [
                (b, h) for b, h in self._offload_pending if b not in freed
            ]
        self.pool.free_sequence(seq.block_ids)
        seq.block_ids = []
        if not already_posted:
            self._post(seq, LLMEngineOutput(finish_reason=reason, error=error).to_dict())
        self._post_done(seq)

    def _post(self, seq: _Seq, item: Any) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(seq.queue.put_nowait, item)

    def _post_done(self, seq: _Seq) -> None:
        self._post(seq, _SENTINEL_DONE)
