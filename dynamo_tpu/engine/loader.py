"""Real-checkpoint loading: HF model directory → (ModelConfig, params).

Reference analogue: local model resolution + GGUF/HF loading
(reference: lib/llm/src/local_model.rs:39-100, hub.rs:126,
model_card/create.rs). TPU-first differences: weights land directly in
the engine's stacked-layer pytree (one [L, ...] leaf per projection so
``lax.scan`` compiles one layer body), converted to the serving dtype on
the host and ``device_put`` with the engine's sharding rules — no
torch in the serving path.

Supported checkpoint format: a local HF Llama-family directory —
``config.json`` + ``*.safetensors`` (single file or index-sharded) +
``tokenizer.json``. Zero-egress: no hub downloads, local paths only.

Weight-name mapping (HF → ours):
  model.embed_tokens.weight                  embed            [V, D]
  model.layers.{i}.self_attn.q_proj.weight   layers.wq[i]     ([qs, D] → T)
  model.layers.{i}.self_attn.k_proj.weight   layers.wk[i]     ([kvs, D] → T)
  model.layers.{i}.self_attn.v_proj.weight   layers.wv[i]     ([kvs, D] → T)
  model.layers.{i}.self_attn.o_proj.weight   layers.wo[i]     ([D, qs] → T)
  model.layers.{i}.mlp.gate_proj.weight      layers.w_gate[i] ([I, D] → T)
  model.layers.{i}.mlp.up_proj.weight        layers.w_up[i]   ([I, D] → T)
  model.layers.{i}.mlp.down_proj.weight      layers.w_down[i] ([D, I] → T)
  model.layers.{i}.input_layernorm.weight    layers.attn_norm[i]
  model.layers.{i}.post_attention_layernorm. layers.mlp_norm[i]
  model.norm.weight                          final_norm
  lm_head.weight (absent when tied)          lm_head          ([V, D] → T)

RoPE convention: HF checkpoints store q/k projections pre-permuted for
the ``rotate_half`` formulation, which is exactly what model._rope
computes — weights load with no permutation fix-up.
"""

from __future__ import annotations

import json
import os
from typing import Any

import numpy as np

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("loader")


def config_from_hf(model_path: str) -> ModelConfig:
    """Parse ``config.json`` into a ModelConfig. Llama-family only
    (LlamaForCausalLM & friends: same tensor layout)."""
    with open(os.path.join(model_path, "config.json")) as f:
        hf = json.load(f)
    archs = hf.get("architectures") or []
    known = {"LlamaForCausalLM", "MistralForCausalLM", "Qwen2ForCausalLM"}
    if archs and not (set(archs) & known):
        log.warning("untested architecture %s — loading with llama layout", archs)
    # Qwen2 hardcodes QKV bias in its modeling code (no config field).
    # NOTE: llama's attention_bias=true flag is deliberately NOT honored
    # here — that layout also puts a bias on o_proj, which the model does
    # not implement; such checkpoints fail loudly in load_params instead
    # of half-loading.
    attn_bias = "Qwen2ForCausalLM" in archs
    hidden = int(hf["hidden_size"])
    heads = int(hf["num_attention_heads"])
    head_dim = int(hf.get("head_dim") or hidden // heads)
    return ModelConfig(
        name=os.path.basename(os.path.normpath(model_path)) or hf.get("model_type", "hf-model"),
        vocab_size=int(hf["vocab_size"]),
        hidden_size=hidden,
        intermediate_size=int(hf["intermediate_size"]),
        num_layers=int(hf["num_hidden_layers"]),
        num_heads=heads,
        num_kv_heads=int(hf.get("num_key_value_heads") or heads),
        head_dim=head_dim,
        rope_theta=float(hf.get("rope_theta", 10000.0)),
        rms_norm_eps=float(hf.get("rms_norm_eps", 1e-5)),
        max_position=int(hf.get("max_position_embeddings", 8192)),
        tie_embeddings=bool(hf.get("tie_word_embeddings", False)),
        attn_bias=attn_bias,
        dtype=str(hf.get("torch_dtype", "bfloat16")).replace("torch.", ""),
    )


def _safetensor_files(model_path: str) -> list[str]:
    index = os.path.join(model_path, "model.safetensors.index.json")
    if os.path.exists(index):
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        return sorted({os.path.join(model_path, v) for v in weight_map.values()})
    single = os.path.join(model_path, "model.safetensors")
    if os.path.exists(single):
        return [single]
    found = sorted(
        os.path.join(model_path, f)
        for f in os.listdir(model_path)
        if f.endswith(".safetensors")
    )
    if not found:
        raise FileNotFoundError(f"no *.safetensors under {model_path}")
    return found


def _read_all_tensors(model_path: str) -> dict[str, np.ndarray]:
    """Read every tensor as numpy (bf16 arrives as ml_dtypes.bfloat16)."""
    from safetensors import safe_open

    out: dict[str, np.ndarray] = {}
    for path in _safetensor_files(model_path):
        with safe_open(path, framework="np") as f:  # type: ignore[arg-type]
            for name in f.keys():
                out[name] = f.get_tensor(name)
    return out


def load_params(
    model_path: str,
    cfg: ModelConfig,
    dtype: Any = None,
    sharding=None,  # dynamo_tpu.parallel.ModelSharding | None
    quant: str = "none",
):
    """safetensors → the engine params pytree, on device.

    Stacks per-layer tensors into the [L, ...] leaves model.py scans over,
    converts to ``dtype`` (default: serving bf16), and places with the
    engine's sharding rules when given (single jax.device_put per leaf —
    XLA shards on transfer, no full-replica staging on any one chip)."""
    import jax
    import jax.numpy as jnp

    dtype = jnp.dtype(dtype or jnp.bfloat16)
    raw = _read_all_tensors(model_path)

    def take(name: str) -> np.ndarray:
        try:
            return raw.pop(name)
        except KeyError:
            raise KeyError(f"checkpoint {model_path} missing tensor {name!r}") from None

    def stack(fmt: str, transpose: bool) -> np.ndarray:
        per = [take(fmt.format(i=i)) for i in range(cfg.num_layers)]
        arr = np.stack([p.T if transpose else p for p in per])
        return arr

    L = "model.layers.{i}"
    params: dict[str, Any] = {
        "embed": take("model.embed_tokens.weight"),
        "layers": {
            "wq": stack(f"{L}.self_attn.q_proj.weight", True),
            "wk": stack(f"{L}.self_attn.k_proj.weight", True),
            "wv": stack(f"{L}.self_attn.v_proj.weight", True),
            "wo": stack(f"{L}.self_attn.o_proj.weight", True),
            "w_gate": stack(f"{L}.mlp.gate_proj.weight", True),
            "w_up": stack(f"{L}.mlp.up_proj.weight", True),
            "w_down": stack(f"{L}.mlp.down_proj.weight", True),
            "attn_norm": stack(f"{L}.input_layernorm.weight", False),
            "mlp_norm": stack(f"{L}.post_attention_layernorm.weight", False),
        },
        "final_norm": take("model.norm.weight"),
    }
    if cfg.attn_bias:
        params["layers"]["bq"] = stack(f"{L}.self_attn.q_proj.bias", False)
        params["layers"]["bk"] = stack(f"{L}.self_attn.k_proj.bias", False)
        params["layers"]["bv"] = stack(f"{L}.self_attn.v_proj.bias", False)
    if not cfg.tie_embeddings:
        params["lm_head"] = take("lm_head.weight").T
    else:
        raw.pop("lm_head.weight", None)  # some tied checkpoints still store it
    leftovers = [k for k in raw if not k.endswith("rotary_emb.inv_freq")]
    biases = [k for k in leftovers if k.endswith(".bias")]
    if biases:
        # Same policy as the GGUF loader: silently dropping projection
        # biases serves wrong logits with no diagnostic.
        raise NotImplementedError(
            f"checkpoint has {len(biases)} unsupported bias tensors (e.g. "
            f"{biases[0]}) — only QKV bias (attn_bias architectures) is wired"
        )
    if leftovers:
        log.warning("ignoring %d unexpected tensors (e.g. %s)", len(leftovers), leftovers[:3])

    # Shape validation before any device transfer.
    expect = {
        "embed": (cfg.vocab_size, cfg.hidden_size),
        ("layers", "wq"): (cfg.num_layers, cfg.hidden_size, cfg.q_size),
        ("layers", "wk"): (cfg.num_layers, cfg.hidden_size, cfg.kv_size),
        ("layers", "wv"): (cfg.num_layers, cfg.hidden_size, cfg.kv_size),
        ("layers", "wo"): (cfg.num_layers, cfg.q_size, cfg.hidden_size),
        ("layers", "w_gate"): (cfg.num_layers, cfg.hidden_size, cfg.intermediate_size),
        ("layers", "w_up"): (cfg.num_layers, cfg.hidden_size, cfg.intermediate_size),
        ("layers", "w_down"): (cfg.num_layers, cfg.intermediate_size, cfg.hidden_size),
    }
    for key, shape in expect.items():
        leaf = params[key] if isinstance(key, str) else params[key[0]][key[1]]
        if tuple(leaf.shape) != shape:
            raise ValueError(f"{key}: checkpoint shape {tuple(leaf.shape)} != expected {shape}")

    return finalize_params(params, dtype=dtype, sharding=sharding, quant=quant)


def finalize_params(params: dict, dtype: Any = None, sharding=None, quant: str = "none"):
    """Shared checkpoint tail (safetensors + GGUF): optional host-side
    int8 quantization, serving-dtype conversion, sharded device placement.

    Quantization happens HOST-side, pre-placement: an 8B bf16 staging
    copy on device is exactly the OOM int8 exists to avoid."""
    import jax
    import jax.numpy as jnp

    dtype = jnp.dtype(dtype or jnp.bfloat16)
    if quant == "int8":
        from dynamo_tpu.engine.quant import quantize_params_np

        params = quantize_params_np(params)

    def place(leaf: np.ndarray, shard) -> Any:
        # int8 weights keep their dtype; everything else converts to the
        # serving dtype (scales included: bf16 scales are plenty).
        host = leaf if leaf.dtype == np.int8 else (
            leaf.astype(dtype) if leaf.dtype != dtype else leaf
        )
        if shard is not None:
            return jax.device_put(host, shard)
        return jnp.asarray(host)

    if sharding is not None:
        shardings = sharding.param_shardings(params)
        return jax.tree.map(place, params, shardings)
    return jax.tree.map(lambda x: place(x, None), params)


def load_config(name_or_path: str) -> ModelConfig:
    """Config only (no weights): local HF dir, .gguf file, or hub name
    (reference: local_model.rs config resolution)."""
    from dynamo_tpu.engine.hub import is_gguf, resolve_model

    path = resolve_model(name_or_path)
    if is_gguf(path):
        from dynamo_tpu.engine.gguf import GGUFFile

        return GGUFFile(path).model_config()
    return config_from_hf(path)


def load_model(name_or_path: str, dtype: Any = None, sharding=None, quant: str = "none"):
    """→ (ModelConfig, params). Accepts a local HF checkpoint directory,
    a .gguf file, or an `org/repo` hub name (resolved through the HF hub
    cache / downloaded when a downloader is available — engine/hub.py;
    reference: hub.rs:126, gguf/)."""
    from dynamo_tpu.engine.hub import is_gguf, resolve_model

    model_path = resolve_model(name_or_path)
    if is_gguf(model_path):
        from dynamo_tpu.engine.gguf import load_gguf_model

        return load_gguf_model(model_path, dtype=dtype, sharding=sharding, quant=quant)
    cfg = config_from_hf(model_path)
    params = load_params(model_path, cfg, dtype=dtype, sharding=sharding, quant=quant)
    n = cfg.param_count()
    log.info("loaded %s: %.2fB params from %s", cfg.name, n / 1e9, model_path)
    return cfg, params
