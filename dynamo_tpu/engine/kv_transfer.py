"""KV page movement: device↔host extraction/injection of paged-cache
blocks, and the host-side wire format.

This is the TPU-native v0 of the reference's NIXL KV data plane
(reference: lib/llm/src/block_manager/storage/nixl.rs, docs/architecture/
kvbm_architecture.md:30-44). GPUs move KV with RDMA; on TPU the
equivalents are host DMA (device_get / device_put) for HBM↔host and the
runtime's TCP response plane for host↔host. The same primitives back
both disaggregated prefill→decode handoff and the G2 host offload tier.

Layout: pages travel as ``[L, n, bs, KVH*hd]`` pairs (k, v) — a pure
slice of the cache's native layout, so extract/inject are single
gather/scatter ops XLA fuses well. ``n`` is bucketed pow2 (block id 0 is
the garbage sink, so padding injects harmlessly).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.model import KVCache


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


@functools.partial(jax.jit, static_argnums=())
def _extract_impl(k: jax.Array, v: jax.Array, ids: jax.Array):
    return k[:, ids], v[:, ids]  # [L, n, bs, KVH*hd]


_extract_replicated_jits: dict = {}


def _extract_replicated(k, v, ids, sharding):
    """Extract with fully-replicated outputs: on a multi-host mesh every
    process must be able to np.asarray the result (a KVH-sharded gather
    would leave shards non-addressable)."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = sharding.mesh
    fn = _extract_replicated_jits.get(id(mesh))
    if fn is None:
        rep = NamedSharding(mesh, PartitionSpec())
        fn = jax.jit(lambda k, v, i: (k[:, i], v[:, i]), out_shardings=(rep, rep))
        _extract_replicated_jits[id(mesh)] = fn
    return fn(k, v, ids)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _inject_impl(k: jax.Array, v: jax.Array, ids: jax.Array, pk: jax.Array, pv: jax.Array):
    return k.at[:, ids].set(pk), v.at[:, ids].set(pv)


def extract_pages(
    cache: KVCache, block_ids: list[int], replicate=None
) -> tuple[np.ndarray, np.ndarray]:
    """Copy the named blocks to host → (k_pages, v_pages), each
    [L, n, bs, KVH*hd] numpy. Must run before the cache is donated to a
    later step (i.e. on the engine thread, synchronously). Pass the
    ModelSharding as ``replicate`` on a sharded cache so the gather
    all-gathers to every host."""
    n = len(block_ids)
    nb = _bucket(n)
    ids = np.zeros((nb,), np.int32)
    ids[:n] = block_ids
    if replicate is not None:
        pk, pv = _extract_replicated(cache.k, cache.v, jnp.asarray(ids), replicate)
    else:
        pk, pv = _extract_impl(cache.k, cache.v, jnp.asarray(ids))
    return np.asarray(pk[:, :n]), np.asarray(pv[:, :n])


def inject_pages(cache: KVCache, block_ids: list[int], pk: np.ndarray, pv: np.ndarray) -> KVCache:
    """Write host pages into the named blocks (donates the cache)."""
    n = len(block_ids)
    assert pk.shape[1] == n and pv.shape[1] == n, "page count mismatch"
    nb = _bucket(n)
    ids = np.zeros((nb,), np.int32)  # pad → block 0 (garbage sink)
    ids[:n] = block_ids
    if nb != n:
        pad = [(0, 0), (0, nb - n)] + [(0, 0)] * (pk.ndim - 2)
        pk = np.pad(pk, pad)
        pv = np.pad(pv, pad)
    dtype = cache.k.dtype
    k, v = _inject_impl(
        cache.k, cache.v, jnp.asarray(ids),
        jnp.asarray(pk, dtype), jnp.asarray(pv, dtype),
    )
    return KVCache(k, v)


# ---------------------------------------------------------------------------
# Wire format (msgpack-safe dicts with raw bytes)
# ---------------------------------------------------------------------------


@dataclass
class KvPagePayload:
    """Host KV pages + metadata, serializable over the response plane."""

    k: np.ndarray  # [L, n, bs, KVH*hd]
    v: np.ndarray
    num_tokens: int  # prompt positions covered by these pages

    def to_dict(self) -> dict:
        # bf16 numpy (ml_dtypes) round-trips via uint16 view.
        k, v = self.k, self.v
        kind = str(k.dtype)
        if kind == "bfloat16":
            k, v = k.view(np.uint16), v.view(np.uint16)
        return {
            "k": k.tobytes(),
            "v": v.tobytes(),
            "shape": list(self.k.shape),
            "dtype": kind,
            "num_tokens": self.num_tokens,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "KvPagePayload":
        import ml_dtypes

        shape = tuple(d["shape"])
        kind = d["dtype"]
        if kind == "bfloat16":
            k = np.frombuffer(d["k"], np.uint16).reshape(shape).view(ml_dtypes.bfloat16)
            v = np.frombuffer(d["v"], np.uint16).reshape(shape).view(ml_dtypes.bfloat16)
        else:
            k = np.frombuffer(d["k"], np.dtype(kind)).reshape(shape)
            v = np.frombuffer(d["v"], np.dtype(kind)).reshape(shape)
        return cls(k=k, v=v, num_tokens=int(d["num_tokens"]))

    # -- chunked streaming --------------------------------------------------
    #
    # A 70B-geometry 2k-token export is ~640 MB — far beyond the framing
    # cap (runtime/framing.py MAX_FRAME) and big enough to stall an event
    # loop if serialized at once. Streams of <=max_bytes frames keep the
    # response plane responsive (reference analogue: NIXL moves KV in
    # block-granular RDMA ops, not one giant message).

    DEFAULT_FRAME_BYTES = 16 << 20

    def to_frames(self, max_bytes: int = DEFAULT_FRAME_BYTES):
        """Yield wire frames: one header, then <=max_bytes data chunks."""
        k, v = self.k, self.v
        kind = str(k.dtype)
        if kind == "bfloat16":
            k, v = k.view(np.uint16), v.view(np.uint16)
        kb, vb = k.tobytes(), v.tobytes()
        yield {
            "kind": "kv_header",
            "shape": list(self.k.shape),
            "dtype": kind,
            "num_tokens": self.num_tokens,
            "k_bytes": len(kb),
            "v_bytes": len(vb),
        }
        for name, buf in (("k", kb), ("v", vb)):
            for off in range(0, len(buf), max_bytes):
                yield {"kind": name, "data": buf[off : off + max_bytes]}

    @classmethod
    def from_frames(cls, frames: list[dict]) -> "KvPagePayload":
        header = frames[0]
        if header.get("kind") != "kv_header":
            raise ValueError("first frame is not a kv_header")
        kb = b"".join(f["data"] for f in frames[1:] if f["kind"] == "k")
        vb = b"".join(f["data"] for f in frames[1:] if f["kind"] == "v")
        if len(kb) != header["k_bytes"] or len(vb) != header["v_bytes"]:
            raise ValueError(
                f"truncated kv stream: k {len(kb)}/{header['k_bytes']} "
                f"v {len(vb)}/{header['v_bytes']}"
            )
        return cls.from_dict({
            "k": kb, "v": vb, "shape": header["shape"],
            "dtype": header["dtype"], "num_tokens": header["num_tokens"],
        })
