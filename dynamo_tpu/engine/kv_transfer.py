"""KV page movement: device↔host extraction/injection of paged-cache
blocks, and the host-side wire format.

This is the TPU-native v0 of the reference's NIXL KV data plane
(reference: lib/llm/src/block_manager/storage/nixl.rs, docs/architecture/
kvbm_architecture.md:30-44). GPUs move KV with RDMA; on TPU the
equivalents are host DMA (device_get / device_put) for HBM↔host and the
runtime's TCP response plane for host↔host. The same primitives back
both disaggregated prefill→decode handoff and the G2 host offload tier.

Layout: pages travel as ``[L, n, bs, KVH*hd]`` pairs (k, v) — a pure
slice of the cache's native layout, so extract/inject are single
gather/scatter ops XLA fuses well. ``n`` is bucketed pow2 (block id 0 is
the garbage sink, so padding injects harmlessly).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from dynamo_tpu.engine.model import KVCache


def _bucket(n: int) -> int:
    b = 1
    while b < n:
        b *= 2
    return b


@jax.jit
def _extract_impl(arrs: tuple, ids: jax.Array):
    return tuple(a[:, ids] for a in arrs)  # each [L, n, bs, ...]


_extract_replicated_jits: dict = {}


def _extract_replicated(arrs: tuple, ids, sharding):
    """Extract with fully-replicated outputs: on a multi-host mesh every
    process must be able to np.asarray the result (a KVH-sharded gather
    would leave shards non-addressable)."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = sharding.mesh
    key = (id(mesh), len(arrs))
    fn = _extract_replicated_jits.get(key)
    if fn is None:
        rep = NamedSharding(mesh, PartitionSpec())
        fn = jax.jit(
            lambda xs, i: tuple(a[:, i] for a in xs),
            out_shardings=tuple(rep for _ in arrs),
        )
        _extract_replicated_jits[key] = fn
    return fn(arrs, ids)


@functools.partial(jax.jit, donate_argnums=(0,))
def _inject_impl(arrs: tuple, ids: jax.Array, pages: tuple):
    return tuple(a.at[:, ids].set(p) for a, p in zip(arrs, pages))


def _cache_arrays(cache: KVCache) -> tuple:
    """The cache's page-parallel arrays in wire order: (k, v) or
    (k, v, k_scale, v_scale) for int8 storage. Every tier/transfer hop
    moves this tuple — int8 pages ship at half the bf16 bytes plus a
    ~3% scale sidecar."""
    if cache.k_scale is not None:
        return (cache.k, cache.v, cache.k_scale, cache.v_scale)
    return (cache.k, cache.v)


def start_extract(cache: KVCache, block_ids: list[int], replicate=None) -> tuple:
    """Dispatch the page gather WITHOUT syncing → (device arrays, each
    [L, n_bucket, bs, ...], true block count n). The gather is enqueued
    on the device stream BEFORE any later donating dispatch, so it reads
    the pre-donation values; the caller harvests with ``finish_extract``
    once ``host_ready`` (engine/runner.py) reports the async D2H copy
    done. This is what lets the streaming KV exporter overlap page
    copies with the remaining prefill chunks."""
    n = len(block_ids)
    nb = _bucket(n)
    ids = np.zeros((nb,), np.int32)
    ids[:n] = block_ids
    arrs = _cache_arrays(cache)
    if replicate is not None:
        out = _extract_replicated(arrs, jnp.asarray(ids), replicate)
    else:
        out = _extract_impl(arrs, jnp.asarray(ids))
    return out, n


def finish_extract(device_pages: tuple, n: int) -> tuple:
    """Sync a ``start_extract`` result → host numpy pages [L, n, ...]."""
    return tuple(np.asarray(p[:, :n]) for p in device_pages)


def extract_pages(cache: KVCache, block_ids: list[int], replicate=None) -> tuple:
    """Copy the named blocks to host → (k, v) numpy pages, each
    [L, n, bs, KVH*hd] — plus (k_scale, v_scale) [L, n, bs, KVH] when the
    cache stores int8. Must run before the cache is donated to a later
    step (i.e. on the engine thread, synchronously). Pass the
    ModelSharding as ``replicate`` on a sharded cache so the gather
    all-gathers to every host."""
    out, n = start_extract(cache, block_ids, replicate)
    return finish_extract(out, n)


def inject_pages(cache: KVCache, block_ids: list[int], *pages) -> KVCache:
    """Write host pages into the named blocks (donates the cache).
    ``pages`` is the tuple ``extract_pages`` produced: (k, v) or
    (k, v, k_scale, v_scale); the arity must match the cache's storage
    format (adapt_pages converts foreign payloads first)."""
    arrs = _cache_arrays(cache)
    if len(pages) != len(arrs):
        raise ValueError(
            f"page payload arity {len(pages)} does not match cache storage "
            f"({'int8' if cache.k_scale is not None else 'dense'}); "
            f"adapt_pages() the payload first"
        )
    n = len(block_ids)
    assert all(p.shape[1] == n for p in pages), "page count mismatch"
    nb = _bucket(n)
    ids = np.zeros((nb,), np.int32)  # pad → block 0 (garbage sink)
    ids[:n] = block_ids
    if nb != n:
        pages = tuple(
            np.pad(p, [(0, 0), (0, nb - n)] + [(0, 0)] * (p.ndim - 2))
            for p in pages
        )
    dev = tuple(
        jnp.asarray(p, a.dtype) for p, a in zip(pages, arrs)
    )
    out = _inject_impl(arrs, jnp.asarray(ids), dev)
    if len(out) == 4:
        return KVCache(*out)
    return KVCache(out[0], out[1])


def delta_blocks(kv_written: int, block_size: int, cursor: int, n_blocks: int) -> tuple[int, int]:
    """→ ``(lo, hi)`` — the full-block delta a live migration still has to
    ship: blocks ``[cursor, hi)`` where ``hi`` counts only positions whose
    KV is actually written (``kv_written``), clamped to the allocated
    block list. Shared by the engine's migration pump and its cutover
    delta pass so the cursor arithmetic is single-sourced: the source
    keeps decoding while chunks stream, and each pump call extracts
    exactly the blocks sealed since the previous cursor."""
    hi = min(kv_written // block_size, n_blocks)
    return cursor, max(hi, cursor)


def quantize_pages_np(k: np.ndarray, v: np.ndarray, num_kv_heads: int):
    """Host-side int8 quantization of float pages [L, n, bs, KVH*hd] →
    (k int8, v int8, k_scale f32 [L, n, bs, KVH], v_scale f32). Same
    absmax scheme (and the same round-half-even) as model.kv_quantize,
    so a page quantized on the host matches one quantized on device —
    heterogeneous fleets (float prefill worker → int8 decode worker)
    stay consistent."""
    def one(x):
        L, n, bs, D = x.shape
        hd = D // num_kv_heads
        xf = np.asarray(x, np.float32).reshape(L, n, bs, num_kv_heads, hd)
        absmax = np.max(np.abs(xf), axis=-1)
        scale = np.where(absmax > 0, absmax, 127.0) / 127.0
        q = np.clip(np.rint(xf / scale[..., None]), -127, 127).astype(np.int8)
        return q.reshape(L, n, bs, D), scale.astype(np.float32)

    kq, ks = one(k)
    vq, vs = one(v)
    return kq, vq, ks, vs


def dequantize_pages_np(k, v, k_scale, v_scale, num_kv_heads: int, dtype):
    """Inverse adapter: int8 pages + scales → float pages in ``dtype``."""
    def one(q, s):
        L, n, bs, D = q.shape
        hd = D // num_kv_heads
        x = q.reshape(L, n, bs, num_kv_heads, hd).astype(np.float32) * s[..., None]
        return x.reshape(L, n, bs, D).astype(dtype)

    return one(k, k_scale), one(v, v_scale)


def _dense_dtype(name):
    """Numpy dtype for a dense-page dtype name (bf16 via ml_dtypes)."""
    if str(name) == "bfloat16":
        import ml_dtypes

        return ml_dtypes.bfloat16
    return np.dtype(str(name))


def adapt_pages(pages: tuple, cache: KVCache, num_kv_heads: int) -> tuple:
    """Convert a page tuple to the cache's storage format: quantize
    float payloads for an int8 cache, dequantize int8 payloads for a
    float cache, pass matching formats through untouched."""
    quant_payload = len(pages) == 4
    quant_cache = cache.k_scale is not None
    if quant_payload == quant_cache:
        return pages
    if quant_cache:
        return quantize_pages_np(pages[0], pages[1], num_kv_heads)
    return dequantize_pages_np(
        *pages, num_kv_heads=num_kv_heads, dtype=_dense_dtype(cache.k.dtype)
    )


def concat_page_run(
    run: list, *, quantized: bool, num_kv_heads: int, dtype
) -> tuple:
    """Concatenate a tier run's per-block page tuples into ONE batched
    payload in the requested storage format: (k, v) when ``quantized`` is
    False, (k, v, k_scale, v_scale) when True. A persistent disk tier can
    hold blocks written under a different ``kv_quant`` setting than this
    process (a dense-era ``--disk-kv-dir`` reused by an int8 worker, or
    vice versa), so a single leading run may MIX arities — each block is
    bridged to the engine's current format first, after which inject /
    adapt_pages see one uniform tuple. ``dtype`` is the dense page dtype
    (name or numpy dtype) used when dequantizing foreign int8 blocks."""
    want = 4 if quantized else 2
    norm = []
    for blk in run:
        if len(blk) == want:
            norm.append(blk)
        elif quantized:
            norm.append(quantize_pages_np(blk[0], blk[1], num_kv_heads))
        else:
            norm.append(dequantize_pages_np(
                *blk, num_kv_heads=num_kv_heads, dtype=_dense_dtype(dtype)
            ))
    return tuple(
        np.concatenate([blk[i] for blk in norm], axis=1) for i in range(want)
    )


def split_page_run(pages: tuple, n_blocks: int) -> list[tuple]:
    """Inverse of :func:`concat_page_run`: slice one batched page tuple
    ([L, n_blocks, bs, …] on every array) back into per-block tuples
    ([L, 1, bs, …]) for individual tier puts — the drain-on-retire
    receiver stores each adopted block under its own hash."""
    return [
        tuple(np.ascontiguousarray(p[:, i : i + 1]) for p in pages)
        for i in range(n_blocks)
    ]


# ---------------------------------------------------------------------------
# Wire format (msgpack-safe dicts with raw bytes)
# ---------------------------------------------------------------------------


@dataclass
class KvPagePayload:
    """Host KV pages + metadata, serializable over the response plane.
    int8 pages carry fp32 scale sidecars (``k_scale``/``v_scale``,
    [L, n, bs, KVH]) — the disagg/peer wire then moves roughly HALF the
    bf16 bytes per block."""

    k: np.ndarray  # [L, n, bs, KVH*hd]
    v: np.ndarray
    num_tokens: int  # prompt positions covered by these pages
    k_scale: np.ndarray | None = None  # [L, n, bs, KVH] fp32 — int8 pages only
    v_scale: np.ndarray | None = None

    def pages(self) -> tuple:
        """The page tuple in engine wire order (kv_transfer inject/
        adapt_pages arity): (k, v) or (k, v, k_scale, v_scale)."""
        if self.k_scale is not None:
            return (self.k, self.v, self.k_scale, self.v_scale)
        return (self.k, self.v)

    @classmethod
    def from_pages(cls, pages: tuple, num_tokens: int) -> "KvPagePayload":
        """Inverse of ``pages()``: wrap an extract_pages/concat_page_run
        tuple — (k, v) or (k, v, k_scale, v_scale) — in a payload."""
        ks, vs = (pages[2], pages[3]) if len(pages) == 4 else (None, None)
        return cls(k=pages[0], v=pages[1], num_tokens=num_tokens,
                   k_scale=ks, v_scale=vs)

    def to_dict(self) -> dict:
        # bf16 numpy (ml_dtypes) round-trips via uint16 view.
        k, v = self.k, self.v
        kind = str(k.dtype)
        if kind == "bfloat16":
            k, v = k.view(np.uint16), v.view(np.uint16)
        out = {
            "k": k.tobytes(),
            "v": v.tobytes(),
            "shape": list(self.k.shape),
            "dtype": kind,
            "num_tokens": self.num_tokens,
        }
        if self.k_scale is not None:
            out["k_scale"] = np.ascontiguousarray(self.k_scale).tobytes()
            out["v_scale"] = np.ascontiguousarray(self.v_scale).tobytes()
            out["scale_shape"] = list(self.k_scale.shape)
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "KvPagePayload":
        import ml_dtypes

        shape = tuple(d["shape"])
        kind = d["dtype"]
        if kind == "bfloat16":
            k = np.frombuffer(d["k"], np.uint16).reshape(shape).view(ml_dtypes.bfloat16)
            v = np.frombuffer(d["v"], np.uint16).reshape(shape).view(ml_dtypes.bfloat16)
        else:
            k = np.frombuffer(d["k"], np.dtype(kind)).reshape(shape)
            v = np.frombuffer(d["v"], np.dtype(kind)).reshape(shape)
        ks = vs = None
        if d.get("k_scale") is not None:
            sshape = tuple(d["scale_shape"])
            ks = np.frombuffer(d["k_scale"], np.float32).reshape(sshape)
            vs = np.frombuffer(d["v_scale"], np.float32).reshape(sshape)
        return cls(k=k, v=v, num_tokens=int(d["num_tokens"]),
                   k_scale=ks, v_scale=vs)

    # -- chunked streaming --------------------------------------------------
    #
    # A 70B-geometry 2k-token export is ~640 MB — far beyond the framing
    # cap (runtime/framing.py MAX_FRAME) and big enough to stall an event
    # loop if serialized at once. Streams of <=max_bytes frames keep the
    # response plane responsive (reference analogue: NIXL moves KV in
    # block-granular RDMA ops, not one giant message).

    DEFAULT_FRAME_BYTES = 16 << 20

    def to_frames(self, max_bytes: int = DEFAULT_FRAME_BYTES):
        """Yield wire frames: one header, then <=max_bytes data chunks.
        Scale sidecars travel as their own small frames after the pages
        (absent for full-precision payloads, so the wire format is
        backward compatible)."""
        k, v = self.k, self.v
        kind = str(k.dtype)
        if kind == "bfloat16":
            k, v = k.view(np.uint16), v.view(np.uint16)
        kb, vb = k.tobytes(), v.tobytes()
        header = {
            "kind": "kv_header",
            "shape": list(self.k.shape),
            "dtype": kind,
            "num_tokens": self.num_tokens,
            "k_bytes": len(kb),
            "v_bytes": len(vb),
        }
        chunks = [("k", kb), ("v", vb)]
        if self.k_scale is not None:
            ksb = np.ascontiguousarray(self.k_scale).tobytes()
            vsb = np.ascontiguousarray(self.v_scale).tobytes()
            header["scale_shape"] = list(self.k_scale.shape)
            header["k_scale_bytes"] = len(ksb)
            header["v_scale_bytes"] = len(vsb)
            chunks += [("k_scale", ksb), ("v_scale", vsb)]
        yield header
        for name, buf in chunks:
            for off in range(0, len(buf), max_bytes):
                yield {"kind": name, "data": buf[off : off + max_bytes]}

    @classmethod
    def from_frames(cls, frames: list[dict]) -> "KvPagePayload":
        header = frames[0]
        if header.get("kind") != "kv_header":
            raise ValueError("first frame is not a kv_header")
        bufs = {
            name: b"".join(f["data"] for f in frames[1:] if f["kind"] == name)
            for name in ("k", "v", "k_scale", "v_scale")
        }
        want = {
            "k": header["k_bytes"], "v": header["v_bytes"],
            "k_scale": header.get("k_scale_bytes", 0),
            "v_scale": header.get("v_scale_bytes", 0),
        }
        for name, n in want.items():
            if len(bufs[name]) != n:
                raise ValueError(
                    f"truncated kv stream: {name} {len(bufs[name])}/{n}"
                )
        d = {
            "k": bufs["k"], "v": bufs["v"], "shape": header["shape"],
            "dtype": header["dtype"], "num_tokens": header["num_tokens"],
        }
        if header.get("scale_shape") is not None:
            d["k_scale"] = bufs["k_scale"]
            d["v_scale"] = bufs["v_scale"]
            d["scale_shape"] = header["scale_shape"]
        return cls.from_dict(d)
