"""On-device batched sampling.

Two jitted variants, chosen host-side per batch (static shapes, no traced
branching):

- ``sample_simple``: greedy / temperature via the Gumbel-max trick — the
  hot path for benchmarks and most traffic; no sort, no penalties.
- ``sample_full``: frequency/presence penalties + exact top-k + top-p
  (nucleus) via a full descending sort. Used only when a batch contains a
  request that asks for any of those.

Per-row PRNG keys: each sequence samples with its own key, derived inside
jit from (row_seed, emission_index) — row_seed is the request's ``seed``
when given (else a per-request random), so seeded requests are
reproducible regardless of batch composition or restarts.

Temperature <= 0 means greedy (argmax) for that row in both variants.

Reference parity: sampling options mapping in the reference preprocessor
(lib/llm/src/preprocessor.rs); execution happens in-engine, as vLLM does
for the reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_GREEDY_EPS = 1e-5
_MASKED = -jnp.inf


def unpack_mask(bits: jax.Array, V: int) -> jax.Array:
    """Packed uint32 bitsets → boolean legality mask: [..., W32] →
    [..., V]. Bit t of the flattened words marks token t legal. The
    packed form is what rides host→device (32x fewer bytes than a bool
    mask; grammar masks are per-(row, verify-slot))."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    b = (bits[..., :, None] >> shifts) & jnp.uint32(1)
    return b.reshape(*bits.shape[:-1], -1)[..., :V] != 0


def apply_mask(logits: jax.Array, mask_bits: jax.Array | None) -> jax.Array:
    """Grammar-mask logits: illegal tokens → -inf, so every downstream
    softmax/argmax/gumbel-max renormalizes over the LEGAL vocabulary —
    masked sampling is exactly the constrained target distribution, and
    masked greedy is the constrained argmax. None = unconstrained
    (byte-identical passthrough; callers dispatch None when no row in
    the batch carries a grammar, so unconstrained traffic never pays a
    where())."""
    if mask_bits is None:
        return logits
    return jnp.where(unpack_mask(mask_bits, logits.shape[-1]), logits, _MASKED)


def _row_gumbel(seeds: jax.Array, steps: jax.Array, V: int) -> jax.Array:
    """Per-row gumbel noise from (seed, emission-index) pairs → [B, V]."""

    def one(s, e):
        key = jax.random.fold_in(jax.random.PRNGKey(s), e)
        return jax.random.gumbel(key, (V,), jnp.float32)

    return jax.vmap(one)(seeds, steps)


@jax.jit
def sample_simple(
    logits: jax.Array,        # [B, V] fp32
    temperature: jax.Array,   # [B] fp32
    seeds: jax.Array,         # [B] uint32 per-row seed
    steps: jax.Array,         # [B] int32 per-row emission index
    mask_bits: jax.Array | None = None,  # [B, W32] uint32 grammar masks
) -> jax.Array:
    logits = apply_mask(logits, mask_bits)
    greedy = temperature < _GREEDY_EPS
    temp = jnp.where(greedy, 1.0, temperature)
    scaled = logits / temp[:, None]
    gumbel = _row_gumbel(seeds, steps, logits.shape[1])
    noisy = jnp.where(greedy[:, None], logits, scaled + gumbel)
    return jnp.argmax(noisy, axis=-1).astype(jnp.int32)


def token_counts(penalty_tokens: jax.Array, V: int) -> jax.Array:
    """[B, L] generated ids (-1 pad) → [B, V] fp32 occurrence counts."""
    B = penalty_tokens.shape[0]
    valid = penalty_tokens >= 0
    safe = jnp.where(valid, penalty_tokens, 0)
    return jnp.zeros((B, V), jnp.float32).at[
        jnp.arange(B)[:, None], safe
    ].add(valid.astype(jnp.float32))


def apply_penalties(
    logits: jax.Array,        # [B, V] fp32
    counts: jax.Array,        # [B, V] fp32 occurrence counts of generated ids
    freq_penalty: jax.Array,  # [B] fp32
    pres_penalty: jax.Array,  # [B] fp32
) -> jax.Array:
    """OpenAI frequency/presence penalties over generated tokens."""
    logits = logits - freq_penalty[:, None] * counts
    return logits - pres_penalty[:, None] * (counts > 0).astype(jnp.float32)


def sample_step(
    logits: jax.Array,       # [B, V] fp32 (penalties already applied)
    temperature: jax.Array,  # [B]
    top_k: jax.Array,        # [B] int32, 0 = off
    top_p: jax.Array,        # [B] fp32, 1.0 = off
    gumbel: jax.Array,       # [B, V] fp32 noise
) -> jax.Array:
    """Exact top-k + top-p (nucleus) + temperature + gumbel-max. The core
    shared by the standalone full sampler and the fused decode loop."""
    greedy = temperature < _GREEDY_EPS
    temp = jnp.where(greedy, 1.0, temperature)
    scaled = logits / temp[:, None]

    V = logits.shape[1]
    svals, sidx = jax.lax.top_k(scaled, V)  # descending sort
    ranks = jnp.arange(V, dtype=jnp.int32)[None, :]
    k = jnp.where(top_k <= 0, V, top_k)[:, None]
    keep_k = ranks < k
    probs = jax.nn.softmax(svals, axis=-1)
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    # nucleus: keep tokens whose preceding cumulative mass < top_p
    keep_p = cum_before < top_p[:, None]
    keep = keep_k & keep_p
    keep = keep.at[:, 0].set(True)  # never mask the argmax
    masked = jnp.where(keep, svals, -jnp.inf)

    noise = jnp.take_along_axis(gumbel, sidx, axis=-1)
    pick = jnp.argmax(jnp.where(greedy[:, None], masked, masked + noise), axis=-1)
    return jnp.take_along_axis(sidx, pick[:, None], axis=-1)[:, 0].astype(jnp.int32)


@jax.jit
def sample_full(
    logits: jax.Array,         # [B, V] fp32
    temperature: jax.Array,    # [B]
    top_k: jax.Array,          # [B] int32, 0 = off
    top_p: jax.Array,          # [B] fp32, 1.0 = off
    penalty_tokens: jax.Array,  # [B, L] int32 previously generated ids, -1 pad
    freq_penalty: jax.Array,   # [B] fp32
    pres_penalty: jax.Array,   # [B] fp32
    seeds: jax.Array,          # [B] uint32
    steps: jax.Array,          # [B] int32
    mask_bits: jax.Array | None = None,  # [B, W32] uint32 grammar masks
) -> jax.Array:
    logits = apply_mask(logits, mask_bits)
    V = logits.shape[1]
    counts = token_counts(penalty_tokens, V)
    logits = apply_penalties(logits, counts, freq_penalty, pres_penalty)
    gumbel = _row_gumbel(seeds, steps, V)
    return sample_step(logits, temperature, top_k, top_p, gumbel)


@jax.jit
def token_logprobs(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Log-probability of each row's chosen token: [B, V], [B] → [B]."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    chosen = jnp.take_along_axis(logits, tokens[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return chosen - logz


@functools.partial(jax.jit, static_argnums=(1,))
def top_k_logprobs(logits: jax.Array, n: int) -> tuple[jax.Array, jax.Array]:
    """Top-n alternative logprobs of the RAW model distribution (OpenAI
    top_logprobs reports pre-sampler probabilities): [B, V] →
    (logprobs [B, n], token ids [B, n]), most likely first."""
    logz = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    vals, ids = jax.lax.top_k(logits, n)
    return vals - logz, ids


def _spec_uniform(seeds: jax.Array, steps: jax.Array) -> jax.Array:
    """Per-(row, draft-position) accept uniforms for speculative
    rejection sampling: key = fold(fold(PRNGKey(seed), step), 1). The
    extra tag fold keeps the stream disjoint from the dense path's
    (seed, step) gumbel stream and from the residual gumbels below."""

    def one(s, e):
        key = jax.random.fold_in(jax.random.fold_in(jax.random.PRNGKey(s), e), 1)
        return jax.random.uniform(key, (), jnp.float32, minval=1e-12, maxval=1.0)

    return jax.vmap(jax.vmap(one, in_axes=(None, 0)), in_axes=(0, 0))(seeds, steps)


def _spec_gumbel(seeds: jax.Array, steps: jax.Array, dense_stream: jax.Array,
                 V: int) -> jax.Array:
    """Per-(row, position) gumbel noise for residual/bonus samples.
    Rows with ``dense_stream`` True draw from the dense path's exact
    (seed, step) key — a row that proposed NO draft then samples its one
    token byte-identically to ``sample_simple`` (speculation is a true
    no-op for it); drafted rows use a tag-folded key so their residual
    draws stay disjoint from every dense draw."""

    def one(s, e, dense):
        base = jax.random.fold_in(jax.random.PRNGKey(s), e)
        tagged = jax.random.fold_in(base, 2)
        key = jnp.where(dense, base, tagged)
        return jax.random.gumbel(key, (V,), jnp.float32)

    return jax.vmap(
        jax.vmap(one, in_axes=(None, 0, None)), in_axes=(0, 0, 0)
    )(seeds, steps, dense_stream)


def spec_acceptance(
    logits: jax.Array,       # [B, S1, V] fp32 — raw verify-pass logits
    drafts: jax.Array,       # [B, S] int32 — proposed draft tokens
    draft_len: jax.Array,    # [B] int32 — per-row true draft length (≤ S)
    temperature: jax.Array,  # [B] fp32 (simple mode; <= 0 → greedy row)
    seeds: jax.Array,        # [B] uint32 per-row sample seed
    steps0: jax.Array,       # [B] int32 emission index of the pass's first token
    mode: str,               # static — "greedy" | "simple"
) -> tuple[jax.Array, jax.Array]:
    """Speculative acceptance over one verify pass → (out [B, S1] int32,
    n_emit [B] int32). Position j's logits score the token that FOLLOWS
    input j, so draft j+1 is checked against position j; the first
    rejected position (or the bonus position S when everything is
    accepted) emits a corrected/bonus token instead. ``out[:, :n_emit]``
    is the emitted run — accepted drafts then exactly one correction.

    - "greedy": accept on exact argmax match; emitted tokens are the
      argmax chain, byte-identical to the dense greedy path (no RNG).
    - "simple": Leviathan-style rejection sampling against the point-mass
      n-gram draft: accept draft d with probability p(d) (one uniform per
      position); on rejection sample from the residual p restricted to
      tokens != d (gumbel-argmax with d masked), which for a point-mass
      proposal leaves the target distribution exactly unchanged. Greedy
      rows inside a simple batch reduce to the argmax rule."""
    B, S1, V = logits.shape
    S = S1 - 1
    jidx = jnp.arange(S, dtype=jnp.int32)[None, :]           # [1, S]
    cand_greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S1]
    if mode == "greedy":
        accept = (drafts == cand_greedy[:, :-1]) & (jidx < draft_len[:, None])
        out = cand_greedy
    else:
        greedy = temperature < _GREEDY_EPS
        temp = jnp.where(greedy, 1.0, temperature)
        scaled = logits / temp[:, None, None]
        logz = jax.nn.logsumexp(scaled, axis=-1)             # [B, S1]
        d_lp = (
            jnp.take_along_axis(scaled[:, :-1], drafts[:, :, None], axis=-1)[..., 0]
            - logz[:, :-1]
        )                                                    # [B, S]
        steps = steps0[:, None] + jnp.arange(S1, dtype=jnp.int32)[None, :]
        u = _spec_uniform(seeds, steps[:, :-1])                    # [B, S]
        accept = jnp.where(
            greedy[:, None],
            drafts == cand_greedy[:, :-1],
            jnp.log(u) < d_lp,
        ) & (jidx < draft_len[:, None])
        # Residual candidates: gumbel-argmax with the rejected draft
        # masked out — at TRUE proposal positions only (j < draft_len);
        # the bonus position (all drafts accepted, or no draft at all)
        # samples the unmasked target distribution. Greedy rows take the
        # raw argmax (their residual IS the argmax — a greedy rejection
        # means draft != argmax).
        gumbel = _spec_gumbel(seeds, steps, draft_len == 0, V)     # [B, S1, V]
        noisy = scaled + gumbel
        mask = jnp.zeros((B, S1, V), bool).at[
            jnp.arange(B)[:, None], jidx, drafts
        ].set(True)
        mask = mask & (
            jnp.arange(S1, dtype=jnp.int32)[None, :] < draft_len[:, None]
        )[..., None]
        cand_sampled = jnp.argmax(
            jnp.where(mask, -jnp.inf, noisy), axis=-1
        ).astype(jnp.int32)
        cand = jnp.where(greedy[:, None], cand_greedy, cand_sampled)
        # Accepted positions emit the draft itself; the first rejection /
        # bonus position emits the candidate.
        a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
        out = jnp.where(
            jnp.arange(S1, dtype=jnp.int32)[None, :] < a[:, None],
            jnp.pad(drafts, ((0, 0), (0, 1))), cand,
        )
        return out, a + 1
    a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)  # [B]
    return out, a + 1


def _tree_uniform(seeds: jax.Array, steps0: jax.Array, S1: int) -> jax.Array:
    """Per-(row, tree-node) accept uniforms for multi-path rejection
    sampling: key = fold(fold(fold(PRNGKey(seed), steps0), node), 3).
    Keyed by NODE SLOT (not depth): sibling rounds at one parent need
    independent draws. Tag 3 keeps the stream disjoint from the dense
    gumbels, the linear-spec uniforms (tag 1) and residuals (tag 2)."""

    def one(s, e0, j):
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.fold_in(jax.random.PRNGKey(s), e0), j), 3
        )
        return jax.random.uniform(key, (), jnp.float32, minval=1e-12, maxval=1.0)

    nodes = jnp.arange(S1, dtype=jnp.int32)
    return jax.vmap(
        jax.vmap(one, in_axes=(None, None, 0)), in_axes=(0, 0, None)
    )(seeds, steps0, nodes)


def _tree_gumbel(seeds: jax.Array, steps0: jax.Array, dense_stream: jax.Array,
                 S1: int, V: int) -> jax.Array:
    """Per-(row, tree-node) gumbel noise for correction/bonus samples at
    the traversal's stopping node. Rows with ``dense_stream`` True (no
    draft at all) draw node 0 from the dense path's exact (seed, step)
    key — speculation is then a true no-op for them; every other draw is
    tag-folded (4) so it stays disjoint from all dense draws."""

    def one(s, e0, j, dense):
        base = jax.random.fold_in(jax.random.PRNGKey(s), e0)
        tagged = jax.random.fold_in(jax.random.fold_in(base, j), 4)
        key = jnp.where(dense & (j == 0), base, tagged)
        return jax.random.gumbel(key, (V,), jnp.float32)

    nodes = jnp.arange(S1, dtype=jnp.int32)
    return jax.vmap(
        jax.vmap(one, in_axes=(None, None, 0, None)),
        in_axes=(0, 0, None, 0),
    )(seeds, steps0, nodes, dense_stream)


def spec_tree_acceptance(
    logits: jax.Array,       # [B, S1, V] fp32 — verify logits per tree node
    tokens: jax.Array,       # [B, S1] int32 — node input tokens (slot 0 = root)
    parents: jax.Array,      # [B, S1] int32 — parent NODE index (< own index; 0 for root)
    draft_len: jax.Array,    # [B] int32 — live draft nodes (tree size - 1)
    temperature: jax.Array,  # [B] fp32 (<= 0 → greedy row)
    seeds: jax.Array,        # [B] uint32 per-row sample seed
    steps0: jax.Array,       # [B] int32 emission index of the pass's first token
    mode: str,               # static — "greedy" | "simple"
    mask_bits: jax.Array | None = None,  # [B, S1, W32] uint32 per-NODE grammar masks
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Multi-path (SpecInfer-style) acceptance over one TREE verify pass
    → (out [B, S1], n_emit [B], path [B, S1], cand [B, S1]).

    Traversal starts at the root and walks accepted edges: at each node
    its live children are tried in slot order and the walk descends into
    the first accepted one; when none accepts (or the node is a leaf)
    the node emits one final corrected/bonus token and the walk stops.
    ``out[:, :n_emit]`` is the emitted run, ``path[k]`` the node whose
    logits emitted ``out[k]`` (clamped to the stopping node past the
    end — the KV-compaction gather and logprob reads stay in-bounds).

    - "greedy": edge (v → j) accepts iff ``tokens[j] == argmax(p_v)``;
      the emitted run IS the argmax chain, byte-identical to dense
      greedy for any tree shape (a linear chain reduces to
      ``spec_acceptance``'s rule exactly).
    - "simple": multi-round rejection sampling per node — child i (slot
      order) accepts with probability p_v(x_i) / (1 - Σ_{j<i} p_v(x_j)),
      the point-mass multi-draft residual schedule; after k rejections
      the stopping node samples the residual with all tried children
      masked (gumbel-argmax), which leaves the target distribution
      exactly unchanged. Sibling tokens must be DISTINCT (the drafters
      guarantee it); width-1 trees reduce to Leviathan acceptance.
      Greedy rows inside a simple batch use the argmax rule.

    **Grammar masks** (``mask_bits`` given): node j's packed bitset
    constrains the distribution AT node j (the one its children are
    checked against and its correction/bonus token samples from) — the
    mask of the FSM state reached after consuming node j's token,
    threaded host-side alongside parents/anc/depth. Illegal logits go to
    -inf BEFORE any of the math above, so acceptance probabilities use
    the masked-RENORMALIZED target p(x)/Z_mask, residuals renormalize
    over the masked vocabulary, and greedy rows take the constrained
    argmax chain — constrained sampled streams are exactly the
    constrained target distribution, constrained greedy is byte-stable
    against the masked-dense path. All-ones rows pass through
    numerically unchanged (where() with an all-true mask is identity)."""
    B, S1, V = logits.shape
    logits = apply_mask(logits, mask_bits)
    node = jnp.arange(S1, dtype=jnp.int32)
    live = (node[None, :] <= draft_len[:, None]) & (node[None, :] >= 1)  # edges
    cand = jnp.argmax(logits, axis=-1).astype(jnp.int32)                 # [B, S1]
    cand_par = jnp.take_along_axis(cand, parents, axis=1)                # cand[parent[j]]
    acc_greedy = (tokens == cand_par) & live
    if mode == "greedy":
        acc = acc_greedy
        final_per_node = cand
    else:
        greedy = temperature < _GREEDY_EPS
        temp = jnp.where(greedy, 1.0, temperature)
        scaled = logits / temp[:, None, None]
        logz = jax.nn.logsumexp(scaled, axis=-1)                         # [B, S1]
        bidx = jnp.arange(B)[:, None]
        # p of node j's token under its PARENT's distribution.
        ptok = jnp.exp(scaled[bidx, parents, tokens] - logz[bidx, parents])
        ptok = jnp.where(live, ptok, 0.0)                                # [B, S1]
        # Earlier-sibling mass: Σ p_v(x_j') over live siblings j' < j.
        sib = (
            (parents[:, :, None] == parents[:, None, :])
            & (node[None, None, :] < node[None, :, None])
            & live[:, None, :]
        )                                                                # [B, j, j']
        prevmass = jnp.einsum("bjk,bk->bj", sib.astype(jnp.float32), ptok)
        Z = 1.0 - prevmass
        u = _tree_uniform(seeds, steps0, S1)
        acc_samp = live & (Z > 0.0) & (u * Z < ptok)
        acc = jnp.where(greedy[:, None], acc_greedy, acc_samp)
        # Final corrected/bonus token per candidate stopping node v:
        # gumbel-argmax of the scaled logits with v's live children
        # masked out. At a leaf the mask is empty (pure bonus sample);
        # after k rejections it is exactly the k-round residual.
        contrib = jnp.zeros((B, S1, V), jnp.float32).at[
            bidx, parents, tokens
        ].add(live.astype(jnp.float32))
        child_mask = contrib > 0.0
        gumbel = _tree_gumbel(seeds, steps0, draft_len == 0, S1, V)
        noisy = jnp.where(child_mask, -jnp.inf, scaled + gumbel)
        final_sampled = jnp.argmax(noisy, axis=-1).astype(jnp.int32)
        final_per_node = jnp.where(greedy[:, None], cand, final_sampled)
    # First accepted child per node (slot order = sibling try order;
    # acc[j] already conditions on every earlier sibling rejecting).
    childmat = (parents[:, None, :] == node[None, :, None]) & acc[:, None, :]
    chosen = jnp.min(
        jnp.where(childmat, node[None, None, :], S1), axis=2
    ).astype(jnp.int32)                                                  # [B, S1]

    def walk(cur, _):
        nxt = jnp.take_along_axis(chosen, cur[:, None], axis=1)[:, 0]
        ok = nxt < S1
        new = jnp.where(ok, nxt, cur)
        return new, (new, ok)

    _, (steps_nodes, oks) = lax.scan(
        walk, jnp.zeros((B,), jnp.int32), None, length=S1 - 1
    )
    path = jnp.concatenate(
        [jnp.zeros((B, 1), jnp.int32), jnp.transpose(steps_nodes)], axis=1
    )                                                                    # [B, S1]
    a = jnp.sum(oks.astype(jnp.int32), axis=0)                           # [B]
    # out[k < a] = token of the accepted depth-(k+1) node; out[a] = the
    # stopping node's final sample; beyond n_emit the values are junk.
    child_at = jnp.concatenate([path[:, 1:], path[:, -1:]], axis=1)
    tok_child = jnp.take_along_axis(tokens, child_at, axis=1)
    final = jnp.take_along_axis(
        final_per_node, jnp.take_along_axis(path, a[:, None], axis=1), axis=1
    )                                                                    # [B, 1]
    out = jnp.where(node[None, :] < a[:, None], tok_child, final)
    return out, a + 1, path, cand


def row_needs_full(top_k, top_p, freq_penalty, pres_penalty) -> bool:
    """Does one request's sampling config require the full sampler? The
    single source of truth for the simple/full split."""
    return bool(
        (top_k and top_k > 0)
        or (top_p is not None and top_p < 1.0)
        or freq_penalty
        or pres_penalty
    )


def needs_full(top_ks, top_ps, freqs, press) -> bool:
    """Host-side variant choice for a batch."""
    return any(
        row_needs_full(k, p, f, pr) for k, p, f, pr in zip(top_ks, top_ps, freqs, press)
    )
