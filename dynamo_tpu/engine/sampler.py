"""On-device batched sampling.

Two jitted variants, chosen host-side per batch (static shapes, no traced
branching):

- ``sample_simple``: greedy / temperature via the Gumbel-max trick — the
  hot path for benchmarks and most traffic; no sort, no penalties.
- ``sample_full``: frequency/presence penalties + exact top-k + top-p
  (nucleus) via a full descending sort. Used only when a batch contains a
  request that asks for any of those.

Per-row PRNG keys: each sequence samples with its own key, derived inside
jit from (row_seed, emission_index) — row_seed is the request's ``seed``
when given (else a per-request random), so seeded requests are
reproducible regardless of batch composition or restarts.

Temperature <= 0 means greedy (argmax) for that row in both variants.

Reference parity: sampling options mapping in the reference preprocessor
(lib/llm/src/preprocessor.rs); execution happens in-engine, as vLLM does
for the reference.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_GREEDY_EPS = 1e-5


def _row_gumbel(seeds: jax.Array, steps: jax.Array, V: int) -> jax.Array:
    """Per-row gumbel noise from (seed, emission-index) pairs → [B, V]."""

    def one(s, e):
        key = jax.random.fold_in(jax.random.PRNGKey(s), e)
        return jax.random.gumbel(key, (V,), jnp.float32)

    return jax.vmap(one)(seeds, steps)


@jax.jit
def sample_simple(
    logits: jax.Array,        # [B, V] fp32
    temperature: jax.Array,   # [B] fp32
    seeds: jax.Array,         # [B] uint32 per-row seed
    steps: jax.Array,         # [B] int32 per-row emission index
) -> jax.Array:
    greedy = temperature < _GREEDY_EPS
    temp = jnp.where(greedy, 1.0, temperature)
    scaled = logits / temp[:, None]
    gumbel = _row_gumbel(seeds, steps, logits.shape[1])
    noisy = jnp.where(greedy[:, None], logits, scaled + gumbel)
    return jnp.argmax(noisy, axis=-1).astype(jnp.int32)


def token_counts(penalty_tokens: jax.Array, V: int) -> jax.Array:
    """[B, L] generated ids (-1 pad) → [B, V] fp32 occurrence counts."""
    B = penalty_tokens.shape[0]
    valid = penalty_tokens >= 0
    safe = jnp.where(valid, penalty_tokens, 0)
    return jnp.zeros((B, V), jnp.float32).at[
        jnp.arange(B)[:, None], safe
    ].add(valid.astype(jnp.float32))


def apply_penalties(
    logits: jax.Array,        # [B, V] fp32
    counts: jax.Array,        # [B, V] fp32 occurrence counts of generated ids
    freq_penalty: jax.Array,  # [B] fp32
    pres_penalty: jax.Array,  # [B] fp32
) -> jax.Array:
    """OpenAI frequency/presence penalties over generated tokens."""
    logits = logits - freq_penalty[:, None] * counts
    return logits - pres_penalty[:, None] * (counts > 0).astype(jnp.float32)


def sample_step(
    logits: jax.Array,       # [B, V] fp32 (penalties already applied)
    temperature: jax.Array,  # [B]
    top_k: jax.Array,        # [B] int32, 0 = off
    top_p: jax.Array,        # [B] fp32, 1.0 = off
    gumbel: jax.Array,       # [B, V] fp32 noise
) -> jax.Array:
    """Exact top-k + top-p (nucleus) + temperature + gumbel-max. The core
    shared by the standalone full sampler and the fused decode loop."""
    greedy = temperature < _GREEDY_EPS
    temp = jnp.where(greedy, 1.0, temperature)
    scaled = logits / temp[:, None]

    V = logits.shape[1]
    svals, sidx = jax.lax.top_k(scaled, V)  # descending sort
    ranks = jnp.arange(V, dtype=jnp.int32)[None, :]
    k = jnp.where(top_k <= 0, V, top_k)[:, None]
    keep_k = ranks < k
    probs = jax.nn.softmax(svals, axis=-1)
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    # nucleus: keep tokens whose preceding cumulative mass < top_p
    keep_p = cum_before < top_p[:, None]
    keep = keep_k & keep_p
    keep = keep.at[:, 0].set(True)  # never mask the argmax
    masked = jnp.where(keep, svals, -jnp.inf)

    noise = jnp.take_along_axis(gumbel, sidx, axis=-1)
    pick = jnp.argmax(jnp.where(greedy[:, None], masked, masked + noise), axis=-1)
    return jnp.take_along_axis(sidx, pick[:, None], axis=-1)[:, 0].astype(jnp.int32)


@jax.jit
def sample_full(
    logits: jax.Array,         # [B, V] fp32
    temperature: jax.Array,    # [B]
    top_k: jax.Array,          # [B] int32, 0 = off
    top_p: jax.Array,          # [B] fp32, 1.0 = off
    penalty_tokens: jax.Array,  # [B, L] int32 previously generated ids, -1 pad
    freq_penalty: jax.Array,   # [B] fp32
    pres_penalty: jax.Array,   # [B] fp32
    seeds: jax.Array,          # [B] uint32
    steps: jax.Array,          # [B] int32
) -> jax.Array:
    V = logits.shape[1]
    counts = token_counts(penalty_tokens, V)
    logits = apply_penalties(logits, counts, freq_penalty, pres_penalty)
    gumbel = _row_gumbel(seeds, steps, V)
    return sample_step(logits, temperature, top_k, top_p, gumbel)


@jax.jit
def token_logprobs(logits: jax.Array, tokens: jax.Array) -> jax.Array:
    """Log-probability of each row's chosen token: [B, V], [B] → [B]."""
    logz = jax.nn.logsumexp(logits, axis=-1)
    chosen = jnp.take_along_axis(logits, tokens[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return chosen - logz


@functools.partial(jax.jit, static_argnums=(1,))
def top_k_logprobs(logits: jax.Array, n: int) -> tuple[jax.Array, jax.Array]:
    """Top-n alternative logprobs of the RAW model distribution (OpenAI
    top_logprobs reports pre-sampler probabilities): [B, V] →
    (logprobs [B, n], token ids [B, n]), most likely first."""
    logz = jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    vals, ids = jax.lax.top_k(logits, n)
    return vals - logz, ids


def row_needs_full(top_k, top_p, freq_penalty, pres_penalty) -> bool:
    """Does one request's sampling config require the full sampler? The
    single source of truth for the simple/full split."""
    return bool(
        (top_k and top_k > 0)
        or (top_p is not None and top_p < 1.0)
        or freq_penalty
        or pres_penalty
    )


def needs_full(top_ks, top_ps, freqs, press) -> bool:
    """Host-side variant choice for a batch."""
    return any(
        row_needs_full(k, p, f, pr) for k, p, f, pr in zip(top_ks, top_ps, freqs, press)
    )
