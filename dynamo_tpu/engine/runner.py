"""ModelRunner: every device dispatch the engine makes, behind one seam.

Single-process serving uses ``LocalRunner`` directly (zero overhead).
Multi-host serving mirrors the JAX SPMD model: ONE logical worker spans H
processes (one per host), every process must issue the SAME jitted calls
on the SAME global mesh, and only process 0 (the leader) looks at
results. The leader's engine drives a ``LeaderRunner`` that broadcasts a
compact descriptor of each dispatch over TCP before executing it locally;
follower processes run ``follower_loop`` which replays the descriptors
against their own ``LocalRunner``. Host inputs are small (tokens, tables,
sampling knobs), so the step stream is cheap; results chain on-device
(windows reference the previous window's output by id, never by value).

Reference analogue: the role the NCCL/MPI launch scripts play for
multi-node engines (reference: components/backends/sglang/slurm_jobs/
submit_job_script.py, components/backends/vllm/launch/dsr1_dep.sh:86-105)
— but TPU-native: jax.distributed + a mirrored dispatch stream instead of
torchrun per-rank processes.

Failure model: a dead follower stalls the collectives; the leader's lease
expires and the cluster routes around the whole worker (same blast radius
as a dead NCCL rank in the reference).
"""

from __future__ import annotations

import functools
import socket
import struct
from collections import OrderedDict
from typing import Any

import msgpack
import numpy as np

import jax
import jax.numpy as jnp

from dynamo_tpu.engine import kv_transfer
from dynamo_tpu.engine import model as M
from dynamo_tpu.engine.config import EngineArgs
from dynamo_tpu.engine.sampler import (
    sample_full,
    sample_simple,
    token_logprobs,
)
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("runner")

_RETAIN = 128  # refs kept for chaining/sampling (identical on all hosts)


@jax.jit
def _fold_tokens(last_toks, toks, slots):
    """Scatter sampled tokens into the persistent per-slot buffer (one
    tiny compiled variant per batch bucket). ``slots`` names each row's
    stable sequence slot; padding rows point at the dummy tail slot."""
    return last_toks.at[slots].set(toks)


@functools.partial(jax.jit, donate_argnums=(0,))
def _bank_write(bank_arr, update, slot):
    """Write one adapter's factor array into bank slot ``slot`` (traced
    scalar — ONE compile per array shape, not per slot; the bank is
    donated so the update is in-place)."""
    return jax.lax.dynamic_update_slice_in_dim(
        bank_arr, update[:, None], slot, axis=1
    )


class StepRef:
    """Opaque handle to a dispatch's device-side results."""

    __slots__ = ("rid", "arrs")

    def __init__(self, rid: int, arrs: tuple):
        self.rid = rid
        self.arrs = arrs


def start_host_fetch(arrs) -> None:
    """Begin async device→host transfers for a dispatch's result arrays.
    Called by the engine at dispatch time so the D2H copy rides the
    tunnel while the device executes subsequent work; the later
    ``np.asarray`` then completes from the host-side buffer instead of
    paying a full blocking roundtrip. No-op for host-resident arrays."""
    for a in arrs:
        fn = getattr(a, "copy_to_host_async", None)
        if fn is not None:
            fn()


def host_ready(arrs) -> bool:
    """True when every array's device computation (and any started host
    copy) has completed — fetching now will not block the caller on
    device work. Arrays without ``is_ready`` (numpy) are always ready."""
    for a in arrs:
        fn = getattr(a, "is_ready", None)
        if fn is not None and not fn():
            return False
    return True


def _pack_np(a: np.ndarray) -> dict:
    a = np.ascontiguousarray(a)
    return {"b": a.tobytes(), "d": str(a.dtype), "s": list(a.shape)}


def _unpack_np(d: dict) -> np.ndarray:
    return np.frombuffer(d["b"], np.dtype(d["d"])).reshape(d["s"])


class LocalRunner:
    """Owns device state (params, KV cache, sharding) and executes
    dispatches. Thread-affinity: engine/scheduler thread only."""

    def __init__(self, args: EngineArgs, params: Any | None = None,
                 seed: int = 0, sharding=None):
        self.args = args
        self.cfg = args.model
        self._seed = seed
        self.sharding = sharding
        self.params = params
        self.cache: M.KVCache | None = None
        self.attn_impl = "xla"
        self._rid = 0
        self._refs: OrderedDict[int, StepRef] = OrderedDict()
        # Per-SLOT latest sampled token [max_num_seqs + 1], kept on
        # device: decode windows chain their input from it (no host
        # sync), and it is fed by both window folds and admission-time
        # first-token samples (async admission — the engine keeps
        # dispatching while first tokens are still in flight). The extra
        # tail slot is the scatter sink for padding rows.
        self._last_toks: jax.Array | None = None
        # Multi-LoRA adapter bank (engine/lora.py): per-target A/B factor
        # stacks [L, lora_slots, ...] in HBM. Dispatches whose batch has
        # at least one adapter row pass (bank, adapter_slots) into the
        # jitted impls; base-only batches pass None and trace the exact
        # pre-LoRA variant. None when lora_slots == 0.
        self.lora_bank: dict[str, jax.Array] | None = None

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self.args.quant == "int8" and self.params is None:
            if self.sharding is None and self.args.tp == 1:
                from dynamo_tpu.engine.quant import random_int8_params_device

                # Generated ON device: int8 from birth AND zero weight
                # upload (an 8 GB host→device push through the axon
                # tunnel costs ~5 minutes at the measured ~25 MB/s).
                self.params = random_int8_params_device(
                    self.cfg, self._seed, self.args.dtype
                )
            else:
                from dynamo_tpu.engine.quant import random_int8_params

                # Multi-device init stays host-side so each process
                # materializes identical addressable shards.
                self.params = random_int8_params(self.cfg, self._seed, self.args.dtype)
        elif self.args.quant == "int8" and not any(
            leaf.dtype == jnp.int8 for leaf in jax.tree.leaves(self.params)
        ):
            if isinstance(jax.tree.leaves(self.params)[0], np.ndarray):
                from dynamo_tpu.engine.quant import quantize_params_np

                self.params = quantize_params_np(self.params)
            else:
                # Device-resident float params: the loader should have
                # quantized host-side (load_model(quant="int8")); pulling
                # them back would defeat the memory savings.
                raise ValueError(
                    "quant='int8' with unquantized device params — pass "
                    "quant='int8' to load_model/load_params instead"
                )
        if self.params is None:
            key = jax.random.PRNGKey(self._seed)
            self.params = M.init_params(self.cfg, key, jnp.dtype(self.args.dtype))
        self.cache = M.init_kv_cache(
            self.cfg, self.args.num_kv_blocks, self.args.block_size,
            jnp.dtype(self.args.dtype), kv_quant=self.args.kv_quant,
        )
        if self.sharding is None and self.args.tp > 1:
            from dynamo_tpu.parallel.mesh import ModelSharding, build_mesh

            self.sharding = ModelSharding(build_mesh(tp=self.args.tp, cfg=self.cfg), self.cfg)
        if self.sharding is not None:
            self.params = self.sharding.shard_params(self.params)
            # Scale arrays shard over the same kv-head axis as the cache
            # lanes, so the (mesh-forced) XLA attention paths dequantize
            # with co-sharded scales — int8 KV composes with tp.
            self.cache = M.KVCache(*self.sharding.shard_cache(self.cache))
        elif isinstance(jax.tree.leaves(self.params)[0], np.ndarray):
            self.params = jax.tree.map(jnp.asarray, self.params)
        from dynamo_tpu.ops.paged_attention import resolve_attn_impl

        # Pallas only single-device (pallas_call is opaque to GSPMD).
        self.attn_impl = (
            "xla" if self.sharding is not None
            else resolve_attn_impl(self.args.attn_impl)
        )
        if self.args.lora_slots > 0:
            from dynamo_tpu.engine.lora import bank_shapes

            dt = jnp.dtype(self.args.dtype)
            # Replicated under tp (GSPMD reshards the skinny deltas);
            # zero-initialized — a slot is garbage until its first
            # upload, and the engine never dispatches a row pointing at
            # an unuploaded slot.
            self.lora_bank = {
                k: jnp.zeros(shape, dt)
                for k, shape in bank_shapes(
                    self.cfg, self.args.lora_slots, self.args.lora_rank
                ).items()
            }

    def stop(self) -> None:
        self._refs.clear()

    # -- ref bookkeeping (must stay deterministic across hosts) -----------

    def _new_ref(self, arrs: tuple, rid: int | None = None) -> StepRef:
        if rid is None:
            rid = self._rid
        self._rid = rid + 1
        ref = StepRef(rid, arrs)
        self._refs[rid] = ref
        while len(self._refs) > _RETAIN:
            self._refs.popitem(last=False)
        return ref

    def ref_by_id(self, rid: int) -> StepRef:
        return self._refs[rid]

    # -- dispatches -------------------------------------------------------

    def _lora_operands(self, adapter_slots):
        """(bank, slots-array) for a dispatch, or (None, None) for the
        exact base-variant trace."""
        if adapter_slots is None:
            return None, None
        if self.lora_bank is None:
            raise ValueError("adapter_slots passed but lora_slots == 0")
        return self.lora_bank, jnp.asarray(adapter_slots, jnp.int32)

    def upload_adapter(self, slot: int, pages) -> None:
        """Scatter one adapter's packed factor pages (engine/lora.py
        LORA_PAGE_KEYS order) into bank slot ``slot``. Device-stream
        ordering makes this safe while windows are in flight: the upload
        is dispatched AFTER them, so already-issued work reads the old
        occupant."""
        from dynamo_tpu.engine.lora import LORA_PAGE_KEYS

        assert self.lora_bank is not None, "lora_slots == 0"
        for key, arr in zip(LORA_PAGE_KEYS, pages):
            bank = self.lora_bank[key]
            self.lora_bank[key] = _bank_write(
                bank, jnp.asarray(arr, bank.dtype), jnp.int32(slot)
            )

    def prefill_batch(self, toks, tables, starts, tlens, adapter_slots=None,
                      *, rid=None) -> StepRef:
        bank, slots = self._lora_operands(adapter_slots)
        logits, self.cache = M.prefill_batch(
            self.cfg, self.params, self.cache,
            jnp.asarray(toks), jnp.asarray(tables),
            jnp.asarray(starts), jnp.asarray(tlens),
            bank, slots,
        )
        return self._new_ref((logits,), rid)

    def prefill_chunk(self, toks, table, pos, tlen, adapter_slot=None,
                      *, rid=None) -> StepRef:
        bank = slot = None
        if adapter_slot is not None and adapter_slot >= 0:
            bank, slot = self.lora_bank, jnp.int32(adapter_slot)
        logits, self.cache = M.prefill(
            self.cfg, self.params, self.cache,
            jnp.asarray(toks), jnp.asarray(table),
            jnp.int32(pos), jnp.int32(tlen),
            bank, slot,
        )
        return self._new_ref((logits,), rid)

    def _ensure_last_toks(self) -> None:
        if self._last_toks is None:
            self._last_toks = jnp.zeros((self.args.max_num_seqs + 1,), jnp.int32)

    def multi_decode(self, K, mode, tokens, chain, positions, tables, active,
                     temps, seeds, steps0, tks, tps, freqs, press, pen,
                     fold_slots=None, top_n=0, adapter_slots=None,
                     *, rid=None) -> StepRef:
        """chain: None | (dst rows, src slots) — rows of this window whose
        input token is the latest on-device sample for that sequence SLOT
        (previous window fold or admission first-token fold; no host
        sync). Shapes stay fixed per batch bucket: chaining is expressed
        as a [B] mask + slot map inside the jit. ``fold_slots`` [B] names
        each row's slot so the window's final tokens land back in the
        buffer (padding rows → dummy tail slot). ``top_n`` (static) adds
        ranked alternative logprobs to the ref. ``adapter_slots`` = None
        (base variant) or [B] int32 per-row LoRA bank slots (-1 = base
        row) — the bank rides the dispatch as one more operand."""
        B = len(tokens)
        self._ensure_last_toks()
        mask = np.zeros((B,), bool)
        srcmap = np.zeros((B,), np.int32)
        if chain is not None:
            dst, src = chain
            mask[np.asarray(dst, np.int64)] = True
            srcmap[np.asarray(dst, np.int64)] = src
        bank, aslots = self._lora_operands(adapter_slots)
        toks_d, logps_d, tvals_d, tids_d, self.cache = M.multi_decode(
            self.cfg, K, mode, int(top_n), self.params, self.cache,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(tables), jnp.asarray(active),
            jnp.asarray(temps), jnp.asarray(seeds), jnp.asarray(steps0),
            jnp.asarray(tks), jnp.asarray(tps),
            jnp.asarray(freqs), jnp.asarray(press), jnp.asarray(pen),
            jnp.asarray(mask), jnp.asarray(srcmap), self._last_toks,
            bank, aslots,
            attn_impl=self.attn_impl,
        )
        if fold_slots is None:
            fold_slots = np.full((B,), self.args.max_num_seqs, np.int32)
        self._last_toks = _fold_tokens(
            self._last_toks, toks_d[-1], jnp.asarray(fold_slots, jnp.int32)
        )
        return self._new_ref((toks_d, logps_d, tvals_d, tids_d), rid)

    def decode_step(self, tokens, positions, tables, active,
                    adapter_slots=None, *, rid=None) -> StepRef:
        bank, aslots = self._lora_operands(adapter_slots)
        logits, self.cache = M.decode_step(
            self.cfg, self.params, self.cache,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(tables), jnp.asarray(active),
            bank, aslots,
            attn_impl=self.attn_impl,
        )
        return self._new_ref((logits,), rid)

    def spec_verify(self, S1, mode, tokens, positions0, draft_len, tables,
                    active, temps, seeds, steps0, fold_slots=None, top_n=0,
                    tree=None, masks=None, adapter_slots=None,
                    *, rid=None) -> StepRef:
        """One speculative verify pass: a single forward over ``S1``
        positions per row (one weight stream) with on-device acceptance.
        ``tree`` = None for a linear draft, or (parents [B, S1],
        anc [B, S1, S1], depth [B, S1]) numpy arrays for a SpecInfer
        token tree — the topology mask rides the same fused gather and
        the accepted root path is compacted on device. ``masks`` = None
        or [B, S1, W32] uint32 packed per-node grammar bitsets (tree
        dispatches only — a constrained batch always upgrades to the
        tree op); acceptance then renormalizes over each node's legal
        vocabulary. The pass's FINAL emitted token folds into the
        per-slot chain buffer like a window's last sample. Ref arrays:
        (out [B, S1], n_emit [B], logps [B, S1], cand [B, S1],
        top_vals, top_ids)."""
        self._ensure_last_toks()
        tp = ta = td = None
        if tree is not None:
            parents, anc, depth = tree
            tp = jnp.asarray(parents, jnp.int32)
            ta = jnp.asarray(anc, jnp.int8)
            td = jnp.asarray(depth, jnp.int32)
        mb = None if masks is None else jnp.asarray(masks, jnp.uint32)
        bank, aslots = self._lora_operands(adapter_slots)
        out, n_emit, logps, cand, tvals, tids, last_tok, self.cache = M.spec_verify(
            self.cfg, int(S1), mode, int(top_n), self.params, self.cache,
            jnp.asarray(tokens), jnp.asarray(positions0),
            jnp.asarray(draft_len), jnp.asarray(tables), jnp.asarray(active),
            jnp.asarray(temps), jnp.asarray(seeds), jnp.asarray(steps0),
            tp, ta, td, mb, bank, aslots,
            fused=self.args.spec_fused, attn_impl=self.attn_impl,
        )
        if fold_slots is None:
            fold_slots = np.full((len(tokens),), self.args.max_num_seqs, np.int32)
        self._last_toks = _fold_tokens(
            self._last_toks, last_tok, jnp.asarray(fold_slots, jnp.int32)
        )
        return self._new_ref((out, n_emit, logps, cand, tvals, tids), rid)

    def stack_rows(self, srcs) -> jax.Array:
        """srcs: list of (StepRef-or-rid, row|None); row None → arr is [V]."""
        rows = []
        for ref, row in srcs:
            if not isinstance(ref, StepRef):
                ref = self.ref_by_id(ref)
            arr = ref.arrs[0]
            rows.append(arr if row is None else arr[row])
        return jnp.stack(rows)

    def sample_rows(self, srcs, temps, tks, tps, pen, freqs, press, seeds,
                    steps, full: bool, fold_slots=None, top_n: int = 0,
                    masks=None):
        """→ (tokens [B], logprobs [B], top_ref|None) as device arrays
        (leader fetches). With ``fold_slots``, the sampled tokens also
        land in the per-slot chain buffer so the next decode window can
        consume them without a host sync (async admission). ``top_n``
        adds ranked alternatives computed from the SAME stacked logits
        (one gather, one logsumexp — not a second pass). ``masks`` =
        None or [B, W32] packed grammar bitsets — the dense-row masked
        sampling path (admission first tokens + single-step decode)."""
        from dynamo_tpu.engine.sampler import top_k_logprobs

        logits = self.stack_rows(srcs)
        mb = None if masks is None else jnp.asarray(masks, jnp.uint32)
        if full:
            out = sample_full(
                logits, jnp.asarray(temps), jnp.asarray(tks), jnp.asarray(tps),
                jnp.asarray(pen), jnp.asarray(freqs), jnp.asarray(press),
                jnp.asarray(seeds), jnp.asarray(steps), mb,
            )
        else:
            out = sample_simple(logits, jnp.asarray(temps), jnp.asarray(seeds),
                                jnp.asarray(steps), mb)
        if fold_slots is not None:
            self._ensure_last_toks()
            self._last_toks = _fold_tokens(
                self._last_toks, out, jnp.asarray(fold_slots, jnp.int32)
            )
        top_ref = None
        if top_n > 0:
            vals, ids = top_k_logprobs(logits, int(top_n))
            top_ref = self._new_ref((vals, ids))
        return out, token_logprobs(logits, out), top_ref

    def embed(self, toks, tlen, *, rid=None) -> StepRef:
        emb = M.embed(self.cfg, self.params, jnp.asarray(toks), jnp.int32(tlen))
        return self._new_ref((emb,), rid)

    def extract_pages(self, block_ids: list[int]) -> tuple:
        """→ (k, v) page arrays, plus (k_scale, v_scale) under int8 KV."""
        return kv_transfer.extract_pages(
            self.cache, block_ids, replicate=self.sharding
        )

    def start_extract_pages(self, block_ids: list[int]) -> tuple:
        """Dispatch a page gather without syncing → (device arrays, n).
        The streaming KV exporter starts the D2H copy on these
        (start_host_fetch) and harvests with ``finish_extract_pages``
        once host_ready — page copies overlap remaining prefill chunks
        instead of blocking the scheduler per chunk."""
        return kv_transfer.start_extract(
            self.cache, block_ids, replicate=self.sharding
        )

    @staticmethod
    def finish_extract_pages(device_pages: tuple, n: int) -> tuple:
        return kv_transfer.finish_extract(device_pages, n)

    def inject_pages(self, block_ids: list[int], *pages) -> None:
        pages = kv_transfer.adapt_pages(pages, self.cache, self.cfg.num_kv_heads)
        self.cache = kv_transfer.inject_pages(self.cache, block_ids, *pages)

    def clear_cache_refs(self) -> None:
        """Drop chain/sample refs (admin /clear_kv_blocks support)."""
        self._refs.clear()


# ---------------------------------------------------------------------------
# Multi-host: leader broadcast + follower replay
# ---------------------------------------------------------------------------


def _send_msg(sock: socket.socket, obj: dict) -> None:
    body = msgpack.packb(obj, use_bin_type=True)
    sock.sendall(struct.pack(">I", len(body)) + body)


def _recv_msg(sock: socket.socket) -> dict | None:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack(">I", hdr)
    body = _recv_exact(sock, n)
    return None if body is None else msgpack.unpackb(body, raw=False)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class LeaderRunner(LocalRunner):
    """LocalRunner that mirrors every dispatch to follower processes.

    ``bind`` accepts ``num_followers`` TCP connections before serving;
    descriptors are pushed in dispatch order (TCP preserves it)."""

    def __init__(self, args, params=None, seed=0, sharding=None,
                 *, listen_addr: str = "0.0.0.0:7411", num_followers: int = 0):
        super().__init__(args, params, seed, sharding)
        self.num_followers = num_followers
        self._listen_addr = listen_addr
        self._socks: list[socket.socket] = []

    def start(self) -> None:
        host, port = self._listen_addr.rsplit(":", 1)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, int(port)))
        srv.listen(self.num_followers)
        log.info("leader waiting for %d followers on %s", self.num_followers, self._listen_addr)
        for _ in range(self.num_followers):
            s, peer = srv.accept()
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks.append(s)
            log.info("follower connected from %s", peer)
        srv.close()
        self._cast({"op": "start"})
        super().start()

    def stop(self) -> None:
        self._cast({"op": "stop"})
        for s in self._socks:
            s.close()
        self._socks.clear()
        super().stop()

    def _cast(self, desc: dict) -> None:
        for s in self._socks:
            _send_msg(s, desc)

    # Each dispatch: broadcast first (followers start immediately), then
    # run locally. rid assignment is deterministic on both sides.

    def prefill_batch(self, toks, tables, starts, tlens, adapter_slots=None,
                      *, rid=None) -> StepRef:
        rid = self._rid
        self._cast({"op": "prefill_batch", "rid": rid,
                    "toks": _pack_np(toks), "tables": _pack_np(tables),
                    "starts": _pack_np(starts), "tlens": _pack_np(tlens),
                    "aslots": None if adapter_slots is None
                    else _pack_np(np.asarray(adapter_slots, np.int32))})
        return super().prefill_batch(toks, tables, starts, tlens,
                                     adapter_slots, rid=rid)

    def prefill_chunk(self, toks, table, pos, tlen, adapter_slot=None,
                      *, rid=None) -> StepRef:
        rid = self._rid
        self._cast({"op": "prefill_chunk", "rid": rid,
                    "toks": _pack_np(toks), "table": _pack_np(table),
                    "pos": int(pos), "tlen": int(tlen),
                    "aslot": None if adapter_slot is None else int(adapter_slot)})
        return super().prefill_chunk(toks, table, pos, tlen, adapter_slot,
                                     rid=rid)

    def upload_adapter(self, slot: int, pages) -> None:
        self._cast({"op": "upload_adapter", "slot": int(slot),
                    "pages": [_pack_np(np.asarray(p)) for p in pages]})
        super().upload_adapter(slot, pages)

    def multi_decode(self, K, mode, tokens, chain, positions, tables, active,
                     temps, seeds, steps0, tks, tps, freqs, press, pen,
                     fold_slots=None, top_n=0, adapter_slots=None,
                     *, rid=None) -> StepRef:
        rid = self._rid
        wire_chain = None
        if chain is not None:
            dst, src = chain
            wire_chain = [list(map(int, dst)), list(map(int, src))]
        self._cast({"op": "multi_decode", "rid": rid, "K": int(K), "mode": mode,
                    "tokens": _pack_np(tokens), "chain": wire_chain,
                    "positions": _pack_np(positions), "tables": _pack_np(tables),
                    "active": _pack_np(active), "temps": _pack_np(temps),
                    "seeds": _pack_np(seeds), "steps0": _pack_np(steps0),
                    "tks": _pack_np(tks), "tps": _pack_np(tps),
                    "freqs": _pack_np(freqs), "press": _pack_np(press),
                    "pen": _pack_np(pen), "top_n": int(top_n),
                    "aslots": None if adapter_slots is None
                    else _pack_np(np.asarray(adapter_slots, np.int32)),
                    "fold": None if fold_slots is None else _pack_np(np.asarray(fold_slots, np.int32))})
        return super().multi_decode(K, mode, tokens, chain, positions, tables,
                                    active, temps, seeds, steps0, tks, tps,
                                    freqs, press, pen, fold_slots, top_n,
                                    adapter_slots, rid=rid)

    def decode_step(self, tokens, positions, tables, active,
                    adapter_slots=None, *, rid=None) -> StepRef:
        rid = self._rid
        self._cast({"op": "decode_step", "rid": rid,
                    "tokens": _pack_np(tokens), "positions": _pack_np(positions),
                    "tables": _pack_np(tables), "active": _pack_np(active),
                    "aslots": None if adapter_slots is None
                    else _pack_np(np.asarray(adapter_slots, np.int32))})
        return super().decode_step(tokens, positions, tables, active,
                                   adapter_slots, rid=rid)

    def spec_verify(self, S1, mode, tokens, positions0, draft_len, tables,
                    active, temps, seeds, steps0, fold_slots=None, top_n=0,
                    tree=None, masks=None, adapter_slots=None,
                    *, rid=None) -> StepRef:
        rid = self._rid
        self._cast({"op": "spec_verify", "rid": rid, "S1": int(S1), "mode": mode,
                    "tokens": _pack_np(tokens), "positions0": _pack_np(positions0),
                    "draft_len": _pack_np(draft_len), "tables": _pack_np(tables),
                    "active": _pack_np(active), "temps": _pack_np(temps),
                    "seeds": _pack_np(seeds), "steps0": _pack_np(steps0),
                    "top_n": int(top_n),
                    "tree": None if tree is None else [
                        _pack_np(np.asarray(a)) for a in tree
                    ],
                    "masks": None if masks is None else _pack_np(
                        np.asarray(masks, np.uint32)
                    ),
                    "aslots": None if adapter_slots is None
                    else _pack_np(np.asarray(adapter_slots, np.int32)),
                    "fold": None if fold_slots is None else _pack_np(np.asarray(fold_slots, np.int32))})
        return super().spec_verify(S1, mode, tokens, positions0, draft_len,
                                   tables, active, temps, seeds, steps0,
                                   fold_slots, top_n, tree, masks,
                                   adapter_slots, rid=rid)

    def sample_rows(self, srcs, temps, tks, tps, pen, freqs, press, seeds,
                    steps, full: bool, fold_slots=None, top_n: int = 0,
                    masks=None):
        wire_srcs = [
            [ref.rid if isinstance(ref, StepRef) else ref,
             None if row is None else int(row)]
            for ref, row in srcs
        ]
        self._cast({"op": "sample_rows", "srcs": wire_srcs,
                    "temps": _pack_np(temps), "tks": _pack_np(tks),
                    "tps": _pack_np(tps), "pen": _pack_np(pen),
                    "freqs": _pack_np(freqs), "press": _pack_np(press),
                    "seeds": _pack_np(seeds), "steps": _pack_np(steps),
                    "full": bool(full), "top_n": int(top_n),
                    "masks": None if masks is None else _pack_np(
                        np.asarray(masks, np.uint32)
                    ),
                    "fold": None if fold_slots is None else _pack_np(np.asarray(fold_slots, np.int32))})
        return super().sample_rows(srcs, temps, tks, tps, pen, freqs, press,
                                   seeds, steps, full, fold_slots, top_n, masks)

    def embed(self, toks, tlen, *, rid=None) -> StepRef:
        rid = self._rid
        self._cast({"op": "embed", "rid": rid, "toks": _pack_np(np.asarray(toks, np.int32)),
                    "tlen": int(tlen)})
        return super().embed(toks, tlen, rid=rid)

    def extract_pages(self, block_ids: list[int]):
        self._cast({"op": "extract_pages", "ids": list(map(int, block_ids))})
        return super().extract_pages(block_ids)

    def start_extract_pages(self, block_ids: list[int]):
        # Followers replay the same gather dispatch (and discard the
        # result) so the SPMD dispatch streams stay aligned.
        self._cast({"op": "start_extract_pages", "ids": list(map(int, block_ids))})
        return super().start_extract_pages(block_ids)

    def inject_pages(self, block_ids: list[int], *pages) -> None:
        def pack(a):
            a = np.asarray(a)
            return _pack_np(a.view(np.uint16) if str(a.dtype) == "bfloat16" else a)

        self._cast({"op": "inject_pages", "ids": list(map(int, block_ids)),
                    "pages": [pack(p) for p in pages],
                    "bf16": str(np.asarray(pages[0]).dtype) == "bfloat16"})
        super().inject_pages(block_ids, *pages)


def follower_loop(args: EngineArgs, leader_addr: str, params=None, seed: int = 0,
                  sharding=None) -> None:
    """Replay the leader's dispatch stream forever (until EOF / stop).

    Every process in the multi-host group must construct the same mesh
    (jax.distributed must already be initialized); this loop performs the
    same jit calls as the leader's engine, keeping the SPMD program
    aligned. Never fetches results."""
    import ml_dtypes

    import time

    host, port = leader_addr.rsplit(":", 1)
    deadline = time.monotonic() + 120.0
    while True:  # leader may still be binding its listener
        try:
            sock = socket.create_connection((host, int(port)), timeout=5.0)
            break
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    runner = LocalRunner(args, params=params, seed=seed, sharding=sharding)
    log.info("follower connected to leader at %s", leader_addr)
    while True:
        desc = _recv_msg(sock)
        if desc is None or desc["op"] == "stop":
            log.info("follower: leader stream closed")
            return
        op = desc["op"]
        if op == "start":
            runner.start()
        elif op == "prefill_batch":
            aslots = desc.get("aslots")
            runner.prefill_batch(
                _unpack_np(desc["toks"]), _unpack_np(desc["tables"]),
                _unpack_np(desc["starts"]), _unpack_np(desc["tlens"]),
                None if aslots is None else _unpack_np(aslots),
                rid=desc["rid"])
        elif op == "prefill_chunk":
            runner.prefill_chunk(
                _unpack_np(desc["toks"]), _unpack_np(desc["table"]),
                desc["pos"], desc["tlen"], desc.get("aslot"), rid=desc["rid"])
        elif op == "upload_adapter":
            runner.upload_adapter(
                desc["slot"], [_unpack_np(p) for p in desc["pages"]])
        elif op == "multi_decode":
            chain = desc["chain"]
            if chain is not None:
                chain = (chain[0], chain[1])
            fold = desc.get("fold")
            aslots = desc.get("aslots")
            runner.multi_decode(
                desc["K"], desc["mode"], _unpack_np(desc["tokens"]), chain,
                _unpack_np(desc["positions"]), _unpack_np(desc["tables"]),
                _unpack_np(desc["active"]), _unpack_np(desc["temps"]),
                _unpack_np(desc["seeds"]), _unpack_np(desc["steps0"]),
                _unpack_np(desc["tks"]), _unpack_np(desc["tps"]),
                _unpack_np(desc["freqs"]), _unpack_np(desc["press"]),
                _unpack_np(desc["pen"]),
                None if fold is None else _unpack_np(fold),
                desc.get("top_n", 0),
                None if aslots is None else _unpack_np(aslots),
                rid=desc["rid"])
        elif op == "decode_step":
            aslots = desc.get("aslots")
            runner.decode_step(
                _unpack_np(desc["tokens"]), _unpack_np(desc["positions"]),
                _unpack_np(desc["tables"]), _unpack_np(desc["active"]),
                None if aslots is None else _unpack_np(aslots),
                rid=desc["rid"])
        elif op == "spec_verify":
            fold = desc.get("fold")
            tree = desc.get("tree")
            wire_masks = desc.get("masks")
            aslots = desc.get("aslots")
            runner.spec_verify(
                desc["S1"], desc["mode"], _unpack_np(desc["tokens"]),
                _unpack_np(desc["positions0"]), _unpack_np(desc["draft_len"]),
                _unpack_np(desc["tables"]), _unpack_np(desc["active"]),
                _unpack_np(desc["temps"]), _unpack_np(desc["seeds"]),
                _unpack_np(desc["steps0"]),
                None if fold is None else _unpack_np(fold),
                desc.get("top_n", 0),
                None if tree is None else tuple(_unpack_np(a) for a in tree),
                None if wire_masks is None else _unpack_np(wire_masks),
                None if aslots is None else _unpack_np(aslots),
                rid=desc["rid"])
        elif op == "sample_rows":
            fold = desc.get("fold")
            wire_masks = desc.get("masks")
            runner.sample_rows(
                [(s[0], s[1]) for s in desc["srcs"]],
                _unpack_np(desc["temps"]), _unpack_np(desc["tks"]),
                _unpack_np(desc["tps"]), _unpack_np(desc["pen"]),
                _unpack_np(desc["freqs"]), _unpack_np(desc["press"]),
                _unpack_np(desc["seeds"]), _unpack_np(desc["steps"]),
                desc["full"], None if fold is None else _unpack_np(fold),
                desc.get("top_n", 0),
                None if wire_masks is None else _unpack_np(wire_masks))
        elif op == "embed":
            runner.embed(_unpack_np(desc["toks"]), desc["tlen"], rid=desc["rid"])
        elif op == "extract_pages":
            runner.extract_pages(desc["ids"])
        elif op == "start_extract_pages":
            runner.start_extract_pages(desc["ids"])
        elif op == "inject_pages":
            pages = [_unpack_np(d) for d in desc["pages"]]
            if desc["bf16"]:
                # Only the k/v pages travel as uint16 views; scale
                # sidecars (if present) are fp32 and pass through.
                pages[0] = pages[0].view(ml_dtypes.bfloat16)
                pages[1] = pages[1].view(ml_dtypes.bfloat16)
            runner.inject_pages(desc["ids"], *pages)
        else:
            raise RuntimeError(f"unknown dispatch op {op!r}")
