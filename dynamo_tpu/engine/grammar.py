"""Grammar-constrained decoding: JSON-schema / regex → token-mask FSM.

The Outlines construction (Willard & Louf, arXiv:2307.09702): compile the
constraint to a character-level (here: BYTE-level) DFA once, then lift it
to a TOKEN-level FSM over the serving vocabulary — for each reachable DFA
state, a token is legal iff walking its bytes through the DFA survives.
Per-sequence decoding state is then a single integer advanced once per
emitted token, and "which tokens are legal next" is an O(1) cached-mask
lookup: exactly the shape the engine needs, because masks are gathered
host-side per verify slot and shipped to the device as packed bitsets
(XGrammar's overlap argument, arXiv:2411.15100 — the mask math is off the
critical path of the forward pass).

Pieces:

- a byte-level regex subset → Thompson NFA → lazily-determinized DFA
  (``_ByteDfa``). The subset covers everything the JSON-schema compiler
  emits plus user ``pattern`` strings: literals, ``.``, ``[...]`` classes
  with ranges/negation, escapes (``\\d \\w \\s`` + punctuation), groups,
  alternation, ``* + ?`` and ``{m}/{m,}/{m,n}`` repetition.
- ``schema_to_regex``: JSON schema → regex. Fixed canonical layout
  (properties in declared order, ``": "`` / ``", "`` separators, no other
  whitespace) — fewer legal choices per state means more FORCED tokens,
  which is what makes constrained drafting near-perfect. Bounded
  recursion depth for nested/untyped values ("json_object" mode is a
  depth-limited any-JSON grammar; JSON nesting is not regular).
- ``TokenFsm``: the token-level lift. Transitions and packed masks are
  computed lazily per reached state and cached — compile cost is paid
  per (schema, state actually visited), not per (schema, full DFA).
- ``GrammarCompiler``: schema-hash-keyed cache of compiled grammars
  (compiled once per distinct ``response_format``, shared across
  requests and sequences; thread-safe — compiles happen off the
  scheduler thread).

Terminal semantics: a state where the byte DFA accepts makes EOS legal
(its mask sets the request's EOS bits); non-terminal states mask EOS, so
a constrained stream can only ever stop on a complete match. A state
with exactly one legal token and no accept is FORCED — the drafter
fast-forwards through forced runs (JSON structure: braces, keys,
separators) without any model signal, because no other continuation can
ever be accepted.

No jax imports here: everything is host-side numpy, usable from the
frontend preprocessor (schema validation) without touching the device
stack.
"""

from __future__ import annotations

import hashlib
import json
import threading

import numpy as np

__all__ = [
    "GrammarError",
    "CompiledGrammar",
    "GrammarCompiler",
    "compile_response_format_regex",
    "schema_to_regex",
    "grammar_vocab",
    "pack_token_ids",
]

# Depth budget for nested / untyped JSON values: regular languages cannot
# count braces, so recursion is expanded to this depth and deeper nesting
# is simply not generable (json_object mode) or rejected (schemas that
# nest beyond it).
DEFAULT_JSON_DEPTH = 4
# Array items generated for schemas without maxItems (regex repetition
# must be bounded somewhere sane; explicit maxItems wins up to this cap).
DEFAULT_MAX_ITEMS = 6
# Unbounded string/number content repetition cap — long enough for real
# payloads, small enough that {m,n} expansion stays out of the picture
# (we compile * on the char class, the cap only applies to explicit
# maxLength handling).
_ANY_BYTE_LO = 0x20


class GrammarError(Exception):
    """Malformed or unsupported constraint spec (schema / regex /
    response_format). Maps to a 400 invalid_request_error at the HTTP
    boundary — typed (DT005) so the serving path never raises bare."""


# ---------------------------------------------------------------------------
# Byte-level regex subset → NFA (Thompson construction)
# ---------------------------------------------------------------------------

_CLASS_ESCAPES = {
    "d": frozenset(range(0x30, 0x3A)),
    "w": frozenset(
        list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
        + list(range(0x61, 0x7B)) + [0x5F]
    ),
    "s": frozenset((0x20, 0x09, 0x0A, 0x0D)),
    "n": frozenset((0x0A,)),
    "t": frozenset((0x09,)),
    "r": frozenset((0x0D,)),
}
# `.` (and the complement universe for negated classes): printable ASCII.
# Free-form non-ASCII would need the DFA to model multi-byte UTF-8
# sequences (else a lone continuation byte is generable and the output
# stops being valid UTF-8); constrained output is ASCII-JSON for now —
# non-ASCII payload still round-trips via \uXXXX escapes, which the
# string grammar accepts.
_DOT = frozenset(range(_ANY_BYTE_LO, 0x7F))


def _escape_set(ch: str) -> frozenset[int]:
    if ch in _CLASS_ESCAPES:
        return _CLASS_ESCAPES[ch]
    if ch in "DWS":
        # Complement over the printable-byte universe (control bytes are
        # never generable — JSON forbids them raw and nothing the schema
        # compiler emits wants them).
        return _DOT - _CLASS_ESCAPES[ch.lower()]
    # Any other ALPHANUMERIC escape (\x, \u, \b, \B, \A, backrefs, ...)
    # is a regex feature this subset does not implement — treating it as
    # a literal would silently compile the WRONG language, so reject it
    # (the frontend turns this into a 400 at validation time).
    if ch.isalnum():
        raise GrammarError(f"unsupported escape \\{ch}")
    # punctuation escape: the literal byte(s)
    b = ch.encode("utf-8")
    if len(b) != 1:
        raise GrammarError(f"unsupported escape \\{ch}")
    return frozenset(b)


class _RegexParser:
    """Recursive-descent parser for the byte-level regex subset → AST.
    AST nodes: ("set", frozenset), ("cat", [..]), ("alt", [..]),
    ("rep", node, min, max|None)."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def _peek(self) -> str | None:
        return self.p[self.i] if self.i < len(self.p) else None

    def _take(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            raise GrammarError(f"unexpected {self.p[self.i]!r} at {self.i} in pattern")
        return node

    def _alt(self):
        branches = [self._seq()]
        while self._peek() == "|":
            self._take()
            branches.append(self._seq())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _seq(self):
        parts = []
        while self._peek() not in (None, "|", ")"):
            parts.append(self._repeat())
        if not parts:
            return ("cat", [])
        return parts[0] if len(parts) == 1 else ("cat", parts)

    def _repeat(self):
        node = self._atom()
        ch = self._peek()
        if ch == "*":
            self._take()
            return ("rep", node, 0, None)
        if ch == "+":
            self._take()
            return ("rep", node, 1, None)
        if ch == "?":
            self._take()
            return ("rep", node, 0, 1)
        if ch == "{":
            self._take()
            spec = ""
            while self._peek() not in (None, "}"):
                spec += self._take()
            if self._peek() != "}":
                raise GrammarError("unterminated {m,n} repetition")
            self._take()
            try:
                if "," in spec:
                    lo_s, hi_s = spec.split(",", 1)
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s.strip() else None
                else:
                    lo = hi = int(spec)
            except ValueError:
                raise GrammarError(f"bad repetition {{{spec}}}") from None
            if lo < 0 or (hi is not None and hi < lo):
                raise GrammarError(f"bad repetition bounds {{{spec}}}")
            return ("rep", node, lo, hi)
        return node

    def _atom(self):
        ch = self._take() if self._peek() is not None else None
        if ch is None:
            raise GrammarError("truncated pattern")
        if ch == "(":
            # non-capturing group marker tolerated
            if self.p[self.i : self.i + 2] == "?:":
                self.i += 2
            node = self._alt()
            if self._peek() != ")":
                raise GrammarError("unbalanced parenthesis")
            self._take()
            return node
        if ch == "[":
            return ("set", self._char_class())
        if ch == ".":
            return ("set", _DOT)
        if ch == "\\":
            if self._peek() is None:
                raise GrammarError("trailing backslash")
            return ("set", _escape_set(self._take()))
        if ch in ")|*+?{":
            raise GrammarError(f"misplaced {ch!r} in pattern")
        b = ch.encode("utf-8")
        if len(b) == 1:
            return ("set", frozenset(b))
        # multi-byte literal: a fixed byte sequence
        return ("cat", [("set", frozenset((x,))) for x in b])

    def _char_class(self) -> frozenset[int]:
        negate = False
        if self._peek() == "^":
            self._take()
            negate = True
        out: set[int] = set()
        first = True
        while True:
            ch = self._peek()
            if ch is None:
                raise GrammarError("unterminated character class")
            if ch == "]" and not first:
                self._take()
                break
            first = False
            self._take()
            if ch == "\\":
                nxt = self._take() if self._peek() is not None else None
                if nxt is None:
                    raise GrammarError("trailing backslash in class")
                if nxt.startswith("x"):
                    raise GrammarError("\\x escapes unsupported in classes")
                s = _escape_set(nxt)
                out |= s
                continue
            lo_b = ch.encode("utf-8")
            if len(lo_b) != 1:
                raise GrammarError("non-ASCII range endpoints unsupported")
            lo = lo_b[0]
            if self._peek() == "-" and self.p[self.i + 1 : self.i + 2] not in ("]", ""):
                self._take()
                hi_ch = self._take()
                if hi_ch == "\\":
                    hi_ch = self._take()
                hi_b = hi_ch.encode("utf-8")
                if len(hi_b) != 1 or hi_b[0] < lo:
                    raise GrammarError(f"bad class range {ch}-{hi_ch}")
                out |= set(range(lo, hi_b[0] + 1))
            else:
                out.add(lo)
        if negate:
            # Negation complements over printable bytes (>= 0x20), not
            # the raw byte range: `[^"\\]` in a JSON-string grammar must
            # not legalize control bytes JSON forbids unescaped.
            return _DOT - frozenset(out)
        return frozenset(out)


class _Nfa:
    """Thompson NFA: states are ints; ``eps[s]`` epsilon successors,
    ``edges[s]`` list of (byteset, target)."""

    def __init__(self):
        self.eps: list[list[int]] = []
        self.edges: list[list[tuple[frozenset[int], int]]] = []

    def state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def build(self, node, start: int, accept: int) -> None:
        kind = node[0]
        if kind == "set":
            self.edges[start].append((node[1], accept))
        elif kind == "cat":
            parts = node[1]
            if not parts:
                self.eps[start].append(accept)
                return
            cur = start
            for i, part in enumerate(parts):
                nxt = accept if i == len(parts) - 1 else self.state()
                self.build(part, cur, nxt)
                cur = nxt
        elif kind == "alt":
            for branch in node[1]:
                s, a = self.state(), self.state()
                self.eps[start].append(s)
                self.eps[a].append(accept)
                self.build(branch, s, a)
        elif kind == "rep":
            _, inner, lo, hi = node
            cur = start
            for _ in range(lo):
                nxt = self.state()
                self.build(inner, cur, nxt)
                cur = nxt
            if hi is None:
                # Kleene tail: loop state
                loop = self.state()
                self.eps[cur].append(loop)
                s, a = self.state(), self.state()
                self.eps[loop].append(s)
                self.eps[a].append(loop)
                self.build(inner, s, a)
                self.eps[loop].append(accept)
            else:
                self.eps[cur].append(accept)
                for _ in range(hi - lo):
                    nxt = self.state()
                    self.build(inner, cur, nxt)
                    self.eps[nxt].append(accept)
                    cur = nxt
        else:  # pragma: no cover - parser emits only the kinds above
            raise GrammarError(f"unknown regex node {kind!r}")


class _ByteDfa:
    """Lazily-determinized byte DFA over a Thompson NFA. States are
    interned frozensets of eps-closed NFA states; every non-empty state
    can reach acceptance (a property of the Thompson construction), so
    liveness checks reduce to "transition exists"."""

    def __init__(self, pattern: str):
        ast = _RegexParser(pattern).parse()
        self.nfa = _Nfa()
        s0, acc = self.nfa.state(), self.nfa.state()
        self.nfa.build(ast, s0, acc)
        self._accept = acc
        self._ids: dict[frozenset[int], int] = {}
        self._sets: list[frozenset[int]] = []
        self._trans: list[dict[int, int | None]] = []  # per state: byte → id|None
        self._accepting: list[bool] = []
        self.start = self._intern(self._closure({s0}))

    def _closure(self, states: set[int]) -> frozenset[int]:
        stack = list(states)
        out = set(states)
        while stack:
            s = stack.pop()
            for t in self.nfa.eps[s]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    def _intern(self, sset: frozenset[int]) -> int:
        sid = self._ids.get(sset)
        if sid is None:
            sid = len(self._sets)
            self._ids[sset] = sid
            self._sets.append(sset)
            self._trans.append({})
            self._accepting.append(self._accept in sset)
        return sid

    def step(self, sid: int, byte: int) -> int | None:
        cache = self._trans[sid]
        if byte in cache:
            return cache[byte]
        moved: set[int] = set()
        for s in self._sets[sid]:
            for byteset, target in self.nfa.edges[s]:
                if byte in byteset:
                    moved.add(target)
        nxt = self._intern(self._closure(moved)) if moved else None
        cache[byte] = nxt
        return nxt

    def accepting(self, sid: int) -> bool:
        return self._accepting[sid]

    def walk(self, sid: int, data: bytes) -> int | None:
        for b in data:
            sid = self.step(sid, b)
            if sid is None:
                return None
        return sid


# ---------------------------------------------------------------------------
# JSON schema → regex
# ---------------------------------------------------------------------------

_REGEX_SPECIALS = set("\\^$.|?*+()[]{}")


def _lit(text: str) -> str:
    """Regex-escape a literal string."""
    return "".join("\\" + c if c in _REGEX_SPECIALS else c for c in text)


# JSON string content, byte-level: any byte >= 0x20 except '"' and '\',
# or a simple escape, or \uXXXX. Permits non-ASCII bytes raw (the byte
# tokenizer emits them; json accepts UTF-8).
_STR_CHAR = '(?:[^"\\\\]|\\\\["\\\\/bfnrt]|\\\\u[0-9a-fA-F]{4})'
# Digit runs are CAPPED (16 int / 15 frac / 3 exp digits): past the cap
# the mask forces the closing delimiter, so a greedy model that would
# otherwise ramble digits to max_tokens terminates — and JSON numbers
# past 2^53 lose precision anyway. Strings stay unbounded unless the
# schema gives maxLength.
_INT = "-?(?:0|[1-9][0-9]{0,15})"
_NUMBER = _INT + "(?:\\.[0-9]{1,15})?(?:[eE][+-]?[0-9]{1,3})?"


def _json_literal_regex(value) -> str:
    return _lit(json.dumps(value, ensure_ascii=True))


def _resolve_ref(ref: str, root: dict) -> dict:
    if not ref.startswith("#/"):
        raise GrammarError(f"only local $ref supported, got {ref!r}")
    node = root
    for part in ref[2:].split("/"):
        part = part.replace("~1", "/").replace("~0", "~")
        if not isinstance(node, dict) or part not in node:
            raise GrammarError(f"unresolvable $ref {ref!r}")
        node = node[part]
    if not isinstance(node, dict):
        raise GrammarError(f"$ref {ref!r} does not name a schema object")
    return node


def _string_regex(schema: dict) -> str:
    if "pattern" in schema:
        pat = schema["pattern"]
        if not isinstance(pat, str):
            raise GrammarError("'pattern' must be a string")
        # Anchors are implicit (the whole string matches); strip the
        # common explicit ones.
        if pat.startswith("^"):
            pat = pat[1:]
        if pat.endswith("$") and not pat.endswith("\\$"):
            pat = pat[:-1]
        _RegexParser(pat).parse()  # validate the subset up front
        return f'"(?:{pat})"'
    lo = schema.get("minLength")
    hi = schema.get("maxLength")
    if lo is None and hi is None:
        return f'"{_STR_CHAR}*"'
    lo = int(lo or 0)
    if hi is None:
        return f'"{_STR_CHAR}{{{lo},}}"'
    hi = int(hi)
    if hi < lo:
        raise GrammarError("maxLength < minLength")
    return f'"{_STR_CHAR}{{{lo},{hi}}}"'


def schema_to_regex(schema: dict, depth: int = DEFAULT_JSON_DEPTH,
                    root: dict | None = None) -> str:
    """JSON schema (the OpenAI structured-output subset) → regex over the
    canonical serialization: properties in declared order (all emitted —
    a superset of any ``required`` list), ``": "`` / ``", "`` separators,
    no other whitespace. Raises :class:`GrammarError` on unsupported
    constructs so the frontend can 400 before any engine work."""
    if root is None:
        root = schema
    if not isinstance(schema, dict):
        raise GrammarError("schema must be a JSON object")
    if "$ref" in schema:
        if depth <= 0:
            raise GrammarError("schema recursion exceeds supported depth")
        return schema_to_regex(_resolve_ref(schema["$ref"], root), depth - 1, root)
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, list) or not vals:
            raise GrammarError("'enum' must be a non-empty array")
        return "(?:" + "|".join(_json_literal_regex(v) for v in vals) + ")"
    if "const" in schema:
        return _json_literal_regex(schema["const"])
    for comb in ("anyOf", "oneOf"):
        if comb in schema:
            subs = schema[comb]
            if not isinstance(subs, list) or not subs:
                raise GrammarError(f"'{comb}' must be a non-empty array")
            return "(?:" + "|".join(
                schema_to_regex(s, depth, root) for s in subs
            ) + ")"
    stype = schema.get("type")
    if isinstance(stype, list):
        return "(?:" + "|".join(
            schema_to_regex({**schema, "type": t}, depth, root) for t in stype
        ) + ")"
    if stype == "string":
        return _string_regex(schema)
    if stype == "integer":
        return _INT
    if stype == "number":
        return _NUMBER
    if stype == "boolean":
        return "(?:true|false)"
    if stype == "null":
        return "null"
    if stype == "object":
        if depth <= 0:
            raise GrammarError("schema nests deeper than the supported depth")
        props = schema.get("properties") or {}
        if not isinstance(props, dict):
            raise GrammarError("'properties' must be an object")
        if not props:
            return "\\{\\}"
        parts = []
        for key, sub in props.items():
            parts.append(_lit(json.dumps(str(key))) + ": "
                         + schema_to_regex(sub if isinstance(sub, dict) else {},
                                           depth - 1, root))
        return "\\{" + ", ".join(parts) + "\\}"
    if stype == "array":
        if depth <= 0:
            raise GrammarError("schema nests deeper than the supported depth")
        item = schema.get("items")
        item_re = schema_to_regex(item if isinstance(item, dict) else {},
                                  depth - 1, root)
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", DEFAULT_MAX_ITEMS))
        if hi < lo:
            raise GrammarError("maxItems < minItems")
        hi = max(hi, lo)
        body_req = ", ".join([f"(?:{item_re})"] * lo) if lo else ""
        extra = hi - lo
        if extra:
            opt = f"(?:, (?:{item_re}))" if lo else None
            if lo:
                tail = f"{opt}{{0,{extra}}}"
                body = body_req + tail
            else:
                body = f"(?:(?:{item_re})(?:, (?:{item_re})){{0,{extra - 1}}})?"
        else:
            body = body_req
        return "\\[" + body + "\\]"
    if stype is None:
        # untyped: any JSON value at the remaining depth
        return _any_value_regex(depth)
    raise GrammarError(f"unsupported schema type {stype!r}")


def _any_value_regex(depth: int) -> str:
    scalar = f'(?:"{_STR_CHAR}*"|{_NUMBER}|true|false|null)'
    if depth <= 0:
        return scalar
    inner = _any_value_regex(depth - 1)
    obj = f'(?:\\{{\\}}|\\{{"{_STR_CHAR}+": {inner}(?:, "{_STR_CHAR}+": {inner}){{0,{DEFAULT_MAX_ITEMS - 1}}}\\}})'
    arr = f"(?:\\[\\]|\\[{inner}(?:, {inner}){{0,{DEFAULT_MAX_ITEMS - 1}}}\\])"
    return f"(?:{scalar}|{obj}|{arr})"


def compile_response_format_regex(rf: dict) -> str | None:
    """OpenAI ``response_format`` dict → constraint regex (None when the
    format imposes no constraint). Raises GrammarError on malformed or
    unsupported specs — the frontend maps that to a 400."""
    if not isinstance(rf, dict):
        raise GrammarError("response_format must be an object")
    ftype = rf.get("type")
    if ftype == "text" or ftype is None:
        return None
    if ftype == "json_object":
        # Any JSON object (depth-bounded): the classic "JSON mode".
        inner = _any_value_regex(DEFAULT_JSON_DEPTH - 1)
        return (f'\\{{\\}}|\\{{"{_STR_CHAR}+": {inner}'
                f'(?:, "{_STR_CHAR}+": {inner}){{0,{DEFAULT_MAX_ITEMS - 1}}}\\}}')
    if ftype == "json_schema":
        js = rf.get("json_schema")
        if not isinstance(js, dict):
            raise GrammarError("response_format.json_schema must be an object")
        schema = js.get("schema")
        if not isinstance(schema, dict):
            raise GrammarError("response_format.json_schema.schema must be an object")
        return schema_to_regex(schema)
    raise GrammarError(f"unsupported response_format type {ftype!r}")


# ---------------------------------------------------------------------------
# Token-level FSM over a vocabulary
# ---------------------------------------------------------------------------


def grammar_vocab(tokenizer) -> dict[int, bytes]:
    """Tokenizer → {token_id: byte string} for every text-producing
    token. Tokens that produce no bytes (specials) are never grammar-
    legal; EOS legality is handled separately via the terminal-state
    mask. ByteTokenizer maps directly (token i < 256 IS byte i — decode
    would lose non-UTF-8 bytes to replacement chars); other tokenizers
    go through best-effort per-id decode."""
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    if isinstance(tokenizer, ByteTokenizer):
        return {i: bytes([i]) for i in range(256)}
    out: dict[int, bytes] = {}
    eos = set(tokenizer.eos_token_ids)
    for tid in range(tokenizer.vocab_size):
        if tid in eos:
            continue
        try:
            text = tokenizer.decode([tid], skip_special_tokens=True)
        except Exception:  # noqa: BLE001 — unknown ids in sparse vocabs just stay illegal
            continue
        if text:
            out[tid] = text.encode("utf-8")
    return out


def pack_token_ids(ids, vocab_size: int) -> np.ndarray:
    """Set of token ids → packed uint32 bitset [ceil(V/32)]."""
    words = (vocab_size + 31) // 32
    out = np.zeros((words,), np.uint32)
    for t in ids:
        t = int(t)
        if 0 <= t < vocab_size:
            out[t >> 5] |= np.uint32(1 << (t & 31))
    return out


def mask_words(vocab_size: int) -> int:
    return (vocab_size + 31) // 32


class CompiledGrammar:
    """One compiled constraint: byte DFA + token-level lift, shared by
    every sequence using the same schema. Thread-safe: lazy state
    computation happens under a lock (compiles run off the scheduler
    thread; per-token advance/mask hits only cached dicts)."""

    def __init__(self, regex: str, vocab: dict[int, bytes], vocab_size: int,
                 spec_hash: str):
        self.hash = spec_hash
        self.vocab_size = vocab_size
        self._vocab = vocab
        self._dfa = _ByteDfa(regex)
        self.start = self._dfa.start
        self._lock = threading.Lock()
        # per byte-DFA state id: {token_id: next_state}
        self._token_trans: dict[int, dict[int, int]] = {}
        # per state id: packed legal-token bitset (WITHOUT eos bits)
        self._base_masks: dict[int, np.ndarray] = {}
        self._forced: dict[int, int | None] = {}

    # -- lazy state lift ---------------------------------------------------

    def _lift(self, state: int) -> dict[int, int]:
        trans = self._token_trans.get(state)
        if trans is not None:
            return trans
        with self._lock:
            trans = self._token_trans.get(state)
            if trans is not None:
                return trans
            trans = {}
            for tid, data in self._vocab.items():
                nxt = self._dfa.walk(state, data)
                if nxt is not None:
                    trans[tid] = nxt
            mask = pack_token_ids(trans.keys(), self.vocab_size)
            forced = None
            if len(trans) == 1 and not self._dfa.accepting(state):
                forced = next(iter(trans))
            self._base_masks[state] = mask
            self._forced[state] = forced
            self._token_trans[state] = trans
            return trans

    # -- per-sequence API --------------------------------------------------

    def advance(self, state: int, token_id: int) -> int | None:
        """FSM state after emitting ``token_id`` (None = illegal — cannot
        happen for masked-sampled tokens; callers treat it defensively)."""
        return self._lift(state).get(int(token_id))

    def legal(self, state: int, token_id: int) -> bool:
        return int(token_id) in self._lift(state)

    def is_terminal(self, state: int) -> bool:
        """True when the match is complete here — EOS becomes legal."""
        return self._dfa.accepting(state)

    def forced(self, state: int) -> int | None:
        """The single legal continuation at a non-terminal state, or None.
        A forced run is draftable with certainty: no other token can ever
        be accepted from this state."""
        self._lift(state)
        return self._forced[state]

    def mask(self, state: int, eos_bits: np.ndarray | None = None) -> np.ndarray:
        """Packed legal-token bitset for ``state``. ``eos_bits`` (packed,
        same width) is OR-ed in at terminal states — non-terminal states
        keep EOS masked so streams cannot stop mid-structure."""
        self._lift(state)
        base = self._base_masks[state]
        if eos_bits is not None and self._dfa.accepting(state):
            return base | eos_bits
        return base

    def states_visited(self) -> int:
        return len(self._token_trans)


class GrammarCompiler:
    """Schema-hash-keyed cache of CompiledGrammar instances over one
    vocabulary. One per engine; compile() is thread-safe and cheap on a
    cache hit (the common case — structured traffic shares schemas)."""

    def __init__(self, vocab: dict[int, bytes], vocab_size: int):
        self.vocab = vocab
        self.vocab_size = vocab_size
        self._lock = threading.Lock()
        self._cache: dict[str, CompiledGrammar] = {}
        self.hits = 0
        self.misses = 0

    @staticmethod
    def spec_hash(rf: dict) -> str:
        return hashlib.sha256(
            json.dumps(rf, sort_keys=True, separators=(",", ":")).encode()
        ).hexdigest()

    def compile(self, rf: dict) -> CompiledGrammar | None:
        """response_format dict → CompiledGrammar (None = unconstrained).
        Raises GrammarError on malformed specs."""
        regex = compile_response_format_regex(rf)
        if regex is None:
            return None
        key = self.spec_hash(rf)
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                self.hits += 1
                return hit
        compiled = CompiledGrammar(regex, self.vocab, self.vocab_size, key)
        with self._lock:
            # racing compiles of the same schema: first one in wins, the
            # duplicate is discarded (both are equivalent).
            hit = self._cache.setdefault(key, compiled)
            if hit is compiled:
                self.misses += 1
            else:
                self.hits += 1
            return hit


def build_compiler(tokenizer_spec: dict | None, vocab_size: int) -> GrammarCompiler:
    """Engine-side factory: tokenizer spec dict (model card format;
    None → byte tokenizer) → GrammarCompiler over that vocabulary,
    packed to the MODEL's vocab_size (ids past the tokenizer's range are
    permanently illegal under any grammar — constrained output is always
    detokenizable)."""
    from dynamo_tpu.llm.tokenizer import load_tokenizer

    tok = load_tokenizer(tokenizer_spec or {"type": "byte"})
    return GrammarCompiler(grammar_vocab(tok), vocab_size)
