"""Engine + model configuration.

Reference analogue: engine args passthrough (components/backends/vllm/src/
dynamo/vllm/args.py) — but here the engine is ours, so the config is too.
All shapes that reach jit are derived here and static.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from dataclasses import dataclass, field


@dataclass(frozen=True)
class ModelConfig:
    """Llama-family architecture hyperparameters."""

    name: str = "test-tiny"
    vocab_size: int = 512
    hidden_size: int = 128
    intermediate_size: int = 256
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: int = 2
    head_dim: int = 32
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_position: int = 8192
    tie_embeddings: bool = True
    dtype: str = "bfloat16"
    # QKV projection bias (Qwen2-family). o_proj stays bias-free, as in
    # the architecture.
    attn_bias: bool = False
    # Mixture-of-experts (0 = dense FFN). Experts shard over the ``ep``
    # mesh axis (parallel/mesh.py) — the reference reaches wide-EP only
    # through engine flags (trtllm_utils.py:140-143, sglang wide-EP docs);
    # here it is a first-class model family.
    num_experts: int = 0
    num_experts_per_token: int = 2
    moe_intermediate_size: int | None = None  # per-expert FFN width (default: intermediate_size)

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    def param_count(self) -> int:
        d, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        if self.num_experts:
            ie = self.moe_intermediate_size or i
            ffn = self.num_experts * 3 * d * ie + d * self.num_experts  # experts + router
        else:
            ffn = 3 * d * i
        per_layer = (
            d * self.q_size + 2 * d * self.kv_size + self.q_size * d  # attn
            + ffn
            + 2 * d                                                   # norms
        )
        if self.attn_bias:
            per_layer += self.q_size + 2 * self.kv_size
        head = 0 if self.tie_embeddings else d * v
        return v * d + self.num_layers * per_layer + d + head

    def active_param_count(self) -> int:
        """Params touched per token (MoE: only top-k experts active)."""
        if not self.num_experts:
            return self.param_count()
        d, v = self.hidden_size, self.vocab_size
        ie = self.moe_intermediate_size or self.intermediate_size
        per_layer = (
            d * self.q_size + 2 * d * self.kv_size + self.q_size * d
            + self.num_experts_per_token * 3 * d * ie + d * self.num_experts
            + 2 * d
        )
        head = 0 if self.tie_embeddings else d * v
        return v * d + self.num_layers * per_layer + d + head

    @staticmethod
    def preset(name: str) -> "ModelConfig":
        presets = {
            # CPU-testable toy model
            "test-tiny": ModelConfig(),
            # ~1.2B params — fits v5e-lite HBM in bf16 with headroom for KV
            "llama-1b": ModelConfig(
                name="llama-1b", vocab_size=32000, hidden_size=2048,
                intermediate_size=5632, num_layers=22, num_heads=32,
                num_kv_heads=4, head_dim=64, rope_theta=500000.0,
                max_position=131072, tie_embeddings=True,
            ),
            # Llama-3.2-3B-class
            "llama-3b": ModelConfig(
                name="llama-3b", vocab_size=128256, hidden_size=3072,
                intermediate_size=8192, num_layers=28, num_heads=24,
                num_kv_heads=8, head_dim=128, rope_theta=500000.0,
                max_position=131072, tie_embeddings=True,
            ),
            # Llama-3.1-8B-class (multi-chip / bf16-tight on one v5e)
            "llama-8b": ModelConfig(
                name="llama-8b", vocab_size=128256, hidden_size=4096,
                intermediate_size=14336, num_layers=32, num_heads=32,
                num_kv_heads=8, head_dim=128, rope_theta=500000.0,
                max_position=131072, tie_embeddings=False,
            ),
            # Qwen2.5-7B-class (QKV bias; fits one v5e with int8)
            "qwen2-7b": ModelConfig(
                name="qwen2-7b", vocab_size=152064, hidden_size=3584,
                intermediate_size=18944, num_layers=28, num_heads=28,
                num_kv_heads=4, head_dim=128, rope_theta=1000000.0,
                max_position=32768, tie_embeddings=False, attn_bias=True,
            ),
            # Mixtral-style MoE (test/dev scale; EP over the ep mesh axis)
            "moe-tiny": ModelConfig(
                name="moe-tiny", vocab_size=512, hidden_size=128,
                intermediate_size=256, num_layers=2, num_heads=4,
                num_kv_heads=2, head_dim=32, num_experts=4,
                num_experts_per_token=2,
            ),
            # DeepSeek-V3-ish wide-EP geometry (BASELINE config #5 shape:
            # many small experts, top-8; real weights need a loader ext.)
            "moe-wide": ModelConfig(
                name="moe-wide", vocab_size=32000, hidden_size=2048,
                intermediate_size=8192, num_layers=12, num_heads=16,
                num_kv_heads=4, head_dim=128, num_experts=64,
                num_experts_per_token=8, moe_intermediate_size=1024,
            ),
            # Llama-3-70B-class (BASELINE.md north-star target, multi-host)
            "llama-70b": ModelConfig(
                name="llama-70b", vocab_size=128256, hidden_size=8192,
                intermediate_size=28672, num_layers=80, num_heads=64,
                num_kv_heads=8, head_dim=128, rope_theta=500000.0,
                max_position=131072, tie_embeddings=False,
            ),
        }
        if name not in presets:
            raise ValueError(f"unknown model preset {name!r}; have {sorted(presets)}")
        return presets[name]


def _pow2_buckets(lo: int, hi: int, factor: int = 2) -> tuple[int, ...]:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= factor
    out.append(hi)
    return tuple(dict.fromkeys(out))


@dataclass
class EngineArgs:
    """Runtime shape/capacity knobs. Every jitted shape derives from here."""

    model: ModelConfig = field(default_factory=ModelConfig)
    block_size: int = 16                 # KV page size (tokens)
    num_kv_blocks: int = 256             # G1 (HBM) pool size
    max_num_seqs: int = 8                # max concurrent sequences in decode
    max_model_len: int = 2048            # max prompt+gen tokens per sequence
    max_prefill_tokens: int = 2048       # longest single prefill chunk
    # bf16 for weights/activations; fp32 sampling.
    dtype: str = "bfloat16"
    # TP mesh axis size (1 = single chip). Sharding rules in parallel/.
    tp: int = 1
    enforce_eager: bool = False          # skip jit (debug)
    prefix_caching: bool = True
    # Weight format: "none" = dtype weights; "int8" = weight-only int8
    # with per-output-channel scales (engine/quant.py) — halves weight
    # bandwidth (the decode bottleneck) and fits llama-8b on one v5e.
    quant: str = "none"
    # KV cache storage format: "none" = pages in ``dtype``; "int8" =
    # pages stored int8 with per-position-per-head fp32 scales riding a
    # parallel array alongside the cache (model.KVCache.k_scale/v_scale,
    # same symmetric absmax scheme as engine/quant.py). Near-halves
    # kv_bytes_per_block, so auto_kv_blocks fits ~2x the sequences in
    # the same HBM budget — a capacity AND batch-size win in the weight-
    # bandwidth-bound decode regime. Every consumer dequantizes at read
    # (XLA gather paths and the Pallas kernels, in-register); every
    # tier/transfer hop (G2/G3 offload, disagg export, peer fetch) moves
    # int8+scale payloads, halving those bytes too. Scales are per
    # WRITTEN POSITION (not per sealed block) so a token's stored value
    # never depends on which path wrote it (prefill / decode window /
    # spec verify) or on later writes — the property that keeps greedy
    # streams byte-stable across pipeline depths and spec modes.
    kv_quant: str = "none"
    # Attention backend (ops/paged_attention.py): "auto" → Pallas kernel
    # on TPU (single-device), XLA gather on CPU. Forced to "xla" under a
    # tp/dp mesh (pallas_call is opaque to GSPMD partitioning).
    attn_impl: str = "auto"
    # Fused decode substeps per host sync (model.multi_decode). >1 is the
    # key throughput lever when host↔device roundtrips are slow; tokens
    # stream in bursts of this size. 1 = classic per-step loop.
    decode_steps: int = 8
    # Emit coalescing: when a stream's consumer lags (GIL-bound frontend
    # path), decode-window deltas already queued merge into one frame up
    # to this many tokens before hitting the wire — strictly less
    # per-token Python work with zero added latency (only backlog merges).
    # 0 disables (one frame per decode window).
    delta_max_tokens: int = 64
    # Optional bounded wait (ms) to gather MORE deltas per frame beyond
    # the backlog: adds up to this much inter-token latency. 0 (default)
    # never waits. Keep ≤ one decode-window duration.
    delta_max_ms: float = 0.0
    # Max prompt tokens admitted per scheduler step (prefill-vs-decode
    # fairness knob). Each admitted prompt still prefills in
    # max_prefill_tokens chunks; this budget only gates how many requests
    # join between decode windows. Too small trickle-admits under bursts —
    # every K-step window then runs at a tiny batch (measured 5x
    # throughput loss on ramp-up); too large starves running decodes.
    # 0 = admit until slots are full.
    admission_budget_tokens: int = 8192
    # Multi-tenant QoS (runtime/qos.py, docs/qos.md): when True the
    # scheduler orders admission and preemption by (priority class,
    # age) — waiting interactive requests admit before batch, and KV-
    # pressure preemption evicts the lowest class/newest-prefill victim
    # first. Requests without a priority all land in one class, which
    # makes the ordering EXACTLY the pre-QoS FIFO/newest-first rules —
    # byte-identical streams for no-QoS traffic either way. False pins
    # every request to one class regardless of wire priority.
    qos_scheduling: bool = True
    # Keep decode windows in flight: window w+1 is dispatched chaining
    # from w's on-device outputs before w is fetched, hiding the
    # host↔device sync roundtrip (~100 ms on tunneled TPUs). Stops are
    # then discovered up to pipeline_depth windows late (≤ depth ×
    # decode_steps wasted tokens per finished sequence). Full-sampler
    # batches always run unpipelined.
    pipeline_windows: bool = True
    # Max decode windows dispatched-but-not-fetched at once (0 = drain
    # each window before dispatching the next, i.e. unpipelined; 1 = the
    # classic one-window pipeline). Depth 2 lets the host ride out a full
    # fetch roundtrip of jitter without ever idling the device; deeper
    # only adds stop-discovery latency. Fetches are started async at
    # dispatch (copy_to_host_async) and harvested by readiness polling,
    # so the host blocks only when the pipeline is full.
    pipeline_depth: int = 2
    # Prefill T-bucket ladder: "fine" (default) inserts 1.5x midpoints
    # into the pow2 ladder through the common range (≤512), halving the
    # worst-case pad; "coarse" is the legacy 2x/4x ladder (fewest
    # compiles); a comma list ("64,128,384") pins an explicit schedule
    # (values round up to block_size multiples; max_prefill_tokens is
    # always appended). Each bucket × table-width pair is one compile —
    # warm the lattice (bench.py --precompile-only) after widening.
    prefill_buckets_spec: str = "fine"
    # Split a suffix whose bucket pad is large into [bucket-sized chunk,
    # re-bucketed tail] chunked-prefill dispatches: a 600-token suffix
    # runs as 512 + (88→96) instead of padding a whole 1024 row. Exact
    # (chunked prefill is exact); costs one extra dispatch, so only
    # splits that save ≥ 2 blocks of padding are taken.
    prefill_tail_split: bool = True
    # Max sequences packed into one prefill dispatch (model.prefill_batch).
    # Default 1 (singles): packing existed because r3 paid a host sync per
    # admission, but async admission pipelines single-row prefills with no
    # sync — and every extra row bucket multiplies the compile lattice
    # that warmup must cover (a cold variant hit mid-run costs a ~30s
    # tunnel compile, measured as a 609-vs-890 tok/s bench regression).
    # Raise it only with a warmed cache covering the (T x Bp x W) matrix.
    prefill_batch_max: int = 1
    # Alternative-logprob width: requests asking for top_logprobs get up
    # to this many ranked alternatives; ONE static width keeps the
    # compile matrix at 2x (with/without) instead of per-N variants.
    # OpenAI caps chat top_logprobs at 20.
    top_logprobs_max: int = 8
    # KV tier stack (block_manager/tiers.py): G2 host-RAM blocks (0 = off)
    # and optional G3 disk spill directory.
    host_kv_blocks: int = 0
    disk_kv_dir: str | None = None
    disk_kv_blocks: int = 4096
    # G4 fleet-SHARED pool: a directory mounted by EVERY engine (NFS,
    # multi-engine-host tmpfs, fused object store). Blocks spill here
    # from G3 keyed by the salted hash chain, so identical prefixes
    # produced by different engines dedup to one file and any engine can
    # onboard a peer's cold prefix without recompute or a live holder.
    fleet_kv_dir: str | None = None
    fleet_kv_blocks: int = 16384
    # Speculative decoding (engine/drafter.py + model.spec_verify): max
    # draft tokens verified per pass (0 = off). Decode is weight-
    # bandwidth-bound — one verify pass streams the weights ONCE and can
    # emit up to spec_tokens+1 tokens per sequence, so acceptance rate
    # directly multiplies tokens-per-weight-pass. Drafts come from
    # host-side n-gram prompt lookup (free — no draft model); greedy
    # rows accept by exact match (byte-identical to the dense path),
    # sampled rows use rejection sampling (distribution unchanged).
    spec_tokens: int = 0
    # n-gram match length for the prompt-lookup drafter: the last
    # spec_ngram generated/prompt tokens are matched against the
    # sequence's own history and the continuation of the most recent
    # earlier occurrence becomes the draft.
    spec_ngram: int = 3
    # Adaptive acceptance EMA per sequence: update weight, the EMA below
    # which a row stops proposing drafts, and how many decode iterations
    # an EMA-disabled row waits before re-probing with a (naturally
    # short, EMA-scaled) draft. Rows whose drafter simply finds no match
    # are NOT throttled — that scan is an O(new tokens) dict lookup and
    # never forces a pipeline drain by itself. Keeps adversarial
    # (incompressible) workloads at the dense path's cost instead of
    # paying rejected verify work forever.
    spec_ema_alpha: float = 0.3
    spec_ema_disable: float = 0.2
    spec_probe_every: int = 16
    # Tree speculation (SpecInfer-style): max branching factor per draft
    # node. 1 = linear drafts only (the PR 5 path, byte-for-byte);
    # >= 2 swaps in the tree drafter (engine/drafter.TreeDrafter):
    # wherever the per-sequence n-gram index has recorded SEVERAL
    # distinct continuations of the trailing context the draft branches,
    # and a Lookahead-style Jacobi pool (model-predicted continuations
    # harvested from every verify pass's logits) drafts on generic
    # traffic with zero history hits. The whole tree still verifies in
    # ONE weight stream via the topology-masked multi-query gather, so
    # the node budget stays spec_tokens — width buys coverage of
    # alternative branches, not extra bandwidth.
    spec_tree_width: int = 1
    # Max tree path depth (0 = spec_tokens). Depth bounds the best-case
    # accepted run; width x depth should comfortably exceed spec_tokens
    # or the budget can never branch.
    spec_tree_depth: int = 0
    # Verify forward shape: True (default) = single-pass fused forward —
    # ONE weight stream scores the whole draft, the bandwidth win.
    # False = teacher-forced scan of the dense decode step — bitwise
    # identical to the dense path on every backend (fused matmul
    # reduction order can differ at the last ulp on some backends, which
    # perturbs reported logprob values, not sampling decisions); keeps
    # only the one-dispatch/one-fetch saving. Parity/debug mode and the
    # golden suite's byte-identity anchor.
    spec_fused: bool = True
    # Streaming KV export flow control (dynamo_tpu/transfer): max host
    # bytes of published-but-unacked chunks one export may buffer. A
    # consumer that stops pulling aborts the stream at this budget (the
    # decode side falls back to local prefill) instead of growing the
    # prefill worker's heap without bound.
    transfer_buffer_bytes: int = 256 << 20
    # Proactive defrag (planner/balancer.py composition): at this KV
    # pool usage fraction the engine fires its migration-offer hook for
    # the CHEAPEST running sequence — relocating it to a pool peer
    # BEFORE allocation failure forces a recompute-preemption. The same
    # hook the preemption boundary already uses (preempt_offer_grace_s),
    # fired ahead of pressure instead of at the cliff. 0 = off (the
    # offer still fires at the preemption boundary as before).
    kv_pressure_offer: float = 0.0
    # Batch-level dispatch gate: speculate only when the EMA-weighted
    # expected tokens per row-pass, mean(1 + ema_i * draft_len_i),
    # clears this threshold. Protects mixed batches (a few drafting rows
    # must not drop everyone else from K-token windows to 1-token
    # passes) and ramp phases where loops have not formed yet. 0 = always
    # speculate when any draft exists (golden tests use this).
    spec_gate: float = 1.5
    # Batch-level adaptive tree budgets (engine.alloc_spec_budgets):
    # instead of a uniform spec_tokens draft-node allowance per row, each
    # verify pass reallocates the FIXED batch node budget
    # (rows x spec_tokens) by acceptance EMA — draft nodes move from
    # EMA-cold rows to hot ones (hot rows may draft up to 2x spec_tokens;
    # every non-cooling row keeps a >= 1-node probe so it can re-heat).
    # Grammar-constrained rows are typically the hottest, so the whole
    # batch's weight-pass amortization improves at EQUAL total budget.
    # False = the uniform per-row allowance (PR 10 behavior, the bench
    # A/B baseline). Correctness is allocation-independent: greedy
    # streams stay byte-identical to dense for any budget split.
    spec_budget_adaptive: bool = True
    # Tokenizer spec dict ({"type": "byte"} / {"type": "hf", ...}) the
    # engine compiles grammar token-mask FSMs over (engine/grammar.py).
    # None = byte tokenizer. Must match the serving tokenizer or masks
    # would legalize undecodable ids; the worker wires its own spec.
    grammar_tokenizer: dict | None = None
    # Multi-LoRA multiplexing (engine/lora.py + block_manager/adapters.py):
    # number of device-resident adapter SLOTS in the HBM adapter bank
    # (0 = LoRA off, no bank allocated, every dispatch byte-identical to
    # pre-LoRA builds). Many more adapters than slots may be registered —
    # they page in on first request through the G2/G3 tier economy and
    # page out cold under second-chance eviction pressure; slots pinned
    # by running sequences are never victims. Each batch row carries an
    # adapter_slot index (-1 = base) and the q/k/v/o projections add the
    # low-rank delta via a batched gathered matmul, so mixed-adapter
    # batches ride the normal prefill/decode/spec dispatches.
    lora_slots: int = 0
    # Static bank rank (max over registered adapters; smaller ranks
    # zero-pad). One rank keeps the compiled dispatch lattice at 2x
    # (with/without adapters) instead of per-rank variants.
    lora_rank: int = 8

    def __post_init__(self):
        # Fail fast on a mistyped ladder spec: anything that is not a
        # named schedule must parse as a comma list of ints, or the error
        # would otherwise surface as a bare int() ValueError deep inside
        # the first bucket_prefill call.
        if self.prefill_buckets_spec not in ("fine", "coarse"):
            self._parse_bucket_list(self.prefill_buckets_spec)
        if self.kv_quant not in ("none", "int8"):
            raise ValueError(
                f"kv_quant must be 'none' or 'int8'; got {self.kv_quant!r}"
            )
        if self.spec_tree_width < 1:
            raise ValueError(
                f"spec_tree_width must be >= 1; got {self.spec_tree_width}"
            )
        if self.spec_tree_depth < 0:
            raise ValueError(
                f"spec_tree_depth must be >= 0 (0 = spec_tokens); got "
                f"{self.spec_tree_depth}"
            )
        if self.lora_slots < 0:
            raise ValueError(f"lora_slots must be >= 0; got {self.lora_slots}")
        if not 0.0 <= self.kv_pressure_offer <= 1.0:
            raise ValueError(
                f"kv_pressure_offer must be in [0, 1]; got {self.kv_pressure_offer}"
            )
        if self.lora_slots > 0 and self.lora_rank <= 0:
            raise ValueError(
                f"lora_rank must be positive when lora_slots > 0; got {self.lora_rank}"
            )
        if self.max_model_len % self.block_size:
            self.max_model_len = ((self.max_model_len // self.block_size) + 1) * self.block_size
        if self.max_prefill_tokens % self.block_size:
            # prefill chunks must be block-aligned (model.py scatter contract)
            self.max_prefill_tokens = (
                (self.max_prefill_tokens // self.block_size) + 1
            ) * self.block_size

    @property
    def blocks_per_seq(self) -> int:
        return self.max_model_len // self.block_size

    # Bucket ladders are cached_properties: bucket_prefill/bucket_decode/
    # bucket_table run on the scheduler hot thread (plan_prefill_chunks
    # probes the ladder O(buckets) times per admitted suffix), so the
    # tuple must be built once, not re-derived per access. EngineArgs is
    # effectively frozen after construction; replace() makes a new
    # instance with a fresh cache.
    @functools.cached_property
    def prefill_buckets(self) -> tuple[int, ...]:
        # Prefill is where the FLOPs are: every padded token runs the
        # full model, so the ladder's stride IS the pad waste (r5 bench:
        # pad_ratio 1.45 on the legacy 2x/4x ladder). "fine" adds 1.5x
        # midpoints to the pow2 ladder through the common range (≤512,
        # where real ShareGPT prompts live) and stays 2x beyond — the
        # tail-split planner (plan_prefill_chunks) covers the long range
        # without more buckets. Values stay block_size-aligned (model.py
        # scatter contract) and each (Bp x T x W) combination is still a
        # separate compile, so the ladder is a knob, not a free lunch.
        spec = self.prefill_buckets_spec
        bs = self.block_size
        if spec not in ("fine", "coarse"):
            vals = sorted({
                min(-(-x // bs) * bs, self.max_prefill_tokens)
                for x in self._parse_bucket_list(spec)
            })
            return tuple(dict.fromkeys(vals + [self.max_prefill_tokens]))
        lo = min(max(bs * 2, 32), self.max_prefill_tokens)
        out = []
        b = lo
        while b < self.max_prefill_tokens:
            out.append(b)
            if spec == "fine":
                mid = -(-(b * 3 // 2) // bs) * bs  # 1.5x, block-aligned
                if b < 512 and mid < self.max_prefill_tokens and mid > b:
                    out.append(mid)
                b *= 2
            else:
                b *= 2 if b < 512 else 4
        out.append(self.max_prefill_tokens)
        return tuple(dict.fromkeys(sorted(out)))

    @functools.cached_property
    def decode_buckets(self) -> tuple[int, ...]:
        # Floor of 8, 4x stride: decode steps are parameter-bandwidth-
        # bound and padded rows cost ~nothing in the Pallas attention
        # path, so coarse batch buckets trade a little sampler work for
        # a much smaller compile matrix (multi_decode variants are the
        # most expensive compiles, 20-40s each on the tunnel).
        return _pow2_buckets(min(8, self.max_num_seqs), self.max_num_seqs, factor=4)

    @functools.cached_property
    def table_buckets(self) -> tuple[int, ...]:
        """Block-table width ladder. Decode/prefill attention cost scales
        with the table width actually passed (model.py derives W from the
        shape), so short sequences must not pay for max_model_len — each
        batch uses the smallest bucket covering its longest sequence
        (VERDICT r2 weak #3). Two buckets only: the Pallas decode kernel
        does work proportional to TRUE lengths (padded table width costs
        ~one skipped grid step per dead chunk), so a wide table is nearly
        free on TPU; the small bucket keeps short-prompt prefill (XLA
        gather path) and CPU tests cheap."""
        small = min(8, self.blocks_per_seq)
        return tuple(dict.fromkeys((small, self.blocks_per_seq)))

    def bucket_table(self, n_blocks: int) -> int:
        for b in self.table_buckets:
            if n_blocks <= b:
                return b
        raise ValueError(
            f"sequence of {n_blocks} blocks exceeds blocks_per_seq={self.blocks_per_seq}"
        )

    def bucket_prefill(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"prefill of {n} tokens exceeds max_prefill_tokens={self.max_prefill_tokens}")

    @staticmethod
    def _parse_bucket_list(spec: str) -> list[int]:
        """Parse an explicit comma-list bucket spec; the ONE shared parse
        for __post_init__ (fail fast at construction) and the ladder
        builder, so validation can't drift from use."""
        try:
            vals = [int(x) for x in spec.split(",") if x.strip()]
        except ValueError:
            vals = []
        if not vals or any(v <= 0 for v in vals):
            raise ValueError(
                f"prefill_buckets_spec must be 'fine', 'coarse' or a comma "
                f"list of positive ints; got {spec!r}"
            )
        return vals

    def plan_prefill_chunks(self, sfx: int) -> list[int]:
        """Chunk plan for one suffix of ``sfx`` tokens (≤ max_prefill_tokens):
        ``[sfx]`` = one dispatch padded to its bucket, or ``[c1, sfx-c1]``
        when splitting the tail into a smaller bucket saves ≥ 2 blocks of
        padding. ``c1`` is a bucket value, hence block-aligned, so the
        second chunk starts on a block boundary (model.py scatter
        contract). Chunked prefill is exact, so the split never changes
        tokens — only the padded-FLOPs bill."""
        direct = self.bucket_prefill(sfx)
        if not self.prefill_tail_split or direct == sfx:
            return [sfx]
        best, best_cost = [sfx], direct
        for c1 in self.prefill_buckets:
            if c1 >= sfx:
                break
            cost = c1 + self.bucket_prefill(sfx - c1)
            # <= : on cost ties the LARGEST first chunk wins (600 →
            # [512, 88→96], not [96, 504]) — one bucket-sized chunk plus
            # a small tail, as documented.
            if cost <= best_cost:
                best, best_cost = [c1, sfx - c1], cost
        if direct - best_cost >= 2 * self.block_size:
            return best
        return [sfx]

    def bucket_prefill_rows(self, n: int) -> int:
        # Pow2 row ladder: steady-state admission waves are small (1-3
        # slots free per step), and padding a 2-seq wave to 8 rows cost
        # 4x its prefill compute (each padded row runs the full model).
        b = 1
        while b < min(n, self.prefill_batch_max):
            b *= 2
        return min(b, self.prefill_batch_max)

    def bucket_decode(self, n: int) -> int:
        for b in self.decode_buckets:
            if n <= b:
                return b
        raise ValueError(f"decode batch {n} exceeds max_num_seqs={self.max_num_seqs}")

    @property
    def effective_pipeline_depth(self) -> int:
        """pipeline_windows is the master enable; depth 0 = unpipelined."""
        return max(0, self.pipeline_depth) if self.pipeline_windows else 0

    def kv_bytes_per_block(self) -> int:
        """HBM bytes one block costs across all layers, k+v, derived
        from the KV STORAGE dtype — not ``dtype`` alone, which silently
        mis-sized ``auto_kv_blocks`` 2x under kv_quant=int8. int8 pages
        carry a per-position-per-head fp32 scale array (model.KVCache),
        so the real cost is 1 byte/elem + 4/head_dim bytes/elem of scale
        overhead (~3% at head_dim=128 → ~1.94x more blocks per byte)."""
        m = self.model
        elems = self.block_size * m.num_kv_heads * m.head_dim
        if self.kv_quant == "int8":
            # int8 page + fp32 scale per (position, kv head).
            per_layer = elems + self.block_size * m.num_kv_heads * 4
        else:
            itemsize = 2 if self.dtype == "bfloat16" else 4
            per_layer = elems * itemsize
        return 2 * m.num_layers * per_layer

    def replace(self, **kw) -> "EngineArgs":
        return dataclasses.replace(self, **kw)

    @staticmethod
    def auto_kv_blocks(hbm_bytes_free: int, args: "EngineArgs", utilization: float = 0.9) -> int:
        """vLLM-style: size the G1 pool from free HBM after weights."""
        per_block = args.kv_bytes_per_block()
        n = int(hbm_bytes_free * utilization) // per_block
        return max(n, args.blocks_per_seq * 2)
