"""Engine + model configuration.

Reference analogue: engine args passthrough (components/backends/vllm/src/
dynamo/vllm/args.py) — but here the engine is ours, so the config is too.
All shapes that reach jit are derived here and static.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field


@dataclass(frozen=True)
class ModelConfig:
    """Llama-family architecture hyperparameters."""

    name: str = "test-tiny"
    vocab_size: int = 512
    hidden_size: int = 128
    intermediate_size: int = 256
    num_layers: int = 2
    num_heads: int = 4
    num_kv_heads: int = 2
    head_dim: int = 32
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    max_position: int = 8192
    tie_embeddings: bool = True
    dtype: str = "bfloat16"
    # QKV projection bias (Qwen2-family). o_proj stays bias-free, as in
    # the architecture.
    attn_bias: bool = False
    # Mixture-of-experts (0 = dense FFN). Experts shard over the ``ep``
    # mesh axis (parallel/mesh.py) — the reference reaches wide-EP only
    # through engine flags (trtllm_utils.py:140-143, sglang wide-EP docs);
    # here it is a first-class model family.
    num_experts: int = 0
    num_experts_per_token: int = 2
    moe_intermediate_size: int | None = None  # per-expert FFN width (default: intermediate_size)

    @property
    def q_size(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_size(self) -> int:
        return self.num_kv_heads * self.head_dim

    def param_count(self) -> int:
        d, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        if self.num_experts:
            ie = self.moe_intermediate_size or i
            ffn = self.num_experts * 3 * d * ie + d * self.num_experts  # experts + router
        else:
            ffn = 3 * d * i
        per_layer = (
            d * self.q_size + 2 * d * self.kv_size + self.q_size * d  # attn
            + ffn
            + 2 * d                                                   # norms
        )
        if self.attn_bias:
            per_layer += self.q_size + 2 * self.kv_size
        head = 0 if self.tie_embeddings else d * v
        return v * d + self.num_layers * per_layer + d + head

    def active_param_count(self) -> int:
        """Params touched per token (MoE: only top-k experts active)."""
        if not self.num_experts:
            return self.param_count()
        d, v = self.hidden_size, self.vocab_size
        ie = self.moe_intermediate_size or self.intermediate_size
        per_layer = (
            d * self.q_size + 2 * d * self.kv_size + self.q_size * d
            + self.num_experts_per_token * 3 * d * ie + d * self.num_experts
            + 2 * d
        )
        head = 0 if self.tie_embeddings else d * v
        return v * d + self.num_layers * per_layer + d + head

    @staticmethod
    def preset(name: str) -> "ModelConfig":
        presets = {
            # CPU-testable toy model
            "test-tiny": ModelConfig(),
            # ~1.2B params — fits v5e-lite HBM in bf16 with headroom for KV
            "llama-1b": ModelConfig(
                name="llama-1b", vocab_size=32000, hidden_size=2048,
                intermediate_size=5632, num_layers=22, num_heads=32,
                num_kv_heads=4, head_dim=64, rope_theta=500000.0,
                max_position=131072, tie_embeddings=True,
            ),
            # Llama-3.2-3B-class
            "llama-3b": ModelConfig(
                name="llama-3b", vocab_size=128256, hidden_size=3072,
                intermediate_size=8192, num_layers=28, num_heads=24,
                num_kv_heads=8, head_dim=128, rope_theta=500000.0,
                max_position=131072, tie_embeddings=True,
            ),
            # Llama-3.1-8B-class (multi-chip / bf16-tight on one v5e)
            "llama-8b": ModelConfig(
                name="llama-8b", vocab_size=128256, hidden_size=4096,
                intermediate_size=14336, num_layers=32, num_heads=32,
                num_kv_heads=8, head_dim=128, rope_theta=500000.0,
                max_position=131072, tie_embeddings=False,
            ),
            # Qwen2.5-7B-class (QKV bias; fits one v5e with int8)
            "qwen2-7b": ModelConfig(
                name="qwen2-7b", vocab_size=152064, hidden_size=3584,
                intermediate_size=18944, num_layers=28, num_heads=28,
                num_kv_heads=4, head_dim=128, rope_theta=1000000.0,
                max_position=32768, tie_embeddings=False, attn_bias=True,
            ),
            # Mixtral-style MoE (test/dev scale; EP over the ep mesh axis)
            "moe-tiny": ModelConfig(
                name="moe-tiny", vocab_size=512, hidden_size=128,
                intermediate_size=256, num_layers=2, num_heads=4,
                num_kv_heads=2, head_dim=32, num_experts=4,
                num_experts_per_token=2,
            ),
            # DeepSeek-V3-ish wide-EP geometry (BASELINE config #5 shape:
            # many small experts, top-8; real weights need a loader ext.)
            "moe-wide": ModelConfig(
                name="moe-wide", vocab_size=32000, hidden_size=2048,
                intermediate_size=8192, num_layers=12, num_heads=16,
                num_kv_heads=4, head_dim=128, num_experts=64,
                num_experts_per_token=8, moe_intermediate_size=1024,
            ),
            # Llama-3-70B-class (BASELINE.md north-star target, multi-host)
            "llama-70b": ModelConfig(
                name="llama-70b", vocab_size=128256, hidden_size=8192,
                intermediate_size=28672, num_layers=80, num_heads=64,
                num_kv_heads=8, head_dim=128, rope_theta=500000.0,
                max_position=131072, tie_embeddings=False,
            ),
        }
        if name not in presets:
            raise ValueError(f"unknown model preset {name!r}; have {sorted(presets)}")
        return presets[name]


def _pow2_buckets(lo: int, hi: int, factor: int = 2) -> tuple[int, ...]:
    out = []
    b = lo
    while b < hi:
        out.append(b)
        b *= factor
    out.append(hi)
    return tuple(dict.fromkeys(out))


@dataclass
class EngineArgs:
    """Runtime shape/capacity knobs. Every jitted shape derives from here."""

    model: ModelConfig = field(default_factory=ModelConfig)
    block_size: int = 16                 # KV page size (tokens)
    num_kv_blocks: int = 256             # G1 (HBM) pool size
    max_num_seqs: int = 8                # max concurrent sequences in decode
    max_model_len: int = 2048            # max prompt+gen tokens per sequence
    max_prefill_tokens: int = 2048       # longest single prefill chunk
    # bf16 for weights/activations; fp32 sampling.
    dtype: str = "bfloat16"
    # TP mesh axis size (1 = single chip). Sharding rules in parallel/.
    tp: int = 1
    enforce_eager: bool = False          # skip jit (debug)
    prefix_caching: bool = True
    # Weight format: "none" = dtype weights; "int8" = weight-only int8
    # with per-output-channel scales (engine/quant.py) — halves weight
    # bandwidth (the decode bottleneck) and fits llama-8b on one v5e.
    quant: str = "none"
    # Attention backend (ops/paged_attention.py): "auto" → Pallas kernel
    # on TPU (single-device), XLA gather on CPU. Forced to "xla" under a
    # tp/dp mesh (pallas_call is opaque to GSPMD partitioning).
    attn_impl: str = "auto"
    # Fused decode substeps per host sync (model.multi_decode). >1 is the
    # key throughput lever when host↔device roundtrips are slow; tokens
    # stream in bursts of this size. 1 = classic per-step loop.
    decode_steps: int = 8
    # Emit coalescing: when a stream's consumer lags (GIL-bound frontend
    # path), decode-window deltas already queued merge into one frame up
    # to this many tokens before hitting the wire — strictly less
    # per-token Python work with zero added latency (only backlog merges).
    # 0 disables (one frame per decode window).
    delta_max_tokens: int = 64
    # Optional bounded wait (ms) to gather MORE deltas per frame beyond
    # the backlog: adds up to this much inter-token latency. 0 (default)
    # never waits. Keep ≤ one decode-window duration.
    delta_max_ms: float = 0.0
    # Max prompt tokens admitted per scheduler step (prefill-vs-decode
    # fairness knob). Each admitted prompt still prefills in
    # max_prefill_tokens chunks; this budget only gates how many requests
    # join between decode windows. Too small trickle-admits under bursts —
    # every K-step window then runs at a tiny batch (measured 5x
    # throughput loss on ramp-up); too large starves running decodes.
    # 0 = admit until slots are full.
    admission_budget_tokens: int = 8192
    # Keep one decode window in flight: window w+1 is dispatched chaining
    # from w's on-device outputs before w is fetched, hiding the
    # host↔device sync roundtrip (~100 ms on tunneled TPUs). Stops are
    # then discovered one window late (≤decode_steps wasted tokens per
    # finished sequence). Full-sampler batches always run unpipelined.
    pipeline_windows: bool = True
    # Max sequences packed into one prefill dispatch (model.prefill_batch).
    # Default 1 (singles): packing existed because r3 paid a host sync per
    # admission, but async admission pipelines single-row prefills with no
    # sync — and every extra row bucket multiplies the compile lattice
    # that warmup must cover (a cold variant hit mid-run costs a ~30s
    # tunnel compile, measured as a 609-vs-890 tok/s bench regression).
    # Raise it only with a warmed cache covering the (T x Bp x W) matrix.
    prefill_batch_max: int = 1
    # Alternative-logprob width: requests asking for top_logprobs get up
    # to this many ranked alternatives; ONE static width keeps the
    # compile matrix at 2x (with/without) instead of per-N variants.
    # OpenAI caps chat top_logprobs at 20.
    top_logprobs_max: int = 8
    # KV tier stack (block_manager/tiers.py): G2 host-RAM blocks (0 = off)
    # and optional G3 disk spill directory.
    host_kv_blocks: int = 0
    disk_kv_dir: str | None = None
    disk_kv_blocks: int = 4096

    def __post_init__(self):
        if self.max_model_len % self.block_size:
            self.max_model_len = ((self.max_model_len // self.block_size) + 1) * self.block_size
        if self.max_prefill_tokens % self.block_size:
            # prefill chunks must be block-aligned (model.py scatter contract)
            self.max_prefill_tokens = (
                (self.max_prefill_tokens // self.block_size) + 1
            ) * self.block_size

    @property
    def blocks_per_seq(self) -> int:
        return self.max_model_len // self.block_size

    @property
    def prefill_buckets(self) -> tuple[int, ...]:
        # 2x stride through the common range, 4x beyond 512: prefill is
        # where the FLOPs are, and a 4x stride meant a median ShareGPT
        # prompt (~130 tok) padded to 512 — measured as ~2/3 of the 8B
        # bench's device time going to prefill padding (BENCH r5 phase
        # breakdown). Each (Bp x T x W) combination is still a separate
        # compile, so the stride widens again past 512 where real prompts
        # thin out.
        lo = min(max(self.block_size * 2, 32), self.max_prefill_tokens)
        out = []
        b = lo
        while b < self.max_prefill_tokens:
            out.append(b)
            b *= 2 if b < 512 else 4
        out.append(self.max_prefill_tokens)
        return tuple(dict.fromkeys(out))

    @property
    def decode_buckets(self) -> tuple[int, ...]:
        # Floor of 8, 4x stride: decode steps are parameter-bandwidth-
        # bound and padded rows cost ~nothing in the Pallas attention
        # path, so coarse batch buckets trade a little sampler work for
        # a much smaller compile matrix (multi_decode variants are the
        # most expensive compiles, 20-40s each on the tunnel).
        return _pow2_buckets(min(8, self.max_num_seqs), self.max_num_seqs, factor=4)

    @property
    def table_buckets(self) -> tuple[int, ...]:
        """Block-table width ladder. Decode/prefill attention cost scales
        with the table width actually passed (model.py derives W from the
        shape), so short sequences must not pay for max_model_len — each
        batch uses the smallest bucket covering its longest sequence
        (VERDICT r2 weak #3). Two buckets only: the Pallas decode kernel
        does work proportional to TRUE lengths (padded table width costs
        ~one skipped grid step per dead chunk), so a wide table is nearly
        free on TPU; the small bucket keeps short-prompt prefill (XLA
        gather path) and CPU tests cheap."""
        small = min(8, self.blocks_per_seq)
        return tuple(dict.fromkeys((small, self.blocks_per_seq)))

    def bucket_table(self, n_blocks: int) -> int:
        for b in self.table_buckets:
            if n_blocks <= b:
                return b
        raise ValueError(
            f"sequence of {n_blocks} blocks exceeds blocks_per_seq={self.blocks_per_seq}"
        )

    def bucket_prefill(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"prefill of {n} tokens exceeds max_prefill_tokens={self.max_prefill_tokens}")

    def bucket_prefill_rows(self, n: int) -> int:
        # Pow2 row ladder: steady-state admission waves are small (1-3
        # slots free per step), and padding a 2-seq wave to 8 rows cost
        # 4x its prefill compute (each padded row runs the full model).
        b = 1
        while b < min(n, self.prefill_batch_max):
            b *= 2
        return min(b, self.prefill_batch_max)

    def bucket_decode(self, n: int) -> int:
        for b in self.decode_buckets:
            if n <= b:
                return b
        raise ValueError(f"decode batch {n} exceeds max_num_seqs={self.max_num_seqs}")

    def kv_bytes_per_block(self) -> int:
        m = self.model
        itemsize = 2 if self.dtype == "bfloat16" else 4
        return 2 * m.num_layers * self.block_size * m.num_kv_heads * m.head_dim * itemsize

    def replace(self, **kw) -> "EngineArgs":
        return dataclasses.replace(self, **kw)

    @staticmethod
    def auto_kv_blocks(hbm_bytes_free: int, args: "EngineArgs", utilization: float = 0.9) -> int:
        """vLLM-style: size the G1 pool from free HBM after weights."""
        per_block = args.kv_bytes_per_block()
        n = int(hbm_bytes_free * utilization) // per_block
        return max(n, args.blocks_per_seq * 2)
