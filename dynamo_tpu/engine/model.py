"""Functional Llama-family forward with a paged KV cache, in pure JAX.

TPU-first design notes (this is the part the reference delegates to
vLLM's CUDA kernels; here it is jnp/lax built for XLA:TPU):

- All shapes static: callers pad token runs / batch sizes to buckets
  (config.py) so each (bucket, variant) compiles once.
- ``lax.scan`` over stacked layer parameters → one compiled layer body,
  fast compiles even at 80 layers; the KV cache rides the scan carry and
  is updated with ``dynamic_update_index_in_dim`` so XLA keeps it
  in-place (callers donate it).
- Paged attention is gather-based: KV pages are indexed out of the cache
  with a block table and attended densely with masking. This is the
  canonical XLA-friendly formulation; a Pallas flash/paged kernel slots
  in behind the same signature (ops/ upgrade path).
- GQA via reshape (no repeat): q [*, KVH, G, hd] against k [*, KVH, hd].
- bf16 weights/activations; norms, rope, softmax and logits in fp32.

Cache layout: k, v each ``[L, num_blocks, block_size, KVH*head_dim]``
(heads merged into lanes: the page ``[bs, KVH*hd]`` is exactly one dense
VMEM/DMA tile, so the Pallas kernel reads pages with zero layout
conversion — a 5D layout forced a whole-cache relayout copy per
pallas_call, measured ~9ms/layer on v5e). Block 0 is a reserved garbage
sink — padded positions write there.

Reference parity: replaces the engine forward of vLLM workers
(reference: components/backends/vllm/src/dynamo/vllm/main.py:90); block
semantics line up with dynamo_tpu.tokens / the reference's
lib/llm/src/tokens.rs so KV identity is consistent framework-wide.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from dynamo_tpu.engine.config import ModelConfig

Params = dict[str, Any]


class KVCache(NamedTuple):
    """Paged KV storage. With ``kv_quant="int8"`` the pages hold int8
    and a parallel per-position-per-head fp32 scale array rides along
    (``k_scale``/``v_scale`` are None for full-precision caches) — the
    same symmetric absmax scheme as engine/quant.py, at the granularity
    that keeps writes path-independent: a token's stored bytes depend
    only on its own K/V vector, never on its block's other occupants, so
    speculative-rollback junk and partial blocks cannot perturb already-
    written positions and greedy streams stay byte-stable across
    prefill/decode/spec write orders."""

    k: jax.Array  # [L, N, bs, KVH*hd]
    v: jax.Array
    k_scale: jax.Array | None = None  # [L, N, bs, KVH] fp32 — int8 only
    v_scale: jax.Array | None = None


def init_kv_cache(
    cfg: ModelConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16,
    kv_quant: str = "none",
) -> KVCache:
    shape = (cfg.num_layers, num_blocks, block_size, cfg.num_kv_heads * cfg.head_dim)
    if kv_quant == "int8":
        sshape = (cfg.num_layers, num_blocks, block_size, cfg.num_kv_heads)
        return KVCache(
            jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
            jnp.zeros(sshape, jnp.float32), jnp.zeros(sshape, jnp.float32),
        )
    return KVCache(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))


def kv_quantize(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric absmax int8 quantization of fresh K/V rows along the
    head dim: x [..., KVH, hd] float → (int8 [..., KVH, hd], fp32 scale
    [..., KVH]). Mirrors quant.py's per-channel scheme (all-zero rows
    get scale 1.0 so dequant is exact zero). Deterministic per written
    vector — the invariant every golden-stability guarantee rests on."""
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(absmax > 0, absmax, 127.0) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Random-init params (benchmarks / tests). Real checkpoints load via
    engine.loader into the same pytree."""
    d, i = cfg.hidden_size, cfg.intermediate_size
    L = cfg.num_layers
    keys = jax.random.split(key, 8)

    def norm_init(k, fan_in, shape):
        return (jax.random.normal(k, shape, jnp.float32) * (fan_in ** -0.5)).astype(dtype)

    layers: dict[str, Any] = {
        "wq": norm_init(keys[1], d, (L, d, cfg.q_size)),
        "wk": norm_init(keys[2], d, (L, d, cfg.kv_size)),
        "wv": norm_init(keys[3], d, (L, d, cfg.kv_size)),
        "wo": norm_init(keys[4], cfg.q_size, (L, cfg.q_size, d)),
        "attn_norm": jnp.ones((L, d), dtype),
        "mlp_norm": jnp.ones((L, d), dtype),
    }
    if cfg.attn_bias:
        bkey = jax.random.fold_in(key, 31)
        layers["bq"] = (jax.random.normal(bkey, (L, cfg.q_size), jnp.float32) * 0.02).astype(dtype)
        layers["bk"] = (jax.random.normal(jax.random.fold_in(bkey, 1), (L, cfg.kv_size), jnp.float32) * 0.02).astype(dtype)
        layers["bv"] = (jax.random.normal(jax.random.fold_in(bkey, 2), (L, cfg.kv_size), jnp.float32) * 0.02).astype(dtype)
    if cfg.num_experts:
        E = cfg.num_experts
        ie = cfg.moe_intermediate_size or i
        layers["w_router"] = norm_init(jax.random.fold_in(key, 7), d, (L, d, E))
        layers["moe_gate"] = norm_init(keys[5], d, (L, E, d, ie))
        layers["moe_up"] = norm_init(keys[6], d, (L, E, d, ie))
        layers["moe_down"] = norm_init(keys[7], ie, (L, E, ie, d))
    else:
        layers["w_gate"] = norm_init(keys[5], d, (L, d, i))
        layers["w_up"] = norm_init(keys[6], d, (L, d, i))
        layers["w_down"] = norm_init(keys[7], i, (L, i, d))
    params: Params = {
        "embed": norm_init(keys[0], d, (cfg.vocab_size, d)),
        "layers": layers,
        "final_norm": jnp.ones((d,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm_init(jax.random.fold_in(key, 99), d, (d, cfg.vocab_size))
    return params


def _rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    norm = xf * lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (norm * w.astype(jnp.float32)).astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding. x: [..., T, heads, hd] (or [..., heads, hd] with
    positions [...]); positions broadcast against x's leading dims."""
    hd = x.shape[-1]
    half = hd // 2
    freq = jnp.arange(half, dtype=jnp.float32)
    inv_freq = theta ** (-freq / half)
    angles = positions[..., None].astype(jnp.float32) * inv_freq  # [..., half]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :half], xf[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _dot_q(x: jax.Array, lp: dict, name: str) -> jax.Array:
    """x @ lp[name], dequantizing int8 weights on the fly. The scale is
    applied POST-matmul on the (small) output — XLA fuses the int8→bf16
    convert into the matmul operand read, so weight traffic stays 1
    byte/param (engine/quant.py; measured 2.4x on v5e)."""
    w = lp[name]
    if w.dtype == jnp.int8:
        y = jnp.dot(x, w.astype(x.dtype))
        return y * lp[name + "_scale"].astype(x.dtype)
    return jnp.dot(x, w)


def _embed_rows(params: Params, tokens: jax.Array, dtype) -> jax.Array:
    e = params["embed"][tokens]
    if e.dtype == jnp.int8:
        scale = params["embed_scale"][tokens].astype(dtype)
        return e.astype(dtype) * scale[..., None]
    return e


def _qkv(h: jax.Array, lp: dict, cfg: ModelConfig):
    """Fused-layout q/k/v projections with optional Qwen2-style bias
    (o_proj is bias-free in that family). Shapes: h [..., D] →
    ([..., q_size], [..., kv_size], [..., kv_size])."""
    q = _dot_q(h, lp, "wq")
    k = _dot_q(h, lp, "wk")
    v = _dot_q(h, lp, "wv")
    if cfg.attn_bias:
        q = q + lp["bq"].astype(q.dtype)
        k = k + lp["bk"].astype(k.dtype)
        v = v + lp["bv"].astype(v.dtype)
    return q, k, v


def _lora_apply(y: jax.Array, h: jax.Array, A: jax.Array, B_: jax.Array,
                slots: jax.Array) -> jax.Array:
    """Batched gathered LoRA matmul (Punica's BGMV shape): per batch row
    b, ``y[b] += (h[b] @ A[slots[b]]) @ B[slots[b]]``. ``A`` [S, in, r]
    and ``B_`` [S, r, out] are one layer's slice of the device adapter
    bank; ``slots`` [B] int32 names each row's resident adapter slot,
    -1 = base. The whole mixed batch rides two skinny einsums — no
    per-adapter sub-batching, which is what keeps multi-tenant batches
    at ~base throughput.

    Base rows take a ``where`` on the ORIGINAL projection values, never
    an add-of-zero (bf16 ``-0.0 + 0.0`` would flip the sign bit), so a
    base row in an adapter-mixed batch is bit-identical to the same row
    on a no-LoRA engine — the byte-identity contract the golden suite
    pins. Per-adapter alpha/rank scaling is folded into B at upload
    (engine/lora.py), so no scalar operand rides here."""
    idx = jnp.maximum(slots, 0)
    Ag = jnp.take(A, idx, axis=0)   # [B, in, r]
    Bg = jnp.take(B_, idx, axis=0)  # [B, r, out]
    if h.ndim == 2:                  # decode: h [B, in]
        t = jnp.einsum("bd,bdr->br", h, Ag)
        delta = jnp.einsum("br,bro->bo", t, Bg)
        mask = (slots >= 0)[:, None]
    else:                            # prefill / spec-verify: h [B, T, in]
        t = jnp.einsum("btd,bdr->btr", h, Ag)
        delta = jnp.einsum("btr,bro->bto", t, Bg)
        mask = (slots >= 0)[:, None, None]
    return jnp.where(mask, y + delta.astype(y.dtype), y)


def _qkv_lora(h: jax.Array, lp: dict, cfg: ModelConfig,
              ll: dict | None, slots: jax.Array | None):
    """_qkv plus the per-row adapter deltas when an adapter bank layer
    slice ``ll`` rides the dispatch (None = the exact base path)."""
    q, k, v = _qkv(h, lp, cfg)
    if ll is not None:
        q = _lora_apply(q, h, ll["qa"], ll["qb"], slots)
        k = _lora_apply(k, h, ll["ka"], ll["kb"], slots)
        v = _lora_apply(v, h, ll["va"], ll["vb"], slots)
    return q, k, v


def _wo_lora(o: jax.Array, lp: dict, ll: dict | None,
             slots: jax.Array | None) -> jax.Array:
    """o-projection with the optional per-row adapter delta."""
    y = _dot_q(o, lp, "wo")
    if ll is not None:
        y = _lora_apply(y, o, ll["oa"], ll["ob"], slots)
    return y


def _mlp(x, lp):
    g = _dot_q(x, lp, "w_gate")
    u = _dot_q(x, lp, "w_up")
    return _dot_q(jax.nn.silu(g.astype(jnp.float32)).astype(x.dtype) * u, lp, "w_down")


def _moe(x: jax.Array, lp: dict, cfg: ModelConfig) -> jax.Array:
    """Top-k routed mixture of experts over the FFN. x: [..., D].

    Expert-parallel formulation: every expert's FFN is computed for every
    token as sharded einsums over the expert axis — with experts sharded
    over the ``ep`` mesh axis each device computes only ITS experts for
    all tokens and the weighted combine is a psum XLA inserts (SPMD
    wide-EP; reference reaches this only through engine flags,
    trtllm_utils.py:140-143). Dense-over-local-experts trades FLOPs for
    perfectly regular MXU work — the standard XLA MoE shape (token-
    dropping/segment-matmul sparsity is a later Pallas upgrade)."""
    orig_shape = x.shape
    D = orig_shape[-1]
    xt = x.reshape(-1, D)
    T = xt.shape[0]
    logits = jnp.dot(xt, lp["w_router"]).astype(jnp.float32)     # [T, E]
    probs = jax.nn.softmax(logits, axis=-1)
    topv, topi = lax.top_k(probs, cfg.num_experts_per_token)
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)          # mixtral renorm
    weights = jnp.zeros_like(probs)
    weights = weights.at[jnp.arange(T)[:, None], topi].set(topv)  # [T, E] sparse
    g = jnp.einsum("td,edi->tei", xt, lp["moe_gate"])
    u = jnp.einsum("td,edi->tei", xt, lp["moe_up"])
    h = jax.nn.silu(g.astype(jnp.float32)).astype(xt.dtype) * u   # [T, E, ie]
    y = jnp.einsum("tei,te,eid->td", h, weights.astype(xt.dtype), lp["moe_down"])
    return y.reshape(orig_shape)


def _ffn(x: jax.Array, lp: dict, cfg: ModelConfig) -> jax.Array:
    return _moe(x, lp, cfg) if cfg.num_experts else _mlp(x, lp)


def _logits(cfg: ModelConfig, params: Params, x: jax.Array) -> jax.Array:
    x = _rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    if cfg.tie_embeddings:
        emb = params["embed"]
        if emb.dtype == jnp.int8:
            y = jnp.dot(x, emb.astype(x.dtype).T).astype(jnp.float32)
            return y * params["embed_scale"][None, :] if y.ndim == 2 else y * params["embed_scale"]
        return jnp.dot(x, emb.T).astype(jnp.float32)
    head = params["lm_head"]
    if head.dtype == jnp.int8:
        y = jnp.dot(x, head.astype(x.dtype)).astype(jnp.float32)
        return y * params["lm_head_scale"][None, :] if y.ndim == 2 else y * params["lm_head_scale"]
    return jnp.dot(x, head).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Prefill: one (possibly prefix-cached) sequence, padded to a length bucket
# ---------------------------------------------------------------------------


def prefill_batch_impl(
    cfg: ModelConfig,
    params: Params,
    cache: KVCache,
    tokens: jax.Array,        # [Bp, T_pad] suffix token ids per row
    block_tables: jax.Array,  # [Bp, W] int32 — blocks for each FULL sequence
    start_pos: jax.Array,     # [Bp] int32 — first suffix position (block-aligned)
    true_len: jax.Array,      # [Bp] int32 — true total length (0 = inactive row)
    lora: dict | None = None,         # adapter bank {qa..ob: [L, S, ...]}
    adapter_slots: jax.Array | None = None,  # [Bp] int32, -1 = base row
) -> tuple[jax.Array, KVCache]:
    """Packed prefill: run Bp sequences' suffixes through the model in ONE
    dispatch, each attending to its own cached prefix pages. Returns
    last-token logits [Bp, V] and the updated cache.

    One-at-a-time prefill was the r3 TTFT killer (VERDICT r3 weak #2):
    each admission paid its own dispatch and ran tiny matmuls alone.
    Packing an admission wave batches the MXU work and collapses the
    dispatch count. Rows are padded to a shared (T, W) bucket; inactive
    rows (true_len=0) write only to garbage block 0.

    Prefix caching contract per row: positions [0, start_pos) are already
    present in the blocks named by ``block_tables`` (whole blocks only);
    suffix positions [start_pos, true_len) are computed here."""
    Bp, T = tokens.shape
    W = block_tables.shape[1]
    bs = cache.k.shape[2]
    KVH, hd = cfg.num_kv_heads, cfg.head_dim
    sfx = jnp.arange(T, dtype=jnp.int32)
    suffix_positions = start_pos[:, None] + sfx[None, :]          # [Bp, T]

    compute_dtype = params["layers"]["attn_norm"].dtype
    x = _embed_rows(params, tokens, compute_dtype)  # [Bp, T, D]

    # Masks (fp32 additive), fixed for all layers.
    neg = jnp.float32(-1e9)
    # suffix→suffix causal, masked beyond each row's true length
    causal = (sfx[None, :] <= sfx[:, None]).astype(jnp.float32)   # [T, T]
    valid_sfx = (suffix_positions < true_len[:, None]).astype(jnp.float32)
    mask_ss = (1.0 - causal[None] * valid_sfx[:, None, :]) * neg  # [Bp, T, T]
    # suffix→prefix: every suffix token sees all of its row's prefix
    ctx = jnp.arange(W * bs, dtype=jnp.int32)
    mask_sp = jnp.where(ctx[None, :] < start_pos[:, None], 0.0, neg)  # [Bp, W*bs]

    # Suffix block scatter targets per row: suffix-local block j lands in
    # table slot start_pos//bs + j (start_pos is block-aligned).
    nb = T // bs
    slot = start_pos[:, None] // bs + jnp.arange(nb, dtype=jnp.int32)[None, :]
    padded_tables = jnp.concatenate(
        [block_tables, jnp.zeros((Bp, nb), jnp.int32)], axis=1
    )
    sfx_block_ids = jnp.take_along_axis(padded_tables, slot, axis=1)  # [Bp, nb]
    # Padded suffix blocks (beyond true_len) → garbage block 0.
    blk_start = start_pos[:, None] + jnp.arange(nb, dtype=jnp.int32)[None, :] * bs
    sfx_block_ids = jnp.where(blk_start < true_len[:, None], sfx_block_ids, 0)
    flat_ids = sfx_block_ids.reshape(Bp * nb)

    scale = hd ** -0.5
    G = cfg.num_heads // KVH

    from dynamo_tpu.ops.paged_attention import gather_dequant_pages

    def layer(carry, xs):
        x, k_cache, v_cache, k_scale, v_scale = carry
        if lora is not None:
            lp, ll, layer_idx = xs
        else:
            (lp, layer_idx), ll = xs, None
        h = _rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv_lora(h, lp, cfg, ll, adapter_slots)
        q = q.reshape(Bp, T, cfg.num_heads, hd)
        k = k.reshape(Bp, T, KVH, hd)
        v = v.reshape(Bp, T, KVH, hd)
        q = _rope(q, suffix_positions, cfg.rope_theta)
        k = _rope(k, suffix_positions, cfg.rope_theta)

        # Write all rows' suffix KV pages in one scatter (rows own
        # disjoint blocks; duplicates only at garbage block 0).
        # int8 storage: quantize at page-write time, scales ride a
        # parallel scatter; the suffix still self-attends its exact
        # register values below (only LATER readers see the rounding).
        if k_scale is not None:
            kq, ksc = kv_quantize(k)
            vq, vsc = kv_quantize(v)
            k_cache = k_cache.at[layer_idx, flat_ids].set(
                kq.reshape(Bp * nb, bs, KVH * hd)
            )
            v_cache = v_cache.at[layer_idx, flat_ids].set(
                vq.reshape(Bp * nb, bs, KVH * hd)
            )
            k_scale = k_scale.at[layer_idx, flat_ids].set(
                ksc.reshape(Bp * nb, bs, KVH)
            )
            v_scale = v_scale.at[layer_idx, flat_ids].set(
                vsc.reshape(Bp * nb, bs, KVH)
            )
        else:
            k_cache = k_cache.at[layer_idx, flat_ids].set(
                k.reshape(Bp * nb, bs, KVH * hd)
            )
            v_cache = v_cache.at[layer_idx, flat_ids].set(
                v.reshape(Bp * nb, bs, KVH * hd)
            )

        # Prefix pages (gathered dense, dequantized for int8 storage) +
        # suffix (already in registers).
        layer_k = lax.dynamic_index_in_dim(k_cache, layer_idx, 0, keepdims=False)
        layer_v = lax.dynamic_index_in_dim(v_cache, layer_idx, 0, keepdims=False)
        sk = sv = None
        if k_scale is not None:
            sk = lax.dynamic_index_in_dim(k_scale, layer_idx, 0, keepdims=False)
            sv = lax.dynamic_index_in_dim(v_scale, layer_idx, 0, keepdims=False)
        pk = gather_dequant_pages(layer_k, sk, block_tables, KVH, hd, x.dtype)
        pv = gather_dequant_pages(layer_v, sv, block_tables, KVH, hd, x.dtype)

        qg = q.reshape(Bp, T, KVH, G, hd)
        # scores vs prefix pages / vs own suffix
        s_p = jnp.einsum("btkgh,bckh->btkgc", qg, pk).astype(jnp.float32) * scale
        s_s = jnp.einsum("btkgh,bskh->btkgs", qg, k).astype(jnp.float32) * scale
        s_p = s_p + mask_sp[:, None, None, None, :]
        s_s = s_s + mask_ss[:, :, None, None, :]
        s = jnp.concatenate([s_p, s_s], axis=-1)
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        p_p, p_s = p[..., : W * bs], p[..., W * bs :]
        o = (
            jnp.einsum("btkgc,bckh->btkgh", p_p, pv)
            + jnp.einsum("btkgs,bskh->btkgh", p_s, v)
        )
        o = o.reshape(Bp, T, cfg.q_size)
        x = x + _wo_lora(o, lp, ll, adapter_slots)

        h = _rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _ffn(h, lp, cfg)
        return (x, k_cache, v_cache, k_scale, v_scale), None

    layer_ids = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    xs_in = (
        (params["layers"], lora, layer_ids) if lora is not None
        else (params["layers"], layer_ids)
    )
    (x, k_cache, v_cache, k_scale, v_scale), _ = lax.scan(
        layer, (x, cache.k, cache.v, cache.k_scale, cache.v_scale), xs_in,
    )

    last = jnp.clip(true_len - start_pos - 1, 0, T - 1)      # [Bp]
    x_last = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]  # [Bp, D]
    logits = _logits(cfg, params, x_last)
    return logits, KVCache(k_cache, v_cache, k_scale, v_scale)


def prefill_impl(
    cfg: ModelConfig,
    params: Params,
    cache: KVCache,
    tokens: jax.Array,       # [T_pad] suffix token ids (prompt minus cached prefix)
    block_table: jax.Array,  # [W] int32 — blocks for the FULL sequence
    start_pos: jax.Array,    # scalar int32 — first suffix position (block-aligned)
    true_len: jax.Array,     # scalar int32 — true total length (prefix + suffix)
    lora: dict | None = None,
    adapter_slot: jax.Array | None = None,  # scalar int32, -1 = base
) -> tuple[jax.Array, KVCache]:
    """Single-sequence prefill: the Bp=1 case of ``prefill_batch_impl``
    (kept as the chunked-prefill / compatibility entry point)."""
    logits, cache = prefill_batch_impl(
        cfg, params, cache,
        tokens[None, :], block_table[None, :],
        jnp.asarray(start_pos, jnp.int32).reshape(1),
        jnp.asarray(true_len, jnp.int32).reshape(1),
        lora,
        None if adapter_slot is None
        else jnp.asarray(adapter_slot, jnp.int32).reshape(1),
    )
    return logits[0], cache


# ---------------------------------------------------------------------------
# Decode: one token for each of B sequences, padded to a batch bucket
# ---------------------------------------------------------------------------


def decode_step_impl(
    cfg: ModelConfig,
    params: Params,
    cache: KVCache,
    tokens: jax.Array,        # [B] int32 — current token per sequence
    positions: jax.Array,     # [B] int32 — position of that token (seq_len-1)
    block_tables: jax.Array,  # [B, W] int32
    active: jax.Array,        # [B] bool — padding rows are False
    lora: dict | None = None,         # adapter bank {qa..ob: [L, S, ...]}
    adapter_slots: jax.Array | None = None,  # [B] int32, -1 = base row
    *,
    attn_impl: str = "auto",  # static: "auto" | "xla" | "pallas" | "pallas_interpret"
) -> tuple[jax.Array, KVCache]:
    """One decode step for a batch. Writes each sequence's new KV at its
    position, attends over its pages, returns logits [B, V] (fp32).

    Attention backend (ops/paged_attention.py): the Pallas kernel walks
    each row's true pages (work ∝ sum(lengths)); the XLA path gathers the
    padded table width (work ∝ B*W*bs) and is the CPU/multi-device
    fallback."""
    from dynamo_tpu.ops.paged_attention import (
        paged_decode_attention,
        paged_decode_attention_xla,
        resolve_attn_impl,
    )

    impl = resolve_attn_impl(attn_impl)
    B = tokens.shape[0]
    W = block_tables.shape[1]
    bs = cache.k.shape[2]

    compute_dtype = params["layers"]["attn_norm"].dtype
    x = _embed_rows(params, tokens, compute_dtype)  # [B, D]

    blk = jnp.where(active, block_tables[jnp.arange(B), positions // bs], 0)
    off = jnp.where(active, positions % bs, 0)
    # token at `positions` attends [0, positions]; inactive rows attend nothing
    lengths = jnp.where(active, positions + 1, 0)

    G = cfg.num_heads // cfg.num_kv_heads

    def layer(carry, xs):
        x, k_cache, v_cache, k_scale, v_scale = carry
        if lora is not None:
            lp, ll, layer_idx = xs
        else:
            (lp, layer_idx), ll = xs, None
        h = _rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv_lora(h, lp, cfg, ll, adapter_slots)
        q = q.reshape(B, cfg.num_heads, cfg.head_dim)
        k = k.reshape(B, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(B, cfg.num_kv_heads, cfg.head_dim)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        qg = q.reshape(B, cfg.num_kv_heads, G, cfg.head_dim)

        # In-place scatter of the new token's KV (inactive rows → garbage
        # block 0), then paged attention over [0, positions]. int8
        # storage quantizes the fresh row at write time, so this step's
        # OWN token is read back dequantized — exactly what any later
        # step would see, keeping the math write-order-independent.
        if k_scale is not None:
            kq, ksc = kv_quantize(k)
            vq, vsc = kv_quantize(v)
            k_cache = k_cache.at[layer_idx, blk, off].set(kq.reshape(B, cfg.kv_size))
            v_cache = v_cache.at[layer_idx, blk, off].set(vq.reshape(B, cfg.kv_size))
            k_scale = k_scale.at[layer_idx, blk, off].set(ksc)
            v_scale = v_scale.at[layer_idx, blk, off].set(vsc)
        else:
            k_cache = k_cache.at[layer_idx, blk, off].set(k.reshape(B, cfg.kv_size))
            v_cache = v_cache.at[layer_idx, blk, off].set(v.reshape(B, cfg.kv_size))
        if impl == "xla":
            o = paged_decode_attention_xla(
                qg, k_cache, v_cache, layer_idx, block_tables, lengths,
                k_scale, v_scale,
            )
        else:
            o = paged_decode_attention(
                qg, k_cache, v_cache, layer_idx, block_tables, lengths,
                k_scale, v_scale,
                interpret=(impl == "pallas_interpret"),
            )
        o = o.reshape(B, cfg.q_size)
        x = x + _wo_lora(o, lp, ll, adapter_slots)

        h = _rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _ffn(h, lp, cfg)
        return (x, k_cache, v_cache, k_scale, v_scale), None

    layer_ids = jnp.arange(cfg.num_layers, dtype=jnp.int32)
    xs_in = (
        (params["layers"], lora, layer_ids) if lora is not None
        else (params["layers"], layer_ids)
    )
    (x, k_cache, v_cache, k_scale, v_scale), _ = lax.scan(
        layer, (x, cache.k, cache.v, cache.k_scale, cache.v_scale), xs_in,
    )

    logits = _logits(cfg, params, x)  # [B, V]
    return logits, KVCache(k_cache, v_cache, k_scale, v_scale)


def multi_decode_impl(
    cfg: ModelConfig,
    num_steps: int,           # static — fused substep count
    mode: str,                # static — "greedy" | "simple" | "full"
    top_n: int,               # static — top-n alternative logprobs (0 = off)
    params: Params,
    cache: KVCache,
    tokens: jax.Array,        # [B] int32 — current token per sequence
    positions: jax.Array,     # [B] int32 — position of that token
    block_tables: jax.Array,  # [B, W] int32 (must cover positions+num_steps)
    active: jax.Array,        # [B] bool
    temperature: jax.Array,   # [B] fp32 (<=0 → greedy)
    seeds: jax.Array,         # [B] uint32 per-row sample seed
    steps0: jax.Array,        # [B] int32 per-row emission index of first substep
    top_k: jax.Array,         # [B] int32 (mode="full"; 0 = off)
    top_p: jax.Array,         # [B] fp32 (mode="full"; 1.0 = off)
    freq_penalty: jax.Array,  # [B] fp32 (mode="full")
    pres_penalty: jax.Array,  # [B] fp32 (mode="full")
    penalty_tokens: jax.Array,  # [B, L] int32 generated-so-far ids, -1 pad (mode="full")
    chain_mask: jax.Array | None = None,  # [B] bool — row chains from last_toks
    chain_src: jax.Array | None = None,   # [B] int32 — SLOT in last_toks
    last_toks: jax.Array | None = None,   # [slots+1] int32 — per-slot latest
                                          # sampled token (device). Fed by every
                                          # window's fold and admission samples,
                                          # in dispatch order, so a chained row
                                          # reads the newest on-device token for
                                          # its slot even with several windows
                                          # in flight (pipeline_depth > 1).
    lora: dict | None = None,             # adapter bank {qa..ob: [L, S, ...]}
    adapter_slots: jax.Array | None = None,  # [B] int32, -1 = base row
    *,
    attn_impl: str = "auto",
) -> tuple[jax.Array, jax.Array, KVCache]:
    """``num_steps`` fused decode+sample steps: sampled tokens feed back on
    device, so the host fetches once per num_steps×B tokens instead of
    per token — and with the engine's window pipeline, consecutive
    windows chain through ``last_toks`` so the device never waits for a
    host fetch either. THE latency lever when the host↔device link is slow (remote
    TPU tunnels ~100ms/roundtrip) and a dispatch saver everywhere; the
    same trick as vLLM's multi-step scheduling, expressed as lax.scan.

    Sampler modes (static → three compiled variants per shape):
    - "greedy": every row argmax; no RNG at all.
    - "simple": temperature via gumbel-max; no sort.
    - "full": frequency/presence penalties + exact top-k/top-p. Penalty
      counts start from ``penalty_tokens`` and are updated ON DEVICE with
      each sampled token, so the whole window stays fused — one request
      with sampler knobs no longer collapses the batch to per-step decode
      (VERDICT r2 weak #5).

    Rows that hit a stop condition mid-window keep generating; the host
    truncates after the sync (wasted work is bounded by num_steps).

    Returns (tokens [num_steps, B], logprobs [num_steps, B] fp32,
    top_vals [num_steps, B, top_n], top_ids [num_steps, B, top_n], cache):
    logprobs are the chosen-token log-softmax values (pre-penalty, raw
    model distribution — OpenAI reports model logprobs, not sampler-
    modified ones); top_* are the raw-distribution ranked alternatives
    (zero-sized when top_n == 0)."""
    from dynamo_tpu.engine.sampler import (
        apply_penalties,
        sample_step,
        token_counts,
        token_logprobs,
        top_k_logprobs,
    )

    B = tokens.shape[0]
    V = cfg.vocab_size
    if chain_mask is not None:
        # Window pipeline: chained rows take their input token from the
        # previous window's on-device output — composed INSIDE the jit so
        # the variant count stays fixed (an eager scatter with
        # data-dependent index counts compiled per distinct count).
        tokens = jnp.where(chain_mask, last_toks[chain_src], tokens)
    counts0 = (
        token_counts(penalty_tokens, V) if mode == "full"
        else jnp.zeros((B, 1), jnp.float32)  # unused placeholder carry
    )

    def row_gumbel(i):
        def noise(s, e):
            key = jax.random.fold_in(jax.random.PRNGKey(s), e)
            return jax.random.gumbel(key, (V,), jnp.float32)

        return jax.vmap(noise)(seeds, steps0 + i)

    def substep(carry, i):
        cache, tok, pos, counts = carry
        logits, cache = decode_step_impl(
            cfg, params, cache, tok, pos, block_tables, active,
            lora, adapter_slots, attn_impl=attn_impl,
        )
        if mode == "greedy":
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        elif mode == "simple":
            greedy = temperature < 1e-5
            temp = jnp.where(greedy, 1.0, temperature)
            scaled = logits / temp[:, None]
            noisy = jnp.where(greedy[:, None], logits, scaled + row_gumbel(i))
            nxt = jnp.argmax(noisy, axis=-1).astype(jnp.int32)
        else:
            penalized = apply_penalties(logits, counts, freq_penalty, pres_penalty)
            nxt = sample_step(penalized, temperature, top_k, top_p, row_gumbel(i))
            counts = counts.at[jnp.arange(B), nxt].add(1.0)
        logp = token_logprobs(logits, nxt)
        if top_n > 0:
            tvals, tids = top_k_logprobs(logits, top_n)
        else:
            tvals = jnp.zeros((B, 0), jnp.float32)
            tids = jnp.zeros((B, 0), jnp.int32)
        return (cache, nxt, pos + 1, counts), (nxt, logp, tvals, tids)

    (cache, _, _, _), (toks, logps, top_vals, top_ids) = lax.scan(
        substep, (cache, tokens, positions, counts0), jnp.arange(num_steps, dtype=jnp.int32)
    )
    return toks, logps, top_vals, top_ids, cache  # [num_steps, B(, top_n)]


def spec_verify_impl(
    cfg: ModelConfig,
    S1: int,                  # static — draft slots + 1 ([last, d1..dS])
    mode: str,                # static — "greedy" | "simple"
    top_n: int,               # static — top-n alternative logprobs (0 = off)
    params: Params,
    cache: KVCache,
    tokens: jax.Array,        # [B, S1] int32 — [last_token, draft_1..draft_S]
    positions0: jax.Array,    # [B] int32 — position of last_token
    draft_len: jax.Array,     # [B] int32 — true draft length per row (≤ S1-1)
    block_tables: jax.Array,  # [B, W] int32 (must cover positions0+draft_len)
    active: jax.Array,        # [B] bool
    temperature: jax.Array,   # [B] fp32 (<=0 → greedy row)
    seeds: jax.Array,         # [B] uint32 per-row sample seed
    steps0: jax.Array,        # [B] int32 per-row emission index of the first token
    tree_parents: jax.Array | None = None,  # [B, S1] int32 — tree mode (below)
    tree_anc: jax.Array | None = None,      # [B, S1, S1] int8 ancestor-or-self
    tree_depth: jax.Array | None = None,    # [B, S1] int32 per-node depth
    mask_bits: jax.Array | None = None,     # [B, S1, W32] uint32 per-node grammar masks
    lora: dict | None = None,               # adapter bank {qa..ob: [L, S, ...]}
    adapter_slots: jax.Array | None = None,  # [B] int32, -1 = base row
    *,
    fused: bool = True,       # static — single-pass forward vs stepwise scan
    attn_impl: str = "auto",  # attention backend: stepwise decode steps AND
                              # the fused path's gather (Pallas fused-gather
                              # kernel on TPU, XLA gather otherwise)
) -> tuple[jax.Array, ...]:
    """Speculative verify: score S1 consecutive positions per row in one
    dispatch. Input j writes its KV at positions0+j and position j's
    logits score the token FOLLOWING input j, exactly as
    ``decode_step_impl`` would have on the j-th sequential step.

    Two forward shapes behind the same contract:

    - ``fused=True`` (default): ONE forward over all S1 positions — the
      single weight stream that makes speculation a bandwidth win
      (tokens-per-weight-pass > 1). Mathematically identical to the
      stepwise path; floating-point reduction order in the batched
      matmuls can differ from the dense step's at the last ulp on some
      backends (greedy token streams match in practice, reported logprob
      VALUES may differ by ~1e-7).
    - ``fused=False``: a teacher-forced ``lax.scan`` of the SAME
      ``decode_step_impl`` the dense path runs — bitwise identical to
      dense decode on every backend by construction. Weights stream S1
      times, so this keeps only the dispatch/fetch saving (one host
      roundtrip per S1 tokens); it is the parity/debug mode and the
      golden suite's byte-identity anchor.

    Per-position validity: slot j of a row is live when j <= draft_len
    (slot 0, the last real token, always is). Dead slots and inactive
    rows scatter their KV to garbage block 0, and causal masking keeps
    live queries from ever seeing them. KV written for drafts BEYOND the
    accepted run is junk by construction — the engine rolls
    ``next_write_pos`` back to the acceptance boundary and the very next
    dispatch rewrites those positions (block lookahead already covers
    them), so nothing downstream observes it.

    **Tree mode** (``tree_parents`` given): the S1 slots form a draft
    TREE (SpecInfer) instead of a chain. Node j writes its KV at SLOT
    position positions0+j (slots are distinct even when depths collide),
    RoPE-rotates at its true sequence position positions0+depth[j], and
    attends paged history plus exactly its ancestor-or-self slots via
    the [S1, S1] topology mask (ops.paged_spec_attention ``anc``).
    Acceptance walks the longest accepted root path
    (sampler.spec_tree_acceptance — argmax chain for greedy rows,
    multi-round rejection sampling for sampled ones), and the accepted
    path's KV is then COMPACTED on device into contiguous positions
    positions0+1..positions0+a (non-accepted branches' writes are
    redirected to garbage block 0) — so the engine's rollback contract
    is identical to the linear path's. Tree mode always runs the fused
    forward: a branched topology has no stepwise decode-step equivalent
    (``fused=False`` is the linear parity anchor only).

    Returns (out [B, S1] emitted tokens, n_emit [B] = accepted+1,
    logps [B, S1] raw chosen-token logprobs, cand [B, S1] per-node
    argmax predictions — free Jacobi-pool food for the drafter,
    top_vals [B, S1, top_n], top_ids [B, S1, top_n], last_tok [B] =
    out[b, n_emit-1] for the chain-buffer fold, cache)."""
    from dynamo_tpu.engine.sampler import (
        spec_acceptance,
        spec_tree_acceptance,
        top_k_logprobs,
    )
    from dynamo_tpu.ops.paged_attention import (
        paged_spec_attention,
        paged_spec_attention_xla,
        resolve_attn_impl,
    )

    B, T = tokens.shape
    bs = cache.k.shape[2]
    KVH, hd = cfg.num_kv_heads, cfg.head_dim
    tree = tree_parents is not None
    slot = jnp.arange(T, dtype=jnp.int32)[None, :]
    use = active[:, None] & (slot <= draft_len[:, None])                 # [B, T]
    # Write position per slot (always slot-ordered: distinct cache slots
    # regardless of tree shape) and RoPE position per node (its true
    # sequence depth — equal to the slot index for a chain).
    wpos = positions0[:, None] + slot                                    # [B, T]
    pos = wpos if not tree else positions0[:, None] + tree_depth

    if fused or tree:
        compute_dtype = params["layers"]["attn_norm"].dtype
        x = _embed_rows(params, tokens, compute_dtype)  # [B, T, D]

        blk = jnp.where(
            use, jnp.take_along_axis(block_tables, wpos // bs, axis=1), 0
        )
        off = jnp.where(use, wpos % bs, 0)
        if tree:
            # Per-query paged-history horizon; the slot window rides on
            # top of it under the topology mask (dead queries/slots are
            # masked out of the anc bits entirely).
            lengths = jnp.where(use, positions0[:, None], 0)
            anc = (
                (tree_anc != 0) & use[:, :, None] & use[:, None, :]
            ).astype(jnp.int8)
        else:
            lengths = jnp.where(use, pos + 1, 0)  # query j attends [0, pos_j]
            anc = None

        G = cfg.num_heads // KVH
        # Fused spec-verify gather (ops.paged_spec_attention): one Pallas
        # kernel walks each row's true pages for all T queries and
        # dequantizes in-register — no materialized relayout copy of the
        # gathered table (the ~9ms/layer XLA tax). Falls back to the XLA
        # gather when the query columns exceed the 128-lane budget or the
        # backend is not TPU-like.
        impl = resolve_attn_impl(attn_impl)
        use_kernel = impl in ("pallas", "pallas_interpret") and KVH * T * G <= 128

        def layer(carry, xs):
            x, k_cache, v_cache, k_scale, v_scale = carry
            if lora is not None:
                lp, ll, layer_idx = xs
            else:
                (lp, layer_idx), ll = xs, None
            h = _rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
            q, k, v = _qkv_lora(h, lp, cfg, ll, adapter_slots)
            q = q.reshape(B, T, cfg.num_heads, hd)
            k = k.reshape(B, T, KVH, hd)
            v = v.reshape(B, T, KVH, hd)
            q = _rope(q, pos, cfg.rope_theta)
            k = _rope(k, pos, cfg.rope_theta)
            qg = q.reshape(B, T, KVH, G, hd)

            # Scatter all T new KV entries, then gather-attend: in-chunk
            # keys come back out of the pages, so query j sees inputs
            # 0..j through the same path the dense step does
            # (write-then-attend) — including the same quantization
            # rounding when the cache is int8.
            if k_scale is not None:
                kq, ksc = kv_quantize(k)
                vq, vsc = kv_quantize(v)
                k_cache = k_cache.at[layer_idx, blk.reshape(-1), off.reshape(-1)].set(
                    kq.reshape(B * T, cfg.kv_size)
                )
                v_cache = v_cache.at[layer_idx, blk.reshape(-1), off.reshape(-1)].set(
                    vq.reshape(B * T, cfg.kv_size)
                )
                k_scale = k_scale.at[layer_idx, blk.reshape(-1), off.reshape(-1)].set(
                    ksc.reshape(B * T, KVH)
                )
                v_scale = v_scale.at[layer_idx, blk.reshape(-1), off.reshape(-1)].set(
                    vsc.reshape(B * T, KVH)
                )
            else:
                k_cache = k_cache.at[layer_idx, blk.reshape(-1), off.reshape(-1)].set(
                    k.reshape(B * T, cfg.kv_size)
                )
                v_cache = v_cache.at[layer_idx, blk.reshape(-1), off.reshape(-1)].set(
                    v.reshape(B * T, cfg.kv_size)
                )
            if use_kernel:
                o = paged_spec_attention(
                    qg, k_cache, v_cache, layer_idx, block_tables, lengths,
                    k_scale, v_scale, anc,
                    interpret=(impl == "pallas_interpret"),
                )
            else:
                o = paged_spec_attention_xla(
                    qg, k_cache, v_cache, layer_idx, block_tables, lengths,
                    k_scale, v_scale, anc=anc,
                )
            o = o.reshape(B, T, cfg.q_size)
            x = x + _wo_lora(o, lp, ll, adapter_slots)

            h = _rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
            x = x + _ffn(h, lp, cfg)
            return (x, k_cache, v_cache, k_scale, v_scale), None

        layer_ids = jnp.arange(cfg.num_layers, dtype=jnp.int32)
        xs_in = (
            (params["layers"], lora, layer_ids) if lora is not None
            else (params["layers"], layer_ids)
        )
        (x, k_cache, v_cache, k_scale, v_scale), _ = lax.scan(
            layer, (x, cache.k, cache.v, cache.k_scale, cache.v_scale), xs_in,
        )
        logits = _logits(cfg, params, x)  # [B, T, V] fp32
        cache = KVCache(k_cache, v_cache, k_scale, v_scale)
    else:
        def substep(c, xs):
            tok_j, pos_j, use_j = xs
            lg, c = decode_step_impl(
                cfg, params, c, tok_j, pos_j, block_tables, use_j,
                lora, adapter_slots, attn_impl=attn_impl,
            )
            return c, lg

        cache, logits_t = lax.scan(
            substep, cache,
            (tokens.T, pos.T, use.T),
        )
        logits = jnp.transpose(logits_t, (1, 0, 2))  # [B, T, V] fp32

    if tree:
        # Grammar masks ride the tree path only: every constrained batch
        # dispatches as a tree (chains are trees), so the linear op below
        # never sees a mask. Acceptance + correction/bonus sampling then
        # renormalize over each node's LEGAL vocabulary
        # (sampler.spec_tree_acceptance) while the reported logprobs stay
        # raw-model values (OpenAI semantics), masked or not.
        out, n_emit, path, cand = spec_tree_acceptance(
            logits, tokens, tree_parents, draft_len, temperature, seeds,
            steps0, mode, mask_bits,
        )
        # Everything downstream reads PATH-ALIGNED logits: emitted token
        # k came from node path[k]'s distribution (path is clamped to
        # the stopping node past n_emit, so the gathers stay in-bounds).
        logits_out = jnp.take_along_axis(logits, path[:, :, None], axis=1)
        # KV compaction: relocate the accepted path's KV from its tree
        # slots to the contiguous positions the engine's rollback
        # contract expects (positions0+k holds the depth-k accepted
        # node); depths beyond the accepted run redirect to garbage
        # block 0. Gather-before-scatter, so aliasing (path[k] == k on
        # chain prefixes) is value-identical, and the moved bytes are
        # ~the KV the pass just wrote — noise next to the weight stream.
        kdepth = jnp.arange(1, T, dtype=jnp.int32)[None, :]       # [1, S]
        src_pos = positions0[:, None] + path[:, 1:]
        dst_pos = positions0[:, None] + kdepth
        keep = active[:, None] & (kdepth < n_emit[:, None])
        src_blk = jnp.take_along_axis(block_tables, src_pos // bs, axis=1)
        src_off = src_pos % bs
        dst_blk = jnp.where(
            keep, jnp.take_along_axis(block_tables, dst_pos // bs, axis=1), 0
        )
        dst_off = jnp.where(keep, dst_pos % bs, 0)
        k_cache, v_cache = cache.k, cache.v
        k_scale, v_scale = cache.k_scale, cache.v_scale
        k_cache = k_cache.at[:, dst_blk, dst_off].set(k_cache[:, src_blk, src_off])
        v_cache = v_cache.at[:, dst_blk, dst_off].set(v_cache[:, src_blk, src_off])
        if k_scale is not None:
            k_scale = k_scale.at[:, dst_blk, dst_off].set(
                k_scale[:, src_blk, src_off]
            )
            v_scale = v_scale.at[:, dst_blk, dst_off].set(
                v_scale[:, src_blk, src_off]
            )
        cache = KVCache(k_cache, v_cache, k_scale, v_scale)
    else:
        drafts = tokens[:, 1:]
        out, n_emit = spec_acceptance(
            logits, drafts, draft_len, temperature, seeds, steps0, mode
        )
        cand = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits_out = logits
    # Raw-distribution logprobs of the EMITTED tokens (dense parity:
    # OpenAI reports model logprobs, not sampler-modified ones).
    logz = jax.nn.logsumexp(logits_out, axis=-1)
    logps = (
        jnp.take_along_axis(logits_out, out[:, :, None], axis=-1)[..., 0] - logz
    )                                                      # [B, T]
    if top_n > 0:
        flat_vals, flat_ids = top_k_logprobs(logits_out.reshape(B * T, -1), top_n)
        top_vals = flat_vals.reshape(B, T, top_n)
        top_ids = flat_ids.reshape(B, T, top_n)
    else:
        top_vals = jnp.zeros((B, T, 0), jnp.float32)
        top_ids = jnp.zeros((B, T, 0), jnp.int32)
    last_tok = jnp.take_along_axis(out, (n_emit - 1)[:, None], axis=1)[:, 0]
    return out, n_emit, logps, cand, top_vals, top_ids, last_tok, cache


def embed_impl(
    cfg: ModelConfig,
    params: Params,
    tokens: jax.Array,    # [T_pad] int32
    true_len: jax.Array,  # scalar int32
) -> jax.Array:
    """Mean-pooled final-norm hidden state over the true tokens → [D]
    fp32. Cache-free causal forward (serves /v1/embeddings; reference:
    lib/llm/src/http/service/openai.rs:302)."""
    T = tokens.shape[0]
    compute_dtype = params["layers"]["attn_norm"].dtype
    x = _embed_rows(params, tokens, compute_dtype)  # [T, D]
    pos = jnp.arange(T, dtype=jnp.int32)
    neg = jnp.float32(-1e9)
    causal = (pos[None, :] <= pos[:, None])
    valid = pos[None, :] < true_len
    mask = jnp.where(causal & valid, 0.0, neg)  # [T, T]
    scale = cfg.head_dim ** -0.5
    G = cfg.num_heads // cfg.num_kv_heads

    def layer(x, lp):
        h = _rms_norm(x, lp["attn_norm"], cfg.rms_norm_eps)
        q, k, v = _qkv(h, lp, cfg)
        q = q.reshape(T, cfg.num_heads, cfg.head_dim)
        k = k.reshape(T, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(T, cfg.num_kv_heads, cfg.head_dim)
        q = _rope(q, pos, cfg.rope_theta)
        k = _rope(k, pos, cfg.rope_theta)
        qg = q.reshape(T, cfg.num_kv_heads, G, cfg.head_dim)
        s = jnp.einsum("tkgh,skh->tkgs", qg, k).astype(jnp.float32) * scale
        s = s + mask[:, None, None, :]
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        o = jnp.einsum("tkgs,skh->tkgh", p, v).reshape(T, cfg.q_size)
        x = x + _dot_q(o, lp, "wo")
        h = _rms_norm(x, lp["mlp_norm"], cfg.rms_norm_eps)
        x = x + _ffn(h, lp, cfg)
        return x, None

    x, _ = lax.scan(layer, x, params["layers"])
    x = _rms_norm(x, params["final_norm"], cfg.rms_norm_eps).astype(jnp.float32)
    w = (pos < true_len).astype(jnp.float32)[:, None]
    return jnp.sum(x * w, axis=0) / jnp.maximum(true_len.astype(jnp.float32), 1.0)


# Jitted entry points (static model config / step count, donated cache).
prefill = functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))(prefill_impl)
prefill_batch = functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))(prefill_batch_impl)
decode_step = functools.partial(
    jax.jit, static_argnums=(0,), static_argnames=("attn_impl",), donate_argnums=(2,)
)(decode_step_impl)
multi_decode = functools.partial(
    jax.jit, static_argnums=(0, 1, 2, 3), static_argnames=("attn_impl",), donate_argnums=(5,)
)(multi_decode_impl)
spec_verify = functools.partial(
    jax.jit, static_argnums=(0, 1, 2, 3),
    static_argnames=("fused", "attn_impl"), donate_argnums=(5,)
)(spec_verify_impl)
embed = functools.partial(jax.jit, static_argnums=(0,))(embed_impl)
