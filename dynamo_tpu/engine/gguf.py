"""GGUF checkpoint ingestion: metadata, tensors, tokenizer.

Reference analogue: the reference's GGUF support (reference:
lib/llm/src/gguf/{mod,content}.rs — metadata + tokenizer parsing feeding
ModelDeploymentCard and the mistralrs/llamacpp engines). Here GGUF feeds
the SAME engine pytree as safetensors (engine/loader.py): a llama-family
GGUF file becomes (ModelConfig, params) + a tokenizers-backed Tokenizer,
so `--model-path model.gguf` serves exactly like an HF directory.

Format (GGUF v2/v3, little-endian):
  magic "GGUF" | u32 version | u64 n_tensors | u64 n_kv
  n_kv x (string key | u32 type | value)       -- metadata
  n_tensors x (string name | u32 n_dims | u64 dims[] | u32 ggml_type
               | u64 offset)                   -- tensor directory
  padding to `general.alignment` (default 32)  -- then tensor data

ggml dims are fastest-axis-first; reading row-major therefore yields the
REVERSED numpy shape, which for weight matrices is (out, in) — the same
orientation as HF *.weight tensors, so the loader transposes identically.

Quantized tensors: Q8_0 (32-element blocks: f16 scale + 32xi8) is
dequantized on the host; F16/BF16/F32 load directly. Other ggml quants
are rejected with a clear error (serve those via --quant int8 on a
F16/F32 export instead).
"""

from __future__ import annotations

import struct
from typing import Any, BinaryIO

import numpy as np

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("gguf")

GGUF_MAGIC = b"GGUF"

# metadata value types
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32, _T_F32, _T_BOOL = range(8)
_T_STRING, _T_ARRAY, _T_U64, _T_I64, _T_F64 = range(8, 13)

_SCALAR_FMT = {
    _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
    _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_BOOL: "<B",
    _T_U64: "<Q", _T_I64: "<q", _T_F64: "<d",
}

# ggml tensor types (ggml.h)
GGML_F32, GGML_F16 = 0, 1
GGML_Q8_0 = 8
GGML_BF16 = 30
_TYPE_NAMES = {GGML_F32: "F32", GGML_F16: "F16", GGML_Q8_0: "Q8_0", GGML_BF16: "BF16"}


def _read_str(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype == _T_STRING:
        return _read_str(f)
    if vtype == _T_ARRAY:
        (etype,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        if etype == _T_STRING:
            return [_read_str(f) for _ in range(count)]
        fmt = _SCALAR_FMT[etype]
        size = struct.calcsize(fmt)
        raw = f.read(size * count)
        vals = [struct.unpack_from(fmt, raw, i * size)[0] for i in range(count)]
        if etype == _T_BOOL:
            vals = [bool(v) for v in vals]
        return vals
    fmt = _SCALAR_FMT[vtype]
    (v,) = struct.unpack(fmt, f.read(struct.calcsize(fmt)))
    return bool(v) if vtype == _T_BOOL else v


class GGUFTensorInfo:
    __slots__ = ("name", "shape", "ggml_type", "offset")

    def __init__(self, name: str, shape: tuple[int, ...], ggml_type: int, offset: int):
        self.name = name
        self.shape = shape          # numpy shape (ggml dims reversed)
        self.ggml_type = ggml_type
        self.offset = offset        # relative to data-section start


class GGUFFile:
    """Parsed GGUF: metadata dict + tensor directory + lazy tensor reads."""

    def __init__(self, path: str):
        self.path = path
        self.metadata: dict[str, Any] = {}
        self.tensors: dict[str, GGUFTensorInfo] = {}
        with open(path, "rb") as f:
            if f.read(4) != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file")
            (version,) = struct.unpack("<I", f.read(4))
            if version not in (2, 3):
                raise ValueError(f"{path}: unsupported GGUF version {version}")
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
            for _ in range(n_kv):
                key = _read_str(f)
                (vtype,) = struct.unpack("<I", f.read(4))
                self.metadata[key] = _read_value(f, vtype)
            for _ in range(n_tensors):
                name = _read_str(f)
                (n_dims,) = struct.unpack("<I", f.read(4))
                dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
                ggml_type, offset = struct.unpack("<IQ", f.read(4 + 8))
                self.tensors[name] = GGUFTensorInfo(
                    name, tuple(reversed(dims)), ggml_type, offset
                )
            align = int(self.metadata.get("general.alignment", 32))
            pos = f.tell()
            self._data_start = (pos + align - 1) // align * align

    # -- tensor reads ------------------------------------------------------

    def tensor(self, name: str) -> np.ndarray:
        """Read + dequantize one tensor (host numpy, fp32 for quantized)."""
        import ml_dtypes

        info = self.tensors.get(name)
        if info is None:
            raise KeyError(f"{self.path}: missing tensor {name!r}")
        n = int(np.prod(info.shape))
        with open(self.path, "rb") as f:
            f.seek(self._data_start + info.offset)
            if info.ggml_type == GGML_F32:
                a = np.frombuffer(f.read(4 * n), np.float32)
            elif info.ggml_type == GGML_F16:
                a = np.frombuffer(f.read(2 * n), np.float16)
            elif info.ggml_type == GGML_BF16:
                a = np.frombuffer(f.read(2 * n), ml_dtypes.bfloat16)
            elif info.ggml_type == GGML_Q8_0:
                if n % 32:
                    raise ValueError(f"{name}: Q8_0 tensor size {n} not /32")
                raw = np.frombuffer(f.read(34 * (n // 32)), np.uint8).reshape(-1, 34)
                scale = raw[:, :2].copy().view(np.float16).astype(np.float32)  # [nb, 1]
                qs = raw[:, 2:].view(np.int8).astype(np.float32)               # [nb, 32]
                a = (qs * scale).reshape(-1)
            else:
                tname = _TYPE_NAMES.get(info.ggml_type, str(info.ggml_type))
                raise NotImplementedError(
                    f"{name}: ggml type {tname} not supported — re-export as "
                    f"F16/BF16/F32 (serve quantized via --quant int8)"
                )
        return a.reshape(info.shape)

    # -- metadata → ModelConfig -------------------------------------------

    def model_config(self, name: str | None = None) -> ModelConfig:
        md = self.metadata
        arch = md.get("general.architecture", "llama")
        if arch not in ("llama", "mistral", "qwen2"):
            log.warning("untested GGUF architecture %r — loading with llama layout", arch)

        def k(suffix: str, default=None):
            return md.get(f"{arch}.{suffix}", default)

        hidden = int(k("embedding_length"))
        heads = int(k("attention.head_count"))
        head_dim = int(k("attention.key_length") or hidden // heads)
        vocab = md.get(f"{arch}.vocab_size")
        if vocab is None:
            vocab = len(md.get("tokenizer.ggml.tokens", []))
            if not vocab:
                raise ValueError("GGUF missing vocab_size and tokenizer tokens")
        tied = "output.weight" not in self.tensors
        # Qwen2 GGUFs carry QKV bias tensors; detect from the tensor list
        # (no metadata flag exists).
        attn_bias = "blk.0.attn_q.bias" in self.tensors
        return ModelConfig(
            name=name or md.get("general.name") or "gguf-model",
            vocab_size=int(vocab),
            hidden_size=hidden,
            intermediate_size=int(k("feed_forward_length")),
            num_layers=int(k("block_count")),
            num_heads=heads,
            num_kv_heads=int(k("attention.head_count_kv") or heads),
            head_dim=head_dim,
            rope_theta=float(k("rope.freq_base", 10000.0)),
            rms_norm_eps=float(k("attention.layer_norm_rms_epsilon", 1e-5)),
            max_position=int(k("context_length", 8192)),
            tie_embeddings=tied,
            attn_bias=attn_bias,
        )

    def eos_token_ids(self) -> list[int]:
        out = []
        for key in ("tokenizer.ggml.eos_token_id",):
            v = self.metadata.get(key)
            if v is not None:
                out.append(int(v))
        return out


# ---------------------------------------------------------------------------
# params pytree
# ---------------------------------------------------------------------------

_LAYER_MAP = {
    # ours → gguf name fmt (numpy shape (out, in) → transpose, like HF)
    "wq": ("blk.{i}.attn_q.weight", True),
    "wk": ("blk.{i}.attn_k.weight", True),
    "wv": ("blk.{i}.attn_v.weight", True),
    "wo": ("blk.{i}.attn_output.weight", True),
    "w_gate": ("blk.{i}.ffn_gate.weight", True),
    "w_up": ("blk.{i}.ffn_up.weight", True),
    "w_down": ("blk.{i}.ffn_down.weight", True),
    "attn_norm": ("blk.{i}.attn_norm.weight", False),
    "mlp_norm": ("blk.{i}.ffn_norm.weight", False),
}


def load_gguf_params(
    g: GGUFFile,
    cfg: ModelConfig,
    dtype: Any = None,
    sharding=None,
    quant: str = "none",
):
    """GGUF tensors → the engine params pytree on device (same contract
    as loader.load_params; placement via loader.finalize_params)."""
    from dynamo_tpu.engine.loader import finalize_params

    consumed: set[str] = set()

    def take(name: str) -> np.ndarray:
        consumed.add(name)
        return g.tensor(name)

    def stack(fmt: str, transpose: bool) -> np.ndarray:
        per = [take(fmt.format(i=i)) for i in range(cfg.num_layers)]
        return np.stack([p.T if transpose else p for p in per])

    params: dict[str, Any] = {
        "embed": take("token_embd.weight"),
        "layers": {
            ours: stack(fmt, tr) for ours, (fmt, tr) in _LAYER_MAP.items()
        },
        "final_norm": take("output_norm.weight"),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = take("output.weight").T
    if cfg.attn_bias:
        params["layers"]["bq"] = stack("blk.{i}.attn_q.bias", False)
        params["layers"]["bk"] = stack("blk.{i}.attn_k.bias", False)
        params["layers"]["bv"] = stack("blk.{i}.attn_v.bias", False)

    leftovers = sorted(set(g.tensors) - consumed)
    biases = [n for n in leftovers if n.endswith(".bias")]
    if biases:
        # Silently dropping OTHER projection biases would serve garbage
        # logits with no diagnostic (QKV bias is handled above).
        raise NotImplementedError(
            f"GGUF has {len(biases)} unsupported bias tensors (e.g. "
            f"{biases[0]})"
        )
    if leftovers:
        log.warning("ignoring %d unexpected GGUF tensors (e.g. %s)",
                    len(leftovers), leftovers[:3])

    expect = {
        "embed": (cfg.vocab_size, cfg.hidden_size),
        ("layers", "wq"): (cfg.num_layers, cfg.hidden_size, cfg.q_size),
        ("layers", "w_down"): (cfg.num_layers, cfg.intermediate_size, cfg.hidden_size),
    }
    for key, shape in expect.items():
        leaf = params[key] if isinstance(key, str) else params[key[0]][key[1]]
        if tuple(leaf.shape) != shape:
            raise ValueError(f"{key}: GGUF shape {tuple(leaf.shape)} != expected {shape}")

    return finalize_params(params, dtype=dtype, sharding=sharding, quant=quant)


def load_gguf_model(path: str, dtype: Any = None, sharding=None, quant: str = "none"):
    """→ (ModelConfig, params) from a .gguf file."""
    g = GGUFFile(path)
    cfg = g.model_config()
    params = load_gguf_params(g, cfg, dtype=dtype, sharding=sharding, quant=quant)
    log.info("loaded %s: %.2fB params from GGUF %s", cfg.name, cfg.param_count() / 1e9, path)
    return cfg, params


# ---------------------------------------------------------------------------
# tokenizer
# ---------------------------------------------------------------------------


def tokenizer_from_gguf(g: GGUFFile):
    """GGUF tokenizer metadata → a `tokenizers.Tokenizer`-backed wrapper
    satisfying llm.tokenizer.Tokenizer (reference: gguf tokenizer parse
    feeding the HF tokenizers type, lib/llm/src/gguf/).

    - model "gpt2": byte-level BPE from tokens + merges.
    - model "llama": SentencePiece-style vocab with scores → Unigram with
      byte fallback + metaspace, the transformers SP→tokenizers mapping
      (byte tokens <0xNN> must decode to bytes, unseen chars must encode
      through them, and add_bos_token must prepend BOS like the HF
      tokenizer.json post-processor does).
    """
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, processors

    md = g.metadata
    tokens: list[str] = md.get("tokenizer.ggml.tokens") or []
    if not tokens:
        raise ValueError("GGUF has no tokenizer.ggml.tokens")
    kind = md.get("tokenizer.ggml.model", "llama")
    bos_id = md.get("tokenizer.ggml.bos_token_id")
    if kind == "gpt2":
        vocab = {t: i for i, t in enumerate(tokens)}
        merges = [tuple(m.split(" ", 1)) for m in md.get("tokenizer.ggml.merges") or []]
        tok = Tokenizer(models.BPE(vocab=vocab, merges=merges, fuse_unk=False))
        tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
        tok.decoder = decoders.ByteLevel()
        add_bos = bool(md.get("tokenizer.ggml.add_bos_token", False))
    elif kind in ("llama", "spm"):
        scores = md.get("tokenizer.ggml.scores") or [0.0] * len(tokens)
        unk = int(md.get("tokenizer.ggml.unknown_token_id", 0))
        tok = Tokenizer(models.Unigram(list(zip(tokens, scores)), unk_id=unk,
                                       byte_fallback=True))
        tok.pre_tokenizer = pre_tokenizers.Metaspace(replacement="▁")
        tok.decoder = decoders.Sequence([
            decoders.Replace("▁", " "),
            decoders.ByteFallback(),
            decoders.Fuse(),
            decoders.Strip(content=" ", left=1),
        ])
        # SentencePiece llama convention: BOS on unless metadata says off.
        add_bos = bool(md.get("tokenizer.ggml.add_bos_token", True))
    else:
        raise NotImplementedError(f"GGUF tokenizer model {kind!r}")
    if add_bos and bos_id is not None:
        bos_tok = tokens[int(bos_id)]
        tok.post_processor = processors.TemplateProcessing(
            single=f"{bos_tok} $A",
            pair=f"{bos_tok} $A {bos_tok} $B",
            special_tokens=[(bos_tok, int(bos_id))],
        )

    from dynamo_tpu.llm.tokenizer import RawTokenizer

    special = [
        i for i in (
            md.get("tokenizer.ggml.bos_token_id"),
            md.get("tokenizer.ggml.eos_token_id"),
            md.get("tokenizer.ggml.padding_token_id"),
        ) if i is not None
    ]
    return RawTokenizer(tok, eos_ids=g.eos_token_ids() or [0], special_ids=special)
