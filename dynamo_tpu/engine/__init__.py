"""TPU-native LLM inference engine.

This is the subsystem the reference *delegates* to vLLM/SGLang/TRT-LLM
(reference: components/backends/vllm/src/dynamo/vllm/main.py:90); here it
is built in-repo, TPU-first:

- pure-functional Llama-family forward in JAX (jnp + lax.scan over
  layers), bf16 on the MXU, static shapes via bucketing;
- paged KV cache as device arrays, written/read with vectorized
  scatter/gather (Pallas kernels are a drop-in upgrade path);
- a continuous-batching scheduler (host-side, outside jit) driving jitted
  prefill/decode steps with donated cache buffers;
- prefix caching through the block manager's sequence-hash reuse, which
  also emits the KV events that feed KV-aware routing.
"""

from dynamo_tpu.engine.config import EngineArgs, ModelConfig
from dynamo_tpu.engine.engine import TpuEngine

__all__ = ["EngineArgs", "ModelConfig", "TpuEngine"]
