"""TPU-native LLM inference engine.

This is the subsystem the reference *delegates* to vLLM/SGLang/TRT-LLM
(reference: components/backends/vllm/src/dynamo/vllm/main.py:90); here it
is built in-repo, TPU-first:

- pure-functional Llama-family forward in JAX (jnp + lax.scan over
  layers), bf16 on the MXU, static shapes via bucketing;
- paged KV cache as device arrays, written/read with vectorized
  scatter/gather (Pallas kernels are a drop-in upgrade path);
- a continuous-batching scheduler (host-side, outside jit) driving jitted
  prefill/decode steps with donated cache buffers;
- prefix caching through the block manager's sequence-hash reuse, which
  also emits the KV events that feed KV-aware routing.
"""

from dynamo_tpu.engine.config import EngineArgs, ModelConfig

__all__ = ["EngineArgs", "ModelConfig", "TpuEngine"]


def __getattr__(name: str):
    # Deferred (PEP 562): engine/engine.py imports transfer.stream, and
    # transfer.stream imports engine.kv_transfer — an eager TpuEngine
    # import here closes that loop and makes `import dynamo_tpu.transfer`
    # (or llm.disagg) fail unless the engine was imported first.
    if name == "TpuEngine":
        from dynamo_tpu.engine.engine import TpuEngine

        return TpuEngine
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
