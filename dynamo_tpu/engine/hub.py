"""Model-name resolution: local path | HF-hub name | GGUF file.

Reference analogue: hub download + model resolution (reference:
lib/llm/src/hub.rs:126 `from_hf`, local_model.rs:39-100) — the reference
resolves `org/repo` through the HF hub cache and downloads when absent.
Here the same resolution order applies:

  1. an existing local path (directory or .gguf file) wins;
  2. `org/repo` is looked up in the HF hub cache
     (``$HF_HUB_CACHE`` | ``$HF_HOME/hub`` | ``~/.cache/huggingface/hub``,
     layout ``models--org--repo/snapshots/<commit>``) — the standard
     cache other tools populate;
  3. if absent and `huggingface_hub` is importable, it is downloaded
     (honors ``HF_HUB_OFFLINE``); otherwise a clear error explains how
     to pre-populate the cache (this image is zero-egress).
"""

from __future__ import annotations

import os
import re

from dynamo_tpu.runtime.logging import get_logger

log = get_logger("hub")

_HUB_NAME = re.compile(r"^[\w.-]+/[\w.-]+$")


def hub_cache_dir() -> str:
    if os.environ.get("HF_HUB_CACHE"):
        return os.environ["HF_HUB_CACHE"]
    if os.environ.get("HF_HOME"):
        return os.path.join(os.environ["HF_HOME"], "hub")
    return os.path.expanduser("~/.cache/huggingface/hub")


def _cached_snapshot(name: str, revision: str | None = None) -> str | None:
    """→ snapshot dir for a cached `org/repo`, or None.

    A pinned `revision` either resolves exactly or fails — silently
    serving different weights than pinned is never acceptable. The
    any-snapshot fallback applies only when nothing was pinned and the
    cache has no refs/main."""
    repo_dir = os.path.join(hub_cache_dir(), "models--" + name.replace("/", "--"))
    snaps = os.path.join(repo_dir, "snapshots")
    if not os.path.isdir(snaps):
        return None
    pinned = revision is not None
    if revision is None:
        # refs/main records the snapshot commit the way the hub cache does.
        ref = os.path.join(repo_dir, "refs", "main")
        if os.path.exists(ref):
            with open(ref) as f:
                revision = f.read().strip()
    if revision:
        cand = os.path.join(snaps, revision)
        if os.path.isdir(cand):
            return cand
        if pinned:
            # NOT a silent-fallback candidate: a pinned revision either
            # resolves exactly here or goes to the downloader (which
            # fetches exactly that revision) — never another snapshot.
            log.info("%s@%s not cached (have %s)", name, revision,
                     sorted(os.listdir(snaps)))
            return None
        log.warning("%s: refs/main points at missing snapshot %s", name, revision)
    commits = os.listdir(snaps)
    if commits:  # nothing pinned: any snapshot (newest mtime)
        commits.sort(key=lambda c: os.path.getmtime(os.path.join(snaps, c)))
        return os.path.join(snaps, commits[-1])
    return None


def is_gguf(path: str) -> bool:
    if path.endswith(".gguf") and os.path.isfile(path):
        return True
    if os.path.isfile(path):
        try:
            with open(path, "rb") as f:
                return f.read(4) == b"GGUF"
        except OSError:
            return False
    return False


def resolve_model(name_or_path: str, revision: str | None = None) -> str:
    """→ a local checkpoint path (HF directory or .gguf file).

    Raises FileNotFoundError with remediation steps when the name cannot
    be resolved offline and no downloader is available."""
    if os.path.exists(name_or_path):
        return name_or_path
    if not _HUB_NAME.match(name_or_path):
        raise FileNotFoundError(
            f"model path {name_or_path!r} does not exist and is not an "
            f"org/repo hub name"
        )
    cached = _cached_snapshot(name_or_path, revision)
    if cached is not None:
        log.info("resolved %s from hub cache: %s", name_or_path, cached)
        return cached
    pin = f"@{revision}" if revision else ""
    remedy = (
        f"{name_or_path}{pin!s} is not in the hub cache ({hub_cache_dir()}) — "
        f"pre-populate the cache (`huggingface-cli download {name_or_path}` "
        f"on a connected machine, then ship $HF_HOME) or pass a local path"
    )
    if os.environ.get("HF_HUB_OFFLINE") in ("1", "ON", "YES", "TRUE"):
        raise FileNotFoundError(remedy + " (HF_HUB_OFFLINE is set)")
    try:
        from huggingface_hub import snapshot_download  # type: ignore[import-not-found]
    except ImportError:
        raise FileNotFoundError(remedy) from None
    log.info("downloading %s from the hub", name_or_path)
    try:
        return snapshot_download(name_or_path, revision=revision)
    except Exception as e:  # noqa: BLE001 — zero-egress / auth / 404
        raise FileNotFoundError(f"hub download failed ({e}); {remedy}") from e
