"""LoRA adapters for the TPU engine: host-side weight generation and the
page format adapters travel in.

Serving model (Punica arXiv 2310.18547 + S-LoRA arXiv 2311.03285 mapped
onto this engine): hundreds of per-customer low-rank fine-tunes of ONE
base model share one engine. Each batch row carries an ``adapter_slot``
index into a device-resident adapter bank and the q/k/v/o projections add
``(h @ A[slot]) @ B[slot]`` via a batched gathered matmul (BGMV) — mixed
batches pay one gather + two skinny matmuls per projection, so adapter
traffic rides the SAME prefill/decode/spec-verify dispatches at near-base
throughput instead of forking per-adapter batches.

The bank holds ``lora_slots`` resident adapters (G1, HBM); the full
adapter population lives as *paged objects* in the block-manager tier
economy (S-LoRA's unified paging): an adapter's weights pack into one
page tuple (``adapter_pages``) keyed by a synthetic sequence hash
(``adapter_tier_hash``) and stored in the SAME G2 host / G3 disk pools as
KV blocks, competing under the same second-chance eviction credits.
Cold-adapter admission pages in from the tiers (or regenerates /
reloads from source), uploads into a slot chosen by the slot pool's
second-chance policy (block_manager/adapters.py), and pays nothing on the
running batch — eviction is free because registration wrote the pages
through to the tiers up front.

Rank is static per bank (``EngineArgs.lora_rank``): adapters declaring a
smaller rank zero-pad their A/B factors, so every dispatch shape stays in
the compiled lattice. The per-adapter scaling (alpha / rank) is folded
into B at registration time — the device math carries no per-adapter
scalars.

Base rows: ``adapter_slot = -1``. The model applies the delta under a
``jnp.where`` row mask (never an add-of-zero, which could flip a -0.0),
so base rows in an adapter-mixed batch are bit-identical to a no-LoRA
engine — the byte-identity contract tests/test_engine_lora.py pins.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from dynamo_tpu.engine.config import ModelConfig
from dynamo_tpu.tokens import HASH_SEED

import xxhash

# Projection targets a LoRA adapter may attach to, in bank order. The
# bank always carries all four (absent targets are zero factors) so the
# dispatch shape is target-independent.
LORA_TARGETS = ("q", "k", "v", "o")

# Bank array names in page order: (A, B) per target. adapter_pages()
# and AdapterBank uploads rely on this exact ordering.
LORA_PAGE_KEYS = tuple(
    f"{t}{ab}" for t in LORA_TARGETS for ab in ("a", "b")
)


class LoraError(Exception):
    """Typed adapter-registry failure (unknown adapter, rank overflow)."""


@dataclass(frozen=True)
class LoraAdapterSpec:
    """One registered adapter: identity + how to (re)materialize it.

    ``seed``-based adapters generate deterministic random factors (the
    bench/test source; real checkpoints plug in through ``pages`` at
    registration). ``scaling`` is the classic alpha/rank multiplier,
    folded into B before upload."""

    name: str
    rank: int
    seed: int = 0
    scaling: float = 1.0
    targets: str = "qkvo"


def adapter_tier_hash(name: str) -> int:
    """Synthetic sequence hash an adapter's page tuple is keyed by in the
    G2/G3 tiers. Domain-separated from token-block hashes (which hash
    packed u32 token ids) by the ``lora:`` prefix over raw bytes."""
    return xxhash.xxh3_64_intdigest(b"lora:" + name.encode(), seed=HASH_SEED)


def _target_dims(cfg: ModelConfig, target: str) -> tuple[int, int]:
    """(fan_in, fan_out) of one projection target."""
    d = cfg.hidden_size
    return {
        "q": (d, cfg.q_size),
        "k": (d, cfg.kv_size),
        "v": (d, cfg.kv_size),
        "o": (cfg.q_size, d),
    }[target]


def make_adapter_pages(
    cfg: ModelConfig, spec: LoraAdapterSpec, max_rank: int, dtype=np.float32,
) -> tuple[np.ndarray, ...]:
    """Materialize one adapter as its page tuple: per LORA_TARGETS order,
    (A [L, in, max_rank], B [L, max_rank, out]) float arrays. Factors are
    deterministic in (name, seed); ranks below ``max_rank`` zero-pad (a
    zero A/B column pair contributes exactly nothing), absent targets are
    all-zero. Scaling is folded into B here. Classic LoRA initializes B
    to zero (identity at step 0); these generated adapters draw BOTH
    factors so tests/benches observe distinct per-adapter outputs —
    checkpoint loaders hand real factors to the same page layout."""
    if spec.rank > max_rank:
        raise LoraError(
            f"adapter {spec.name!r} rank {spec.rank} exceeds the bank's "
            f"lora_rank={max_rank}"
        )
    L = cfg.num_layers
    r = spec.rank
    root = np.random.default_rng(
        xxhash.xxh3_64_intdigest(spec.name.encode(), seed=spec.seed & 0x7FFFFFFF)
    )
    pages: list[np.ndarray] = []
    for t in LORA_TARGETS:
        fan_in, fan_out = _target_dims(cfg, t)
        A = np.zeros((L, fan_in, max_rank), dtype)
        B = np.zeros((L, max_rank, fan_out), dtype)
        if t in spec.targets:
            A[:, :, :r] = (root.standard_normal((L, fan_in, r)) * fan_in ** -0.5).astype(dtype)
            B[:, :r, :] = (
                root.standard_normal((L, r, fan_out)) * (0.5 * r ** -0.5) * spec.scaling
            ).astype(dtype)
        pages.append(A)
        pages.append(B)
    return tuple(pages)


def bank_shapes(cfg: ModelConfig, slots: int, max_rank: int) -> dict[str, tuple]:
    """Device adapter-bank array shapes, keyed like LORA_PAGE_KEYS:
    A factors [L, slots, in, rank], B factors [L, slots, rank, out].
    Layer-leading so the model's lax.scan splits the bank per layer."""
    shapes: dict[str, tuple] = {}
    for t in LORA_TARGETS:
        fan_in, fan_out = _target_dims(cfg, t)
        shapes[f"{t}a"] = (cfg.num_layers, slots, fan_in, max_rank)
        shapes[f"{t}b"] = (cfg.num_layers, slots, max_rank, fan_out)
    return shapes


def adapter_bank_bytes(cfg: ModelConfig, slots: int, max_rank: int,
                       itemsize: int = 2) -> int:
    """HBM bytes of the device adapter bank (all targets, both factors) —
    the G1 footprint the slot count buys."""
    per_slot = 0
    for t in LORA_TARGETS:
        fan_in, fan_out = _target_dims(cfg, t)
        per_slot += cfg.num_layers * max_rank * (fan_in + fan_out)
    return slots * per_slot * itemsize
