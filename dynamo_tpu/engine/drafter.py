"""Host-side draft-token proposers for speculative decoding.

The engine's verify pass (model.spec_verify) scores any proposed draft
in one weight stream; WHERE drafts come from is pluggable behind the
``Drafter`` interface. Backends:

- ``NgramDrafter`` — n-gram prompt lookup (Saxena 2023, "Prompt Lookup
  Decoding"): match the sequence's trailing n-gram against its own
  prompt+generated history and propose the continuation of the most
  recent earlier occurrence. Zero model cost, zero RNG draws, and
  exactly the TPU-native shape — the expensive half (verification) runs
  on device while drafting is a dict lookup on the host.
- ``TreeDrafter`` — token TREES (SpecInfer, Miao et al. 2023): where
  the per-sequence index holds SEVERAL distinct continuations of the
  same n-gram context, the draft branches instead of committing to one;
  a single topology-masked verify pass then scores every path for the
  price of one weight stream, so expected accepted tokens per pass
  strictly dominates any single linear draft of the same node budget.
  It also carries a Lookahead-style (Fu et al. 2024, arXiv:2402.02057)
  **Jacobi n-gram pool**: every verify pass computes, for free, the
  model's own predicted next token at EVERY tree node — (context,
  prediction) pairs harvested from those logits seed a per-sequence
  candidate pool that drafts on generic traffic with zero history hits
  (the history index only fires once the sequence repeats itself).

A draft-model backend (small model proposing tokens, Leviathan et al.
2023) slots in behind the same methods; its ``draft`` would dispatch
device work, which is why the interface takes the whole token list
rather than a delta.

State is PER SEQUENCE (``new_state``) and fed incrementally: ``draft``
absorbs tokens appended since the last call before matching, so the
steady-state cost is O(new tokens), not O(history). Preemption-by-
recompute keeps ``seq.tokens`` intact, so drafter state survives it
unchanged.
"""

from __future__ import annotations

# Occurrence positions retained per n-gram context: the most recent
# MAX_OCC ends. The linear drafter only ever reads the newest; the tree
# drafter branches over the distinct continuations these ends name.
MAX_OCC = 8
# Jacobi pool bounds: contexts tracked per sequence and candidate
# continuations per context (hit-count-evicted). Small on purpose — the
# pool is a recency cache of the model's own predictions, not an index.
POOL_MAX_CONTEXTS = 512
POOL_MAX_CANDS = 4


class DraftConstraint:
    """Grammar hook for constrained drafting (duck-typed; the engine
    passes a token-FSM adapter). ``state`` is the FSM state at the draft
    ROOT (after every emitted token); ``step`` returns the successor
    state for a legal continuation or None; ``forced`` names the single
    legal continuation of a non-terminal state (or None). An illegal
    draft node can never be accepted — the verify mask zeroes it — so
    pruning to legal continuations is pure win, and a forced token is
    draftable with CERTAINTY (no model signal needed): JSON structure
    (braces, keys, separators) fast-forwards through the draft for
    free."""

    __slots__ = ("state", "step", "forced")

    def __init__(self, state, step, forced):
        self.state = state
        self.step = step
        self.forced = forced


def constrain_chain(draft: list[int], constraint: DraftConstraint,
                    budget: int) -> list[int]:
    """Linear-draft constraint filter: truncate at the first FSM-illegal
    token, then extend with forced continuations up to ``budget`` (the
    grammar often knows the next run of tokens exactly — structural JSON
    — even when the n-gram index has nothing)."""
    out: list[int] = []
    st = constraint.state
    for tok in draft:
        if len(out) >= budget:
            return out
        ns = constraint.step(st, tok)
        if ns is None:
            break
        out.append(int(tok))
        st = ns
    while len(out) < budget:
        f = constraint.forced(st)
        if f is None:
            break
        out.append(int(f))
        st = constraint.step(st, f)
    return out


class TreeDraft:
    """One proposed draft tree. Node 0 is the implicit ROOT (the
    sequence's last real token — the verify pass's slot-0 input);
    ``tokens[i]`` / ``parents[i]`` describe draft node ``i+1``, with
    ``parents[i]`` a NODE index in ``[0, i+1)`` — creation order is
    topological, so a parent always precedes its children."""

    __slots__ = ("tokens", "parents")

    def __init__(self, tokens: list[int] | None = None,
                 parents: list[int] | None = None):
        self.tokens = tokens or []
        self.parents = parents or []

    def __len__(self) -> int:
        return len(self.tokens)

    @property
    def num_nodes(self) -> int:
        return len(self.tokens) + 1

    def depths(self) -> list[int]:
        """Per-node depth including the root (depth 0) → [num_nodes]."""
        out = [0]
        for p in self.parents:
            out.append(out[p] + 1)
        return out

    @property
    def max_depth(self) -> int:
        return max(self.depths())

    def is_chain(self) -> bool:
        """True when the tree is a single path — the engine then rides
        the PR 5 linear verify op unchanged (width=1 ≡ linear by
        construction)."""
        return all(p == i for i, p in enumerate(self.parents))

    def truncate(self, n_nodes: int) -> None:
        """Keep only the first ``n_nodes`` draft nodes (batch-budget
        trim). Creation order is topological (a parent always precedes
        its children), so dropping a suffix always leaves a valid tree
        — and with primary-chain-first expansion the kept prefix is
        exactly what a smaller budget would have drafted."""
        del self.tokens[n_nodes:]
        del self.parents[n_nodes:]

    def chain_tokens(self) -> list[int]:
        assert self.is_chain()
        return list(self.tokens)


class NgramState:
    """Incremental n-gram index over one sequence's token history:
    ``index[ngram] = end positions of its occurrences`` (most recent
    last, capped at MAX_OCC) — excluding the n-gram that ends at the
    final token, which is the lookup KEY (indexing it would make every
    lookup find itself). Keeping the occurrence SET rather than only the
    newest end is the raw material tree drafting branches on: distinct
    continuations of the same context become sibling draft nodes."""

    __slots__ = ("index", "observed", "pool")

    def __init__(self):
        self.index: dict[tuple[int, ...], list[int]] = {}
        self.observed = 0  # positions with their ending n-gram indexed
        self.pool: JacobiPool | None = None  # lazily built (tree drafter)


class NgramDrafter:
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the trailing ``n``-gram. Deterministic
    (no RNG — unseeded-request reproducibility is untouched)."""

    def __init__(self, n: int):
        if n < 1:
            raise ValueError(f"spec_ngram must be >= 1, got {n}")
        self.n = n

    def new_state(self) -> NgramState:
        return NgramState()

    def observe(self, state: NgramState, hist: list[int], node_tokens,
                parents, node_live, cand) -> None:
        """Verify-pass feedback hook (no-op here; the Jacobi pool in
        ``TreeDrafter`` consumes it)."""

    def _absorb(self, tokens: list[int], state: NgramState) -> None:
        """Index n-grams ending at positions [n-1, L-2]. The tail n-gram
        (ending at L-1) stays unindexed until the sequence grows past
        it."""
        n = self.n
        L = len(tokens)
        start = max(n - 1, state.observed)
        for e in range(start, L - 1):
            occ = state.index.setdefault(tuple(tokens[e - n + 1 : e + 1]), [])
            occ.append(e)
            if len(occ) > MAX_OCC:
                del occ[0]
        state.observed = max(state.observed, L - 1)

    def draft(self, tokens: list[int], state: NgramState, max_len: int) -> list[int]:
        """→ up to ``max_len`` proposed next tokens (possibly empty)."""
        n = self.n
        L = len(tokens)
        if max_len <= 0 or L < n + 1:
            return []
        self._absorb(tokens, state)
        occ = state.index.get(tuple(tokens[L - n :]))
        if not occ:
            return []
        e = occ[-1]  # most recent occurrence
        # Self-extending copy: when the continuation run reaches the tail
        # of the history, keep copying from the draft itself — a period-p
        # loop then drafts max_len tokens (cycling the loop) instead of
        # stopping p tokens in. Repetitive generation usually has short
        # periods, so this is where most of the draft length comes from.
        out: list[int] = []
        src = e + 1
        for _ in range(max_len):
            out.append(tokens[src] if src < L else out[src - L])
            src += 1
        return out


class JacobiPool:
    """Lookahead-style candidate pool: maps a short trailing context to
    the model-predicted continuations observed at verify time. Every
    verify pass scores S+1 positions; the per-node argmax (``cand``)
    is what the model WOULD emit after that node's token — a free
    (context → continuation) sample, including at rejected branches.
    Contexts and candidates are recency/hit bounded; lookups are exact
    context matches (g-gram), so drafting from the pool costs one dict
    probe per node, independent of history length."""

    __slots__ = ("g", "table")

    def __init__(self, g: int):
        self.g = max(1, g)
        # ctx → {token: hits}; dict order doubles as recency (re-insert
        # on touch), candidate dicts hit-count-capped at POOL_MAX_CANDS.
        self.table: dict[tuple[int, ...], dict[int, int]] = {}

    def record(self, ctx: tuple[int, ...], nxt: int) -> None:
        cands = self.table.pop(ctx, None)
        if cands is None:
            cands = {}
            if len(self.table) >= POOL_MAX_CONTEXTS:
                # Drop the least recently touched context.
                self.table.pop(next(iter(self.table)))
        cands[nxt] = cands.get(nxt, 0) + 1
        if len(cands) > POOL_MAX_CANDS:
            # Evict the coldest candidate, never the one just recorded.
            worst = min(cands, key=lambda t: (cands[t], t == nxt))
            del cands[worst]
        self.table[ctx] = cands  # re-insert = most recent

    def lookup(self, ctx: tuple[int, ...]) -> list[int]:
        """Candidate continuations, best (most hits) first."""
        cands = self.table.get(ctx)
        if not cands:
            return []
        return sorted(cands, key=lambda t: -cands[t])


class TreeDrafter(NgramDrafter):
    """Tree drafting over two signal sources: the history n-gram index
    (branching wherever a context has several distinct recorded
    continuations) and the Jacobi pool (model-predicted continuations,
    the zero-history-hit path). Expansion is primary-chain-first: the
    best candidate chain is grown to full depth FIRST — so the tree
    always contains the linear draft as its backbone and the extra
    budget buys side branches — then alternates fill what is left."""

    def __init__(self, n: int, width: int, depth: int, pool_g: int = 2):
        super().__init__(n)
        if width < 1:
            raise ValueError(f"spec_tree_width must be >= 1, got {width}")
        self.width = width
        self.depth = depth
        self.pool_g = pool_g

    def new_state(self) -> NgramState:
        st = NgramState()
        st.pool = JacobiPool(self.pool_g)
        return st

    def observe(self, state: NgramState, hist: list[int], node_tokens,
                parents, node_live, cand) -> None:
        """Refresh the Jacobi pool from one verify pass: for every live
        node j, the g-gram context ending at node j (walking parents
        toward the root and into the history tail) paired with the
        model's argmax prediction ``cand[j]`` — a free (context →
        continuation) sample at EVERY node, accepted or not.
        ``hist`` is the sequence history at dispatch (hist[-1] is the
        root token); ``node_live`` is the live node count."""
        pool = state.pool
        if pool is None:
            return
        g = pool.g
        # Per-node context: token chain of length ≤ g ending at the node.
        chains: list[tuple[int, ...]] = []
        for j in range(node_live):
            if j == 0:
                chains.append(tuple(hist[-g:]))
            else:
                p = int(parents[j])
                chains.append((chains[p] + (int(node_tokens[j]),))[-g:])
            pool.record(chains[j], int(cand[j]))

    def _candidates(self, tokens: list[int], state: NgramState,
                    path: tuple[int, ...], width: int) -> list[int]:
        """Distinct continuation candidates for the context
        ``history + path``, best first: history-index continuations in
        recency order, then Jacobi-pool predictions by hit count."""
        n = self.n
        L = len(tokens)
        out: list[int] = []
        seen: set[int] = set()
        if L + len(path) >= n:
            if len(path) >= n:
                key = path[-n:]
            else:
                key = tuple(tokens[L - (n - len(path)):]) + path
            for e in reversed(state.index.get(key, ())):
                # Continuation of the occurrence ending at e (_absorb
                # records ends up to L-2, so e+1 is always in range).
                tok = tokens[e + 1]
                if tok not in seen:
                    seen.add(tok)
                    out.append(tok)
                    if len(out) >= width:
                        return out
        if state.pool is not None:
            g = state.pool.g
            ctx = (tuple(tokens[max(0, L - g):]) + path)[-g:]
            for tok in state.pool.lookup(ctx):
                if tok not in seen:
                    seen.add(tok)
                    out.append(tok)
                    if len(out) >= width:
                        break
        return out

    def draft_tree(self, tokens: list[int], state: NgramState,
                   budget: int, width: int | None = None,
                   depth: int | None = None,
                   constraint: DraftConstraint | None = None) -> TreeDraft:
        """→ a TreeDraft with up to ``budget`` draft nodes, branching up
        to ``width`` per node, paths up to ``depth`` deep. Empty when
        neither the index nor the pool has anything to say.

        With a ``constraint``, candidates are filtered to FSM-legal
        continuations BEFORE a node is added (illegal nodes can never be
        accepted — pruning is pure win), forced states contribute their
        single legal token even with zero index/pool signal, and paths
        may run to the full node budget (forced runs are certainties;
        the depth knob only shapes model-guessed branches)."""
        width = self.width if width is None else width
        depth = self.depth if depth is None else depth
        tree = TreeDraft()
        if budget <= 0 or depth <= 0 or not tokens:
            return tree
        self._absorb(tokens, state)

        remaining = [budget]

        def expand(path: tuple[int, ...], parent_idx: int, depth_left: int,
                   fsm_state=None) -> None:
            if depth_left <= 0 or remaining[0] <= 0:
                return
            if constraint is None:
                cands = self._candidates(tokens, state, path, width)
            else:
                forced = constraint.forced(fsm_state)
                if forced is not None:
                    cands = [forced]
                else:
                    cands = [
                        tok for tok in
                        self._candidates(tokens, state, path, width * 2)
                        if constraint.step(fsm_state, tok) is not None
                    ][:width]
            for tok in cands:
                if remaining[0] <= 0:
                    return
                tree.tokens.append(int(tok))
                tree.parents.append(parent_idx)
                remaining[0] -= 1
                # Primary-chain-first: recurse before trying the next
                # sibling, so the best chain reaches full depth before
                # any budget goes to alternates.
                expand(
                    path + (int(tok),), len(tree.tokens), depth_left - 1,
                    None if constraint is None
                    else constraint.step(fsm_state, tok),
                )

        # Constrained paths may use the whole budget (forced fast-
        # forward); unconstrained trees keep the depth shape knob.
        depth_cap = budget if constraint is not None else min(depth, budget)
        expand((), 0, depth_cap,
               None if constraint is None else constraint.state)
        return tree


def build_drafter(args) -> NgramDrafter:
    """EngineArgs → drafter instance. The single construction seam for
    future backends (draft model, Medusa-style heads). Width 1 keeps the
    PR 5 linear n-gram drafter byte-for-byte; width > 1 builds the tree
    drafter (history branching + Jacobi pool)."""
    width = getattr(args, "spec_tree_width", 1)
    if width <= 1:
        return NgramDrafter(args.spec_ngram)
    depth = getattr(args, "spec_tree_depth", 0) or args.spec_tokens
    return TreeDrafter(args.spec_ngram, width, depth)
