"""Host-side draft-token proposers for speculative decoding.

The engine's verify pass (model.spec_verify) scores any proposed draft
in one weight stream; WHERE drafts come from is pluggable behind the
``Drafter`` interface. The default is n-gram prompt lookup (Saxena 2023,
"Prompt Lookup Decoding"): match the sequence's trailing n-gram against
its own prompt+generated history and propose the continuation of the
most recent earlier occurrence. Zero model cost, zero RNG draws, and
exactly the TPU-native shape — the expensive half (verification) runs
on device while drafting is a dict lookup on the host.

A draft-model backend (small model proposing tokens, Leviathan et al.
2023) slots in behind the same two methods; its ``draft`` would dispatch
device work, which is why the interface takes the whole token list
rather than a delta.

State is PER SEQUENCE (``new_state``) and fed incrementally: ``draft``
absorbs tokens appended since the last call before matching, so the
steady-state cost is O(new tokens), not O(history). Preemption-by-
recompute keeps ``seq.tokens`` intact, so drafter state survives it
unchanged.
"""

from __future__ import annotations


class NgramState:
    """Incremental n-gram index over one sequence's token history:
    ``index[ngram] = end position of its most recent occurrence`` —
    excluding the n-gram that ends at the final token, which is the
    lookup KEY (indexing it would make every lookup find itself)."""

    __slots__ = ("index", "observed")

    def __init__(self):
        self.index: dict[tuple[int, ...], int] = {}
        self.observed = 0  # positions with their ending n-gram indexed


class NgramDrafter:
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the trailing ``n``-gram. Deterministic
    (no RNG — unseeded-request reproducibility is untouched)."""

    def __init__(self, n: int):
        if n < 1:
            raise ValueError(f"spec_ngram must be >= 1, got {n}")
        self.n = n

    def new_state(self) -> NgramState:
        return NgramState()

    def draft(self, tokens: list[int], state: NgramState, max_len: int) -> list[int]:
        """→ up to ``max_len`` proposed next tokens (possibly empty)."""
        n = self.n
        L = len(tokens)
        if max_len <= 0 or L < n + 1:
            return []
        # Absorb history: index n-grams ending at positions [n-1, L-2].
        # The tail n-gram (ending at L-1) stays unindexed until the
        # sequence grows past it.
        start = max(n - 1, state.observed)
        for e in range(start, L - 1):
            state.index[tuple(tokens[e - n + 1 : e + 1])] = e
        state.observed = max(state.observed, L - 1)
        e = state.index.get(tuple(tokens[L - n :]))
        if e is None:
            return []
        # Self-extending copy: when the continuation run reaches the tail
        # of the history, keep copying from the draft itself — a period-p
        # loop then drafts max_len tokens (cycling the loop) instead of
        # stopping p tokens in. Repetitive generation usually has short
        # periods, so this is where most of the draft length comes from.
        out: list[int] = []
        src = e + 1
        for _ in range(max_len):
            out.append(tokens[src] if src < L else out[src - L])
            src += 1
        return out


def build_drafter(args) -> NgramDrafter:
    """EngineArgs → drafter instance. The single construction seam for
    future backends (draft model, Medusa-style heads)."""
    return NgramDrafter(args.spec_ngram)
