"""dynamo_tpu — a TPU-native distributed LLM inference serving framework.

Capabilities modeled on NVIDIA Dynamo (see SURVEY.md): OpenAI-compatible
frontend, KV-cache-aware routing over a global radix index, disaggregated
prefill/decode, multi-tier paged-KV block management, request migration, and
SLA-driven planning — but the compute path is JAX/XLA/Pallas on TPU and the
data planes are designed for ICI/DCN + host DMA rather than NCCL/NIXL.

Layer map (bottom-up), mirroring the reference's layering
(reference: lib/runtime, lib/llm, components/ — SURVEY.md §1):

- ``dynamo_tpu.runtime``  — distributed runtime kernel: KV store w/ leases +
  watches (control plane), Namespace→Component→Endpoint registry, TCP
  request/response plane, AsyncEngine pipeline, routing, metrics, config.
- ``dynamo_tpu.llm``      — OpenAI protocol types, SSE, preprocessor,
  detokenizing backend, model cards, discovery.
- ``dynamo_tpu.kv_router``— KV-cache-aware routing: radix indexer, cost
  scheduler, event publishers.
- ``dynamo_tpu.engine``   — the TPU inference engine: JAX models, paged KV
  cache, Pallas paged attention, continuous batching.
- ``dynamo_tpu.block_manager`` — multi-tier KV block pools (HBM/host/disk).
- ``dynamo_tpu.mocker``   — CPU-only fake engine for routing/serving tests.
- ``dynamo_tpu.planner``  — SLA-driven autoscaling.
- ``dynamo_tpu.parallel`` — meshes, shardings, ring attention.
"""

__version__ = "0.1.0"
