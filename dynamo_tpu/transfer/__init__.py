"""Streaming KV data plane: chunked, flow-controlled movement of paged
KV-cache blocks between workers (the TPU-native NIXL analogue).

See :mod:`dynamo_tpu.transfer.stream` for the protocol and
``docs/disagg.md`` for the end-to-end flow.
"""

from dynamo_tpu.transfer.stream import (
    KvChunk,
    KvChunkAssembler,
    KvStreamExport,
    PulledKvStream,
    TransferAbortedError,
    TransferError,
    TransferTimeoutError,
    chunk_to_frames,
    inject_payload_from_chunks,
    pull_kv_stream,
    read_kv_payload_frames,
    serve_kv_window,
)

__all__ = [
    "KvChunk",
    "KvChunkAssembler",
    "KvStreamExport",
    "PulledKvStream",
    "TransferAbortedError",
    "TransferError",
    "TransferTimeoutError",
    "chunk_to_frames",
    "inject_payload_from_chunks",
    "pull_kv_stream",
    "read_kv_payload_frames",
    "serve_kv_window",
]
