"""Streaming KV data plane: the chunked wire protocol and its two ends.

Reference analogue: the NIXL KV data plane (reference: lib/llm/src/
block_manager/storage/nixl.rs, docs/architecture/kvbm_architecture.md)
moves cache blocks with block-granular RDMA ops *while* prefill is still
running. On TPU the equivalents are host DMA for HBM→host (already
started asynchronously by the engine, engine/kv_transfer.py) and the
runtime's TCP response plane for host→host; this module is the host→host
half plus the shared chunk bookkeeping.

Protocol (all frames msgpack-safe dicts, ordered within one stream):

- ``kv_chunk`` header — one contiguous run of prompt blocks: ``idx``
  (chunk sequence number), ``block_offset`` (first prompt block the run
  covers), plus the KvPagePayload header fields (shape/dtype/byte counts,
  int8 scale sidecar sizes when the publisher stores quantized pages).
- ``k`` / ``v`` / ``k_scale`` / ``v_scale`` data frames — ≤ frame_bytes
  each, same framing as the legacy one-shot payload.
- ``kv_more`` — window over (credit exhausted or nothing new within the
  wait); the consumer pulls again from ``cursor``.
- ``kv_eos`` — stream sealed and fully delivered (carries the totals).
- ``kv_abort`` — publisher aborted (prefill death/preemption, or the
  consumer fell behind the flow-control budget).

Flow control is credit-based and receiver-driven: each pull names a
``cursor`` (acks everything before it — the publisher frees those host
pages) and a ``credit_bytes`` window, so unacked bytes in flight are
bounded by construction. A consumer that stops pulling cannot grow the
publisher's heap past ``max_buffer_bytes``: the stream aborts instead
(disagg is an optimization — the decode side falls back to local
prefill, never to an OOM'd prefill worker).

Failures are typed (:class:`TransferError` tree) so ``llm/disagg.py``
can catch exactly the data plane's failure domain and fall back.
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass
from typing import AsyncIterator

from dynamo_tpu.engine.kv_transfer import KvPagePayload
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("transfer")

DEFAULT_CREDIT_BYTES = 32 << 20
DEFAULT_FRAME_BYTES = 16 << 20
_DATA_KINDS = ("k", "v", "k_scale", "v_scale")
# Floor for a de-prioritized pull's window: even a fully contended
# budget lets a background stream advance one modest window per turn,
# so pacing slows migrations but can never wedge them.
MIN_WINDOW_BYTES = 1 << 20


class CreditBudget:
    """Shared credit accounting across one process's concurrent KV pulls.

    The credit-flow protocol already bounds each STREAM's in-flight
    bytes; this bounds their SUM, with a priority tier. Disagg prefill
    pulls are on the request critical path (TTFT) and always get their
    full ask; background pulls — balancer/planner migrations — get
    whatever of ``total_bytes`` the outstanding windows have left,
    floored at :data:`MIN_WINDOW_BYTES`. Rebalancing therefore shapes
    its own bandwidth around the disagg plane instead of competing with
    it (ISSUE 19 tentpole (c); docs/performance.md has the budget math).

    Thread-safe; windows are short-lived (acquire → one pull window →
    release), so a busy disagg plane throttles migrations within one
    window turn.
    """

    def __init__(self, total_bytes: int = 2 * DEFAULT_CREDIT_BYTES,
                 priority_kinds: tuple = ("disagg",)):
        self.total_bytes = total_bytes
        self.priority_kinds = frozenset(priority_kinds)
        self._lock = threading.Lock()
        self._outstanding: dict[str, int] = {}
        self.charged_bytes: dict[str, int] = {}  # per-kind delivered bytes

    def acquire(self, kind: str, want: int) -> int:
        """Reserve credit for one pull window. → granted bytes (== want
        for priority kinds; bounded by the budget's headroom otherwise)."""
        with self._lock:
            if kind in self.priority_kinds:
                grant = want
            else:
                used = sum(self._outstanding.values())
                grant = max(MIN_WINDOW_BYTES, min(want, self.total_bytes - used))
            self._outstanding[kind] = self._outstanding.get(kind, 0) + grant
            return grant

    def release(self, kind: str, granted: int, delivered: int = 0) -> None:
        with self._lock:
            left = self._outstanding.get(kind, 0) - granted
            if left > 0:
                self._outstanding[kind] = left
            else:
                self._outstanding.pop(kind, None)
            if delivered:
                self.charged_bytes[kind] = self.charged_bytes.get(kind, 0) + delivered

    def outstanding(self, kind: str | None = None) -> int:
        with self._lock:
            if kind is not None:
                return self._outstanding.get(kind, 0)
            return sum(self._outstanding.values())


_process_budget: CreditBudget | None = None


def process_credit_budget() -> CreditBudget:
    """The per-process shared budget (worker processes host both the
    disagg decode handler and the migration receiver, so one instance
    arbitrates between them)."""
    global _process_budget
    if _process_budget is None:
        _process_budget = CreditBudget()
    return _process_budget


class TransferError(Exception):
    """Base class for KV data-plane failures — the whole plane's failure
    domain, so consumers can catch it precisely and fall back to local
    prefill (disagg is never a correctness dependency)."""


class TransferAbortedError(TransferError):
    """The publisher aborted the stream: prefill died or was preempted,
    or the consumer fell behind the flow-control budget (overrun)."""


class TransferTimeoutError(TransferError):
    """The stream stalled: the export never appeared, or no new chunk
    arrived within the pull deadline."""


@dataclass
class KvChunk:
    """One streamed unit: the KV pages of a contiguous run of prompt
    blocks, in extract_pages wire order — (k, v) or
    (k, v, k_scale, v_scale) for int8 storage."""

    block_offset: int  # first prompt block this run covers
    pages: tuple       # np arrays, each [L, n, bs, ...]
    num_tokens: int    # prompt positions covered (n * block_size)

    @property
    def num_blocks(self) -> int:
        return int(self.pages[0].shape[1])

    @property
    def nbytes(self) -> int:
        return sum(int(p.nbytes) for p in self.pages)

    def to_wire(self) -> dict:
        """→ msgpack-safe dict (KvPagePayload wire form + block_offset);
        the engine's inject path consumes a list of these."""
        d = KvPagePayload.from_pages(self.pages, self.num_tokens).to_dict()
        d["block_offset"] = self.block_offset
        return d


# ---------------------------------------------------------------------------
# Publisher side
# ---------------------------------------------------------------------------


class KvStreamExport:
    """Publisher end of one streaming KV export.

    Written by the prefill engine's scheduler thread (``publish`` /
    ``seal`` / ``abort`` — all non-blocking: the scheduler must never
    wait on a consumer), drained by the async ``kv_fetch`` endpoint on
    the worker's event loop (``chunks_since`` / ``ack`` /
    ``wait_change``). ``max_buffer_bytes`` bounds unacked host bytes: a
    consumer that stops acking aborts the stream instead of growing the
    prefill worker's heap without bound.
    """

    def __init__(self, handle: str, *, max_buffer_bytes: int = 256 << 20):
        self.handle = handle
        self.max_buffer_bytes = max_buffer_bytes
        self._lock = threading.Lock()
        self._chunks: list[KvChunk | None] = []  # acked entries dropped to None
        self._buffered_bytes = 0
        self.total_bytes = 0
        self.sealed = False
        self.num_tokens = 0
        self.num_blocks = 0
        self.abort_reason: str | None = None
        self._waiter_loop: asyncio.AbstractEventLoop | None = None
        self._waiter_event: asyncio.Event | None = None

    # -- publisher (engine scheduler thread) ------------------------------

    def publish(self, chunk: KvChunk) -> bool:
        """Append one chunk. → False when the stream is (now) aborted —
        the caller should stop extracting for it. Never blocks."""
        with self._lock:
            if self.abort_reason is not None:
                return False
            if self._buffered_bytes + chunk.nbytes > self.max_buffer_bytes:
                # Flow-control overrun: the consumer is too slow or gone.
                # Free the buffered pages NOW — holding them until the
                # export TTL reap is exactly the heap pressure the
                # budget exists to prevent.
                self.abort_reason = "overrun"
                self._chunks = [None] * len(self._chunks)
                self._buffered_bytes = 0
            else:
                self._chunks.append(chunk)
                self._buffered_bytes += chunk.nbytes
                self.total_bytes += chunk.nbytes
        self._notify()
        return self.abort_reason is None

    def seal(self, *, num_blocks: int, num_tokens: int) -> None:
        """Prefill done, all chunks published; totals become final."""
        with self._lock:
            if self.abort_reason is None:
                self.sealed = True
                self.num_blocks = num_blocks
                self.num_tokens = num_tokens
        self._notify()

    def abort(self, reason: str) -> None:
        with self._lock:
            if self.sealed or self.abort_reason is not None:
                return
            self.abort_reason = reason
            # Free buffered pages promptly — nobody will pull them.
            self._chunks = [None] * len(self._chunks)
            self._buffered_bytes = 0
        self._notify()

    def _notify(self) -> None:
        ev, loop = self._waiter_event, self._waiter_loop
        if ev is not None and loop is not None:
            try:
                loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                # Consumer loop already closed — nothing left to wake.
                pass

    # -- consumer (event loop) --------------------------------------------

    def chunk_count(self) -> int:
        with self._lock:
            return len(self._chunks)

    def state(self) -> tuple[int, bool, str | None]:
        """→ (published chunk count, sealed, abort reason)."""
        with self._lock:
            return len(self._chunks), self.sealed, self.abort_reason

    def ack(self, cursor: int) -> None:
        """The consumer has durably received chunks [0, cursor): release
        their host pages (the flow-control credit return path)."""
        with self._lock:
            for i in range(min(cursor, len(self._chunks))):
                c = self._chunks[i]
                if c is not None:
                    self._buffered_bytes -= c.nbytes
                    self._chunks[i] = None

    def chunks_since(self, cursor: int, credit_bytes: int) -> list[tuple[int, KvChunk]]:
        """→ [(idx, chunk)] from ``cursor``, bounded by ``credit_bytes``
        (always at least one chunk when any is available, so a chunk
        larger than the credit window still makes progress)."""
        out: list[tuple[int, KvChunk]] = []
        budget = credit_bytes
        with self._lock:
            if self.abort_reason is not None:
                # Aborting nulls every buffered entry; an empty window
                # sends the caller back to state(), which reports the
                # abort as a clean kv_abort frame instead of a spurious
                # cursor-went-backwards protocol error.
                return out
            for i in range(cursor, len(self._chunks)):
                c = self._chunks[i]
                if c is None:
                    raise TransferError(
                        f"chunk {i} re-requested after ack (cursor went backwards)"
                    )
                if out and c.nbytes > budget:
                    break
                out.append((i, c))
                budget -= c.nbytes
        return out

    async def wait_change(self, cursor: int, timeout: float) -> None:
        """Wait (bounded) until a chunk past ``cursor`` exists or the
        stream sealed/aborted."""
        loop = asyncio.get_running_loop()
        with self._lock:
            if self._waiter_event is None or self._waiter_loop is not loop:
                self._waiter_event = asyncio.Event()
                self._waiter_loop = loop
            ev = self._waiter_event
            if len(self._chunks) > cursor or self.sealed or self.abort_reason:
                return
            ev.clear()
        try:
            await asyncio.wait_for(ev.wait(), max(timeout, 0.0))
        except asyncio.TimeoutError:
            pass


# ---------------------------------------------------------------------------
# Wire framing
# ---------------------------------------------------------------------------


def chunk_to_frames(idx: int, chunk: KvChunk, max_bytes: int = DEFAULT_FRAME_BYTES):
    """Yield one chunk's wire frames: a ``kv_chunk`` header (the legacy
    payload header plus idx/block_offset — int8 scale sidecars ride the
    same fields) followed by ≤ ``max_bytes`` data frames."""
    payload = KvPagePayload.from_pages(chunk.pages, chunk.num_tokens)
    frames = payload.to_frames(max_bytes)
    header = dict(next(frames))
    header["kind"] = "kv_chunk"
    header["idx"] = idx
    header["block_offset"] = chunk.block_offset
    yield header
    yield from frames


class KvChunkAssembler:
    """Incremental reader: feed wire frames in order, get completed
    :class:`KvChunk` objects out. Understands both ``kv_chunk`` stream
    headers and legacy one-shot ``kv_header`` payloads, so the disagg
    pull loop and the peer-KV fetcher share one reader."""

    def __init__(self):
        self._header: dict | None = None
        self._data: list[dict] = []
        self._want = 0
        self._got = 0

    def feed(self, frame: dict) -> KvChunk | None:
        """→ a completed chunk, or None while one is still assembling.
        Raises :class:`TransferError` on malformed/out-of-order frames
        (truncation inside a chunk is caught by the byte-count check)."""
        kind = frame.get("kind")
        if kind in ("kv_chunk", "kv_header"):
            if self._header is not None:
                raise TransferError("chunk header before previous chunk completed")
            self._header = frame
            self._want = (
                frame.get("k_bytes", 0) + frame.get("v_bytes", 0)
                + frame.get("k_scale_bytes", 0) + frame.get("v_scale_bytes", 0)
            )
            self._got = 0
            self._data = []
            return self._complete() if self._want == 0 else None
        if kind in _DATA_KINDS:
            if self._header is None:
                raise TransferError(f"{kind} data frame before any chunk header")
            self._data.append(frame)
            self._got += len(frame.get("data") or b"")
            return self._complete() if self._got >= self._want else None
        raise TransferError(f"unexpected frame kind {kind!r} in kv stream")

    @property
    def mid_chunk(self) -> bool:
        return self._header is not None

    def _complete(self) -> KvChunk:
        header = dict(self._header)
        block_offset = int(header.pop("block_offset", 0) or 0)
        header["kind"] = "kv_header"
        try:
            payload = KvPagePayload.from_frames([header, *self._data])
        except ValueError as e:
            # Per-kind byte-count mismatch (one kind over, another short).
            # Stay inside the plane's typed failure domain.
            raise TransferError(f"malformed kv chunk: {e}") from e
        self._header = None
        self._data = []
        return KvChunk(
            block_offset=block_offset,
            pages=payload.pages(),
            num_tokens=payload.num_tokens,
        )


async def read_kv_payload_frames(frames: AsyncIterator[dict]) -> KvPagePayload:
    """Assemble a legacy single-payload stream (one ``kv_header`` + data
    frames) through the shared assembler. Raises :class:`TransferError`
    on a declined stream ({"error": ...} first frame), an empty stream,
    or truncation."""
    asm = KvChunkAssembler()
    chunk: KvChunk | None = None
    got_any = False
    async for frame in frames:
        if not got_any and frame.get("error"):
            raise TransferError(str(frame["error"]))
        got_any = True
        done = asm.feed(frame)
        if done is not None:
            chunk = done
    if chunk is None:
        raise TransferError("empty or truncated kv payload stream")
    return KvPagePayload.from_pages(chunk.pages, chunk.num_tokens)


# ---------------------------------------------------------------------------
# Server pump (prefill worker's kv_fetch endpoint)
# ---------------------------------------------------------------------------


async def serve_kv_window(
    export: KvStreamExport,
    cursor: int,
    credit_bytes: int,
    wait_s: float,
    frame_bytes: int = DEFAULT_FRAME_BYTES,
    chaos=None,
):
    """Serve one pull window: frames for chunks [cursor, m) bounded by
    ``credit_bytes``, then a terminal marker — ``kv_eos`` when the
    stream is sealed and fully delivered, ``kv_more`` when the credit
    window filled or nothing new arrived within ``wait_s``, ``kv_abort``
    on publisher abort. ``cursor`` acks (frees) everything before it.

    ``chaos`` (runtime/chaos.py) is consulted AFTER each chunk's frames:
    a kill-mid-transfer draw raises ChaosKillError between chunks, which
    the endpoint server turns into a transport cut — exactly what a
    prefill worker dying mid-stream looks like on the wire."""
    export.ack(cursor)
    loop = asyncio.get_running_loop()
    deadline = loop.time() + max(wait_s, 0.0)
    sent = cursor
    budget = credit_bytes
    while True:
        _count, sealed, abort = export.state()
        if abort is not None:
            yield {"kind": "kv_abort", "reason": abort}
            return
        window = export.chunks_since(sent, budget)
        for idx, chunk in window:
            for frame in chunk_to_frames(idx, chunk, frame_bytes):
                yield frame
            sent = idx + 1
            budget -= chunk.nbytes
            if chaos is not None:
                chaos.maybe_cut_transfer()
        _count, sealed, abort = export.state()
        if abort is not None:
            yield {"kind": "kv_abort", "reason": abort}
            return
        if sealed and sent >= export.chunk_count():
            yield {
                "kind": "kv_eos",
                "total_chunks": sent,
                "num_blocks": export.num_blocks,
                "num_tokens": export.num_tokens,
            }
            return
        remaining = deadline - loop.time()
        if budget <= 0 or remaining <= 0:
            yield {"kind": "kv_more", "cursor": sent}
            return
        await export.wait_change(sent, remaining)


# ---------------------------------------------------------------------------
# Client pump (decode worker's pull loop)
# ---------------------------------------------------------------------------


@dataclass
class PulledKvStream:
    """Everything one completed pull produced, plus the overlap
    accounting the bench/metrics report."""

    chunks: list
    num_tokens: int
    num_blocks: int
    total_bytes: int
    overlapped_bytes: int  # received while remote prefill was still running

    @property
    def overlap_frac(self) -> float:
        return self.overlapped_bytes / self.total_bytes if self.total_bytes else 0.0


async def pull_kv_stream(
    window_call,
    *,
    credit_bytes: int = DEFAULT_CREDIT_BYTES,
    stall_timeout_s: float = 20.0,
    window_wait_s: float = 2.0,
    prefill_done=None,
    failed=None,
    on_inflight=None,
    budget: CreditBudget | None = None,
    budget_kind: str = "disagg",
) -> PulledKvStream:
    """Drive the windowed pull until ``kv_eos``.

    ``window_call(cursor, credit_bytes, wait_s)`` → async iterator of one
    window's frames (a fresh kv_fetch RPC per window; the cursor acks the
    previous window, returning its flow-control credit).

    ``stall_timeout_s`` bounds time WITHOUT progress, not the whole
    transfer — a healthy many-GB stream may take longer than any fixed
    total. ``prefill_done`` (nullary → bool) classifies each chunk as
    overlapped (arrived while the remote prefill still ran) or not;
    ``failed`` (nullary → bool) reports that the remote prefill FAILED —
    a prefill that died before registering its export never produces
    kv_abort on the wire (the server just keeps answering ``kv_more``),
    so without this signal the pull would wait out the full stall budget;
    ``on_inflight(bytes)`` reports assembled-but-uninjected bytes for the
    inflight gauge.

    ``budget`` (a :class:`CreditBudget`) arbitrates the credit window
    PER PULL WINDOW across the process's concurrent streams: each
    window's advertised credit is what the budget grants ``budget_kind``
    at that moment, and delivered bytes are charged back on release —
    a background (non-priority) kind pulls smaller windows while the
    disagg plane is busy instead of doubling in-flight bytes.

    Raises TransferAbortedError / TransferTimeoutError / TransferError.
    """
    asm = KvChunkAssembler()
    chunks: list[KvChunk] = []
    total_bytes = 0
    overlapped = 0
    cursor = 0
    deadline = time.monotonic() + stall_timeout_s
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise TransferTimeoutError(
                f"kv stream stalled at chunk {cursor} ({total_bytes} bytes in)"
            )
        eos: dict | None = None
        progressed = False
        granted = credit_bytes
        if budget is not None:
            granted = budget.acquire(budget_kind, credit_bytes)
        window_bytes = 0
        window = window_call(cursor, granted, min(window_wait_s, remaining))
        try:
            async for frame in window:
                if frame.get("error"):
                    raise TransferError(str(frame["error"]))
                kind = frame.get("kind")
                if kind == "kv_abort":
                    raise TransferAbortedError(str(frame.get("reason") or "aborted"))
                if kind == "kv_eos":
                    eos = frame
                    break
                if kind == "kv_more":
                    break
                chunk = asm.feed(frame)
                if chunk is not None:
                    chunks.append(chunk)
                    cursor += 1
                    progressed = True
                    total_bytes += chunk.nbytes
                    window_bytes += chunk.nbytes
                    if prefill_done is not None and not prefill_done():
                        overlapped += chunk.nbytes
                    if on_inflight is not None:
                        on_inflight(total_bytes)
        finally:
            aclose = getattr(window, "aclose", None)
            if aclose is not None:
                await aclose()
            if budget is not None:
                budget.release(budget_kind, granted, delivered=window_bytes)
        if asm.mid_chunk:
            raise TransferError("kv stream cut mid-chunk")
        if eos is None and not progressed and failed is not None and failed():
            raise TransferAbortedError("remote prefill failed before sealing the stream")
        if eos is not None:
            if cursor != int(eos.get("total_chunks") or cursor):
                raise TransferError(
                    f"kv stream ended at chunk {cursor}, "
                    f"publisher sealed {eos.get('total_chunks')}"
                )
            return PulledKvStream(
                chunks=chunks,
                num_tokens=int(eos.get("num_tokens") or 0),
                num_blocks=int(eos.get("num_blocks") or 0),
                total_bytes=total_bytes,
                overlapped_bytes=overlapped,
            )
        if progressed:
            deadline = time.monotonic() + stall_timeout_s


def inject_payload_from_chunks(pulled: PulledKvStream) -> dict:
    """→ the ``kv_transfer_params.inject`` dict the engine consumes:
    chunk-granular, so admission scatters each run separately instead of
    concatenating one giant host payload."""
    return {
        "chunks": [c.to_wire() for c in pulled.chunks],
        "num_tokens": pulled.num_tokens,
        "num_blocks": pulled.num_blocks,
    }
