"""KV-aware worker selection: overlap-weighted cost + softmax sampling.

Reference analogue: lib/llm/src/kv_router/scheduler.rs —
cost = ``overlap_score_weight × potential_prefill_blocks +
potential_decode_blocks`` per worker, min-max normalized, then
softmax-sampled with ``router_temperature`` (0 ⇒ deterministic argmin;
scheduler.rs:272-340,356-439). Temperature>0 spreads bursts of identical
prompts across workers instead of herding them onto one.

Transfer-vs-recompute pricing: when a global prefix directory is live
(fleet/directory.py) the router also passes each candidate's FETCHABLE
depth — prefix blocks it is missing locally but could pull from a
directory-listed holder over the credit-flow transfer plane
(llm/peer_kv.py). Those blocks are priced at ``transfer_block_cost``
(< 1.0: a DMA'd block is cheaper than recomputing it, Mooncake's
transfer-vs-compute tradeoff) instead of full recompute, so a cold but
idle engine next to a warm peer can beat a warm but saturated one —
the directory stops being a stickiness booster and becomes an economy.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass

from dynamo_tpu.kv_router.indexer import OverlapScores
from dynamo_tpu.kv_router.sequence import ActiveSequences

WorkerId = int


@dataclass
class KvSchedulerConfig:
    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    # Cost of pulling one missing prefix block from a peer, in units of
    # recomputing one block locally (0 = transfers are free, 1 = no
    # cheaper than recompute — directory pricing effectively off).
    # ~0.35 matches the measured peer-fetch vs prefill ratio on the
    # loopback transfer plane (BENCH_DISAGG_r08 frame throughput vs
    # prefill tok/s); a WAN-separated fleet wants it near 1.
    transfer_block_cost: float = 0.35


@dataclass
class Placement:
    worker: WorkerId
    overlap_blocks: int
    total_blocks: int
    # Blocks the chosen worker should PULL from a peer (directory-priced
    # transfer); 0 when the plain overlap path won.
    fetch_blocks: int = 0


class KvScheduler:
    def __init__(self, config: KvSchedulerConfig | None = None, rng: random.Random | None = None):
        self.config = config or KvSchedulerConfig()
        self._rng = rng or random.Random()

    def schedule(
        self,
        workers: list[WorkerId],
        request_blocks: int,
        overlaps: OverlapScores,
        active: ActiveSequences,
        fetchable: dict[WorkerId, int] | None = None,
    ) -> Placement:
        """Pick a worker for a request spanning ``request_blocks`` blocks.

        ``fetchable`` maps worker → the deepest leading-run depth any
        OTHER directory-listed holder has for this request (absolute
        blocks from the root); the part beyond the worker's own overlap
        is what a transfer would save, priced at transfer_block_cost."""
        if not workers:
            raise ValueError("no workers")
        costs: list[float] = []
        for w in workers:
            overlap = min(overlaps.scores.get(w, 0), request_blocks)
            fetch = self._fetch_blocks(w, overlap, request_blocks, fetchable)
            potential_prefill = (
                request_blocks
                - overlap
                - fetch
                + self.config.transfer_block_cost * fetch
            )
            potential_decode = active.active_blocks(w) + request_blocks
            costs.append(
                self.config.overlap_score_weight * potential_prefill + potential_decode
            )
        idx = softmax_sample(costs, self.config.router_temperature, self._rng)
        w = workers[idx]
        overlap = min(overlaps.scores.get(w, 0), request_blocks)
        return Placement(
            worker=w,
            overlap_blocks=overlap,
            total_blocks=request_blocks,
            fetch_blocks=self._fetch_blocks(w, overlap, request_blocks, fetchable),
        )

    @staticmethod
    def _fetch_blocks(
        w: WorkerId, overlap: int, request_blocks: int,
        fetchable: dict[WorkerId, int] | None,
    ) -> int:
        if not fetchable:
            return 0
        return max(0, min(fetchable.get(w, 0), request_blocks) - overlap)


def softmax_sample(costs: list[float], temperature: float, rng: random.Random) -> int:
    """Sample an index ∝ softmax(-normalized_cost / temperature).
    temperature <= 0 → argmin (ties broken at random, as the reference
    does to avoid herding)."""
    lo, hi = min(costs), max(costs)
    if temperature <= 0.0 or hi == lo:
        best = [i for i, c in enumerate(costs) if c == lo]
        return rng.choice(best)
    norm = [(c - lo) / (hi - lo) for c in costs]
    logits = [-n / temperature for n in norm]
    m = max(logits)
    exps = [math.exp(l - m) for l in logits]
    total = sum(exps)
    r = rng.random() * total
    acc = 0.0
    for i, e in enumerate(exps):
        acc += e
        if r <= acc:
            return i
    return len(costs) - 1
