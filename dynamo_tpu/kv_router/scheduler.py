"""KV-aware worker selection: overlap-weighted cost + softmax sampling.

Reference analogue: lib/llm/src/kv_router/scheduler.rs —
cost = ``overlap_score_weight × potential_prefill_blocks +
potential_decode_blocks`` per worker, min-max normalized, then
softmax-sampled with ``router_temperature`` (0 ⇒ deterministic argmin;
scheduler.rs:272-340,356-439). Temperature>0 spreads bursts of identical
prompts across workers instead of herding them onto one.

Transfer-vs-recompute pricing: when a global prefix directory is live
(fleet/directory.py) the router also passes each candidate's FETCHABLE
depth — prefix blocks it is missing locally but could pull from a
directory-listed holder over the credit-flow transfer plane
(llm/peer_kv.py). Those blocks are priced at ``transfer_block_cost``
(< 1.0: a DMA'd block is cheaper than recomputing it, Mooncake's
transfer-vs-compute tradeoff) instead of full recompute, so a cold but
idle engine next to a warm peer can beat a warm but saturated one —
the directory stops being a stickiness booster and becomes an economy.

Migration-aware placement (Llumnix composition): when a fleet balancer
runs (planner/balancer.py), landing on a loaded-but-warm engine is no
longer a terminal decision — the balancer can relocate the decode later
for roughly one migration's worth of transfer. With
``migrate_cost_blocks`` set, each candidate's decode-load term is capped
at the fleet mean plus that cost: excess load above the mean is priced
as "admit here, shed later" instead of at face value, so cache affinity
wins ties it would otherwise lose to a cold idle engine. ``None``
(default) keeps the original pricing for balancer-less deployments.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass

from dynamo_tpu.kv_router.indexer import OverlapScores
from dynamo_tpu.kv_router.sequence import ActiveSequences

WorkerId = int


@dataclass
class KvSchedulerConfig:
    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    # Cost of pulling one missing prefix block from a peer, in units of
    # recomputing one block locally (0 = transfers are free, 1 = no
    # cheaper than recompute — directory pricing effectively off).
    # ~0.35 matches the measured peer-fetch vs prefill ratio on the
    # loopback transfer plane (BENCH_DISAGG_r08 frame throughput vs
    # prefill tok/s); a WAN-separated fleet wants it near 1.
    transfer_block_cost: float = 0.35
    # Migration-aware decode pricing: cap each candidate's decode-load
    # term at fleet_mean + migrate_cost_blocks (the amortized price of
    # one later balancer move, in blocks). None = off — load is priced
    # at face value, correct when no balancer will relocate decodes.
    migrate_cost_blocks: float | None = None


@dataclass
class Placement:
    worker: WorkerId
    overlap_blocks: int
    total_blocks: int
    # Blocks the chosen worker should PULL from a peer (directory-priced
    # transfer); 0 when the plain overlap path won.
    fetch_blocks: int = 0


class KvScheduler:
    def __init__(self, config: KvSchedulerConfig | None = None, rng: random.Random | None = None):
        self.config = config or KvSchedulerConfig()
        self._rng = rng or random.Random()

    def schedule(
        self,
        workers: list[WorkerId],
        request_blocks: int,
        overlaps: OverlapScores,
        active: ActiveSequences,
        fetchable: dict[WorkerId, int] | None = None,
    ) -> Placement:
        """Pick a worker for a request spanning ``request_blocks`` blocks.

        ``fetchable`` maps worker → the deepest leading-run depth any
        OTHER directory-listed holder has for this request (absolute
        blocks from the root); the part beyond the worker's own overlap
        is what a transfer would save, priced at transfer_block_cost."""
        if not workers:
            raise ValueError("no workers")
        per_worker: list[tuple[int, int]] = []  # (overlap, fetch) per worker
        loads: list[int] = []
        for w in workers:
            overlap = min(overlaps.scores.get(w, 0), request_blocks)
            fetch = self._fetch_blocks(w, overlap, request_blocks, fetchable)
            per_worker.append((overlap, fetch))
            loads.append(active.active_blocks(w))
        priced = self._priced_loads(loads)
        costs: list[float] = []
        for (overlap, fetch), load in zip(per_worker, priced):
            potential_prefill = (
                request_blocks
                - overlap
                - fetch
                + self.config.transfer_block_cost * fetch
            )
            potential_decode = load + request_blocks
            costs.append(
                self.config.overlap_score_weight * potential_prefill + potential_decode
            )
        idx = softmax_sample(costs, self.config.router_temperature, self._rng)
        overlap, fetch = per_worker[idx]
        return Placement(
            worker=workers[idx],
            overlap_blocks=overlap,
            total_blocks=request_blocks,
            fetch_blocks=fetch,
        )

    def _priced_loads(self, loads: list[int]) -> list[float]:
        """Decode-load term per worker under migration-aware pricing.

        With a balancer running, load above the fleet mean is transient —
        the balancer sheds it — so excess beyond mean + migrate_cost_blocks
        is not charged against a warm candidate."""
        cap_extra = self.config.migrate_cost_blocks
        if cap_extra is None or len(loads) < 2:
            return [float(l) for l in loads]
        mean = sum(loads) / len(loads)
        return [min(float(l), mean + cap_extra) for l in loads]

    @staticmethod
    def _fetch_blocks(
        w: WorkerId, overlap: int, request_blocks: int,
        fetchable: dict[WorkerId, int] | None,
    ) -> int:
        if not fetchable:
            return 0
        return max(0, min(fetchable.get(w, 0), request_blocks) - overlap)


def softmax_sample(costs: list[float], temperature: float, rng: random.Random) -> int:
    """Sample an index ∝ softmax(-normalized_cost / temperature).
    temperature <= 0 → argmin (ties broken at random, as the reference
    does to avoid herding)."""
    lo, hi = min(costs), max(costs)
    if temperature <= 0.0 or hi == lo:
        best = [i for i, c in enumerate(costs) if c == lo]
        return rng.choice(best)
    norm = [(c - lo) / (hi - lo) for c in costs]
    logits = [-n / temperature for n in norm]
    m = max(logits)
    exps = [math.exp(l - m) for l in logits]
    total = sum(exps)
    r = rng.random() * total
    acc = 0.0
    for i, e in enumerate(exps):
        acc += e
        if r <= acc:
            return i
    return len(costs) - 1
