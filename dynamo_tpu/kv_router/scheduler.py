"""KV-aware worker selection: overlap-weighted cost + softmax sampling.

Reference analogue: lib/llm/src/kv_router/scheduler.rs —
cost = ``overlap_score_weight × potential_prefill_blocks +
potential_decode_blocks`` per worker, min-max normalized, then
softmax-sampled with ``router_temperature`` (0 ⇒ deterministic argmin;
scheduler.rs:272-340,356-439). Temperature>0 spreads bursts of identical
prompts across workers instead of herding them onto one.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass

from dynamo_tpu.kv_router.indexer import OverlapScores
from dynamo_tpu.kv_router.sequence import ActiveSequences

WorkerId = int


@dataclass
class KvSchedulerConfig:
    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0


@dataclass
class Placement:
    worker: WorkerId
    overlap_blocks: int
    total_blocks: int


class KvScheduler:
    def __init__(self, config: KvSchedulerConfig | None = None, rng: random.Random | None = None):
        self.config = config or KvSchedulerConfig()
        self._rng = rng or random.Random()

    def schedule(
        self,
        workers: list[WorkerId],
        request_blocks: int,
        overlaps: OverlapScores,
        active: ActiveSequences,
    ) -> Placement:
        """Pick a worker for a request spanning ``request_blocks`` blocks."""
        if not workers:
            raise ValueError("no workers")
        costs: list[float] = []
        for w in workers:
            overlap = min(overlaps.scores.get(w, 0), request_blocks)
            potential_prefill = request_blocks - overlap
            potential_decode = active.active_blocks(w) + request_blocks
            costs.append(
                self.config.overlap_score_weight * potential_prefill + potential_decode
            )
        idx = softmax_sample(costs, self.config.router_temperature, self._rng)
        w = workers[idx]
        return Placement(
            worker=w,
            overlap_blocks=min(overlaps.scores.get(w, 0), request_blocks),
            total_blocks=request_blocks,
        )


def softmax_sample(costs: list[float], temperature: float, rng: random.Random) -> int:
    """Sample an index ∝ softmax(-normalized_cost / temperature).
    temperature <= 0 → argmin (ties broken at random, as the reference
    does to avoid herding)."""
    lo, hi = min(costs), max(costs)
    if temperature <= 0.0 or hi == lo:
        best = [i for i, c in enumerate(costs) if c == lo]
        return rng.choice(best)
    norm = [(c - lo) / (hi - lo) for c in costs]
    logits = [-n / temperature for n in norm]
    m = max(logits)
    exps = [math.exp(l - m) for l in logits]
    total = sum(exps)
    r = rng.random() * total
    acc = 0.0
    for i, e in enumerate(exps):
        acc += e
        if r <= acc:
            return i
    return len(costs) - 1
