"""KV-aware worker selection: overlap-weighted cost + softmax sampling.

Reference analogue: lib/llm/src/kv_router/scheduler.rs —
cost = ``overlap_score_weight × potential_prefill_blocks +
potential_decode_blocks`` per worker, min-max normalized, then
softmax-sampled with ``router_temperature`` (0 ⇒ deterministic argmin;
scheduler.rs:272-340,356-439). Temperature>0 spreads bursts of identical
prompts across workers instead of herding them onto one.

Transfer-vs-recompute pricing: when a global prefix directory is live
(fleet/directory.py) the router also passes each candidate's FETCHABLE
depth — prefix blocks it is missing locally but could pull from a
directory-listed holder over the credit-flow transfer plane
(llm/peer_kv.py). Those blocks are priced at ``transfer_block_cost``
(< 1.0: a DMA'd block is cheaper than recomputing it, Mooncake's
transfer-vs-compute tradeoff) instead of full recompute, so a cold but
idle engine next to a warm peer can beat a warm but saturated one —
the directory stops being a stickiness booster and becomes an economy.

Migration-aware placement (Llumnix composition): when a fleet balancer
runs (planner/balancer.py), landing on a loaded-but-warm engine is no
longer a terminal decision — the balancer can relocate the decode later
for roughly one migration's worth of transfer. With
``migrate_cost_blocks`` set, each candidate's decode-load term is capped
at the fleet mean plus that cost: excess load above the mean is priced
as "admit here, shed later" instead of at face value, so cache affinity
wins ties it would otherwise lose to a cold idle engine. ``None``
(default) keeps the original pricing for balancer-less deployments.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass

from dynamo_tpu.kv_router.indexer import OverlapScores
from dynamo_tpu.kv_router.sequence import ActiveSequences

WorkerId = int


@dataclass
class KvSchedulerConfig:
    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    # Candidate pruning: score only `shortlist ∪ least-loaded-m ∪
    # sticky/directory hits` instead of every worker. The shortlist is
    # the overlap index's ranked top-k holders (indexer.find_matches
    # top_k); least-loaded-m comes from the ActiveSequences idle heap.
    # 0 disables pruning entirely — the full-scan loop runs byte-for-byte
    # as before (the escape hatch). Fleets no larger than
    # shortlist_k + least_loaded_m always take the full scan: pruning
    # there saves nothing and the exact argmin is free.
    shortlist_k: int = 16
    least_loaded_m: int = 4
    # Cost of pulling one missing prefix block from a peer, in units of
    # recomputing one block locally (0 = transfers are free, 1 = no
    # cheaper than recompute — directory pricing effectively off).
    # ~0.35 matches the measured peer-fetch vs prefill ratio on the
    # loopback transfer plane (BENCH_DISAGG_r08 frame throughput vs
    # prefill tok/s); a WAN-separated fleet wants it near 1.
    transfer_block_cost: float = 0.35
    # Migration-aware decode pricing: cap each candidate's decode-load
    # term at fleet_mean + migrate_cost_blocks (the amortized price of
    # one later balancer move, in blocks). None = off — load is priced
    # at face value, correct when no balancer will relocate decodes.
    migrate_cost_blocks: float | None = None


@dataclass
class Placement:
    worker: WorkerId
    overlap_blocks: int
    total_blocks: int
    # Blocks the chosen worker should PULL from a peer (directory-priced
    # transfer); 0 when the plain overlap path won.
    fetch_blocks: int = 0
    # Observability: how many workers were actually cost-scored, and
    # whether the full-scan path ran (True for shortlist_k=0, small
    # fleets, or an unsynced roster — the pruned path's fallback).
    candidates_considered: int = 0
    full_scan: bool = True


class KvScheduler:
    def __init__(self, config: KvSchedulerConfig | None = None, rng: random.Random | None = None):
        self.config = config or KvSchedulerConfig()
        self._rng = rng or random.Random()

    def schedule(
        self,
        workers: list[WorkerId],
        request_blocks: int,
        overlaps: OverlapScores,
        active: ActiveSequences,
        fetchable: dict[WorkerId, int] | None = None,
        workers_set: set[WorkerId] | None = None,
        fetch_default: int = 0,
    ) -> Placement:
        """Pick a worker for a request spanning ``request_blocks`` blocks.

        ``fetchable`` maps worker → the deepest leading-run depth any
        OTHER directory-listed holder has for this request (absolute
        blocks from the root); the part beyond the worker's own overlap
        is what a transfer would save, priced at transfer_block_cost.

        With ``shortlist_k > 0`` and a fleet larger than
        shortlist_k + least_loaded_m, only the candidate set
        `overlap holders ∪ fetchable holders ∪ least-loaded-m` is scored
        (O(k), not O(fleet)). Every worker with nonzero overlap/fetch that
        survived index top-k pruning is in the set, and among the
        zero-overlap rest cost differs only by load — so when the index
        shortlist covers all holders the pruned argmin equals the
        full-scan argmin exactly (docs/performance.md, shortlist recall
        policy). ``workers_set`` (eligible-worker membership) avoids an
        O(fleet) set build when the caller already has one."""
        if not workers:
            raise ValueError("no workers")
        k = self.config.shortlist_k
        m = self.config.least_loaded_m
        if k <= 0 or len(workers) <= k + m or active.roster_size() == 0:
            return self._schedule_full(workers, request_blocks, overlaps, active,
                                       fetchable, fetch_default)
        wset = workers_set if workers_set is not None else set(workers)
        cand: list[WorkerId] = []
        seen: set[WorkerId] = set()
        for w in overlaps.scores:
            if w in wset:
                seen.add(w)
                cand.append(w)
        if fetchable:
            for w in fetchable:
                if w in wset and w not in seen:
                    seen.add(w)
                    cand.append(w)
        for w in active.least_loaded(m, exclude=seen):
            if w in wset:
                cand.append(w)
        if not cand:
            return self._schedule_full(workers, request_blocks, overlaps, active,
                                       fetchable, fetch_default)
        mean = active.roster_mean_load()
        return self._score(cand, request_blocks, overlaps, active, fetchable,
                           fetch_default, mean=mean, full_scan=False)

    def _schedule_full(
        self,
        workers: list[WorkerId],
        request_blocks: int,
        overlaps: OverlapScores,
        active: ActiveSequences,
        fetchable: dict[WorkerId, int] | None,
        fetch_default: int = 0,
    ) -> Placement:
        """Legacy O(fleet) scan — the shortlist_k=0 escape hatch. Scores
        every worker and derives the fleet mean from the scored loads,
        byte-identical to the pre-shortlist scheduler."""
        per_worker: list[tuple[int, int]] = []  # (overlap, fetch) per worker
        loads: list[int] = []
        for w in workers:
            overlap = min(overlaps.scores.get(w, 0), request_blocks)
            fetch = self._fetch_blocks(w, overlap, request_blocks, fetchable, fetch_default)
            per_worker.append((overlap, fetch))
            loads.append(active.active_blocks(w))
        priced = self._priced_loads(loads)
        costs: list[float] = []
        for (overlap, fetch), load in zip(per_worker, priced):
            potential_prefill = (
                request_blocks
                - overlap
                - fetch
                + self.config.transfer_block_cost * fetch
            )
            potential_decode = load + request_blocks
            costs.append(
                self.config.overlap_score_weight * potential_prefill + potential_decode
            )
        idx = softmax_sample(costs, self.config.router_temperature, self._rng)
        overlap, fetch = per_worker[idx]
        return Placement(
            worker=workers[idx],
            overlap_blocks=overlap,
            total_blocks=request_blocks,
            fetch_blocks=fetch,
            candidates_considered=len(workers),
            full_scan=True,
        )

    def _score(
        self,
        cand: list[WorkerId],
        request_blocks: int,
        overlaps: OverlapScores,
        active: ActiveSequences,
        fetchable: dict[WorkerId, int] | None,
        fetch_default: int,
        mean: float,
        full_scan: bool,
    ) -> Placement:
        """Cost-score ``cand`` only, using the incrementally-maintained
        roster mean for migration-aware load pricing instead of an
        O(fleet) recompute."""
        cap_extra = self.config.migrate_cost_blocks
        cap = None if cap_extra is None else mean + cap_extra
        per_worker: list[tuple[int, int]] = []
        costs: list[float] = []
        for w in cand:
            overlap = min(overlaps.scores.get(w, 0), request_blocks)
            fetch = self._fetch_blocks(w, overlap, request_blocks, fetchable, fetch_default)
            per_worker.append((overlap, fetch))
            load = float(active.active_blocks(w))
            if cap is not None and load > cap:
                load = cap
            potential_prefill = (
                request_blocks
                - overlap
                - fetch
                + self.config.transfer_block_cost * fetch
            )
            potential_decode = load + request_blocks
            costs.append(
                self.config.overlap_score_weight * potential_prefill + potential_decode
            )
        idx = softmax_sample(costs, self.config.router_temperature, self._rng)
        overlap, fetch = per_worker[idx]
        return Placement(
            worker=cand[idx],
            overlap_blocks=overlap,
            total_blocks=request_blocks,
            fetch_blocks=fetch,
            candidates_considered=len(cand),
            full_scan=full_scan,
        )

    def _priced_loads(self, loads: list[int]) -> list[float]:
        """Decode-load term per worker under migration-aware pricing.

        With a balancer running, load above the fleet mean is transient —
        the balancer sheds it — so excess beyond mean + migrate_cost_blocks
        is not charged against a warm candidate."""
        cap_extra = self.config.migrate_cost_blocks
        if cap_extra is None or len(loads) < 2:
            return [float(l) for l in loads]
        mean = sum(loads) / len(loads)
        return [min(float(l), mean + cap_extra) for l in loads]

    @staticmethod
    def _fetch_blocks(
        w: WorkerId, overlap: int, request_blocks: int,
        fetchable: dict[WorkerId, int] | None,
        default: int = 0,
    ) -> int:
        """``default`` is the compact-fetchable fallback depth for workers
        the dict doesn't list (pruned mode lists holders only; everyone
        else's max-over-other-holders run is the global best run)."""
        if not fetchable:
            return 0
        return max(0, min(fetchable.get(w, default), request_blocks) - overlap)


def softmax_sample(costs: list[float], temperature: float, rng: random.Random) -> int:
    """Sample an index ∝ softmax(-normalized_cost / temperature).
    temperature <= 0 → argmin (ties broken at random, as the reference
    does to avoid herding)."""
    lo, hi = min(costs), max(costs)
    if temperature <= 0.0 or hi == lo:
        best = [i for i, c in enumerate(costs) if c == lo]
        return rng.choice(best)
    norm = [(c - lo) / (hi - lo) for c in costs]
    logits = [-n / temperature for n in norm]
    m = max(logits)
    exps = [math.exp(l - m) for l in logits]
    total = sum(exps)
    r = rng.random() * total
    acc = 0.0
    for i, e in enumerate(exps):
        acc += e
        if r <= acc:
            return i
    return len(costs) - 1
