"""KvPushRouter: the KV-cache-aware routing engine.

Reference analogue: ``KvRouter``/``KvPushRouter`` (reference: lib/llm/src/
kv_router.rs:225-369): hash the request's prompt blocks, look up per-worker
prefix overlap in the live index, pick the lowest-cost worker (softmax
temperature), inject ``estimated_prefix_hit_num_blocks``, direct-route, and
track the request in the active-sequence ledger until its stream ends.

Index freshness: one KV-event stream subscription per live worker instance
(publisher.KvEventSubscription), reconciled against discovery; a worker
vanishing (lease expiry or stream death) drops its index state. Engines
that publish no events run in ``use_kv_events=False`` mode with the
TTL-predictive ApproxKvIndexer (reference: kv_router.rs:170-176).
"""

from __future__ import annotations

import asyncio
import contextlib
import time
from dataclasses import dataclass
from typing import Any, AsyncIterator

from dynamo_tpu.kv_router.approx import ApproxKvIndexer
from dynamo_tpu.kv_router.protocols import KVHitRateEvent
from dynamo_tpu.kv_router.indexer import RadixIndex, ShardedRadixIndex
from dynamo_tpu.kv_router.publisher import KvEventSubscription
from dynamo_tpu.kv_router.scheduler import KvScheduler, KvSchedulerConfig
from dynamo_tpu.kv_router.sequence import ActiveSequences
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import get_logger
from dynamo_tpu.runtime.messaging import (
    NoHandlerError,
    OverloadedError,
    TruncatedStreamError,
)
from dynamo_tpu.runtime.push_router import NoInstancesError, PushRouter
from dynamo_tpu.tokens import adapter_hash_seed, compute_block_hashes

log = get_logger("kv_router")


@dataclass
class KvRouterConfig:
    block_size: int = 16
    overlap_score_weight: float = 1.0
    router_temperature: float = 0.0
    use_kv_events: bool = True
    approx_ttl_s: float = 120.0
    max_attempts: int = 3
    # Index sharding (reference: KvIndexerSharded, indexer.rs:856-985):
    # >0 runs the event-driven index across this many shard threads so
    # event floods never stall the routing loop. 0 = single in-loop index.
    index_shards: int = 0
    # Cross-worker KV reuse (the reference's G4 remote tier,
    # lib/llm/src/block_manager.rs:68-81): when the chosen worker's local
    # overlap trails another worker's by at least this many blocks, the
    # request carries a ``peer_prefix`` hint naming that worker; the
    # chosen worker fetches the prefix pages from the peer's host tier
    # (llm/peer_kv.py) instead of recomputing them. 0 disables.
    peer_fetch_min_blocks: int = 4
    # Migration-aware placement (planner/balancer.py): when a fleet
    # balancer relocates decodes off hot engines, set this to the
    # amortized per-move cost in blocks — the scheduler then caps each
    # candidate's decode-load term at fleet_mean + this, pricing
    # "admit on the warm engine, balancer sheds later" over landing
    # cold. None = off (no balancer, load priced at face value).
    migrate_cost_blocks: float | None = None
    # Cluster-scale candidate pruning (docs/performance.md
    # "Control-plane scaling"): the index returns a ranked top-k holder
    # shortlist and the scheduler scores only shortlist ∪ least-loaded-m
    # ∪ sticky/directory hits — O(k) per placement instead of O(fleet).
    # 0 = full scan, byte-for-byte the pre-shortlist behavior. Fleets no
    # larger than shortlist_k + least_loaded_m always take the full scan.
    shortlist_k: int = 16
    least_loaded_m: int = 4


# How long a cached discovery roster stays fresh without a version bump.
# The version counter covers registration/lease/breaker *events*, but an
# open circuit transitions to half-open silently on read — a purely
# version-keyed cache would starve the probe. 100 ms keeps the O(fleet)
# roster scan off the per-request path while admitting probes within a
# tenth of a second of their cooldown.
_ROSTER_TTL_S = 0.1


# Placement decisions are sub-millisecond dict work; the default
# seconds-scale buckets would flatten the whole distribution into the
# first bucket. 50 µs … 1 s covers pruned hot path through full-scan
# stalls at 1000 engines.
_PLACE_BUCKETS = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 1.0, float("inf"),
)


def register_router_metrics(registry) -> dict:
    """Placement hot-path series (documented in docs/observability.md,
    cataloged by DT006). Returns the metrics dict KvPushRouter accepts;
    merge with the transfer_choices counter where the fleet economy is
    wired."""
    return {
        "place_seconds": registry.histogram(
            "router_place_seconds",
            "Placement decision latency: hash chain, overlap lookup, cost schedule",
            buckets=_PLACE_BUCKETS,
        ),
        "candidates_considered": registry.counter(
            "router_candidates_considered",
            "Workers cost-scored by placements; divide by router_decisions_total for mean candidate-set size",
        ),
        "shortlist_fallback": registry.counter(
            "router_shortlist_fallback_total",
            "Placements that ran the O(fleet) full scan while shortlist pruning was enabled",
        ),
    }


class KvPushRouter:
    """AsyncEngine shape over a DIRECT PushRouter."""

    def __init__(self, push_router: PushRouter, config: KvRouterConfig | None = None,
                 event_sink=None, decisions=None, directory=None, metrics=None):
        self.config = config or KvRouterConfig()
        # callable(KVHitRateEvent) — routing-quality observability
        # (reference: scheduler.rs KVHitRateEvent → components/metrics).
        self.event_sink = event_sink
        # Fleet sticky-routing cache (fleet/decisions.py ScopedDecisions):
        # placements published by SIBLING frontend processes act as an
        # overlap floor, so a conversation's follow-up turn routes to the
        # engine holding its prefix no matter which process accepts it.
        self.decisions = decisions
        # Global prefix directory (fleet/directory.py PrefixDirectory):
        # ground-truth block residency for transfer-vs-recompute pricing.
        # A worker's OWN directory run floors its overlap (the index only
        # sees G1 events; the directory also knows its G2-G4 holdings),
        # and the deepest run held by anyone ELSE prices as a transfer.
        self.directory = directory
        # Optional {"transfer_choices": counter} — the
        # fleet_kv_transfer_vs_recompute_total{choice} feed.
        self._m = metrics or {}
        self.push = push_router
        self.discovery = push_router.discovery
        self.messaging = push_router.messaging
        self.scheduler = KvScheduler(
            KvSchedulerConfig(
                overlap_score_weight=self.config.overlap_score_weight,
                router_temperature=self.config.router_temperature,
                migrate_cost_blocks=self.config.migrate_cost_blocks,
                shortlist_k=self.config.shortlist_k,
                least_loaded_m=self.config.least_loaded_m,
            )
        )
        self.active = ActiveSequences()
        # Cached discovery roster (shortlist mode only): list + membership
        # set + roster sync into ActiveSequences, refreshed on discovery
        # version change or _ROSTER_TTL_S, whichever comes first.
        self._roster: list[int] = []
        self._roster_set: set[int] = set()
        self._roster_version: int = -1
        self._roster_stamp: float = 0.0
        if not self.config.use_kv_events:
            self.index: RadixIndex | ShardedRadixIndex | ApproxKvIndexer = (
                ApproxKvIndexer(ttl_s=self.config.approx_ttl_s)
            )
        elif self.config.index_shards > 0:
            self.index = ShardedRadixIndex(self.config.index_shards)
        else:
            self.index = RadixIndex()
        self._subs: dict[int, KvEventSubscription] = {}
        self._sub_started: dict[int, float] = {}
        self._sync_task: asyncio.Task | None = None
        self._resync = asyncio.Event()
        self._bg_tasks: set[asyncio.Task] = set()

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> "KvPushRouter":
        if self.config.use_kv_events and self._sync_task is None:
            self._reconcile()
            self._sync_task = asyncio.get_running_loop().create_task(self._sync_loop())
        return self

    async def close(self) -> None:
        if self._sync_task is not None:
            self._sync_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._sync_task
        for sub in list(self._subs.values()):
            await sub.close()
        self._subs.clear()
        if isinstance(self.index, ShardedRadixIndex):
            self.index.close()

    async def _sync_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while True:
            v = self.discovery.version  # read BEFORE reconcile: no lost wakeup
            self._resync.clear()
            self._reconcile()
            waiter = loop.create_task(self.discovery.wait_changed(v))
            resync = loop.create_task(self._resync.wait())
            try:
                await asyncio.wait({waiter, resync}, return_when=asyncio.FIRST_COMPLETED)
            finally:
                waiter.cancel()
                resync.cancel()

    def _spawn(self, coro) -> None:
        task = asyncio.get_running_loop().create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def _reconcile(self) -> None:
        assert isinstance(self.index, (RadixIndex, ShardedRadixIndex))
        live = {i.instance_id: i for i in self.discovery.available()}
        for wid in list(self._subs):
            if wid not in live:
                sub = self._subs.pop(wid)
                self._spawn(sub.close())
                self.index.remove_worker(wid)
                self.active.remove_worker(wid)
        for wid, inst in live.items():
            if wid not in self._subs:
                sub = KvEventSubscription(
                    self.messaging, inst, self.index.apply, self._on_sub_end
                )
                self._subs[wid] = sub
                self._sub_started[wid] = asyncio.get_running_loop().time()
                sub.start()

    def _on_sub_end(self, wid: int) -> None:
        # Stream died (worker gone or event gap): drop state; if the worker
        # is still discovered, the reconcile pass resubscribes fresh. A
        # subscription that died young (endpoint missing/broken) is retried
        # with a delay so a permanently-failing worker can't hot-loop us.
        self._subs.pop(wid, None)
        if isinstance(self.index, (RadixIndex, ShardedRadixIndex)):
            self.index.remove_worker(wid)
        loop = asyncio.get_running_loop()
        lifetime = loop.time() - self._sub_started.pop(wid, 0.0)
        if lifetime < 1.0:
            loop.call_later(1.0, self._resync.set)
        else:
            self._resync.set()

    # -- routing ----------------------------------------------------------

    def _place(self, token_ids: list[int], excluded: set[int] = frozenset(),
               adapter_id: str | None = None):
        """Shared placement recipe: hash → overlap lookup → cost schedule.
        → (Placement, hashes, per-worker overlap scores, eligible
        workers, directory runs). Raises NoInstancesError when no
        candidate.

        ``adapter_id`` salts the block hashes (tokens.adapter_hash_seed)
        exactly as the engines do, so stickiness and overlap scoring are
        keyed by (model, adapter): a conversation lands where both its KV
        prefix AND its adapter are warm, and an identical prompt under a
        different adapter can never ride another identity's cache."""
        t0 = time.perf_counter() if self._m else 0.0
        bs = self.config.block_size
        hashes = compute_block_hashes(token_ids, bs, adapter_hash_seed(adapter_id))
        request_blocks = max(1, (len(token_ids) + bs - 1) // bs)
        k = self.config.shortlist_k
        if k > 0:
            # Shortlist mode: amortize the O(fleet) discovery scan behind
            # a (version, TTL)-keyed roster cache and keep the
            # ActiveSequences idle heap synced to it.
            v = self.discovery.version
            now = time.monotonic()
            if v != self._roster_version or now - self._roster_stamp > _ROSTER_TTL_S:
                self._roster = self.discovery.instance_ids()
                self._roster_set = set(self._roster)
                self._roster_version = v
                self._roster_stamp = now
                self.active.sync_roster(self._roster)
            if excluded:
                workers = [w for w in self._roster if w not in excluded]
                eligible_set = set(workers)
            else:
                workers = self._roster
                eligible_set = self._roster_set
        else:
            workers = [w for w in self.discovery.instance_ids() if w not in excluded]
            eligible_set = None  # legacy membership checks scan the list
        if not workers:
            raise NoInstancesError("no available instances")
        overlaps = self.index.find_matches(hashes, top_k=k)
        if self.decisions is not None:
            # Cross-process stickiness: a sibling's published placement is
            # an overlap FLOOR fed to the same cost schedule — a deeper
            # live-index match still wins, and a dead/excluded worker is
            # simply not boosted (the index can't vouch for the cache).
            cached = self.decisions.lookup(hashes)
            if cached is not None:
                wid, depth = cached
                member = wid in (eligible_set if eligible_set is not None else workers)
                if member and depth > overlaps.scores.get(wid, 0):
                    overlaps.scores[wid] = depth
        # Would the scheduler actually prune? (Mirrors its own predicate.)
        prune = (
            k > 0
            and len(workers) > k + self.config.least_loaded_m
            and self.active.roster_size() > 0
        )
        dir_runs: dict[int, int] = {}
        fetchable: dict[int, int] | None = None
        fetch_default = 0
        if self.directory is not None:
            dir_runs = {
                wid: d for wid, d in self.directory.best_runs(hashes).items()
                if wid not in excluded
            }
            if dir_runs:
                for wid, d in dir_runs.items():
                    # Own holdings floor the overlap: the live index only
                    # mirrors G1 events, the directory also knows the
                    # worker's G2-G4 (and drained-in) residency. (Only
                    # listed holders can floor — everyone else's run is 0.)
                    member = wid in (eligible_set if eligible_set is not None else workers)
                    if member and d > overlaps.scores.get(wid, 0):
                        overlaps.scores[wid] = d
                # Per-candidate transferable depth: the deepest run some
                # OTHER holder (any pool — a prefill-role engine serves
                # kv_prefix too) could stream to it.
                if prune:
                    # O(holders): for any worker, max-over-others is the
                    # global best run — or the second best if the worker
                    # IS the best holder. Non-holders take fetch_default.
                    top1_w, top1_d, top2_d = 0, 0, 0
                    for wid, d in dir_runs.items():
                        if d > top1_d:
                            top2_d, top1_d, top1_w = top1_d, d, wid
                        elif d > top2_d:
                            top2_d = d
                    fetch_default = top1_d
                    fetchable = {}
                    for wid in dir_runs:
                        if wid in eligible_set:
                            peer = top2_d if wid == top1_w else top1_d
                            if peer:
                                fetchable[wid] = peer
                    fetchable = fetchable or None
                else:
                    fetchable = {}
                    for w in workers:
                        peer = max(
                            (d for wid, d in dir_runs.items() if wid != w),
                            default=0,
                        )
                        if peer:
                            fetchable[w] = peer
                    fetchable = fetchable or None
        placement = self.scheduler.schedule(
            workers, request_blocks, overlaps, self.active, fetchable=fetchable,
            workers_set=eligible_set, fetch_default=fetch_default,
        )
        if self._m:
            h = self._m.get("place_seconds")
            if h is not None:
                h.observe(time.perf_counter() - t0)
            c = self._m.get("candidates_considered")
            if c is not None:
                c.inc(placement.candidates_considered)
            if k > 0 and placement.full_scan:
                f = self._m.get("shortlist_fallback")
                if f is not None:
                    f.inc()
        return placement, hashes, overlaps.scores, workers, dir_runs

    def _peer_hint(self, placement, scores: dict[int, int],
                   eligible: list[int],
                   dir_runs: dict[int, int] | None = None) -> dict | None:
        """G4 cross-worker reuse hint: the workers holding the most extra
        prefix blocks relative to the chosen placement, if the gap clears
        ``peer_fetch_min_blocks``. Index-scored candidates are filtered
        to ``eligible`` (the index can lag discovery); directory-listed
        holders are lease-live by construction and may sit in OTHER pools
        (a prefill-role or draining engine serves kv_prefix too, so it
        need not be in the placement set). The hint carries every viable
        holder deepest-first — the fetcher fails over down the list —
        plus the legacy single-holder fields."""
        m = self.config.peer_fetch_min_blocks
        if m <= 0:
            return None
        live = set(eligible)
        cand: dict[int, int] = {}
        for wid, overlap in scores.items():
            if wid != placement.worker and wid in live:
                cand[wid] = max(cand.get(wid, 0), int(overlap))
        for wid, depth in (dir_runs or {}).items():
            if wid != placement.worker:
                cand[wid] = max(cand.get(wid, 0), int(depth))
        floor = placement.overlap_blocks + m
        ranked = sorted(
            ((d, wid) for wid, d in cand.items() if d >= floor), reverse=True
        )
        if not ranked:
            return None
        holders = [
            {"instance_id": wid, "num_blocks": d} for d, wid in ranked[:3]
        ]
        return {**holders[0], "holders": holders}

    def find_best_match(self, token_ids: list[int],
                        adapter_id: str | None = None) -> tuple[int, int]:
        """→ (worker_instance_id, overlap_blocks) without routing — the
        reference's `query_instance_id` surface (kv_router.rs:225-264)."""
        placement, _, _, _, _ = self._place(token_ids, adapter_id=adapter_id)
        return placement.worker, placement.overlap_blocks

    async def generate(self, request: Any, context: Context) -> AsyncIterator[Any]:
        token_ids = list(request.get("token_ids") or []) if isinstance(request, dict) else []
        adapter_id = request.get("adapter_id") if isinstance(request, dict) else None

        if isinstance(request, dict) and request.get("annotations", {}).get("query_instance_id"):
            wid, overlap = self.find_best_match(token_ids, adapter_id)
            yield {"worker_instance_id": wid, "overlap_blocks": overlap}
            return

        attempts = 0
        excluded: set[int] = set()
        last_err: Exception | None = None
        # KV transfer state the CALLER attached (disagg inject/export)
        # is preserved verbatim; our own peer hint is recomputed per
        # attempt so a retry never carries a stale/failed peer.
        user_ktp = request.get("kv_transfer_params") if isinstance(request, dict) else None
        # Live-migration resume leg: pin the FIRST attempt to the
        # destination that holds the staged KV. A pre-stream failure
        # (destination died after committing) falls through to normal
        # placement — the resume identity rides the request, so any
        # worker serves the leg by re-prefilling, still byte-identical.
        # ``rebind: False`` (dead decision store) skips the stickiness
        # rewrite; otherwise the first frame from the destination
        # rebinds the decision cache atomically below.
        mig_pin = (user_ktp or {}).get("migration_resume") if isinstance(user_ktp, dict) else None
        pin_wid = mig_pin.get("instance") if isinstance(mig_pin, dict) else None
        no_rebind = isinstance(mig_pin, dict) and mig_pin.get("rebind") is False
        while attempts < self.config.max_attempts:
            attempts += 1
            try:
                placement, hashes, scores, eligible, dir_runs = self._place(
                    token_ids, excluded, adapter_id
                )
            except NoInstancesError:
                break
            wid = placement.worker
            if pin_wid is not None:
                if pin_wid in eligible:
                    wid = pin_wid
                pin_wid = None  # the pin governs the first attempt only
            if self.event_sink is not None:
                try:
                    self.event_sink(KVHitRateEvent(
                        worker_id=wid,
                        isl_blocks=placement.total_blocks,
                        overlap_blocks=placement.overlap_blocks,
                    ))
                except Exception:  # noqa: BLE001 — observability never breaks routing
                    log.exception("hit-rate event sink failed")
            if isinstance(request, dict):
                request = dict(request)
                request["estimated_prefix_hit_num_blocks"] = (
                    placement.overlap_blocks if wid == placement.worker
                    else int(scores.get(wid, 0))
                )
                if user_ktp:
                    request["kv_transfer_params"] = user_ktp
                else:
                    hint = self._peer_hint(placement, scores, eligible, dir_runs)
                    request["kv_transfer_params"] = (
                        {"peer_prefix": hint} if hint is not None else None
                    )
                    if (
                        self.directory is not None
                        and "transfer_choices" in self._m
                        and 0 < self.config.peer_fetch_min_blocks
                        <= placement.total_blocks - placement.overlap_blocks
                    ):
                        # Economy outcome for a non-trivially-missing
                        # prefix: pull it from a holder, or prefill it.
                        self._m["transfer_choices"].inc(
                            choice="transfer" if hint else "recompute"
                        )
            self.active.add_request(
                context.id, wid, placement.total_blocks, placement.overlap_blocks, len(token_ids)
            )
            if isinstance(self.index, ApproxKvIndexer):
                self.index.record_routing(wid, hashes)
            first = True
            stream = self.push.generate(request, context, instance_id=wid)
            try:
                async for item in stream:
                    if first:
                        first = False
                        self.active.mark_prefill_complete(context.id)
                        if self.decisions is not None and not no_rebind:
                            # Publish only once the stream started: the
                            # worker demonstrably accepted the request,
                            # so its cache really holds this prefix. For
                            # a migration resume leg this IS the atomic
                            # stickiness rebind to the destination.
                            self.decisions.record(hashes, wid)
                    yield item
                return
            except (
                NoInstancesError,  # worker vanished between placement and dispatch
                TruncatedStreamError,
                NoHandlerError,
                OverloadedError,  # admission-gate refusal: place on next-best
                ConnectionError,
                OSError,
            ) as e:
                last_err = e
                if not first:
                    raise  # mid-stream death: Migration's responsibility
                log.warning("kv route to %x failed pre-stream: %s", wid, e)
                excluded.add(wid)
                continue
            finally:
                self.active.free(context.id)
                # Deterministic close: an abandoned inner stream must run its
                # finallys (span end, wire cancel) now, not at async-GC.
                await stream.aclose()
        raise last_err or NoInstancesError("no available instances")
