"""KV router wire protocols: cache events + worker metrics.

Reference analogue: lib/llm/src/kv_router/protocols.rs:43-180
(``KvCacheEvent{Stored,Removed,Cleared}``, ``ForwardPassMetrics``
{WorkerStats, KvStats}) — msgpack dicts on the wire here.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

# Event types
STORED = "stored"
REMOVED = "removed"
CLEARED = "cleared"


@dataclass
class StoredBlock:
    block_hash: int          # chained sequence hash (tokens.py semantics)
    parent_hash: int | None  # parent sequence hash (None = root block)

    def to_dict(self) -> dict:
        return {"block_hash": self.block_hash, "parent_hash": self.parent_hash}

    @classmethod
    def from_dict(cls, d: dict) -> "StoredBlock":
        return cls(block_hash=int(d["block_hash"]), parent_hash=d.get("parent_hash"))


@dataclass
class KvCacheEvent:
    """One cache mutation on one worker. ``event_id`` is a per-worker
    monotonic sequence number so the indexer can detect gaps."""

    kind: str                                    # stored | removed | cleared
    event_id: int = 0
    blocks: list[StoredBlock] = field(default_factory=list)   # for stored
    block_hashes: list[int] = field(default_factory=list)     # for removed

    @classmethod
    def stored(cls, blocks: list[StoredBlock], event_id: int = 0) -> "KvCacheEvent":
        return cls(kind=STORED, event_id=event_id, blocks=blocks)

    @classmethod
    def removed(cls, hashes: list[int], event_id: int = 0) -> "KvCacheEvent":
        return cls(kind=REMOVED, event_id=event_id, block_hashes=hashes)

    @classmethod
    def cleared(cls, event_id: int = 0) -> "KvCacheEvent":
        return cls(kind=CLEARED, event_id=event_id)

    def to_dict(self) -> dict:
        return {
            "kind": self.kind,
            "event_id": self.event_id,
            "blocks": [b.to_dict() for b in self.blocks],
            "block_hashes": list(self.block_hashes),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "KvCacheEvent":
        return cls(
            kind=d["kind"],
            event_id=int(d.get("event_id", 0)),
            blocks=[StoredBlock.from_dict(b) for b in d.get("blocks") or []],
            block_hashes=[int(h) for h in d.get("block_hashes") or []],
        )


@dataclass
class WorkerStats:
    request_active_slots: int = 0
    request_total_slots: int = 0
    num_requests_waiting: int = 0

    def to_dict(self) -> dict:
        return {
            "request_active_slots": self.request_active_slots,
            "request_total_slots": self.request_total_slots,
            "num_requests_waiting": self.num_requests_waiting,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "WorkerStats":
        return cls(
            request_active_slots=int(d.get("request_active_slots", 0)),
            request_total_slots=int(d.get("request_total_slots", 0)),
            num_requests_waiting=int(d.get("num_requests_waiting", 0)),
        )


@dataclass
class KvStats:
    kv_active_blocks: int = 0
    kv_total_blocks: int = 0
    gpu_cache_usage_perc: float = 0.0      # name kept for dashboard parity
    gpu_prefix_cache_hit_rate: float = 0.0

    def to_dict(self) -> dict:
        return {
            "kv_active_blocks": self.kv_active_blocks,
            "kv_total_blocks": self.kv_total_blocks,
            "gpu_cache_usage_perc": self.gpu_cache_usage_perc,
            "gpu_prefix_cache_hit_rate": self.gpu_prefix_cache_hit_rate,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "KvStats":
        return cls(
            kv_active_blocks=int(d.get("kv_active_blocks", 0)),
            kv_total_blocks=int(d.get("kv_total_blocks", 0)),
            gpu_cache_usage_perc=float(d.get("gpu_cache_usage_perc", 0.0)),
            gpu_prefix_cache_hit_rate=float(d.get("gpu_prefix_cache_hit_rate", 0.0)),
        )


@dataclass
class KVHitRateEvent:
    """One routing decision's prefix-hit outcome (reference:
    lib/llm/src/kv_router/scheduler.rs:107-214 emits these on NATS;
    here they flow to an injectable sink — metrics and the recorder)."""

    worker_id: int
    isl_blocks: int       # request length in blocks
    overlap_blocks: int   # prefix blocks already on the chosen worker

    @property
    def hit_rate(self) -> float:
        return self.overlap_blocks / self.isl_blocks if self.isl_blocks else 0.0

    def to_dict(self) -> dict:
        return {"worker_id": self.worker_id, "isl_blocks": self.isl_blocks,
                "overlap_blocks": self.overlap_blocks}


@dataclass
class ForwardPassMetrics:
    """Per-worker load snapshot served on the ``load_metrics`` endpoint
    (reference: kv_router/publisher.rs:481-523)."""

    worker: WorkerStats = field(default_factory=WorkerStats)
    kv: KvStats = field(default_factory=KvStats)

    def to_dict(self) -> dict:
        return {"worker": self.worker.to_dict(), "kv": self.kv.to_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "ForwardPassMetrics":
        return cls(
            worker=WorkerStats.from_dict(d.get("worker") or {}),
            kv=KvStats.from_dict(d.get("kv") or {}),
        )
