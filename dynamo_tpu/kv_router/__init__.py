"""KV-cache-aware routing.

Reference analogue: lib/llm/src/kv_router/ — the headline subsystem
(3x TTFT claim): workers publish KV cache block events + load metrics;
the frontend maintains a global radix tree over block hashes and routes
each request to the worker with the best (prefix-overlap, load) cost.
"""

from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    KvStats,
    WorkerStats,
)

__all__ = ["KvCacheEvent", "ForwardPassMetrics", "WorkerStats", "KvStats"]
