"""Active-sequence tracking: the router's view of each worker's load.

Reference analogue: ``ActiveSequences``/``ActiveSequencesMultiWorker``
(reference: lib/llm/src/kv_router/sequence.rs:51-232,240-521): per worker,
the blocks and tokens of requests it is currently serving — *including*
the request being placed ("potential" load) — with prefill-complete and
free transitions. The cost scheduler reads these to balance load.
"""

from __future__ import annotations

from dataclasses import dataclass

WorkerId = int


@dataclass
class _ActiveReq:
    worker: WorkerId
    new_blocks: int      # blocks this request adds (non-overlapping)
    tokens: int          # prompt tokens still prefilling (0 once complete)


class ActiveSequences:
    """Multi-worker active-request ledger (router-side bookkeeping only —
    workers are the source of truth for their real usage)."""

    def __init__(self):
        self._reqs: dict[str, _ActiveReq] = {}
        self._blocks: dict[WorkerId, int] = {}
        self._prefill_tokens: dict[WorkerId, int] = {}
        self._count: dict[WorkerId, int] = {}

    def add_request(
        self, request_id: str, worker: WorkerId, total_blocks: int, overlap_blocks: int, prompt_tokens: int
    ) -> None:
        new_blocks = max(0, total_blocks - overlap_blocks)
        self._reqs[request_id] = _ActiveReq(worker, new_blocks, prompt_tokens)
        self._blocks[worker] = self._blocks.get(worker, 0) + new_blocks
        self._prefill_tokens[worker] = self._prefill_tokens.get(worker, 0) + prompt_tokens
        self._count[worker] = self._count.get(worker, 0) + 1

    def mark_prefill_complete(self, request_id: str) -> None:
        req = self._reqs.get(request_id)
        if req is not None and req.tokens:
            self._prefill_tokens[req.worker] -= req.tokens
            req.tokens = 0

    def free(self, request_id: str) -> None:
        req = self._reqs.pop(request_id, None)
        if req is None:
            return
        self._blocks[req.worker] = self._blocks.get(req.worker, 0) - req.new_blocks
        if req.tokens:
            self._prefill_tokens[req.worker] -= req.tokens
        self._count[req.worker] = self._count.get(req.worker, 0) - 1

    def remove_worker(self, worker: WorkerId) -> None:
        for rid in [r for r, req in self._reqs.items() if req.worker == worker]:
            self._reqs.pop(rid)
        self._blocks.pop(worker, None)
        self._prefill_tokens.pop(worker, None)
        self._count.pop(worker, None)

    def active_blocks(self, worker: WorkerId) -> int:
        return self._blocks.get(worker, 0)

    def prefill_tokens(self, worker: WorkerId) -> int:
        return self._prefill_tokens.get(worker, 0)

    def active_count(self, worker: WorkerId) -> int:
        return self._count.get(worker, 0)
