"""Active-sequence tracking: the router's view of each worker's load.

Reference analogue: ``ActiveSequences``/``ActiveSequencesMultiWorker``
(reference: lib/llm/src/kv_router/sequence.rs:51-232,240-521): per worker,
the blocks and tokens of requests it is currently serving — *including*
the request being placed ("potential" load) — with prefill-complete and
free transitions. The cost scheduler reads these to balance load.

Cluster-scale addition: the ledger also maintains the *fleet aggregates*
the scheduler used to recompute per request — a running total of active
blocks (for the fleet-load mean) and a lazily-invalidated min-heap of
(load, worker) for least-loaded-m candidate selection. Both are updated
on load deltas, so placement stops paying O(fleet) per request
(docs/performance.md "Control-plane scaling").
"""

from __future__ import annotations

import heapq
from collections.abc import Iterable
from dataclasses import dataclass

WorkerId = int


@dataclass
class _ActiveReq:
    worker: WorkerId
    new_blocks: int      # blocks this request adds (non-overlapping)
    tokens: int          # prompt tokens still prefilling (0 once complete)


class ActiveSequences:
    """Multi-worker active-request ledger (router-side bookkeeping only —
    workers are the source of truth for their real usage)."""

    def __init__(self):
        self._reqs: dict[str, _ActiveReq] = {}
        self._blocks: dict[WorkerId, int] = {}
        self._prefill_tokens: dict[WorkerId, int] = {}
        self._count: dict[WorkerId, int] = {}
        # -- incremental fleet aggregates (shortlist scheduling) ----------
        # Roster = workers eligible for placement, synced by the router on
        # discovery-version change (O(fleet) once per roster change, not
        # per request). The heap uses lazy deletion: every load delta for
        # a rostered worker pushes a fresh (load, worker) entry; stale
        # entries are discarded on pop by comparing against current load.
        self._roster: set[WorkerId] = set()
        self._roster_total: int = 0           # sum of rostered workers' blocks
        self._heap: list[tuple[int, WorkerId]] = []

    # -- request transitions ----------------------------------------------

    def add_request(
        self, request_id: str, worker: WorkerId, total_blocks: int, overlap_blocks: int, prompt_tokens: int
    ) -> None:
        new_blocks = max(0, total_blocks - overlap_blocks)
        self._reqs[request_id] = _ActiveReq(worker, new_blocks, prompt_tokens)
        load = self._blocks.get(worker, 0) + new_blocks
        self._blocks[worker] = load
        self._prefill_tokens[worker] = self._prefill_tokens.get(worker, 0) + prompt_tokens
        self._count[worker] = self._count.get(worker, 0) + 1
        if worker in self._roster:
            self._roster_total += new_blocks
            self._push(load, worker)

    def mark_prefill_complete(self, request_id: str) -> None:
        req = self._reqs.get(request_id)
        if req is not None and req.tokens:
            self._prefill_tokens[req.worker] -= req.tokens
            req.tokens = 0

    def free(self, request_id: str) -> None:
        req = self._reqs.pop(request_id, None)
        if req is None:
            return
        load = self._blocks.get(req.worker, 0) - req.new_blocks
        self._blocks[req.worker] = load
        if req.tokens:
            self._prefill_tokens[req.worker] -= req.tokens
        self._count[req.worker] = self._count.get(req.worker, 0) - 1
        if req.worker in self._roster:
            self._roster_total -= req.new_blocks
            self._push(load, req.worker)

    def remove_worker(self, worker: WorkerId) -> None:
        for rid in [r for r, req in self._reqs.items() if req.worker == worker]:
            self._reqs.pop(rid)
        if worker in self._roster:
            self._roster.discard(worker)
            self._roster_total -= self._blocks.get(worker, 0)
        self._blocks.pop(worker, None)
        self._prefill_tokens.pop(worker, None)
        self._count.pop(worker, None)

    # -- point reads -------------------------------------------------------

    def active_blocks(self, worker: WorkerId) -> int:
        return self._blocks.get(worker, 0)

    def prefill_tokens(self, worker: WorkerId) -> int:
        return self._prefill_tokens.get(worker, 0)

    def active_count(self, worker: WorkerId) -> int:
        return self._count.get(worker, 0)

    # -- fleet aggregates --------------------------------------------------

    def _push(self, load: int, worker: WorkerId) -> None:
        heapq.heappush(self._heap, (load, worker))
        if len(self._heap) > max(64, 4 * len(self._roster)):
            self._rebuild_heap()

    def _rebuild_heap(self) -> None:
        self._heap = [(self._blocks.get(w, 0), w) for w in self._roster]
        heapq.heapify(self._heap)

    def sync_roster(self, workers: Iterable[WorkerId]) -> None:
        """Set the placement-eligible roster (call on discovery change)."""
        roster = set(workers)
        if roster == self._roster:
            return
        self._roster = roster
        self._roster_total = sum(self._blocks.get(w, 0) for w in roster)
        self._rebuild_heap()

    def roster_size(self) -> int:
        return len(self._roster)

    def roster_mean_load(self) -> float:
        """Mean active blocks across the roster (0.0 on an empty roster)."""
        if not self._roster:
            return 0.0
        return self._roster_total / len(self._roster)

    def least_loaded(self, m: int, exclude: frozenset[WorkerId] | set[WorkerId] = frozenset()) -> list[WorkerId]:
        """Up to ``m`` distinct least-loaded rostered workers, skipping
        ``exclude``. Lazy-deletion pops: an entry is valid only if the
        worker is rostered and the recorded load equals its current load
        (a fresher entry always exists otherwise, pushed on the delta)."""
        out: list[WorkerId] = []
        keep: list[tuple[int, WorkerId]] = []
        seen: set[WorkerId] = set()
        heap = self._heap
        while heap and len(out) < m:
            load, w = heapq.heappop(heap)
            if w in seen or w not in self._roster:
                continue
            if load != self._blocks.get(w, 0):
                continue  # stale; the fresher entry is still in the heap
            seen.add(w)
            keep.append((load, w))
            if w not in exclude:
                out.append(w)
        for entry in keep:
            heapq.heappush(heap, entry)
        return out
