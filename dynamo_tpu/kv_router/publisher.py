"""Worker-side KV event + load metrics publication.

Reference analogue: lib/llm/src/kv_router/publisher.rs — the reference
pushes KV events to a NATS subject and serves ``load_metrics`` over its
stats plane. Our runtime's request plane is a bidirectional streaming RPC,
so events ride a *server-streaming endpoint* instead of a broker: the
router opens a long-lived ``kv_events`` stream to each worker; the worker
first replays a snapshot of currently-registered blocks, then live events.
Worker death ends the stream, which the router turns into a full drop of
that worker's index state — same convergence story as NATS + etcd leases.

Endpoints served per worker:
- ``kv_events``: subscribe stream (snapshot + live KvCacheEvents)
- ``load_metrics``: one-shot ForwardPassMetrics
  (reference: kv_router/publisher.rs:481-523)
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Any, AsyncIterator, Callable

from dynamo_tpu.block_manager.pool import BlockPool
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics, KvCacheEvent, StoredBlock
from dynamo_tpu.runtime.component import endpoint_subject
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.logging import get_logger

log = get_logger("kv_publisher")

KV_EVENTS_ENDPOINT = "kv_events"
LOAD_METRICS_ENDPOINT = "load_metrics"


async def _next_or_cancelled(q: asyncio.Queue, ctx: Context):
    """Await the next queue item, waking early if the request context is
    cancelled (server drain / subscriber disconnect). None = stop."""
    getter = asyncio.get_running_loop().create_task(q.get())
    canceller = asyncio.get_running_loop().create_task(ctx.wait_cancelled())
    try:
        done, _ = await asyncio.wait({getter, canceller}, return_when=asyncio.FIRST_COMPLETED)
        if getter in done:
            # dyntpu: allow[DT002] reason=getter is in asyncio.wait's done set — result() cannot block, it just unwraps
            return getter.result()
        return None
    finally:
        getter.cancel()
        canceller.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await getter


class KvEventBroadcaster:
    """Fan-out of a worker's KV cache events to any number of subscriber
    streams. ``publish`` is thread-safe (engine emits from its scheduler
    thread)."""

    def __init__(self, pool: BlockPool, max_queue: int = 4096):
        self.pool = pool
        self.max_queue = max_queue
        self._loop: asyncio.AbstractEventLoop | None = None
        self._subscribers: set[asyncio.Queue] = set()

    def bind(self, loop: asyncio.AbstractEventLoop | None = None) -> "KvEventBroadcaster":
        self._loop = loop or asyncio.get_running_loop()
        return self

    # Called from the engine/pool (possibly another thread).
    def publish(self, event: KvCacheEvent) -> None:
        loop = self._loop
        if loop is None:
            return
        loop.call_soon_threadsafe(self._fanout, event)

    def _fanout(self, event: KvCacheEvent) -> None:
        for q in list(self._subscribers):
            if q.qsize() >= self.max_queue:
                # Slow subscriber: drop it; it will resubscribe and resync
                # from a fresh snapshot.
                self._subscribers.discard(q)
                q.put_nowait(None)  # poison → end stream
                log.warning("dropping slow kv_events subscriber")
            else:
                q.put_nowait(event)

    async def handler(self, payload: Any, ctx: Context) -> AsyncIterator[dict]:
        """Endpoint handler: snapshot, then live events until cancel."""
        q: asyncio.Queue = asyncio.Queue()
        self._subscribers.add(q)
        try:
            # Snapshot precedes any live event queued after subscription.
            snap = self.pool.snapshot()
            yield KvCacheEvent.cleared(event_id=0).to_dict()  # reset marker
            if snap:
                yield KvCacheEvent(
                    kind="stored",
                    event_id=0,  # snapshot events carry id 0 (pre-stream)
                    blocks=[StoredBlock(h, p) for h, p in snap],
                ).to_dict()
            while not ctx.cancelled:
                event = await _next_or_cancelled(q, ctx)
                if event is None:
                    return
                yield event.to_dict()
        finally:
            self._subscribers.discard(q)


async def serve_kv_endpoints(
    component,
    broadcaster: KvEventBroadcaster,
    metrics_fn: Callable[[], ForwardPassMetrics],
):
    """Attach kv_events + load_metrics endpoints to a worker component."""
    broadcaster.bind()

    async def metrics_handler(payload: Any, ctx: Context):
        yield metrics_fn().to_dict()

    # kv_events streams never end on their own: cancel them on shutdown.
    h1 = await component.endpoint(KV_EVENTS_ENDPOINT).serve(broadcaster.handler, drain_timeout=0.0)
    h2 = await component.endpoint(LOAD_METRICS_ENDPOINT).serve(metrics_handler)
    return h1, h2


class KvEventSubscription:
    """Router-side: one long-lived subscription to a worker's kv_events
    stream, feeding an index apply-callback. Ends (and reports) on worker
    death."""

    def __init__(
        self,
        messaging,
        instance,
        apply: Callable[[int, KvCacheEvent], bool],
        on_end: Callable[[int], None],
    ):
        self.messaging = messaging
        self.instance = instance
        self.apply = apply
        self.on_end = on_end
        self._task: asyncio.Task | None = None
        self._ctx = Context()

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def _run(self) -> None:
        wid = self.instance.instance_id
        subject = endpoint_subject(
            self.instance.namespace, self.instance.component, KV_EVENTS_ENDPOINT
        )
        try:
            stream = await self.messaging.call(self.instance.address, subject, None, self._ctx)
            async for item in stream:
                event = KvCacheEvent.from_dict(item)
                if not self.apply(wid, event):
                    log.warning("kv event gap from worker %x; resyncing", wid)
                    return  # on_end triggers resubscribe
        except asyncio.CancelledError:
            return
        except Exception as e:  # noqa: BLE001 — stream death = worker gone/restarting
            log.info("kv_events stream from %x ended: %s", wid, e)
        finally:
            self.on_end(wid)

    async def close(self) -> None:
        self._ctx.cancel()
        if self._task is not None:
            self._task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._task
