"""Global KV index: which worker holds which cached blocks.

Reference analogue: the radix tree + event-driven indexer
(reference: lib/llm/src/kv_router/indexer.rs:222-446,641-766).

Because block identity is the *chained* sequence hash (tokens.py), the
"radix tree" collapses to a hash-keyed node table: a node's key already
encodes its whole prefix, so matching a request is walking its hash list
until a miss, accumulating per-worker consecutive-match depth. Node
children links exist for cascade-removal bookkeeping.

The reference also hardens against event gaps with per-worker event_id
tracking; we mirror that: a gap triggers a full drop of the worker's
state (the subscription layer re-snapshots).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from dynamo_tpu.kv_router.protocols import CLEARED, REMOVED, STORED, KvCacheEvent

WorkerId = int


@dataclass
class OverlapScores:
    """worker → number of consecutive prompt blocks already cached there."""

    scores: dict[WorkerId, int] = field(default_factory=dict)

    def best(self) -> int:
        return max(self.scores.values(), default=0)


class _Node:
    __slots__ = ("hash", "parent", "children", "workers")

    def __init__(self, h: int, parent: int | None):
        self.hash = h
        self.parent = parent
        self.children: set[int] = set()
        self.workers: set[WorkerId] = set()


class RadixIndex:
    """Single-threaded (asyncio) index over chained block hashes."""

    def __init__(self):
        self._nodes: dict[int, _Node] = {}
        self._worker_blocks: dict[WorkerId, set[int]] = {}
        self._worker_event_ids: dict[WorkerId, int] = {}

    # -- queries ----------------------------------------------------------

    def find_matches(self, seq_hashes: list[int]) -> OverlapScores:
        """Per-worker consecutive-prefix depth over the request's block
        hash chain."""
        scores: dict[WorkerId, int] = {}
        alive: set[WorkerId] | None = None
        for depth, h in enumerate(seq_hashes, start=1):
            node = self._nodes.get(h)
            if node is None or not node.workers:
                break
            current = node.workers if alive is None else (alive & node.workers)
            if not current:
                break
            for w in current:
                scores[w] = depth
            alive = set(current)
        return OverlapScores(scores)

    def workers(self) -> set[WorkerId]:
        return set(self._worker_blocks)

    def num_blocks(self, worker: WorkerId) -> int:
        return len(self._worker_blocks.get(worker, ()))

    # -- event application -------------------------------------------------

    def apply(self, worker: WorkerId, event: KvCacheEvent) -> bool:
        """Apply one worker event. Returns False when an event-id gap was
        detected (caller should drop + resubscribe the worker)."""
        if event.event_id == 0:
            # Pre-stream events (subscription reset marker / snapshot):
            # outside the gap-tracked live sequence.
            if event.kind == CLEARED:
                self.remove_worker(worker)
                return True
        else:
            last = self._worker_event_ids.get(worker)
            if last is not None and event.event_id != last + 1:
                self.remove_worker(worker)
                return False
            self._worker_event_ids[worker] = event.event_id
        if event.kind == STORED:
            for b in event.blocks:
                self._store(worker, b.block_hash, b.parent_hash)
        elif event.kind == REMOVED:
            for h in event.block_hashes:
                self._remove(worker, h)
        elif event.kind == CLEARED:
            blocks = self._worker_blocks.get(worker, set())
            for h in list(blocks):
                self._remove(worker, h)
        return True

    def _store(self, worker: WorkerId, h: int, parent: int | None) -> None:
        node = self._nodes.get(h)
        if node is None:
            node = self._nodes[h] = _Node(h, parent)
            if parent is not None:
                pnode = self._nodes.get(parent)
                if pnode is not None:
                    pnode.children.add(h)
        node.workers.add(worker)
        self._worker_blocks.setdefault(worker, set()).add(h)

    def _remove(self, worker: WorkerId, h: int) -> None:
        node = self._nodes.get(h)
        if node is None:
            return
        node.workers.discard(worker)
        blocks = self._worker_blocks.get(worker)
        if blocks is not None:
            blocks.discard(h)
        self._prune(node)

    def _prune(self, node: _Node) -> None:
        # Iterative: block chains can be thousands deep (long contexts).
        while not node.workers and not node.children:
            self._nodes.pop(node.hash, None)
            if node.parent is None:
                return
            pnode = self._nodes.get(node.parent)
            if pnode is None:
                return
            pnode.children.discard(node.hash)
            node = pnode

    def remove_worker(self, worker: WorkerId) -> None:
        """Worker died or resubscribed: drop all its blocks."""
        for h in list(self._worker_blocks.get(worker, ())):
            self._remove(worker, h)
        self._worker_blocks.pop(worker, None)
        self._worker_event_ids.pop(worker, None)
