"""Global KV index: which worker holds which cached blocks.

Reference analogue: the radix tree + event-driven indexer
(reference: lib/llm/src/kv_router/indexer.rs:222-446,641-766).

Because block identity is the *chained* sequence hash (tokens.py), the
"radix tree" collapses to a hash-keyed node table: a node's key already
encodes its whole prefix, so matching a request is walking its hash list
until a miss, accumulating per-worker consecutive-match depth. Node
children links exist for cascade-removal bookkeeping.

The reference also hardens against event gaps with per-worker event_id
tracking; we mirror that: a gap triggers a full drop of the worker's
state (the subscription layer re-snapshots).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from dynamo_tpu.kv_router.protocols import CLEARED, REMOVED, STORED, KvCacheEvent

WorkerId = int


@dataclass
class OverlapScores:
    """worker → number of consecutive prompt blocks already cached there.

    When produced with ``top_k > 0`` the dict holds only the k deepest
    holders (a ranked shortlist), not every holder in the fleet."""

    scores: dict[WorkerId, int] = field(default_factory=dict)

    def best(self) -> int:
        return max(self.scores.values(), default=0)


class _Node:
    __slots__ = ("hash", "parent", "children", "workers")

    def __init__(self, h: int, parent: int | None):
        self.hash = h
        self.parent = parent
        self.children: set[int] = set()
        self.workers: set[WorkerId] = set()


class RadixIndex:
    """Single-threaded (asyncio) index over chained block hashes."""

    def __init__(self):
        self._nodes: dict[int, _Node] = {}
        self._worker_blocks: dict[WorkerId, set[int]] = {}
        self._worker_event_ids: dict[WorkerId, int] = {}

    # -- queries ----------------------------------------------------------

    def find_matches(self, seq_hashes: list[int], top_k: int = 0) -> OverlapScores:
        """Per-worker consecutive-prefix depth over the request's block
        hash chain.

        ``top_k == 0``: full scores dict, every holder (legacy behavior,
        byte-identical to the pre-shortlist code path).

        ``top_k > 0``: ranked shortlist of at most ``top_k`` holders,
        deepest first. Instead of rewriting every surviving worker's
        score at every depth (O(holders x chain)), the walk records only
        *drop events* — the depth at which a worker stops matching — and
        scores each holder exactly once: O(chain + holders)."""
        if top_k <= 0:
            scores: dict[WorkerId, int] = {}
            alive: set[WorkerId] | None = None
            for depth, h in enumerate(seq_hashes, start=1):
                node = self._nodes.get(h)
                if node is None or not node.workers:
                    break
                current = node.workers if alive is None else (alive & node.workers)
                if not current:
                    break
                for w in current:
                    scores[w] = depth
                alive = set(current)
            return OverlapScores(scores)
        return self._find_top_k(seq_hashes, top_k)

    def _find_top_k(self, seq_hashes: list[int], top_k: int) -> OverlapScores:
        alive: set[WorkerId] | None = None
        drops: list[tuple[int, set[WorkerId]]] = []  # (depth scored, workers)
        depth_reached = 0
        for depth, h in enumerate(seq_hashes, start=1):
            node = self._nodes.get(h)
            if node is None or not node.workers:
                break
            current = node.workers if alive is None else (alive & node.workers)
            if not current:
                break
            if alive is not None and len(current) < len(alive):
                drops.append((depth - 1, alive - current))
            alive = set(current)
            depth_reached = depth
        scores: dict[WorkerId, int] = {}
        if alive:
            for w in alive:
                scores[w] = depth_reached
                if len(scores) >= top_k:
                    break
        for d, ws in reversed(drops):
            if len(scores) >= top_k:
                break
            for w in ws:
                scores[w] = d
                if len(scores) >= top_k:
                    break
        return OverlapScores(scores)

    def workers(self) -> set[WorkerId]:
        return set(self._worker_blocks)

    def num_blocks(self, worker: WorkerId) -> int:
        return len(self._worker_blocks.get(worker, ()))

    # -- event application -------------------------------------------------

    def apply(self, worker: WorkerId, event: KvCacheEvent) -> bool:
        """Apply one worker event. Returns False when an event-id gap was
        detected (caller should drop + resubscribe the worker)."""
        if event.event_id == 0:
            # Pre-stream events (subscription reset marker / snapshot):
            # outside the gap-tracked live sequence.
            if event.kind == CLEARED:
                self.remove_worker(worker)
                return True
        else:
            last = self._worker_event_ids.get(worker)
            if last is not None and event.event_id != last + 1:
                self.remove_worker(worker)
                return False
            self._worker_event_ids[worker] = event.event_id
        if event.kind == STORED:
            for b in event.blocks:
                self._store(worker, b.block_hash, b.parent_hash)
        elif event.kind == REMOVED:
            for h in event.block_hashes:
                self._remove(worker, h)
        elif event.kind == CLEARED:
            self._drop_blocks(worker)
        return True

    def _store(self, worker: WorkerId, h: int, parent: int | None) -> None:
        node = self._nodes.get(h)
        if node is None:
            node = self._nodes[h] = _Node(h, parent)
            if parent is not None:
                pnode = self._nodes.get(parent)
                if pnode is not None:
                    pnode.children.add(h)
        node.workers.add(worker)
        self._worker_blocks.setdefault(worker, set()).add(h)

    def _remove(self, worker: WorkerId, h: int) -> None:
        node = self._nodes.get(h)
        if node is None:
            return
        node.workers.discard(worker)
        blocks = self._worker_blocks.get(worker)
        if blocks is not None:
            blocks.discard(h)
        self._prune(node)

    def _prune(self, node: _Node) -> None:
        # Iterative: block chains can be thousands deep (long contexts).
        while not node.workers and not node.children:
            self._nodes.pop(node.hash, None)
            if node.parent is None:
                return
            pnode = self._nodes.get(node.parent)
            if pnode is None:
                return
            pnode.children.discard(node.hash)
            node = pnode

    def _drop_blocks(self, worker: WorkerId) -> None:
        # Batch removal via the per-worker node index: pop the worker's
        # whole hash set once, detach it from each node, then prune only
        # the nodes that actually emptied. The old path called _remove per
        # hash, re-fetching and mutating the per-worker set for every
        # block — under zonal-failure churn at 1000 engines that sweep is
        # the router's dominant stall.
        blocks = self._worker_blocks.pop(worker, None)
        if not blocks:
            return
        emptied: list[_Node] = []
        for h in blocks:
            node = self._nodes.get(h)
            if node is None:
                continue
            node.workers.discard(worker)
            if not node.workers:
                emptied.append(node)
        for node in emptied:
            self._prune(node)

    def remove_worker(self, worker: WorkerId) -> None:
        """Worker died or resubscribed: drop all its blocks."""
        self._drop_blocks(worker)
        self._worker_event_ids.pop(worker, None)


class ShardedRadixIndex:
    """Scale-out indexer (reference: ``KvIndexerSharded``,
    lib/llm/src/kv_router/indexer.rs:856-985): workers are assigned to
    shards least-loaded-first, each shard owns an independent
    ``RadixIndex`` driven by its own thread, and ``find_matches`` merges
    per-shard scores (a worker's blocks live wholly in its shard, so the
    merged dicts are disjoint).

    Python twist on the reference's tokio-tasks-per-shard: daemon threads
    with ordered per-shard queues. The payoff here is less about raw
    events/s (the GIL bounds dict mutation) and more that event FLOODS
    never run on the routing asyncio loop — routing latency stays flat
    while shard threads chew through bursts (tools/profile_indexer.py
    measures both). Overflow policy matches the reference's gap story:
    a shard queue past its bound drops that worker's state and reports
    False so the subscription layer re-snapshots; all mutations ride the
    queue, so drop → resnapshot ordering is preserved."""

    def __init__(self, num_shards: int = 4, max_queue: int = 8192):
        import queue as _queue
        import threading

        self.num_shards = max(1, num_shards)
        self.max_queue = max_queue
        self._shards = [RadixIndex() for _ in range(self.num_shards)]
        self._locks = [threading.Lock() for _ in range(self.num_shards)]
        self._queues: list[_queue.Queue] = [_queue.Queue() for _ in range(self.num_shards)]
        self._assign: dict[WorkerId, int] = {}
        self._counts = [0] * self.num_shards
        # A removed worker that rejoins (gap/overflow → resnapshot) MUST
        # land on its old shard: its queued remove op and the fresh
        # snapshot then share one queue, so ordering guarantees the state
        # never straddles two shards (find_matches merges assuming
        # disjoint workers). Bounded: it only holds ints.
        self._last_shard: dict[WorkerId, int] = {}
        self._worker_event_ids: dict[WorkerId, int] = {}
        self._threads = [
            threading.Thread(target=self._shard_loop, args=(i,),
                             name=f"kv-index-shard-{i}", daemon=True)
            for i in range(self.num_shards)
        ]
        for t in self._threads:
            t.start()

    def _shard_loop(self, i: int) -> None:
        # Ops are drained in batches under ONE lock acquisition, with an
        # explicit yield between batches: per-op lock cycling starves
        # concurrent find_matches callers (measured p99 26→0.1 ms with
        # batching, tools/profile_indexer.py).
        import queue as _queue
        import time as _time

        q, shard, lock = self._queues[i], self._shards[i], self._locks[i]
        while True:
            batch = [q.get()]
            while len(batch) < 256 and batch[-1] is not None:
                try:
                    batch.append(q.get_nowait())
                except _queue.Empty:
                    break
            stop = batch[-1] is None
            if stop:
                batch.pop()
            with lock:
                for kind, worker, event in batch:
                    if kind == "apply":
                        shard.apply(worker, event)
                    else:
                        shard.remove_worker(worker)
            for _ in range(len(batch) + (1 if stop else 0)):
                q.task_done()
            if stop:
                return
            _time.sleep(0)  # let queued find_matches grab the lock

    def _shard_of(self, worker: WorkerId) -> int:
        s = self._assign.get(worker)
        if s is None:
            s = self._last_shard.get(worker)  # sticky rejoin (see above)
            if s is None:
                s = min(range(self.num_shards), key=lambda i: self._counts[i])
            self._assign[worker] = s
            self._counts[s] += 1
        return s

    # -- RadixIndex-compatible surface -------------------------------------

    def apply(self, worker: WorkerId, event: KvCacheEvent) -> bool:
        # Gap tracking stays synchronous (cheap int compare) so the
        # caller's drop+resnapshot contract is preserved; the heavy dict
        # mutation is what moves to the shard thread.
        if event.event_id == 0:
            if event.kind == CLEARED:
                self.remove_worker(worker)
                return True
        else:
            last = self._worker_event_ids.get(worker)
            if last is not None and event.event_id != last + 1:
                self.remove_worker(worker)
                return False
            self._worker_event_ids[worker] = event.event_id
        s = self._shard_of(worker)
        if self._queues[s].qsize() >= self.max_queue:
            # Back-pressure: cheaper to resync this worker from a fresh
            # snapshot than to buffer an unbounded backlog.
            self.remove_worker(worker)
            return False
        self._queues[s].put(("apply", worker, event))
        return True

    def remove_worker(self, worker: WorkerId) -> None:
        s = self._assign.pop(worker, None)
        self._worker_event_ids.pop(worker, None)
        if s is not None:
            self._counts[s] -= 1
            if len(self._last_shard) > 4096:
                self._last_shard.clear()  # churn bound; stickiness is best-effort
            self._last_shard[worker] = s
            self._queues[s].put(("remove", worker, None))

    def find_matches(self, seq_hashes: list[int], top_k: int = 0) -> OverlapScores:
        scores: dict[WorkerId, int] = {}
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                scores.update(shard.find_matches(seq_hashes, top_k=top_k).scores)
        if top_k > 0 and len(scores) > top_k:
            # Per-shard shortlists are disjoint (a worker lives wholly in
            # one shard); re-rank the union down to the global top-k.
            import heapq as _heapq

            scores = dict(_heapq.nlargest(top_k, scores.items(), key=lambda kv: kv[1]))
        return OverlapScores(scores)

    def workers(self) -> set[WorkerId]:
        out: set[WorkerId] = set()
        for shard, lock in zip(self._shards, self._locks):
            with lock:
                out |= shard.workers()
        return out

    def num_blocks(self, worker: WorkerId) -> int:
        s = self._assign.get(worker)
        if s is None:
            return 0
        with self._locks[s]:
            return self._shards[s].num_blocks(worker)

    def flush(self) -> None:
        """Block until every queued mutation has been applied (tests,
        shutdown barriers)."""
        for q in self._queues:
            q.join()

    def close(self) -> None:
        for q in self._queues:
            q.put(None)
        for t in self._threads:
            t.join(timeout=5.0)
