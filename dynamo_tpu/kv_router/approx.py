"""ApproxKvIndexer: predicted-cache index for engines that publish no KV
events.

Reference analogue: lib/llm/src/kv_router/approx.rs:166-294 — on each
routing decision, optimistically record the request's blocks as present
on the chosen worker with a TTL (the reference uses 120 s, matching
typical engine cache residency); expired entries lapse lazily. Same
``find_matches`` interface as the real index.
"""

from __future__ import annotations

import heapq
import time

from dynamo_tpu.kv_router.indexer import OverlapScores, WorkerId

DEFAULT_TTL_S = 120.0


class ApproxKvIndexer:
    def __init__(self, ttl_s: float = DEFAULT_TTL_S, clock=time.monotonic):
        self.ttl_s = ttl_s
        self._clock = clock
        self._by_hash: dict[int, dict[WorkerId, float]] = {}  # hash → worker → expiry
        self._heap: list[tuple[float, int, WorkerId]] = []

    def _expire(self) -> None:
        now = self._clock()
        while self._heap and self._heap[0][0] <= now:
            _, h, w = heapq.heappop(self._heap)
            workers = self._by_hash.get(h)
            if workers is not None:
                exp = workers.get(w)
                if exp is not None and exp <= now:
                    del workers[w]
                    if not workers:
                        del self._by_hash[h]

    def record_routing(self, worker: WorkerId, seq_hashes: list[int]) -> None:
        """The request was sent to `worker`: assume its blocks will be (or
        are) cached there for the TTL."""
        exp = self._clock() + self.ttl_s
        for h in seq_hashes:
            self._by_hash.setdefault(h, {})[worker] = exp
            heapq.heappush(self._heap, (exp, h, worker))

    def find_matches(self, seq_hashes: list[int]) -> OverlapScores:
        self._expire()
        scores: dict[WorkerId, int] = {}
        alive: set[WorkerId] | None = None
        for depth, h in enumerate(seq_hashes, start=1):
            present = self._by_hash.get(h)
            if not present:
                break
            current = set(present) if alive is None else (alive & set(present))
            if not current:
                break
            for w in current:
                scores[w] = depth
            alive = current
        return OverlapScores(scores)

    def remove_worker(self, worker: WorkerId) -> None:
        for h in [h for h, ws in self._by_hash.items() if worker in ws]:
            self._by_hash[h].pop(worker, None)
            if not self._by_hash[h]:
                del self._by_hash[h]
