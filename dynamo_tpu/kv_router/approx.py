"""ApproxKvIndexer: predicted-cache index for engines that publish no KV
events.

Reference analogue: lib/llm/src/kv_router/approx.rs:166-294 — on each
routing decision, optimistically record the request's blocks as present
on the chosen worker with a TTL (the reference uses 120 s, matching
typical engine cache residency); expired entries lapse lazily. Same
``find_matches`` interface as the real index.
"""

from __future__ import annotations

import heapq
import time

from dynamo_tpu.kv_router.indexer import OverlapScores, WorkerId

DEFAULT_TTL_S = 120.0


class ApproxKvIndexer:
    def __init__(self, ttl_s: float = DEFAULT_TTL_S, clock=time.monotonic):
        self.ttl_s = ttl_s
        self._clock = clock
        self._by_hash: dict[int, dict[WorkerId, float]] = {}  # hash → worker → expiry
        self._by_worker: dict[WorkerId, set[int]] = {}  # worker → hashes (removal index)
        self._heap: list[tuple[float, int, WorkerId]] = []

    def _drop_entry(self, h: int, w: WorkerId) -> None:
        workers = self._by_hash.get(h)
        if workers is not None:
            workers.pop(w, None)
            if not workers:
                del self._by_hash[h]
        hashes = self._by_worker.get(w)
        if hashes is not None:
            hashes.discard(h)
            if not hashes:
                del self._by_worker[w]

    def _expire(self) -> None:
        now = self._clock()
        while self._heap and self._heap[0][0] <= now:
            _, h, w = heapq.heappop(self._heap)
            workers = self._by_hash.get(h)
            if workers is not None:
                exp = workers.get(w)
                if exp is not None and exp <= now:
                    self._drop_entry(h, w)

    def record_routing(self, worker: WorkerId, seq_hashes: list[int]) -> None:
        """The request was sent to `worker`: assume its blocks will be (or
        are) cached there for the TTL."""
        exp = self._clock() + self.ttl_s
        hashes = self._by_worker.setdefault(worker, set())
        for h in seq_hashes:
            self._by_hash.setdefault(h, {})[worker] = exp
            hashes.add(h)
            heapq.heappush(self._heap, (exp, h, worker))

    def find_matches(self, seq_hashes: list[int], top_k: int = 0) -> OverlapScores:
        self._expire()
        scores: dict[WorkerId, int] = {}
        alive: set[WorkerId] | None = None
        drops: list[tuple[int, set[WorkerId]]] = []
        depth_reached = 0
        for depth, h in enumerate(seq_hashes, start=1):
            present = self._by_hash.get(h)
            if not present:
                break
            current = set(present) if alive is None else (alive & set(present))
            if not current:
                break
            if top_k <= 0:
                for w in current:
                    scores[w] = depth
            else:
                if alive is not None and len(current) < len(alive):
                    drops.append((depth - 1, alive - current))
                depth_reached = depth
            alive = current
        if top_k <= 0:
            return OverlapScores(scores)
        if alive:
            for w in alive:
                scores[w] = depth_reached
                if len(scores) >= top_k:
                    break
        for d, ws in reversed(drops):
            if len(scores) >= top_k:
                break
            for w in ws:
                scores[w] = d
                if len(scores) >= top_k:
                    break
        return OverlapScores(scores)

    def remove_worker(self, worker: WorkerId) -> None:
        # Per-worker hash index: O(worker's entries), not a sweep of the
        # whole table (quadratic under fleet-wide churn).
        for h in list(self._by_worker.get(worker, ())):
            workers = self._by_hash.get(h)
            if workers is not None:
                workers.pop(worker, None)
                if not workers:
                    del self._by_hash[h]
        self._by_worker.pop(worker, None)
