"""Paged decode attention: Pallas TPU kernel + XLA reference.

The role vLLM's paged-attention CUDA kernels play for the reference
(reference: components/backends/vllm/src/dynamo/vllm/main.py:90 delegates
to vLLM's engine; its CUDA kernels are the analogue of this file).

Why a kernel at all: the XLA formulation gathers the full (bucketed)
block-table width `W*bs` out of the page pool per layer per step —
~3x HBM traffic on padded context (materialize + re-read) regardless of
each sequence's true length. The kernel instead walks each row's actual
pages: one DMA per page (a page is contiguous ``[bs, KVH*hd]`` in the
cache layout), online-softmax accumulation, work proportional to
``sum(lengths)`` rather than ``B*W*bs``.

Design notes (measured on v5e, see tools/profile_decode.py):

- The FULL cache ``[L, N, bs, KVH*hd]`` stays in HBM (`pl.ANY`) in its
  native dense layout (a 5D [.., KVH, hd] layout forced a whole-cache
  relayout copy per pallas_call — ~9ms/layer measured on v5e, the reason
  the cache is stored heads-merged). The layer index is a scalar-prefetch
  operand, which also
  removes the per-layer ``dynamic_slice`` copies the gather path needs.
- Grid ``(B, CMAX)``: chunk c of row b processes up to P pages.
  Cross-step software pipelining: every live step issues the DMAs of the
  *next* live step (double-buffered), so page fetch overlaps compute
  across rows, not just within a row.
- **Block-diagonal q**: per-head lane slices of the KV buffer relayout
  on every access (hd=64 is sub-lane-tile) and measured ~15us/chunk.
  Instead the caller bakes q into a block-diagonal matrix
  ``[KVH*hd, KVH*G]`` so ONE MXU op yields all heads' scores
  ``[P*bs, KVH*G]``; the online softmax is column-wise (axis-0 reduces),
  and the accumulator is kept transposed ``[KVH*hd, KVH*G]`` so every
  correction is a row-vector broadcast. Zero relayouts, zero transposes
  in the kernel; the per-head diagonal is extracted by XLA afterwards.
- Dead steps (chunk beyond the row's length, padding rows) skip DMA and
  compute entirely — padding costs ~grid-iteration overhead only.
- Per-DMA cost measured ~0.6us: pages should be >=32KB to approach
  bandwidth. Page bytes = block_size x KVH x hd x 2 (bf16), so for
  8B-class geometries (KVH*hd = 1024) the default ``block_size=16``
  already gives 32KB pages — r5 bench: decode substeps run AT the int8
  weight-stream roofline (~9 ms vs the 9.8 ms floor) at bs=16, so
  larger blocks buy nothing there. Prefer 64-256 only for SMALL kv
  widths (e.g. KVH*hd <= 256) where bs=16 pages drop under 8KB.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def resolve_attn_impl(requested: str = "auto") -> str:
    """'auto' → 'pallas' on TPU-like backends, else 'xla'."""
    if requested != "auto":
        return requested
    try:
        backend = jax.default_backend()
    except RuntimeError:
        backend = "cpu"
    return "pallas" if backend in ("tpu", "axon") else "xla"


# ---------------------------------------------------------------------------
# XLA reference implementation (also the CPU / multi-device path)
# ---------------------------------------------------------------------------


def gather_dequant_pages(
    layer_cache: jax.Array,   # [N, bs, KVH*hd] — one layer's pages
    layer_scale: jax.Array | None,  # [N, bs, KVH] fp32 | None
    block_tables: jax.Array,  # [B, W] int32
    KVH: int, hd: int, dtype,
):
    """Gather a batch's pages out of the pool and (for int8 storage)
    dequantize with the per-position-per-head scales → [B, W*bs, KVH, hd]
    in ``dtype``. The int8→float convert rides the gather output, so the
    materialized copy stays half the bf16 path's bytes on the read side
    (the write side — the gather itself — is what the Pallas kernels
    remove entirely)."""
    B, W = block_tables.shape
    bs = layer_cache.shape[1]
    pages = layer_cache[block_tables].reshape(B, W * bs, KVH, hd)
    if layer_scale is None:
        return pages
    sc = layer_scale[block_tables].reshape(B, W * bs, KVH)
    # Dequantize in f32 and round ONCE into ``dtype`` — multiplying in
    # bf16 would read the same stored byte back as a different value
    # than the Pallas kernel / host adapters (which also widen to f32),
    # breaking cross-path consistency for the same block.
    return (pages.astype(jnp.float32) * sc[..., None]).astype(dtype)


def paged_decode_attention_xla(
    q: jax.Array,            # [B, KVH, G, hd]
    k_cache: jax.Array,      # [L, N, bs, KVH*hd]
    v_cache: jax.Array,
    layer_idx: jax.Array,    # scalar int32
    block_tables: jax.Array, # [B, W] int32
    lengths: jax.Array,      # [B] int32 — attend positions [0, length)
    k_scale: jax.Array | None = None,  # [L, N, bs, KVH] fp32 — int8 cache only
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Gather-based formulation (the r3 path, hoisted here).  With
    ``k_scale``/``v_scale`` the cache holds int8 pages and the gather
    dequantizes in the same fused expression.  Returns [B, KVH, G, hd]
    in q.dtype."""
    B, KVH, G, hd = q.shape
    layer_k = lax.dynamic_index_in_dim(k_cache, layer_idx, 0, keepdims=False)
    layer_v = lax.dynamic_index_in_dim(v_cache, layer_idx, 0, keepdims=False)
    sk = sv = None
    if k_scale is not None:
        sk = lax.dynamic_index_in_dim(k_scale, layer_idx, 0, keepdims=False)
        sv = lax.dynamic_index_in_dim(v_scale, layer_idx, 0, keepdims=False)
    pk = gather_dequant_pages(layer_k, sk, block_tables, KVH, hd, q.dtype)
    pv = gather_dequant_pages(layer_v, sv, block_tables, KVH, hd, q.dtype)
    scale = hd ** -0.5
    ctx = jnp.arange(pk.shape[1], dtype=jnp.int32)
    mask = jnp.where(ctx[None, :] < lengths[:, None], 0.0, jnp.float32(NEG_INF))
    s = jnp.einsum("bkgh,bckh->bkgc", q, pk).astype(jnp.float32) * scale
    s = s + mask[:, None, None, :]
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bkgc,bckh->bkgh", p, pv)


def paged_spec_attention_xla(
    q: jax.Array,            # [B, T, KVH, G, hd] — T consecutive query positions
    k_cache: jax.Array,      # [L, N, bs, KVH*hd]
    v_cache: jax.Array,
    layer_idx: jax.Array,    # scalar int32
    block_tables: jax.Array, # [B, W] int32
    lengths: jax.Array,      # [B, T] int32 — query t attends [0, lengths[b, t])
    k_scale: jax.Array | None = None,  # [L, N, bs, KVH] fp32 — int8 cache only
    v_scale: jax.Array | None = None,
    anc: jax.Array | None = None,  # [B, T, T] — tree topology mask (below)
) -> jax.Array:
    """Multi-query generalization of ``paged_decode_attention_xla`` for
    the speculative verify pass: T consecutive positions per row attend
    their own causal prefix out of the SAME gathered pages (one gather
    per layer for all T queries — the single-pass shape that lets a
    verify step score draft_len+1 logit rows in one weight stream).
    T=1 reduces exactly to the decode formulation, so CPU/XLA greedy
    byte-identity between the spec and dense paths holds by construction.
    With scales the gathered pages dequantize in the same expression.

    **Tree mode** (``anc`` given): the T in-flight rows form a draft
    TREE. Node j's KV is written at slot position ``hist + j``, where
    ``hist`` is the row's paged-history horizon — ``lengths[b, t]``
    carries that per-query horizon (the caller passes positions0 for
    every live query, 0 for dead ones).  Query t attends ``[0, hist)``
    paged history PLUS exactly the in-flight slots s with
    ``anc[b, t, s]`` nonzero — its ancestor-or-self set.  The linear draft is the special case
    ``anc[t, s] = (s <= t)`` with ``lengths[b, t] = hist`` (equivalent
    to the non-tree call with ``lengths[b, t] = hist + t + 1``), so the
    tree mask is a strict generalization of the causal ramp.
    Returns [B, T, KVH, G, hd] in q.dtype. (``paged_spec_attention`` is
    the Pallas upgrade: the gather+dequant happen in-register, no
    materialized relayout copy.)"""
    B, T, KVH, G, hd = q.shape
    layer_k = lax.dynamic_index_in_dim(k_cache, layer_idx, 0, keepdims=False)
    layer_v = lax.dynamic_index_in_dim(v_cache, layer_idx, 0, keepdims=False)
    sk = sv = None
    if k_scale is not None:
        sk = lax.dynamic_index_in_dim(k_scale, layer_idx, 0, keepdims=False)
        sv = lax.dynamic_index_in_dim(v_scale, layer_idx, 0, keepdims=False)
    pk = gather_dequant_pages(layer_k, sk, block_tables, KVH, hd, q.dtype)
    pv = gather_dequant_pages(layer_v, sv, block_tables, KVH, hd, q.dtype)
    scale = hd ** -0.5
    ctx = jnp.arange(pk.shape[1], dtype=jnp.int32)
    hist_mask = ctx[None, None, :] < lengths[:, :, None]    # [B, T, W*bs]
    if anc is None:
        attend = hist_mask
    else:
        # Tree: slot s of the in-flight rows lives at paged position
        # hist + s; gather the per-query ancestor bit for positions in
        # the slot window.
        slot = ctx[None, None, :] - lengths[:, :, None]     # [B, T, C]
        in_window = (slot >= 0) & (slot < T)
        anc_g = jnp.take_along_axis(
            (anc != 0), jnp.clip(slot, 0, T - 1), axis=2
        )                                                   # [B, T, C]
        attend = hist_mask | (in_window & anc_g)
    mask = jnp.where(attend, 0.0, jnp.float32(NEG_INF))
    s = jnp.einsum("btkgh,bckh->btkgc", q, pk).astype(jnp.float32) * scale
    s = s + mask[:, :, None, None, :]
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("btkgc,bckh->btkgh", p, pv)


# ---------------------------------------------------------------------------
# Pallas TPU kernel — ONE multi-query kernel for both consumers.
#
# Decode is the T=1 case; the speculative verify pass runs T = S+1 query
# positions per row through the SAME kernel (the "fused gather": the
# [last, d1..dS] rows attend straight out of the page pool — no
# materialized `layer_k[block_tables]` relayout copy, which costs
# ~9ms/layer at 8B geometry, the header's XLA gather tax). With int8
# cache storage the per-page DMAs move HALF the bytes and the dequant
# happens in-register right after the page lands in VMEM, using
# per-position-per-head scales prefetched per row block.
# ---------------------------------------------------------------------------


def _mq_kernel(
    # scalar prefetch
    layer_ref,    # [1] int32
    rowlen_ref,   # [B] int32 — max attend length per row (chunk walk bound)
    tables_ref,   # [B, W] int32
    # operands (anc present only in tree mode; kscale/vscale when quantized)
    *refs,
    # static
    pages_per_chunk: int,
    head_dim: int,
    quantized: bool,
    tree_slots: int = 0,
):
    refs = list(refs)
    qbd_ref, lenvec_ref = refs[:2]
    refs = refs[2:]
    anc_ref = None
    if tree_slots:
        anc_ref, refs = refs[0], refs[1:]
    if quantized:
        (kscale_ref, vscale_ref, k_hbm, v_hbm,
         o_ref, kbuf, vbuf, m_scr, l_scr, acc_scr, slot_ref, started_ref,
         sem) = refs
    else:
        (k_hbm, v_hbm,
         o_ref, kbuf, vbuf, m_scr, l_scr, acc_scr, slot_ref, started_ref,
         sem) = refs
        kscale_ref = vscale_ref = None
    # qbd_ref    VMEM [1, KVH*hd, H] — block-diag q, softmax scale folded in
    # lenvec_ref VMEM [1, H] int32 — per query COLUMN attend length; in
    #            tree mode the per-column HISTORY horizon (slots ride on top)
    # anc_ref    VMEM [1, T, H] int8 — tree mode: anc[s, col] = query col
    #            may attend in-flight slot s (its ancestor-or-self set)
    # kscale_ref VMEM [1, W, bs, KVH] f32 — per-position-per-head scales
    # k_hbm      ANY  [L, N, bs, KVH*hd]
    # o_ref      VMEM [1, KVH*hd, H] — attention out, transposed
    # kbuf/vbuf  VMEM [2, P, bs, KVH*hd] (cache dtype; int8 when quantized)
    # m/l        VMEM [8, 128] f32 — row 0, first H lanes live
    # acc        VMEM [KVH*hd, H] f32
    # slot/started SMEM [1] int32; sem DMA sems [2, 2, P]
    P = pages_per_chunk
    b = pl.program_id(0)
    c = pl.program_id(1)
    B = pl.num_programs(0)
    layer = layer_ref[0]
    bs = kbuf.shape[2]
    D = kbuf.shape[3]       # KVH*hd
    H = qbd_ref.shape[2]    # KVH*T*G (total query columns)
    hd = head_dim
    KVH = D // hd
    CH = P * bs             # tokens per chunk

    length = rowlen_ref[b]
    nchunks = lax.div(length + CH - 1, CH)
    live = c < nchunks

    @pl.when((b == 0) & (c == 0))
    def _init_globals():
        slot_ref[0] = 0
        started_ref[0] = 0

    def chunk_dmas(row, chunk, slot):
        """DMA descriptors for (row, chunk) into buffer `slot`; page p is
        guarded by the row's true page count."""
        rem = rowlen_ref[row] - chunk * CH
        npages = jnp.minimum(lax.div(rem + bs - 1, bs), P)
        out = []
        for p in range(P):
            page = tables_ref[row, chunk * P + p]
            out.append((
                p < npages,
                pltpu.make_async_copy(k_hbm.at[layer, page], kbuf.at[slot, p], sem.at[slot, 0, p]),
                pltpu.make_async_copy(v_hbm.at[layer, page], vbuf.at[slot, p], sem.at[slot, 1, p]),
            ))
        return out

    def issue(row, chunk, slot):
        for ok, dk, dv in chunk_dmas(row, chunk, slot):
            @pl.when(ok)
            def _():
                dk.start()
                dv.start()

    @pl.when(live)
    def _body():
        cur = slot_ref[0]

        # Global warmup: the very first live step has no predecessor.
        @pl.when(started_ref[0] == 0)
        def _():
            issue(b, c, cur)
            started_ref[0] = 1

        # Software pipeline: issue the next live step's pages.
        # Successor is (b, c+1) if this row continues, else chunk 0 of
        # the next non-empty row (scalar search past padding rows).
        nxt = 1 - cur
        row_continues = c + 1 < nchunks

        @pl.when(row_continues)
        def _():
            issue(b, c + 1, nxt)

        @pl.when(~row_continues)
        def _():
            # First non-empty row after b (B if none). A fori_loop, not a
            # while_loop: the scan is O(B) scalar work either way, and a
            # while cond that reads a ref has no interpret-mode discharge
            # rule — this form keeps the kernel CPU-interpret-testable.
            def scan_row(r, best):
                cand = (r > b) & (rowlen_ref[r] > 0) & (r < best)
                return jnp.where(cand, r, best)

            nxt_row = lax.fori_loop(0, B, scan_row, B)

            @pl.when(nxt_row < B)
            def _():
                issue(nxt_row, 0, nxt)

        # Init row accumulators at the row's first chunk.
        @pl.when(c == 0)
        def _():
            m_scr[...] = jnp.full_like(m_scr, NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

        # Wait for this step's pages.
        for ok, dk, dv in chunk_dmas(b, c, cur):
            @pl.when(ok)
            def _():
                dk.wait()
                dv.wait()
        slot_ref[0] = nxt

        # Context-position validity, column orientation [P*bs, 1].
        pos = c * CH + lax.broadcasted_iota(jnp.int32, (P * bs, 1), 0)
        valid = pos < length

        k_chunk = kbuf[cur].reshape(P * bs, D)
        v_chunk = vbuf[cur].reshape(P * bs, D)
        if quantized:
            # In-register dequant of the just-landed int8 pages: expand
            # this chunk's [P, bs, KVH] scales across the head lanes and
            # multiply — the DMA moved half the bytes, the float page
            # never exists outside VMEM.
            ksc = jnp.broadcast_to(
                kscale_ref[0, pl.ds(c * P, P)][..., None], (P, bs, KVH, hd)
            ).reshape(P * bs, D)
            vsc = jnp.broadcast_to(
                vscale_ref[0, pl.ds(c * P, P)][..., None], (P, bs, KVH, hd)
            ).reshape(P * bs, D)
            k_chunk = (k_chunk.astype(jnp.float32) * ksc).astype(qbd_ref.dtype)
            v_chunk = (v_chunk.astype(jnp.float32) * vsc).astype(qbd_ref.dtype)
        # Unfetched tail pages hold garbage (possibly NaN): k is
        # neutralized by the score mask, v must be zeroed (0*NaN=NaN).
        v_chunk = jnp.where(valid, v_chunk, 0)

        # All heads' scores in one MXU op via the block-diagonal q.
        s = lax.dot_general(
            k_chunk, qbd_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # [P*bs, H]
        # Per-COLUMN causal horizon: column (k, t, g) attends positions
        # [0, lengths[b, t]) — for decode (T=1) every column carries the
        # row length and this is exactly the old row mask. Tree mode
        # adds the topology bits: in-flight slot s_i sits at paged
        # position hist + s_i and column t attends it only when
        # anc[s_i, col] is set (T compares on the VPU, T is small).
        att = pos < lenvec_ref[0:1, :]
        if tree_slots:
            for s_i in range(tree_slots):
                att = att | (
                    (pos == lenvec_ref[0:1, :] + s_i)
                    & (anc_ref[0, s_i, :][None, :] != 0)
                )
        s = jnp.where(att, s, NEG_INF)

        m_prev = m_scr[0:1, :H]                            # [1, H]
        l_prev = l_scr[0:1, :H]
        m_cur = jnp.max(s, axis=0, keepdims=True)          # [1, H]
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)                     # [1, H]
        p = jnp.exp(s - m_new)                             # [P*bs, H]
        l_new = corr * l_prev + jnp.sum(p, axis=0, keepdims=True)
        # Transposed accumulator [D, H]: corrections broadcast over rows.
        pv = lax.dot_general(
            v_chunk, p.astype(v_chunk.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # [D, H]
        acc_scr[...] = acc_scr[...] * corr + pv
        m_scr[0:1, :H] = m_new
        l_scr[0:1, :H] = l_new

        # Row done → normalize and emit (still transposed; XLA takes the
        # per-head diagonal outside).
        @pl.when(c == nchunks - 1)
        def _():
            l = jnp.maximum(l_scr[0:1, :H], 1e-30)
            o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)

    # Keep padding rows' output defined (their stale block is otherwise
    # flushed as-is; harmless numerically but keep it clean).
    @pl.when((~live) & (c == 0))
    def _zero():
        o_ref[0] = jnp.zeros_like(o_ref[0])


def _paged_attention_mq(
    q: jax.Array,            # [B, T, KVH, G, hd]
    k_cache: jax.Array,      # [L, N, bs, KVH*hd] — dense pages, no
    v_cache: jax.Array,      #   per-call layout conversion
    layer_idx: jax.Array,    # scalar int32
    block_tables: jax.Array, # [B, W] int32
    lengths: jax.Array,      # [B, T] int32
    k_scale: jax.Array | None,  # [L, N, bs, KVH] fp32 | None
    v_scale: jax.Array | None,
    pages_per_chunk: int,
    interpret: bool,
    anc: jax.Array | None = None,  # [B, T, T] — tree topology mask
) -> jax.Array:
    """Shared Pallas driver: T query positions per row walk the row's
    true pages once. Returns [B, T, KVH, G, hd] in q.dtype."""
    B, T, KVH, G, hd = q.shape
    bs = k_cache.shape[2]
    assert k_cache.shape[3] == KVH * hd, "cache must be [L, N, bs, KVH*hd]"
    W = block_tables.shape[1]
    H = KVH * T * G
    if H > 128:
        raise NotImplementedError(
            f"{H} query columns (KVH*T*G) > 128 lanes; shard heads (tp) "
            f"or fall back to the XLA gather path"
        )
    quantized = k_scale is not None
    P = pages_per_chunk or max(1, 512 // bs)
    P = min(P, W)
    if W % P:  # pad the table so chunks tile it exactly
        pad = P - W % P
        block_tables = jnp.pad(block_tables, ((0, 0), (0, pad)))
        W += pad
    chunks_max = W // P

    # Block-diagonal q with the softmax scale folded in:
    # qbd[b, j*hd+h, k*(T*G)+t*G+g] = q[b,t,k,g,h] * scale * (j==k).
    eye = jnp.eye(KVH, dtype=q.dtype)
    qbd = jnp.einsum("btkgh,jk->bjhktg", q * (hd ** -0.5), eye)
    qbd = qbd.reshape(B, KVH * hd, H)
    # Per-column attend horizon, same (k, t, g) column order as qbd.
    lengths = jnp.asarray(lengths, jnp.int32)
    lenvec = jnp.broadcast_to(
        lengths[:, None, :, None], (B, KVH, T, G)
    ).reshape(B, H)
    rowlen = jnp.max(lengths, axis=1)  # chunk-walk bound per row
    if anc is not None:
        # Tree mode: the walk must also cover the T in-flight slots at
        # positions [hist, hist + T); rows with no live node at all
        # (anc identically zero — padding rows) stay empty so the
        # prefetch skip keeps them ~free.
        live_row = jnp.any(anc != 0, axis=(1, 2))
        rowlen = jnp.where(live_row, rowlen + T, 0)

    operands = [qbd, lenvec]
    in_specs = [
        pl.BlockSpec((1, KVH * hd, H), lambda b, c, *_: (b, 0, 0)),
        pl.BlockSpec((1, H), lambda b, c, *_: (b, 0)),
    ]
    if anc is not None:
        # Column-order ancestor bits [B, T_slot, H]: anc_cols[b, s, col]
        # with col = (k*T + t)*G + g — the same (k, t, g) layout as
        # lenvec/qbd, prefetched per row block alongside the scales.
        anc_b = jnp.asarray(anc != 0, jnp.int8).transpose(0, 2, 1)  # [B, Ts, Tq]
        anc_cols = jnp.broadcast_to(
            anc_b[:, :, None, :, None], (B, T, KVH, T, G)
        ).reshape(B, T, H)
        operands.append(anc_cols)
        in_specs.append(pl.BlockSpec((1, T, H), lambda b, c, *_: (b, 0, 0)))
    if quantized:
        # Scales ride as per-row VMEM blocks gathered OUTSIDE the kernel:
        # [B, W, bs, KVH] fp32 is 1/head_dim the page bytes, so the XLA
        # gather here is noise next to the page traffic the kernel saves.
        sk = lax.dynamic_index_in_dim(k_scale, layer_idx, 0, keepdims=False)
        sv = lax.dynamic_index_in_dim(v_scale, layer_idx, 0, keepdims=False)
        operands += [sk[block_tables], sv[block_tables]]
        in_specs += [
            pl.BlockSpec((1, W, bs, KVH), lambda b, c, *_: (b, 0, 0, 0)),
            pl.BlockSpec((1, W, bs, KVH), lambda b, c, *_: (b, 0, 0, 0)),
        ]
    operands += [k_cache, v_cache]
    in_specs += [
        pl.BlockSpec(memory_space=pl.ANY),
        pl.BlockSpec(memory_space=pl.ANY),
    ]

    kernel = functools.partial(
        _mq_kernel, pages_per_chunk=P, head_dim=hd, quantized=quantized,
        tree_slots=T if anc is not None else 0,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, chunks_max),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, KVH * hd, H), lambda b, c, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, P, bs, KVH * hd), k_cache.dtype),
            pltpu.VMEM((2, P, bs, KVH * hd), v_cache.dtype),
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((KVH * hd, H), jnp.float32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 2, P)),
        ],
    )
    o_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH * hd, H), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer_idx, jnp.int32).reshape(1),
        rowlen,
        jnp.asarray(block_tables, jnp.int32),
        *operands,
    )
    # [B, KVH*hd, KVH*T*G] → per-head diagonal → [B, T, KVH, G, hd].
    o6 = o_t.reshape(B, KVH, hd, KVH, T, G)
    return jnp.einsum("bkhktg->btkgh", o6)


@functools.partial(
    jax.jit,
    static_argnames=("pages_per_chunk", "interpret"),
)
def paged_decode_attention(
    q: jax.Array,            # [B, KVH, G, hd]
    k_cache: jax.Array,      # [L, N, bs, KVH*hd]
    v_cache: jax.Array,
    layer_idx: jax.Array,    # scalar int32
    block_tables: jax.Array, # [B, W] int32
    lengths: jax.Array,      # [B] int32
    k_scale: jax.Array | None = None,  # [L, N, bs, KVH] fp32 — int8 cache only
    v_scale: jax.Array | None = None,
    *,
    pages_per_chunk: int = 0,  # 0 → auto (~512 tokens per chunk)
    interpret: bool = False,
) -> jax.Array:
    B, KVH, G, hd = q.shape
    if KVH * G > 128:
        raise NotImplementedError(
            f"{KVH * G} query heads > 128 lanes; shard heads (tp) first"
        )
    o = _paged_attention_mq(
        q[:, None], k_cache, v_cache, layer_idx, block_tables,
        jnp.asarray(lengths, jnp.int32)[:, None], k_scale, v_scale,
        pages_per_chunk, interpret,
    )
    return o[:, 0]


@functools.partial(
    jax.jit,
    static_argnames=("pages_per_chunk", "interpret"),
)
def paged_spec_attention(
    q: jax.Array,            # [B, T, KVH, G, hd]
    k_cache: jax.Array,      # [L, N, bs, KVH*hd]
    v_cache: jax.Array,
    layer_idx: jax.Array,    # scalar int32
    block_tables: jax.Array, # [B, W] int32
    lengths: jax.Array,      # [B, T] int32
    k_scale: jax.Array | None = None,  # [L, N, bs, KVH] fp32 — int8 cache only
    v_scale: jax.Array | None = None,
    anc: jax.Array | None = None,  # [B, T, T] — tree topology mask
    *,
    pages_per_chunk: int = 0,
    interpret: bool = False,
) -> jax.Array:
    """Fused spec-verify gather: the [last, d1..dS] multi-query rows
    attend straight out of the page pool in ONE kernel — per-page DMAs,
    in-register dequant when the cache is int8, online softmax — instead
    of the XLA path's materialized (dequantized) relayout copy of the
    whole gathered table (the ~9ms/layer tax in the module header).
    With ``anc`` the rows form a draft TREE: ``lengths`` carries each
    query's paged-history horizon and the [T, T] ancestor mask rides as
    one more per-row prefetched operand (see
    ``paged_spec_attention_xla``) — tree verify is the same
    one-weight-stream gather, just with T extra VPU compares per chunk.
    Requires KVH*T*G ≤ 128 lanes; callers fall back to
    ``paged_spec_attention_xla`` beyond that (model.spec_verify does)."""
    return _paged_attention_mq(
        q, k_cache, v_cache, layer_idx, block_tables, lengths,
        k_scale, v_scale, pages_per_chunk, interpret, anc,
    )
