"""Paged decode attention: Pallas TPU kernel + XLA reference.

The role vLLM's paged-attention CUDA kernels play for the reference
(reference: components/backends/vllm/src/dynamo/vllm/main.py:90 delegates
to vLLM's engine; its CUDA kernels are the analogue of this file).

Why a kernel at all: the XLA formulation gathers the full (bucketed)
block-table width `W*bs` out of the page pool per layer per step —
~3x HBM traffic on padded context (materialize + re-read) regardless of
each sequence's true length. The kernel instead walks each row's actual
pages: one DMA per page (a page is contiguous ``[bs, KVH*hd]`` in the
cache layout), online-softmax accumulation, work proportional to
``sum(lengths)`` rather than ``B*W*bs``.

Design notes (measured on v5e, see tools/profile_decode.py):

- The FULL cache ``[L, N, bs, KVH*hd]`` stays in HBM (`pl.ANY`) in its
  native dense layout (a 5D [.., KVH, hd] layout forced a whole-cache
  relayout copy per pallas_call — ~9ms/layer measured on v5e, the reason
  the cache is stored heads-merged). The layer index is a scalar-prefetch
  operand, which also
  removes the per-layer ``dynamic_slice`` copies the gather path needs.
- Grid ``(B, CMAX)``: chunk c of row b processes up to P pages.
  Cross-step software pipelining: every live step issues the DMAs of the
  *next* live step (double-buffered), so page fetch overlaps compute
  across rows, not just within a row.
- **Block-diagonal q**: per-head lane slices of the KV buffer relayout
  on every access (hd=64 is sub-lane-tile) and measured ~15us/chunk.
  Instead the caller bakes q into a block-diagonal matrix
  ``[KVH*hd, KVH*G]`` so ONE MXU op yields all heads' scores
  ``[P*bs, KVH*G]``; the online softmax is column-wise (axis-0 reduces),
  and the accumulator is kept transposed ``[KVH*hd, KVH*G]`` so every
  correction is a row-vector broadcast. Zero relayouts, zero transposes
  in the kernel; the per-head diagonal is extracted by XLA afterwards.
- Dead steps (chunk beyond the row's length, padding rows) skip DMA and
  compute entirely — padding costs ~grid-iteration overhead only.
- Per-DMA cost measured ~0.6us: pages should be >=32KB to approach
  bandwidth. Page bytes = block_size x KVH x hd x 2 (bf16), so for
  8B-class geometries (KVH*hd = 1024) the default ``block_size=16``
  already gives 32KB pages — r5 bench: decode substeps run AT the int8
  weight-stream roofline (~9 ms vs the 9.8 ms floor) at bs=16, so
  larger blocks buy nothing there. Prefer 64-256 only for SMALL kv
  widths (e.g. KVH*hd <= 256) where bs=16 pages drop under 8KB.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def resolve_attn_impl(requested: str = "auto") -> str:
    """'auto' → 'pallas' on TPU-like backends, else 'xla'."""
    if requested != "auto":
        return requested
    try:
        backend = jax.default_backend()
    except RuntimeError:
        backend = "cpu"
    return "pallas" if backend in ("tpu", "axon") else "xla"


# ---------------------------------------------------------------------------
# XLA reference implementation (also the CPU / multi-device path)
# ---------------------------------------------------------------------------


def paged_decode_attention_xla(
    q: jax.Array,            # [B, KVH, G, hd]
    k_cache: jax.Array,      # [L, N, bs, KVH*hd]
    v_cache: jax.Array,
    layer_idx: jax.Array,    # scalar int32
    block_tables: jax.Array, # [B, W] int32
    lengths: jax.Array,      # [B] int32 — attend positions [0, length)
) -> jax.Array:
    """Gather-based formulation (the r3 path, hoisted here).  Returns
    [B, KVH, G, hd] in q.dtype."""
    B, KVH, G, hd = q.shape
    W = block_tables.shape[1]
    bs = k_cache.shape[2]
    layer_k = lax.dynamic_index_in_dim(k_cache, layer_idx, 0, keepdims=False)
    layer_v = lax.dynamic_index_in_dim(v_cache, layer_idx, 0, keepdims=False)
    pk = layer_k[block_tables].reshape(B, W * bs, KVH, hd)
    pv = layer_v[block_tables].reshape(B, W * bs, KVH, hd)
    scale = hd ** -0.5
    ctx = jnp.arange(W * bs, dtype=jnp.int32)
    mask = jnp.where(ctx[None, :] < lengths[:, None], 0.0, jnp.float32(NEG_INF))
    s = jnp.einsum("bkgh,bckh->bkgc", q, pk).astype(jnp.float32) * scale
    s = s + mask[:, None, None, :]
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bkgc,bckh->bkgh", p, pv)


def paged_spec_attention_xla(
    q: jax.Array,            # [B, T, KVH, G, hd] — T consecutive query positions
    k_cache: jax.Array,      # [L, N, bs, KVH*hd]
    v_cache: jax.Array,
    layer_idx: jax.Array,    # scalar int32
    block_tables: jax.Array, # [B, W] int32
    lengths: jax.Array,      # [B, T] int32 — query t attends [0, lengths[b, t])
) -> jax.Array:
    """Multi-query generalization of ``paged_decode_attention_xla`` for
    the speculative verify pass: T consecutive positions per row attend
    their own causal prefix out of the SAME gathered pages (one gather
    per layer for all T queries — the single-pass shape that lets a
    verify step score draft_len+1 logit rows in one weight stream).
    T=1 reduces exactly to the decode formulation, so CPU/XLA greedy
    byte-identity between the spec and dense paths holds by construction.
    Returns [B, T, KVH, G, hd] in q.dtype. (A Pallas multi-query kernel
    is the TPU upgrade path, same seam as the decode kernel.)"""
    B, T, KVH, G, hd = q.shape
    W = block_tables.shape[1]
    bs = k_cache.shape[2]
    layer_k = lax.dynamic_index_in_dim(k_cache, layer_idx, 0, keepdims=False)
    layer_v = lax.dynamic_index_in_dim(v_cache, layer_idx, 0, keepdims=False)
    pk = layer_k[block_tables].reshape(B, W * bs, KVH, hd)
    pv = layer_v[block_tables].reshape(B, W * bs, KVH, hd)
    scale = hd ** -0.5
    ctx = jnp.arange(W * bs, dtype=jnp.int32)
    mask = jnp.where(
        ctx[None, None, :] < lengths[:, :, None], 0.0, jnp.float32(NEG_INF)
    )                                                       # [B, T, W*bs]
    s = jnp.einsum("btkgh,bckh->btkgc", q, pk).astype(jnp.float32) * scale
    s = s + mask[:, :, None, None, :]
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("btkgc,bckh->btkgh", p, pv)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------


def _decode_kernel(
    # scalar prefetch
    layer_ref,    # [1] int32
    lengths_ref,  # [B] int32
    tables_ref,   # [B, W] int32
    # operands
    qbd_ref,      # VMEM [1, KVH*hd, KVH*G] — block-diag q, scale folded in
    k_hbm,        # ANY  [L, N, bs, KVH*hd] (bitcast view of the cache)
    v_hbm,
    # outputs
    o_ref,        # VMEM [1, KVH*hd, KVH*G] — attention out, transposed
    # scratch
    kbuf,         # VMEM [2, P, bs, KVH*hd]
    vbuf,
    m_scr,        # VMEM [8, 128] f32 — row 0, first KVH*G lanes live
    l_scr,        # VMEM [8, 128] f32
    acc_scr,      # VMEM [KVH*hd, KVH*G] f32
    slot_ref,     # SMEM [1] int32 — DMA double-buffer cursor
    started_ref,  # SMEM [1] int32 — global warmup flag
    sem,          # DMA sems [2, 2, P]
    *,
    pages_per_chunk: int,
):
    P = pages_per_chunk
    b = pl.program_id(0)
    c = pl.program_id(1)
    B = pl.num_programs(0)
    layer = layer_ref[0]
    bs = kbuf.shape[2]
    D = kbuf.shape[3]       # KVH*hd
    H = qbd_ref.shape[2]    # KVH*G (total query heads)
    CH = P * bs             # tokens per chunk

    length = lengths_ref[b]
    nchunks = lax.div(length + CH - 1, CH)
    live = c < nchunks

    @pl.when((b == 0) & (c == 0))
    def _init_globals():
        slot_ref[0] = 0
        started_ref[0] = 0

    def chunk_dmas(row, chunk, slot):
        """DMA descriptors for (row, chunk) into buffer `slot`; page p is
        guarded by the row's true page count."""
        rem = lengths_ref[row] - chunk * CH
        npages = jnp.minimum(lax.div(rem + bs - 1, bs), P)
        out = []
        for p in range(P):
            page = tables_ref[row, chunk * P + p]
            out.append((
                p < npages,
                pltpu.make_async_copy(k_hbm.at[layer, page], kbuf.at[slot, p], sem.at[slot, 0, p]),
                pltpu.make_async_copy(v_hbm.at[layer, page], vbuf.at[slot, p], sem.at[slot, 1, p]),
            ))
        return out

    def issue(row, chunk, slot):
        for ok, dk, dv in chunk_dmas(row, chunk, slot):
            @pl.when(ok)
            def _():
                dk.start()
                dv.start()

    @pl.when(live)
    def _body():
        cur = slot_ref[0]

        # Global warmup: the very first live step has no predecessor.
        @pl.when(started_ref[0] == 0)
        def _():
            issue(b, c, cur)
            started_ref[0] = 1

        # Software pipeline: issue the next live step's pages.
        # Successor is (b, c+1) if this row continues, else chunk 0 of
        # the next non-empty row (scalar search past padding rows).
        nxt = 1 - cur
        row_continues = c + 1 < nchunks

        @pl.when(row_continues)
        def _():
            issue(b, c + 1, nxt)

        @pl.when(~row_continues)
        def _():
            nxt_row = lax.while_loop(
                lambda r: (r < B) & (lengths_ref[jnp.minimum(r, B - 1)] == 0),
                lambda r: r + 1,
                b + 1,
            )

            @pl.when(nxt_row < B)
            def _():
                issue(nxt_row, 0, nxt)

        # Init row accumulators at the row's first chunk.
        @pl.when(c == 0)
        def _():
            m_scr[...] = jnp.full_like(m_scr, NEG_INF)
            l_scr[...] = jnp.zeros_like(l_scr)
            acc_scr[...] = jnp.zeros_like(acc_scr)

        # Wait for this step's pages.
        for ok, dk, dv in chunk_dmas(b, c, cur):
            @pl.when(ok)
            def _():
                dk.wait()
                dv.wait()
        slot_ref[0] = nxt

        # Context-position validity, column orientation [P*bs, 1].
        pos = c * CH + lax.broadcasted_iota(jnp.int32, (P * bs, 1), 0)
        valid = pos < length

        k_chunk = kbuf[cur].reshape(P * bs, D)
        v_chunk = vbuf[cur].reshape(P * bs, D)
        # Unfetched tail pages hold garbage (possibly NaN): k is
        # neutralized by the score mask, v must be zeroed (0*NaN=NaN).
        v_chunk = jnp.where(valid, v_chunk, 0)

        # All heads' scores in one MXU op via the block-diagonal q.
        s = lax.dot_general(
            k_chunk, qbd_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # [P*bs, H]
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_scr[0:1, :H]                            # [1, H]
        l_prev = l_scr[0:1, :H]
        m_cur = jnp.max(s, axis=0, keepdims=True)          # [1, H]
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)                     # [1, H]
        p = jnp.exp(s - m_new)                             # [P*bs, H]
        l_new = corr * l_prev + jnp.sum(p, axis=0, keepdims=True)
        # Transposed accumulator [D, H]: corrections broadcast over rows.
        pv = lax.dot_general(
            v_chunk, p.astype(v_chunk.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                  # [D, H]
        acc_scr[...] = acc_scr[...] * corr + pv
        m_scr[0:1, :H] = m_new
        l_scr[0:1, :H] = l_new

        # Row done → normalize and emit (still transposed; XLA takes the
        # per-head diagonal outside).
        @pl.when(c == nchunks - 1)
        def _():
            l = jnp.maximum(l_scr[0:1, :H], 1e-30)
            o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)

    # Keep padding rows' output defined (their stale block is otherwise
    # flushed as-is; harmless numerically but keep it clean).
    @pl.when((~live) & (c == 0))
    def _zero():
        o_ref[0] = jnp.zeros_like(o_ref[0])


@functools.partial(
    jax.jit,
    static_argnames=("pages_per_chunk", "interpret"),
)
def paged_decode_attention(
    q: jax.Array,            # [B, KVH, G, hd]
    k_cache: jax.Array,      # [L, N, bs, KVH*hd] — dense pages, no
    v_cache: jax.Array,      #   per-call layout conversion
    layer_idx: jax.Array,    # scalar int32
    block_tables: jax.Array, # [B, W] int32
    lengths: jax.Array,      # [B] int32
    *,
    pages_per_chunk: int = 0,  # 0 → auto (~512 tokens per chunk)
    interpret: bool = False,
) -> jax.Array:
    B, KVH, G, hd = q.shape
    L, N, bs = k_cache.shape[0], k_cache.shape[1], k_cache.shape[2]
    assert k_cache.shape[3] == KVH * hd, "cache must be [L, N, bs, KVH*hd]"
    W = block_tables.shape[1]
    if KVH * G > 128:
        raise NotImplementedError(
            f"{KVH * G} query heads > 128 lanes; shard heads (tp) first"
        )
    P = pages_per_chunk or max(1, 512 // bs)
    P = min(P, W)
    if W % P:  # pad the table so chunks tile it exactly
        pad = P - W % P
        block_tables = jnp.pad(block_tables, ((0, 0), (0, pad)))
        W += pad
    chunks_max = W // P

    # Block-diagonal q with the softmax scale folded in:
    # qbd[b, j*hd+h, k*G+g] = q[b,k,g,h] * scale * (j==k).
    eye = jnp.eye(KVH, dtype=q.dtype)
    qbd = jnp.einsum("bkgh,jk->bjhkg", q * (hd ** -0.5), eye)
    qbd = qbd.reshape(B, KVH * hd, KVH * G)

    kernel = functools.partial(_decode_kernel, pages_per_chunk=P)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, chunks_max),
        in_specs=[
            pl.BlockSpec((1, KVH * hd, KVH * G), lambda b, c, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((1, KVH * hd, KVH * G), lambda b, c, *_: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, P, bs, KVH * hd), k_cache.dtype),
            pltpu.VMEM((2, P, bs, KVH * hd), v_cache.dtype),
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((8, 128), jnp.float32),
            pltpu.VMEM((KVH * hd, KVH * G), jnp.float32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SMEM((1,), jnp.int32),
            pltpu.SemaphoreType.DMA((2, 2, P)),
        ],
    )
    o_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH * hd, KVH * G), q.dtype),
        interpret=interpret,
    )(
        jnp.asarray(layer_idx, jnp.int32).reshape(1),
        jnp.asarray(lengths, jnp.int32),
        jnp.asarray(block_tables, jnp.int32),
        qbd,
        k_cache,
        v_cache,
    )
    # [B, KVH*hd, KVH*G] → per-head diagonal → [B, KVH, G, hd].
    o5 = o_t.reshape(B, KVH, hd, KVH, G)
    return jnp.einsum("bkhkg->bkgh", o5)
