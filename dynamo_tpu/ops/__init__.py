"""TPU kernels (Pallas) + XLA reference implementations for the hot ops.

The engine's compute path stays pure-JAX where XLA already does the right
thing (dense matmuls, norms, sampling); Pallas takes over where XLA's
formulation is structurally wasteful — paged attention, where a gather
materializes `W*bs` padded context per layer per step regardless of the
sequence's true length (VERDICT r3 weak #1).
"""

from dynamo_tpu.ops.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_xla,
    resolve_attn_impl,
)

__all__ = [
    "paged_decode_attention",
    "paged_decode_attention_xla",
    "resolve_attn_impl",
]
