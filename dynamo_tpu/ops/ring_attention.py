"""Ring attention: causal attention with the sequence sharded over a
mesh axis — the long-context prefill primitive.

The reference has NO sequence/context parallelism anywhere (SURVEY §2.6:
long context is handled by engine --max-model-len + KV offload), so this
is net-new TPU design per SURVEY §7: shard the sequence over an ``sp``
mesh axis, keep q local, and rotate (k, v) chunks around the ring with
``lax.ppermute`` (XLA lowers to ICI neighbor exchanges), accumulating
online-softmax partials. Compute and communication overlap naturally:
each ring step's permute is independent of that step's attention math,
and XLA schedules them concurrently.

Causality over the ring: the device holding query chunk i only
accumulates kv chunks j<=i fully, chunk j==i with the local causal mask,
and skips j>i (their contribution is masked, and m/l guards keep the
skipped steps from polluting the accumulators).

Memory: each device holds T/n of q, k, v and one in-flight kv chunk —
peak activation memory for a T-token prefill drops by ~n, which is the
whole point: a 1M-token prompt on v5e-16 becomes 62.5k tokens per chip.

Usage: wrap in shard_map over the sp axis (see ``ring_prefill`` below
and tests/test_ring_attention.py for the mesh plumbing).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NEG_INF = -1e30


def ring_attention_local(
    q: jax.Array,  # [Tc, H, hd] — this device's query chunk (roped)
    k: jax.Array,  # [Tc, KVH, hd] — this device's key chunk (roped)
    v: jax.Array,  # [Tc, KVH, hd]
    axis_name: str,
    *,
    causal: bool = True,
) -> jax.Array:
    """Per-device body (call under shard_map over ``axis_name``).
    Supports GQA (H a multiple of KVH). Returns [Tc, H, hd] in q.dtype."""
    Tc, H, hd = q.shape
    KVH = k.shape[1]
    G = H // KVH
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    scale = hd ** -0.5
    qg = q.reshape(Tc, KVH, G, hd)
    local = jnp.arange(Tc, dtype=jnp.int32)
    q_pos = me * Tc + local  # global positions of this device's queries

    def step(i, carry):
        k_cur, v_cur, m, l, acc = carry
        src = lax.rem(me - i + n, n)  # origin device of the kv chunk in hand
        kv_pos = src * Tc + local
        s = jnp.einsum("tkgh,skh->tkgs", qg, k_cur).astype(jnp.float32) * scale
        if causal:
            mask = kv_pos[None, :] <= q_pos[:, None]  # [Tc, Tc]
            s = jnp.where(mask[:, None, None, :], s, NEG_INF)
        m_cur = jnp.max(s, axis=-1)                    # [Tc, KVH, G]
        m_new = jnp.maximum(m, m_cur)
        # A fully-masked step contributes nothing; keep m finite so the
        # correction exp() stays well-defined.
        m_safe = jnp.where(m_new == NEG_INF, 0.0, m_new)
        p = jnp.exp(s - m_safe[..., None])
        p = jnp.where(s == NEG_INF, 0.0, p)
        corr = jnp.exp(jnp.where(m == NEG_INF, NEG_INF, m - m_safe))
        l_new = corr * l + jnp.sum(p, axis=-1)
        pv = jnp.einsum("tkgs,skh->tkgh", p.astype(v_cur.dtype), v_cur)
        acc_new = acc * corr[..., None] + pv.astype(jnp.float32)
        # Rotate kv to the next device (XLA: ICI neighbor exchange).
        perm = [(j, (j + 1) % n) for j in range(n)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, l_new, acc_new)

    # pvary: constants start replicated under shard_map; the carry becomes
    # device-varying after step 1, so the loop types must match up front.
    m0 = lax.pvary(jnp.full((Tc, KVH, G), NEG_INF, jnp.float32), (axis_name,))
    l0 = lax.pvary(jnp.zeros((Tc, KVH, G), jnp.float32), (axis_name,))
    acc0 = lax.pvary(jnp.zeros((Tc, KVH, G, hd), jnp.float32), (axis_name,))
    _, _, _, l, acc = lax.fori_loop(0, n, step, (k, v, m0, l0, acc0))
    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.reshape(Tc, H, hd).astype(q.dtype)


def ring_prefill(
    mesh: Mesh,
    axis_name: str,
    q: jax.Array,  # [T, H, hd] — full sequence (sharded or to-be-sharded)
    k: jax.Array,  # [T, KVH, hd]
    v: jax.Array,
    *,
    causal: bool = True,
) -> jax.Array:
    """Causal attention for a long sequence sharded over ``axis_name``.
    T must divide evenly by the axis size."""
    from jax.experimental.shard_map import shard_map

    spec = P(axis_name, None, None)
    fn = shard_map(
        functools.partial(ring_attention_local, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    sharding = NamedSharding(mesh, spec)
    return fn(
        jax.device_put(q, sharding),
        jax.device_put(k, sharding),
        jax.device_put(v, sharding),
    )
