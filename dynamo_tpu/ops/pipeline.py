"""Pipeline parallelism: layer stages sharded over a ``pp`` mesh axis.

Reference analogue: the PP sizes the reference passes to its engines
(reference: components/backends/trtllm/src/dynamo/trtllm/utils/
trtllm_utils.py:134-138 — PP is engine-internal there). TPU-native
formulation: the stacked layer parameters ``[L, ...]`` shard over
``pp`` (device s holds layers [s·L/n, (s+1)·L/n)); activations flow
stage→stage with ``lax.ppermute`` on a GPipe microbatch schedule, so all
stages work concurrently on different microbatches.

Schedule: M microbatches through n stages takes M+n-1 steps (bubble
fraction (n-1)/(M+n-1)); microbatch m enters stage 0 at step m and exits
stage n-1 at step m+n-1. The final psum gathers the last stage's
outputs to every device (outputs are zero elsewhere).

This is the serving-side PP primitive (one forward, no backward); the
engine integration point is the layer scan in model.py — a pp-sharded
engine runs ``pipeline_apply`` with the decode batch split into
microbatches and the KV cache layer-sharded over the same axis
(cache axis 0 is layers, so ``P("pp", ...)`` keeps every stage's pages
local). Single-chip benches cannot exercise it; parity is pinned on the
virtual-device mesh in tests/test_pipeline.py.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def pipeline_apply_local(
    x_mb: jax.Array,       # [M, mb, D] — all microbatches (replicated input)
    local_layers: Any,     # pytree with leading local-layer axis (this stage's slice)
    layer_fn: Callable,    # (x [mb, D], layer_params) -> x [mb, D]
    axis_name: str,
) -> jax.Array:
    """Per-device body (run under shard_map over ``axis_name``).
    Returns [M, mb, D] outputs, identical on every device."""
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    M = x_mb.shape[0]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def stage(x):
        def body(c, lp):
            return layer_fn(c, lp), None

        y, _ = lax.scan(body, x, local_layers)
        return y

    def step(t, carry):
        recv, outputs = carry
        # Stage 0 injects microbatch t; later stages consume the permuted
        # activation from their predecessor.
        inject = x_mb[jnp.clip(t, 0, M - 1)]
        inp = jnp.where(me == 0, inject, recv)
        out = stage(inp)
        # The last stage emits microbatch t-(n-1) (it has now traversed
        # every stage); other steps/stages write nothing.
        out_idx = jnp.clip(t - (n - 1), 0, M - 1)
        valid = (me == n - 1) & (t >= n - 1) & (t - (n - 1) < M)
        outputs = jnp.where(valid, outputs.at[out_idx].set(out), outputs)
        recv_next = lax.ppermute(out, axis_name, perm)
        return (recv_next, outputs)

    recv0 = lax.pvary(jnp.zeros_like(x_mb[0]), (axis_name,))
    out0 = lax.pvary(jnp.zeros_like(x_mb), (axis_name,))
    _, outputs = lax.fori_loop(0, M + n - 1, step, (recv0, out0))
    # Only the last stage holds real outputs; zeros elsewhere → psum
    # broadcasts them to the whole group.
    return lax.psum(outputs, axis_name)


def pipeline_apply(
    mesh: Mesh,
    axis_name: str,
    params_stacked: Any,   # pytree, leading axis L divisible by the pp size
    x: jax.Array,          # [B, D] — full batch (replicated)
    layer_fn: Callable,
    num_microbatches: int,
) -> jax.Array:
    """GPipe-microbatched forward of a stacked-layer network with the
    layer axis sharded over ``axis_name``. Returns [B, D]."""
    from jax.experimental.shard_map import shard_map

    B, D = x.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    x_mb = x.reshape(M, B // M, D)

    layer_spec = jax.tree.map(lambda _: P(axis_name), params_stacked)
    fn = shard_map(
        functools.partial(pipeline_apply_local, layer_fn=layer_fn, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(), layer_spec),
        out_specs=P(),
    )
    sharded = jax.tree.map(
        lambda leaf: jax.device_put(
            leaf, NamedSharding(mesh, P(axis_name, *([None] * (leaf.ndim - 1))))
        ),
        params_stacked,
    )
    out = fn(jax.device_put(x_mb, NamedSharding(mesh, P())), sharded)
    return out.reshape(B, D)
