"""Multi-tier paged-KV block management.

Reference analogue: lib/llm/src/block_manager.rs:68-173 (KVBM: G1 device /
G2 pinned host / G3 disk / G4 remote tiers with sequence-hash reuse and an
offload manager). Here the tiers map to TPU memory:

- G1 = HBM: the engine's paged cache arrays; this package does the
  *bookkeeping* (allocation, ref-counts, prefix reuse, eviction) while the
  bytes live in the engine's jax arrays.
- G2 = host RAM: numpy mirrors filled by device→host DMA (offload.py).
- G3 = local disk (later).

The pool emits KV cache events on block registration/eviction — the same
events that feed the KV-aware router's global index.
"""

from dynamo_tpu.block_manager.adapters import AdapterSlotPool, NoFreeAdapterSlotsError
from dynamo_tpu.block_manager.pool import BlockPool, NoFreeBlocksError

__all__ = [
    "AdapterSlotPool",
    "BlockPool",
    "NoFreeAdapterSlotsError",
    "NoFreeBlocksError",
]
