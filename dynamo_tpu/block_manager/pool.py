"""G1 (device/HBM) block pool: allocation, ref-counting, prefix reuse, LRU
eviction, KV event emission.

Reference analogue: lib/llm/src/block_manager/pool.rs:156,457 (active +
inactive pools with sequence-hash reuse matching) and the block lifecycle
Reset→Partial→Complete→Registered (block_manager/block/registry.rs).

States here:

- **free**: on the free list, contents meaningless.
- **active**: ref_count > 0, owned by ≥1 live sequence. A block becomes
  *registered* (hash known, event emitted) once it holds a full block of
  tokens; shared prefix blocks are active with ref_count > 1.
- **cached**: ref_count == 0 but registered — contents retained for
  future prefix hits, evictable LRU-first.

Block id 0 is reserved as the garbage sink for padded writes (model.py
contract) and never allocated.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable

from dynamo_tpu.kv_router.protocols import KvCacheEvent, StoredBlock

EventSink = Callable[[KvCacheEvent], None]


class NoFreeBlocksError(Exception):
    pass


class _Block:
    __slots__ = ("block_id", "ref_count", "seq_hash", "parent_hash")

    def __init__(self, block_id: int):
        self.block_id = block_id
        self.ref_count = 0
        self.seq_hash: int | None = None
        self.parent_hash: int | None = None


class BlockPool:
    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        event_sink: EventSink | None = None,
        enable_prefix_caching: bool = True,
    ):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.enable_prefix_caching = enable_prefix_caching
        self._blocks = [_Block(i) for i in range(num_blocks)]
        self._free: list[int] = list(range(num_blocks - 1, 0, -1))  # pop() → low ids first
        self._cached: dict[int, int] = {}          # seq_hash → block_id (registered)
        self._lru: OrderedDict[int, None] = OrderedDict()  # block_id → None, oldest first
        # Radix fan-out: parent seq_hash → number of REGISTERED children.
        # A hash with >= 2 children is a branch point (shared prefix that
        # several continuations diverge from) — the tier eviction policy
        # protects those blocks from one-off-prompt churn.
        self._children: dict[int, int] = {}
        self._event_sink = event_sink
        self._event_id = 0
        # Mutations run on the engine scheduler thread while snapshot()/
        # metrics run on the asyncio loop thread (kv_events subscribers,
        # load_metrics) — every public method takes this lock.
        self._lock = threading.RLock()
        # prefix-cache observability
        self.hit_blocks = 0
        self.miss_blocks = 0

    # -- events -----------------------------------------------------------

    def set_event_sink(self, sink: EventSink | None) -> None:
        """Late-bind the event sink (workers construct engine-then-
        broadcaster). Events emitted before binding are recoverable via
        snapshot()."""
        self._event_sink = sink

    def _emit(self, event: KvCacheEvent) -> None:
        if self._event_sink is not None:
            self._event_id += 1
            event.event_id = self._event_id
            self._event_sink(event)

    # -- capacity ---------------------------------------------------------

    @property
    def num_free(self) -> int:
        """Blocks obtainable right now (free list + evictable cached)."""
        with self._lock:
            return len(self._free) + len(self._lru)

    @property
    def num_active(self) -> int:
        return self.num_blocks - 1 - self.num_free

    @property
    def usage(self) -> float:
        cap = self.num_blocks - 1
        return self.num_active / cap if cap else 0.0

    @property
    def hit_rate(self) -> float:
        total = self.hit_blocks + self.miss_blocks
        return self.hit_blocks / total if total else 0.0

    # -- allocation -------------------------------------------------------

    def match_prefix(self, seq_hashes: list[int]) -> list[int]:
        """Longest run of leading hashes present in the cache → block ids.
        (Chained hashes: a hit at i implies hits at 0..i-1 had the same
        content, so greedy front-matching is exact.)"""
        if not self.enable_prefix_caching:
            return []
        with self._lock:
            out: list[int] = []
            for h in seq_hashes:
                bid = self._cached.get(h)
                if bid is None:
                    break
                out.append(bid)
            return out

    def allocate_sequence(self, seq_hashes: list[int], total_blocks: int) -> tuple[list[int], int]:
        """Allocate ``total_blocks`` for a sequence whose complete-prompt
        block hashes are ``seq_hashes``. Reuses cached prefix blocks.

        → (block_ids, num_hit_blocks). Raises NoFreeBlocksError (nothing
        allocated) if the pool can't satisfy the request."""
        with self._lock:
            hits = self.match_prefix(seq_hashes)
            need_new = total_blocks - len(hits)
            if need_new > len(self._free) + len(self._lru) - self._lru_overlap(hits):
                raise NoFreeBlocksError(f"need {need_new}, have {self.num_free}")
            # Claim hits first (removes them from the evictable LRU).
            for bid in hits:
                self._ref(bid)
            block_ids = list(hits)
            try:
                for _ in range(need_new):
                    block_ids.append(self._pop_free())
            except NoFreeBlocksError:
                for bid in block_ids:
                    self._unref(bid)
                raise
            self.hit_blocks += len(hits)
            self.miss_blocks += max(0, len(seq_hashes) - len(hits))
            return block_ids, len(hits)

    def allocate_block(self) -> int:
        """One fresh block (decode growth). Raises NoFreeBlocksError."""
        with self._lock:
            return self._pop_free()

    def _lru_overlap(self, hits: list[int]) -> int:
        # hits currently in LRU will leave it on _ref; they don't reduce
        # the evictable supply for the *new* blocks beyond themselves.
        return sum(1 for b in hits if b in self._lru)

    def _pop_free(self) -> int:
        if self._free:
            bid = self._free.pop()
        elif self._lru:
            bid, _ = self._lru.popitem(last=False)  # oldest
            self._evict(bid)
        else:
            raise NoFreeBlocksError("pool exhausted")
        b = self._blocks[bid]
        b.ref_count = 1
        b.seq_hash = None
        b.parent_hash = None
        return bid

    def _evict(self, bid: int) -> None:
        b = self._blocks[bid]
        if b.seq_hash is not None:
            self._cached.pop(b.seq_hash, None)
            self._emit(KvCacheEvent.removed([b.seq_hash]))
            self._drop_child(b.parent_hash)
            b.seq_hash = None
            b.parent_hash = None

    def _drop_child(self, parent_hash: int | None) -> None:
        if parent_hash is None:
            return
        n = self._children.get(parent_hash, 0) - 1
        if n > 0:
            self._children[parent_hash] = n
        else:
            self._children.pop(parent_hash, None)

    def _ref(self, bid: int) -> None:
        b = self._blocks[bid]
        b.ref_count += 1
        if b.ref_count == 1:
            self._lru.pop(bid, None)

    def _unref(self, bid: int) -> None:
        b = self._blocks[bid]
        b.ref_count -= 1
        if b.ref_count > 0:
            return
        if b.seq_hash is not None and self.enable_prefix_caching:
            self._lru[bid] = None  # retained, evictable
            self._lru.move_to_end(bid)
        else:
            b.seq_hash = None
            self._free.append(bid)

    # -- registration (block completion) ----------------------------------

    def register_block(self, bid: int, seq_hash: int, parent_hash: int | None) -> int:
        """A sequence filled this block: record its identity and emit a
        `stored` event. If an identical registered block already exists
        (same hash, concurrent fill), the caller keeps its copy but the
        canonical cache entry stays with the first — returns the canonical
        block id."""
        with self._lock:
            b = self._blocks[bid]
            canonical = self._cached.get(seq_hash)
            if canonical is not None:
                return canonical  # already registered (this block or a twin): no re-emit
            b.seq_hash = seq_hash
            b.parent_hash = parent_hash
            if self.enable_prefix_caching:
                self._cached[seq_hash] = bid
                if parent_hash is not None:
                    self._children[parent_hash] = self._children.get(parent_hash, 0) + 1
                self._emit(KvCacheEvent.stored([StoredBlock(seq_hash, parent_hash)]))
            return bid

    def hash_fanout(self, seq_hash: int) -> int:
        """Registered children of this hash in the radix chain."""
        with self._lock:
            return self._children.get(seq_hash, 0)

    def hash_protected(self, seq_hash: int) -> bool:
        """Should the KV tiers protect this block from churn eviction?
        True for branch points (>= 2 registered children — shared
        prefixes several continuations diverge from, e.g. a system
        prompt) and blocks multiple live sequences currently share."""
        with self._lock:
            if self._children.get(seq_hash, 0) >= 2:
                return True
            bid = self._cached.get(seq_hash)
            return bid is not None and self._blocks[bid].ref_count >= 2

    # -- release ----------------------------------------------------------

    def free_sequence(self, block_ids: list[int]) -> None:
        with self._lock:
            for bid in block_ids:
                self._unref(bid)

    def snapshot(self) -> list[tuple[int, int | None]]:
        """All currently-registered (hash, parent_hash) pairs in original
        registration order (parents before children — dict insertion
        order). Used to seed a new KV-event subscriber. Thread-safe: may
        be called from the asyncio loop while the engine thread mutates."""
        with self._lock:
            return [(h, self._blocks[bid].parent_hash) for h, bid in self._cached.items()]

    def clear(self) -> int:
        """Drop every cached (ref 0) block — admin /clear_kv_blocks path
        (reference: lib/llm/src/http/service/clear_kv_blocks.rs). Emits a
        `removed` event for exactly the hashes dropped: blocks still
        referenced by running sequences stay registered, so a blanket
        `cleared` would desync remote radix indexers. → count dropped."""
        with self._lock:
            dropped: list[int] = []
            for bid in list(self._lru):
                self._lru.pop(bid)
                b = self._blocks[bid]
                if b.seq_hash is not None:
                    self._cached.pop(b.seq_hash, None)
                    dropped.append(b.seq_hash)
                    self._drop_child(b.parent_hash)
                    b.seq_hash = None
                    b.parent_hash = None
                self._free.append(bid)
            if dropped:
                self._emit(KvCacheEvent.removed(dropped))
            return len(dropped)
