"""Adapter slot pool: the G1 (HBM) residency economy for LoRA adapters.

S-LoRA's unified-paging idea mapped onto this block manager: the device
adapter bank has a fixed number of SLOTS (engine/lora.py describes the
bank itself); which adapter occupies which slot is decided here with the
same second-chance credit policy the KV tiers use (block_manager/
tiers.py) — hits top up credit, spared eviction scans decay it, so a
recently-hot adapter survives a burst of one-off tenants but a cold one
still ages out. Adapters pinned by RUNNING sequences are never victims:
an in-flight batch row reads its slot's bank weights on every dispatch,
so eviction is only legal once the last sequence using the adapter
finished (the engine releases pins at finish/preempt; the serial device
stream orders any subsequent upload after already-dispatched windows, so
zombie rows of just-finished sequences still read the old weights).

Thread affinity: acquire/release run on the engine's scheduler thread
only (same contract as BlockPool); the integer stats are read racily by
bench/metrics like every other monotonic counter.
"""

from __future__ import annotations

from collections import OrderedDict

from dynamo_tpu.block_manager.pool import NoFreeBlocksError
from dynamo_tpu.block_manager.tiers import MAX_CREDIT


class NoFreeAdapterSlotsError(NoFreeBlocksError):
    """Every slot is pinned by a running sequence. Subclasses
    NoFreeBlocksError so engine admission applies its standard
    resource-pressure handling (requeue, retry when capacity frees)."""


class AdapterSlotPool:
    """Maps adapter ids to device bank slots with pinning + second-chance
    eviction. ``acquire`` → (slot, needs_upload); the caller uploads the
    adapter's weights into the slot when asked and MUST ``release`` once
    per acquire when the sequence finishes."""

    def __init__(self, num_slots: int):
        if num_slots <= 0:
            raise ValueError("num_slots must be positive")
        self.num_slots = num_slots
        self._free: list[int] = list(range(num_slots))
        self._order: OrderedDict[str, int] = OrderedDict()  # resident, LRU→MRU
        self._credit: dict[str, int] = {}
        self._pins: dict[str, int] = {}
        self._ever_evicted: set[str] = set()
        # Monotonic stats (racy cross-thread reads are fine):
        self.hits = 0          # acquires served by a resident slot
        self.pageins = 0       # uploads into a slot (cold fetch happened)
        self.evictions = 0     # resident adapters displaced for a page-in
        self.repageins = 0     # page-ins of previously-evicted adapters
        self.protected_scans = 0  # eviction scans that spared a warm entry

    @property
    def resident(self) -> int:
        return len(self._order)

    def resident_ids(self) -> list[str]:
        return list(self._order)

    def slot_of(self, adapter_id: str) -> int | None:
        return self._order.get(adapter_id)

    def _pop_victim(self) -> tuple[str, int]:
        """Oldest unpinned zero-credit resident; warm entries are spared
        (credit decayed, re-queued MRU) within one bounded scan, pinned
        entries are never eligible. Raises NoFreeAdapterSlotsError when
        everything is pinned."""
        scans = 0
        limit = len(self._order)
        while scans < limit:
            aid, slot = self._order.popitem(last=False)
            scans += 1
            if self._pins.get(aid, 0) > 0:
                self._order[aid] = slot  # pinned: re-queue, not evictable
                continue
            c = self._credit.get(aid, 0)
            if c <= 0:
                self._credit.pop(aid, None)
                return aid, slot
            self._credit[aid] = c - 1
            self._order[aid] = slot
            self.protected_scans += 1
        # Everything scanned was pinned or warm: fall back to the oldest
        # unpinned entry regardless of credit (bounded, never livelocks).
        for aid in list(self._order):
            if self._pins.get(aid, 0) == 0:
                slot = self._order.pop(aid)
                self._credit.pop(aid, None)
                return aid, slot
        raise NoFreeAdapterSlotsError(
            "every adapter slot is pinned by a running sequence"
        )

    def acquire(self, adapter_id: str) -> tuple[int, bool, str | None]:
        """Pin ``adapter_id`` into a slot → (slot, needs_upload,
        evicted_adapter_id). ``needs_upload`` means the caller must write
        the adapter's weights into the slot before dispatching rows that
        reference it."""
        slot = self._order.get(adapter_id)
        if slot is not None:
            self._order.move_to_end(adapter_id)
            self._credit[adapter_id] = min(
                self._credit.get(adapter_id, 0) + 1, MAX_CREDIT
            )
            self._pins[adapter_id] = self._pins.get(adapter_id, 0) + 1
            self.hits += 1
            return slot, False, None
        evicted: str | None = None
        if self._free:
            slot = self._free.pop()
        else:
            evicted, slot = self._pop_victim()
            self._ever_evicted.add(evicted)
            self.evictions += 1
        self._order[adapter_id] = slot
        # Credit is EARNED by hits (same policy as the KV tiers): a fresh
        # page-in starts cold, so one-shot tenants age out first.
        self._pins[adapter_id] = self._pins.get(adapter_id, 0) + 1
        self.pageins += 1
        if adapter_id in self._ever_evicted:
            self.repageins += 1
        return slot, True, evicted

    def release(self, adapter_id: str) -> None:
        """Drop one pin (sequence finished/preempted). The adapter stays
        resident — only eviction pressure removes it."""
        n = self._pins.get(adapter_id, 0)
        if n <= 1:
            self._pins.pop(adapter_id, None)
        else:
            self._pins[adapter_id] = n - 1

    def drop(self, adapter_id: str) -> None:
        """Remove a resident entry outright, returning its slot to the
        free list. The FAILED-UPLOAD unwind: acquire() marks residency
        before the caller uploads, so an upload that errors must not
        leave the adapter looking resident — the next acquire would skip
        the upload and rows would decode against a zero/partial bank
        slot. Only legal with no outstanding pins beyond the caller's
        own (a fresh page-in holds exactly one)."""
        slot = self._order.pop(adapter_id, None)
        self._credit.pop(adapter_id, None)
        self._pins.pop(adapter_id, None)
        if slot is not None:
            self._free.append(slot)
            self.pageins = max(0, self.pageins - 1)  # the page-in never landed

    def stats(self) -> dict:
        return {
            "resident": self.resident,
            "num_slots": self.num_slots,
            "hits": self.hits,
            "pageins": self.pageins,
            "evictions": self.evictions,
            "repageins": self.repageins,
            "protected_scans": self.protected_scans,
        }
