"""KV cache tiers beyond HBM: G2 host RAM and G3 local disk.

Reference analogue: the KVBM tier stack G1 device / G2 pinned host / G3
disk with offload + onboard (reference: lib/llm/src/block_manager.rs:
68-81, block_manager/offload.rs:16-46). TPU redesign: blocks are
identified by their chained sequence hash (tokens.py semantics), pages
move HBM↔host with the engine's DMA primitives (engine/kv_transfer.py),
and offload is *write-through with batching* — sealed blocks are copied
host-side once per scheduler step in one batched extract — rather than
the reference's eviction-time write-back, because a TPU cache donation
invalidates old device buffers and eviction happens mid-allocation where
a synchronous extract would serialize admission.

Lookup path on prefix miss in G1: G2 dict hit → pages; G2 miss → G3 file
hit → pages (promoted back into G2). Both tiers are plain LRU over
hash-keyed pages and thread-safe.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict

import numpy as np


class HostBlockPool:
    """G2: host-RAM pages keyed by sequence hash, LRU-bounded.

    A "page" is the tuple of per-block arrays the engine extracts:
    ``(k, v)`` for full-precision caches, ``(k, v, k_scale, v_scale)``
    for int8 KV — the pools are format-agnostic, so the same
    ``capacity_blocks`` budget holds ~2x the tokens under int8."""

    def __init__(self, capacity_blocks: int, spill=None):
        self.capacity = capacity_blocks
        self._pages: OrderedDict[int, tuple[np.ndarray, ...]] = OrderedDict()
        self._lock = threading.Lock()
        self._spill = spill  # callable(hash, *pages) — e.g. DiskBlockPool.put
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._pages)

    def put(self, seq_hash: int, *pages: np.ndarray) -> None:
        spilled = []
        # Own the storage: callers pass views into shared batch buffers
        # (engine extracts up to 64 blocks per DMA and slices per block);
        # retaining a view would pin the whole batch buffer and break the
        # capacity accounting.
        pages = tuple(a.copy() if a.base is not None else a for a in pages)
        with self._lock:
            if seq_hash in self._pages:
                self._pages.move_to_end(seq_hash)
                return
            self._pages[seq_hash] = pages
            while len(self._pages) > self.capacity:
                h, pgs = self._pages.popitem(last=False)
                spilled.append((h, pgs))
        for h, pgs in spilled:
            if self._spill is not None:
                self._spill(h, *pgs)

    def get(self, seq_hash: int) -> tuple[np.ndarray, ...] | None:
        with self._lock:
            pages = self._pages.get(seq_hash)
            if pages is not None:
                self._pages.move_to_end(seq_hash)
                self.hits += 1
                return pages
        self.misses += 1
        return None

    def contains(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._pages

    def clear(self) -> int:
        with self._lock:
            n = len(self._pages)
            self._pages.clear()
            return n


class DiskBlockPool:
    """G3: one file per block hash under a directory, LRU by mtime order
    (tracked in-process; files from a previous process are adopted)."""

    def __init__(self, directory: str, capacity_blocks: int):
        self.dir = directory
        self.capacity = capacity_blocks
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._order: OrderedDict[int, None] = OrderedDict()
        for fname in sorted(
            os.listdir(directory),
            key=lambda f: os.path.getmtime(os.path.join(directory, f)),
        ):
            if fname.endswith(".npz"):
                try:
                    self._order[int(fname[:-4])] = None
                except ValueError:
                    pass
        self.hits = 0
        self.misses = 0

    def _path(self, seq_hash: int) -> str:
        return os.path.join(self.dir, f"{seq_hash}.npz")

    def __len__(self) -> int:
        with self._lock:
            return len(self._order)

    def put(self, seq_hash: int, *pages: np.ndarray) -> None:
        k, v = pages[0], pages[1]
        evict: list[int] = []
        with self._lock:
            if seq_hash in self._order:
                self._order.move_to_end(seq_hash)
                return
            self._order[seq_hash] = None
            while len(self._order) > self.capacity:
                evict.append(self._order.popitem(last=False)[0])
        # bf16 numpy (ml_dtypes) isn't npz-portable → store uint16 view.
        kind = str(k.dtype)
        if kind == "bfloat16":
            k, v = k.view(np.uint16), v.view(np.uint16)
        extra = {}
        if len(pages) == 4:  # int8 pages carry fp32 scale sidecars
            extra = {"k_scale": pages[2], "v_scale": pages[3]}
        tmp = self._path(seq_hash) + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, k=k, v=v, dtype=np.bytes_(kind), **extra)
        os.replace(tmp, self._path(seq_hash))
        for h in evict:
            try:
                os.remove(self._path(h))
            except OSError:
                pass

    def get(self, seq_hash: int) -> tuple[np.ndarray, ...] | None:
        path = self._path(seq_hash)
        try:
            with np.load(path) as z:
                k, v, kind = z["k"], z["v"], bytes(z["dtype"]).decode()
                scales = (
                    (z["k_scale"], z["v_scale"]) if "k_scale" in z.files else ()
                )
        except (OSError, KeyError, ValueError):
            self.misses += 1
            return None
        if kind == "bfloat16":
            import ml_dtypes

            k, v = k.view(ml_dtypes.bfloat16), v.view(ml_dtypes.bfloat16)
        with self._lock:
            if seq_hash in self._order:
                self._order.move_to_end(seq_hash)
        self.hits += 1
        return (k, v, *scales)

    def contains(self, seq_hash: int) -> bool:
        with self._lock:
            return seq_hash in self._order

    def clear(self) -> int:
        with self._lock:
            hashes = list(self._order)
            self._order.clear()
        for h in hashes:
            try:
                os.remove(self._path(h))
            except OSError:
                pass
        return len(hashes)


class TierStack:
    """G2(+G3) lookup/offload facade the engine talks to.

    - ``offload(pairs)``: write-through sealed blocks (bounded per call —
      the offload queue analogue of the reference's OffloadManager
      priority queues; overflow is dropped, it is only a cache).
    - ``lookup_run(hashes)``: longest consecutive run of leading hashes
      available across tiers → list of (k, v) pages, promoting G3 hits
      into G2.
    """

    MAX_OFFLOAD_PER_STEP = 64

    def __init__(self, host: HostBlockPool | None, disk: DiskBlockPool | None):
        self.host = host
        self.disk = disk
        if host is not None and disk is not None:
            host._spill = disk.put
        self.offloaded_blocks = 0
        self.onboarded_blocks = 0

    @property
    def enabled(self) -> bool:
        return self.host is not None or self.disk is not None

    def offload(self, pairs: list[tuple]) -> int:
        """pairs: (seq_hash, *page_arrays) — (hash, k, v) for dense
        caches, (hash, k, v, k_scale, v_scale) for int8. → number
        offloaded."""
        n = 0
        for seq_hash, *pages in pairs[: self.MAX_OFFLOAD_PER_STEP]:
            if self.host is not None:
                self.host.put(seq_hash, *pages)
            elif self.disk is not None:
                self.disk.put(seq_hash, *pages)
            n += 1
        self.offloaded_blocks += n
        return n

    def peek_run_len(self, hashes: list[int]) -> int:
        """Length of the leading run resident in ANY tier — no page copies,
        no G3→G2 promotion (cheap existence probe for llm/peer_kv.py)."""
        n = 0
        for h in hashes:
            if not (
                (self.host is not None and self.host.contains(h))
                or (self.disk is not None and self.disk.contains(h))
            ):
                break
            n += 1
        return n

    def lookup_run(self, hashes: list[int]) -> list[tuple[np.ndarray, ...]]:
        out: list[tuple[np.ndarray, ...]] = []
        for h in hashes:
            pages = self.host.get(h) if self.host is not None else None
            if pages is None and self.disk is not None:
                pages = self.disk.get(h)
                if pages is not None and self.host is not None:
                    self.host.put(h, *pages)
            if pages is None:
                break
            out.append(pages)
        self.onboarded_blocks += len(out)
        return out

    def read_run(self, hashes: list[int]) -> list[tuple[np.ndarray, ...]]:
        """Non-promoting ``lookup_run``: G3 hits are NOT copied into G2 and
        the onboard counter is untouched. For serving a PEER's fetch
        (llm/peer_kv.py) — exporting a block must not evict this worker's
        own hot pages or masquerade as a local onboard."""
        out: list[tuple[np.ndarray, ...]] = []
        for h in hashes:
            pages = self.host.get(h) if self.host is not None else None
            if pages is None and self.disk is not None:
                pages = self.disk.get(h)
            if pages is None:
                break
            out.append(pages)
        return out

    def stats(self) -> dict:
        return {
            "g2_blocks": len(self.host) if self.host else 0,
            "g2_hits": self.host.hits if self.host else 0,
            "g3_blocks": len(self.disk) if self.disk else 0,
            "g3_hits": self.disk.hits if self.disk else 0,
            "offloaded_blocks": self.offloaded_blocks,
            "onboarded_blocks": self.onboarded_blocks,
        }
